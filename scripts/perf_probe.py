"""TPU perf probe for the flagship model: A/B attention impls, remat, batch.

Run on the real chip (no JAX_PLATFORMS override):
    python scripts/perf_probe.py [variant ...]
Variants: jnp8 flash8 jnp16 flash16 jnp16r jnp32r attnmicro
Default: all step variants.

A hard watchdog (CA_PROBE_TIMEOUT seconds, default 900) SIGKILLs the whole
process group if the accelerator runtime wedges: a hung device tunnel makes
jax.devices()/compilation block forever in C++ where no Python exception or
signal handler can reach, and the runtime forks helper processes that would
otherwise survive the probe and keep the device wedged for the next run
(BENCH_r05 "probe hung").  killpg is the only reliable way out.
"""

import os
import signal
import sys
import threading
import time


def _arm_watchdog():
    timeout_s = float(os.environ.get("CA_PROBE_TIMEOUT", "900"))
    if timeout_s <= 0:
        return
    # own process group, so the watchdog's killpg takes the accelerator
    # runtime's forked helpers down with us (and nothing else)
    if os.getpid() != os.getpgid(0):
        try:
            os.setpgid(0, 0)
        except OSError:
            pass

    def _fire():
        print(
            f"[perf_probe] watchdog: no completion within {timeout_s:.0f}s — "
            "killing process group (wedged accelerator runtime)",
            file=sys.stderr,
            flush=True,
        )
        try:
            os.killpg(os.getpgid(0), signal.SIGKILL)
        except OSError:
            os.kill(os.getpid(), signal.SIGKILL)

    t = threading.Timer(timeout_s, _fire)
    t.daemon = True
    t.start()
    return t


_WATCHDOG = _arm_watchdog()

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")

from cluster_anywhere_tpu.models import TransformerConfig, make_train_step
from cluster_anywhere_tpu.parallel import MeshSpec, make_mesh


def base_cfg(**kw):
    return TransformerConfig(
        vocab_size=32000,
        d_model=1024,
        n_layers=8,
        n_heads=16,
        n_kv_heads=8,
        d_head=64,
        d_ff=4096,
        max_seq_len=1024,
        dtype=jnp.bfloat16,
        **kw,
    )


def run_step(name, cfg, b, t, n=10):
    mesh = make_mesh(MeshSpec(dp=1))
    step, init_state = make_train_step(cfg, mesh)
    params, opt = init_state(jax.random.PRNGKey(0))
    batch = {"ids": jnp.asarray(np.random.randint(0, 32000, (b, t + 1), dtype=np.int32))}
    jstep = jax.jit(step, donate_argnums=(0, 1))
    t0 = time.time()
    params, opt, loss = jstep(params, opt, batch)
    jax.block_until_ready(loss)
    compile_s = time.time() - t0
    t0 = time.time()
    for _ in range(n):
        params, opt, loss = jstep(params, opt, batch)
    jax.block_until_ready(loss)
    dt = (time.time() - t0) / n
    print(
        f"{name:10s}: {dt*1000:7.1f} ms/step  {b*t/dt:10,.0f} tok/s  "
        f"(compile {compile_s:.0f}s, loss {float(loss):.3f})",
        flush=True,
    )
    return dt


def attn_micro():
    from cluster_anywhere_tpu.ops.attention import flash_attention, reference_attention

    b, t, h, d = 8, 1024, 16, 64
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (b, t, h, d), jnp.bfloat16)
    k = jax.random.normal(ks[1], (b, t, h, d), jnp.bfloat16)
    v = jax.random.normal(ks[2], (b, t, h, d), jnp.bfloat16)

    def bench(name, fn):
        f = jax.jit(jax.grad(lambda q, k, v: fn(q, k, v).astype(jnp.float32).sum(), argnums=(0, 1, 2)))
        out = f(q, k, v)
        jax.block_until_ready(out)
        t0 = time.time()
        for _ in range(20):
            out = f(q, k, v)
        jax.block_until_ready(out)
        print(f"attn {name:24s}: {(time.time()-t0)/20*1000:7.2f} ms fwd+bwd", flush=True)

    bench("jnp", lambda q, k, v: reference_attention(q, k, v, causal=True))
    for bq, bk in ((128, 128), (256, 256), (512, 512), (256, 512), (512, 1024), (1024, 1024)):
        bench(
            f"flash bq{bq} bk{bk}",
            lambda q, k, v, bq=bq, bk=bk: flash_attention(
                q, k, v, causal=True, block_q=bq, block_k=bk
            ),
        )


def serve_smoke():
    """Serving-plane smoke under the probe's watchdog: start a cluster,
    deploy a tiny ContinuousLLMServer, stream one SSE request through the
    HTTP proxy, tear down.  A wedged accelerator runtime (or a serve
    regression) can't hang the harness — the watchdog killpg's us."""
    import socket

    import cluster_anywhere_tpu as ca
    from cluster_anywhere_tpu import serve
    from cluster_anywhere_tpu.llm.processor import ProcessorConfig
    from cluster_anywhere_tpu.llm.serve_llm import build_continuous_llm_deployment
    from cluster_anywhere_tpu.microbenchmark import _sse_request

    ca.init(num_cpus=4)
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    serve.start(host="127.0.0.1", port=port)
    app = build_continuous_llm_deployment(
        ProcessorConfig(max_prompt_len=64, max_new_tokens=8),
        slots=2, num_replicas=1, sse_ingress=True,
    )
    serve.run(app, name="probesmoke", route_prefix="/probesmoke")
    status, ttft, total, n_events = _sse_request(
        "127.0.0.1", port, "/probesmoke",
        {"prompt": "probe smoke", "max_new_tokens": 8}, timeout=120,
    )
    assert status == 200, f"serve smoke: HTTP {status}"
    assert n_events >= 8, f"serve smoke: {n_events} SSE events (wanted >= 8)"
    print(
        f"serve smoke : {n_events} tokens streamed, TTFT {ttft*1e3:7.1f} ms "
        f"(cold: includes jit compile), total {total*1e3:7.1f} ms",
        flush=True,
    )
    serve.delete("probesmoke")
    serve.shutdown()
    ca.shutdown()


VARIANTS = {
    "jnp8": lambda: run_step("jnp b8", base_cfg(attn_impl="jnp"), 8, 1024),
    "flash8": lambda: run_step("flash b8", base_cfg(attn_impl="flash"), 8, 1024),
    "jnp16": lambda: run_step("jnp b16", base_cfg(attn_impl="jnp"), 16, 1024),
    "flash16": lambda: run_step("flash b16", base_cfg(attn_impl="flash"), 16, 1024),
    "jnp16r": lambda: run_step("jnp b16 rm", base_cfg(attn_impl="jnp", remat=True), 16, 1024),
    "jnp32r": lambda: run_step("jnp b32 rm", base_cfg(attn_impl="jnp", remat=True), 32, 1024),
    "attnmicro": attn_micro,
    "serve": serve_smoke,
}


def main():
    names = [a for a in sys.argv[1:] if a in VARIANTS] or ["jnp8", "flash8", "jnp16", "flash16"]
    print(f"devices: {jax.devices()}", flush=True)
    from cluster_anywhere_tpu.util.logplane import log_stats

    lp0 = log_stats()
    for n in names:
        VARIANTS[n]()
    # trailing JSON record for the BENCH harness: log-plane counter deltas
    # over the probe (zeros unless capture is active in this process — the
    # row exists either way so "plane off" and "never recorded" differ)
    import json as _json

    lp1 = log_stats()
    print(
        _json.dumps(
            {"logplane_deltas": {k: lp1[k] - lp0.get(k, 0) for k in lp1}}
        ),
        flush=True,
    )
    if _WATCHDOG is not None:
        _WATCHDOG.cancel()  # clean exit: don't let the timer outlive main


if __name__ == "__main__":
    main()
