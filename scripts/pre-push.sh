#!/bin/sh
# Git pre-push hook: run `ca lint --changed` over the files this branch
# touches (plus untracked) before anything leaves the machine.  The whole
# tree is still analyzed (the RPC contract is cross-file); only the reported
# finding set narrows to your diff, so the loop stays a few seconds.
#
# Install (from the repo root):
#   ln -sf ../../scripts/pre-push.sh .git/hooks/pre-push
#
# Bypass for a single push (e.g. landing a lint-rule change that flags
# pre-existing code you are fixing in the next commit):
#   git push --no-verify
set -e
cd "$(dirname "$0")/.."
# hooks run from .git/hooks via symlink; fall back to git's toplevel when
# invoked some other way
[ -d cluster_anywhere_tpu ] || cd "$(git rev-parse --show-toplevel)"
exec python3 -m cluster_anywhere_tpu.analysis.lint --changed
