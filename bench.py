"""Benchmark driver: prints ONE JSON line with the headline metric.

Headline: 1:1 async actor-call throughput, directly comparable to the
reference's microbenchmark "1:1 actor calls async" = 8107.0/s
(BASELINE.md, release/perf_metrics/microbenchmark.json).  Supplementary
metrics (async tasks, sync tasks, put bandwidth, TPU model step) go to
stderr.

Usage: python bench.py [--quick]
"""

import json
import os
import sys
import time
from typing import Optional

QUICK = "--quick" in sys.argv

BASELINE_ACTOR_ASYNC = 8107.0  # reference: 1:1 actor calls async (per second)


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def bench_core():
    import cluster_anywhere_tpu as ca

    # 4 pool workers regardless of core count: on small hosts more processes
    # just contend; on big hosts the driver IO thread is the bottleneck anyway
    ca.init(num_cpus=4)

    @ca.remote
    def noop():
        return None

    @ca.remote
    class Sink:
        def ping(self):
            return None

    n_small = 500 if QUICK else 4000
    rounds = 1 if QUICK else 6

    # warmup — and settle: prestarted-worker interpreter startups compete
    # with the head for cores and poison the first timed rounds
    ca.get([noop.remote() for _ in range(200)], timeout=60)
    actor = Sink.remote()
    ca.get(actor.ping.remote())
    if not QUICK:
        time.sleep(2.0)

    # best-of-N: this host is shared, so co-tenant bursts halve individual
    # rounds; the best round is the honest capability number
    best_tasks = 0.0
    for _ in range(rounds):
        t0 = time.time()
        ca.get([noop.remote() for _ in range(n_small)], timeout=120)
        best_tasks = max(best_tasks, n_small / (time.time() - t0))
    log(f"tasks_async_per_s: {best_tasks:.1f} (baseline 8032.4)")

    from cluster_anywhere_tpu.core.protocol import wire_stats

    ws0 = wire_stats()
    best_actor = 0.0
    for _ in range(rounds):
        t0 = time.time()
        ca.get([actor.ping.remote() for _ in range(n_small)], timeout=120)
        best_actor = max(best_actor, n_small / (time.time() - t0))
    log(f"actor_calls_async_per_s: {best_actor:.1f} (baseline 8107.0)")
    ws1 = wire_stats()
    d_msgs = ws1["messages_sent"] - ws0["messages_sent"]
    d_frames = ws1["frames_sent"] - ws0["frames_sent"]
    log(
        f"rpc_batching[actor burst]: {d_msgs} logical msgs in {d_frames} frames "
        f"({d_msgs / max(1, d_frames):.1f} msgs/frame, "
        f"{ws1['template_renders'] - ws0['template_renders']} template renders)"
    )

    n_sync = 100 if QUICK else 500
    t0 = time.time()
    for _ in range(n_sync):
        ca.get(noop.remote())
    sync_rate = n_sync / (time.time() - t0)
    log(f"tasks_sync_per_s: {sync_rate:.1f} (baseline 1013.2)")

    # put bandwidth (shared-memory store).  One untimed round first: it sizes
    # and pre-faults the arena, matching the baseline's plasma store whose
    # memory is pre-allocated before the benchmark ever runs
    import numpy as np

    size = 64 * 1024 * 1024 if QUICK else 256 * 1024 * 1024
    # ndarray, not bytes: pickle-5 only emits out-of-band buffers for
    # ndarray/bytearray, and the zero-copy shm path is what the baseline measures
    arr = np.frombuffer(np.random.bytes(size), dtype=np.uint8)
    probe = _MemcpyProbe(arr)
    reps = 2 if QUICK else 5
    warm = [ca.put(arr) for _ in range(reps)]
    del warm
    time.sleep(1.0)  # slice reclaim drains; pages stay faulted
    best_put = 0.0
    ceiling = 0.0
    # best-of-3, the ceiling probe interleaved with the put rounds: this
    # host's memcpy bandwidth swings >2x with co-tenant load, so the ratio
    # is only meaningful when both sides see the same conditions
    for _ in range(3):
        ceiling = max(ceiling, probe.measure())
        t0 = time.time()
        refs = [ca.put(arr) for _ in range(reps)]
        dt = time.time() - t0
        best_put = max(best_put, reps * size / dt / 1e9)
        del refs
        time.sleep(0.5)
    log(
        f"put_gb_per_s: {best_put:.2f} (baseline 18.52; this host's 1-thread "
        f"memcpy ceiling {ceiling:.2f} -> put at {best_put/ceiling:.0%} of ceiling)"
    )

    # log-plane counter deltas for the BENCH json: the cluster started fresh
    # in this process, so the head's cluster-wide aggregates ARE the run's
    # deltas (capture volume + drops prove the plane stayed out of the way)
    logplane = {}
    drainplane = {}
    try:
        stats = ca.cluster_stats()
        logplane = {
            k: stats.get(k, 0)
            for k in (
                "ca_log_lines_total", "ca_log_bytes_total",
                "ca_log_dropped_total", "log_lines_shipped",
                "log_lines_dropped",
            )
        }
        log(f"logplane counters: {logplane}")
        # drain-plane counters: a clean bench run proves the plane is free
        # when idle (all zeros) — a chaos/preemption run shows its work
        drainplane = {
            k: stats.get(k, 0)
            for k in (
                "nodes_drained", "drain_actors_migrated",
                "drain_objects_migrated", "drain_deadline_kills",
                "drain_tasks_evacuated",
            )
        }
        log(f"drain counters: {drainplane}")
    except Exception:
        pass

    # ownership-plane counters: in a clean bench run the object lifetime
    # traffic settles owner-resident — refs_head_fallback ~0 and the head's
    # obj_refs RPC count near zero are the structural halves of the claim
    ownerplane = {}
    try:
        from cluster_anywhere_tpu.core.ownership import owner_stats
        from cluster_anywhere_tpu.core.worker import global_worker

        ownerplane = owner_stats()
        rc = global_worker().head_call("stats").get("rpc_counts", {})
        ownerplane["head_obj_refs_rpcs"] = rc.get("obj_refs", 0)
        ownerplane["head_owner_sync_rpcs"] = rc.get("owner_sync", 0)
        log(f"ownerplane counters: {ownerplane}")
    except Exception:
        pass

    # metrics-plane block: the head's self-instrumentation (event-loop lag
    # p50/p99, per-RPC dispatch histogram summary) and the time-series
    # store's retained footprint — the series future saturation work
    # re-benchmarks against
    metricsplane = {}
    try:
        from cluster_anywhere_tpu.core.worker import global_worker

        w = global_worker()
        snap = w.head_call("metrics_snapshot")["metrics"]

        from cluster_anywhere_tpu.util.metrics import (
            histogram_quantile as hist_pct,
            merged_histogram as merged_hist,
        )

        lb, lbk, lcount = merged_hist(snap.get("ca_head_loop_lag_hist_seconds"))
        db, dbk, dcount = merged_hist(snap.get("ca_head_dispatch_seconds"))
        ts_meta = w.head_call("timeseries", names=[]).get("meta", {})
        dropped = snap.get("ca_metrics_dropped_total", {}).get("data", {})
        metricsplane = {
            "loop_lag_samples": lcount,
            "loop_lag_p50_ms": round(hist_pct(lb, lbk, lcount, 0.50) * 1e3, 3),
            "loop_lag_p99_ms": round(hist_pct(lb, lbk, lcount, 0.99) * 1e3, 3),
            "dispatch_rpcs": dcount,
            "dispatch_methods": len((snap.get("ca_head_dispatch_seconds") or {}).get("data", {})),
            "dispatch_p50_ms": round(hist_pct(db, dbk, dcount, 0.50) * 1e3, 3),
            "dispatch_p99_ms": round(hist_pct(db, dbk, dcount, 0.99) * 1e3, 3),
            "timeseries_series": ts_meta.get("n_series", 0),
            "timeseries_memory_bytes": ts_meta.get("memory_bytes", 0),
            "metrics_dropped_total": int(sum(dropped.values())),
        }
        log(f"metricsplane: {metricsplane}")
    except Exception:
        pass

    ca.shutdown()
    return (
        best_tasks, best_actor, sync_rate, logplane, drainplane, ownerplane,
        metricsplane,
    )


class _MemcpyProbe:
    """Raw single-thread memcpy bandwidth into pre-faulted /dev/shm, GB/s —
    the physical bound a put (one serialize-free copy into the store) can
    approach on this host.  Printing it next to put_gb_per_s separates
    framework overhead from host memory physics."""

    def __init__(self, src):
        import mmap
        import os

        import numpy as np

        self.src = src
        size = len(src)
        path = f"/dev/shm/ca_memcpy_probe_{os.getpid()}"
        fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o600)
        try:
            os.ftruncate(fd, size)
            self._m = mmap.mmap(fd, size)
        finally:
            os.close(fd)
            os.unlink(path)
        self.dst = np.frombuffer(memoryview(self._m), dtype=np.uint8)
        self.dst[:] = src  # fault the pages before any timed copy

    def measure(self, rounds: int = 2) -> float:
        best = 0.0
        for _ in range(rounds):
            t0 = time.perf_counter()
            self.dst[:] = self.src
            best = max(best, len(self.src) / (time.perf_counter() - t0) / 1e9)
        return best


def _check_flash_numerics():
    """One-shot compiled (NOT interpret-mode) flash-vs-dense numerics check on
    the real device, so a wrong kernel can never silently ship a fast number."""
    import jax
    import jax.numpy as jnp

    from cluster_anywhere_tpu.ops.attention import flash_attention, reference_attention

    ks = jax.random.split(jax.random.PRNGKey(7), 3)
    # flagship head shape (d_head=128): check the kernel at what we ship
    q = jax.random.normal(ks[0], (2, 256, 4, 128), jnp.bfloat16)
    k = jax.random.normal(ks[1], (2, 256, 4, 128), jnp.bfloat16)
    v = jax.random.normal(ks[2], (2, 256, 4, 128), jnp.bfloat16)
    got = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))(q, k, v)
    want = jax.jit(lambda q, k, v: reference_attention(q, k, v, causal=True))(q, k, v)
    err = float(jnp.max(jnp.abs(got.astype(jnp.float32) - want.astype(jnp.float32))))
    ok = err < 0.05  # bf16 tolerance
    log(f"flash numerics (compiled): max_abs_err={err:.4f} {'OK' if ok else 'MISMATCH'}")
    return ok


def bench_model():
    """Train-step throughput of the flagship model on the local accelerator.

    Times are synced by reading the loss back to host (block_until_ready does
    not force completion through the axon tunnel).  Both attention paths are
    timed (A/B) so a slower kernel can never silently become the dispatch
    default; the headline is the better of the two.

    Returns None on success, else a short skip-reason string that the driver
    records into the BENCH json (a silently missing model row looked
    identical to "never attempted")."""
    try:
        import jax

        devs = jax.devices()
        log(f"devices: {devs}")
        import jax.numpy as jnp
        import numpy as np

        from cluster_anywhere_tpu.models import TransformerConfig, make_train_step
        from cluster_anywhere_tpu.parallel import MeshSpec, make_mesh

        on_tpu = devs[0].platform not in ("cpu",)
        flash_ok = _check_flash_numerics() if on_tpu else False

        # v5e bf16 peak per chip; MFU printed against it so every round is
        # accountable to the number (SURVEY §7.6 bar: >=40%).  Two counts:
        # "full" credits the 4·t²·d·h square attention (the loose convention
        # some reports use); "causal" halves the attention term because a
        # causal flash kernel only computes the lower triangle — the honest
        # number, and the headline here.
        PEAK_TFLOPS = 197.0

        def model_flops_per_step(cfg, b, t, causal_discount=False):
            e, h, kv, d = cfg.d_model, cfg.n_heads, cfg.n_kv_heads, cfg.d_head
            f, L, V = cfg.d_ff, cfg.n_layers, cfg.vocab_size
            per_tok_layer = 2 * (e * h * d + 2 * e * kv * d + h * d * e + 3 * e * f)
            attn_per_seq_layer = 4 * t * t * d * h * (0.5 if causal_discount else 1.0)
            fwd = b * t * per_tok_layer * L + b * attn_per_seq_layer * L + b * t * 2 * e * V
            return 3 * fwd  # bwd ~= 2x fwd

        def run(attn_impl: str, donate: Optional[bool] = None, **cfg_overrides):
            base = dict(
                vocab_size=32000,
                d_model=1024 if on_tpu else 128,
                n_layers=8 if on_tpu else 2,
                # d_head=128 fills the MXU's 128-lane contraction; at equal
                # FLOPs the d_head=64/h=16 shape measured 84.1 ms vs this
                # shape's 73.7 ms (both at (512,512) tiles; r4's default-tile
                # run was 86.6 ms).  GQA kv=4 beats kv=8 in time AND MFU.
                n_heads=8 if on_tpu else 4,
                n_kv_heads=4 if on_tpu else 4,
                d_head=128 if on_tpu else 16,
                d_ff=4096 if on_tpu else 256,
                max_seq_len=1024,
                dtype=jnp.bfloat16 if on_tpu else jnp.float32,
                attn_impl=attn_impl,
                # measured best tiles for fwd+bwd at d_head=128, t=1024 on
                # v5e ((256,512)/(512,1024) within 1%; (256,1024) -8%)
                flash_block_q=512,
                flash_block_k=512,
            )
            base.update(cfg_overrides)
            cfg = TransformerConfig(**base)
            if cfg.n_experts:
                # MoE routes over the ep axis; single-process bench uses
                # ep=1 (all experts resident) — the A/B isolates routing +
                # expert-FFN cost, not cross-chip all_to_all
                mesh = make_mesh(MeshSpec(ep=1, dp=len(devs)))
            else:
                mesh = make_mesh(MeshSpec(dp=len(devs)))
            step, init_state = make_train_step(cfg, mesh)
            params, opt_state = init_state(jax.random.PRNGKey(0))
            b, t = (8, 1024) if on_tpu else (4, 128)
            batch = {
                "ids": jnp.asarray(
                    np.random.randint(0, cfg.vocab_size, (b, t + 1), dtype=np.int32)
                )
            }
            # donation + partial-manual shard_map is pathological on this
            # backend: the MoE step ran 3.4 s donated vs 74 ms undonated
            # (measured, SCALE.md) — the input-output aliasing forces the
            # tunnel runtime into per-buffer round trips.  Dense (no
            # shard_map) donates fine and saves the param-copy HBM.
            if donate is None:
                donate = not cfg.n_experts
            jstep = jax.jit(step, donate_argnums=(0, 1) if donate else ())
            params, opt_state, loss = jstep(params, opt_state, batch)  # compile
            _ = float(loss)  # host readback = real completion barrier
            n = 3 if QUICK else 10
            t0 = time.time()
            for _ in range(n):
                params, opt_state, loss = jstep(params, opt_state, batch)
            _ = float(loss)
            dt = (time.time() - t0) / n
            # peak scales with the dp mesh size: the step's FLOPs spread
            # across every local chip
            denom = dt * 1e12 * PEAK_TFLOPS * len(devs)
            mfu = model_flops_per_step(cfg, b, t) / denom * 100
            mfu_causal = (
                model_flops_per_step(cfg, b, t, causal_discount=True) / denom * 100
            )
            log(
                f"model_step[{attn_impl}]: {dt*1000:.1f} ms, "
                f"tokens_per_s: {b*t/dt:,.0f}, mfu_pct: {mfu:.1f} "
                f"(causal-discounted {mfu_causal:.1f}) ({devs[0].platform})"
            )
            return dt, b * t / dt, (mfu, mfu_causal)

        dt_jnp, tok_jnp, mfu_jnp = run("jnp")
        if flash_ok:  # a numerically wrong kernel must not set the headline
            dt_flash, tok_flash, mfu_flash = run("flash")
        else:
            dt_flash, tok_flash, mfu_flash = dt_jnp, tok_jnp, mfu_jnp
        dt, tokens, mfu = min(
            (dt_jnp, tok_jnp, mfu_jnp), (dt_flash, tok_flash, mfu_flash),
            key=lambda x: x[0],
        )
        log(
            f"model_step_s: {dt*1000:.1f} ms, tokens_per_s: {tokens:,.0f}, "
            f"mfu_pct: {mfu[0]:.1f} (causal-discounted {mfu[1]:.1f}) "
            f"({devs[0].platform})"
        )
        # MoE A/B: same stack with the FFN switched to 4 top-1 experts
        # (parallel/moe.py).  tokens/s only — MoE FLOP accounting differs
        # (each token visits one expert + router), so MFU vs the dense
        # count would mislead.
        if not QUICK:
            try:
                # MoE A/B at L4, undonated, jnp attention on BOTH sides:
                # - jnp attn: the ep shard_map is manual over 'ep' but
                #   GSPMD-auto elsewhere, which Mosaic kernels can't join;
                # - no donation: see the aliasing pathology above;
                # - L4: the 4-expert stack at L8 is 360M params and an
                #   UNdonated step needs two param+opt copies -> HBM spill
                #   (4.3 s measured).  Holding depth/attn/donation fixed,
                #   the pair isolates dense-FFN vs top-1 expert routing.
                dt_d4, tok_d4, _ = run("jnp", donate=False, n_layers=4)
                dt_moe, tok_moe, _ = run(
                    "jnp", donate=False, n_layers=4, n_experts=4
                )
                log(
                    f"model_step_moe[L4 e4 top-1, jnp attn]: {dt_moe*1000:.1f} ms, "
                    f"tokens_per_s: {tok_moe:,.0f} "
                    f"(dense L4 A/B: {dt_d4*1000:.1f} ms / {tok_d4:,.0f} tok/s)"
                )
            except Exception as e:  # MoE bench is supplementary
                log(f"moe bench skipped: {type(e).__name__}: {e}")
    except Exception as e:
        reason = f"{type(e).__name__}: {e}"
        log(f"model bench skipped: {reason}")
        return reason
    return None


def _device_probe_ok(timeout_s: Optional[float] = None) -> bool:
    """Probe accelerator availability in a subprocess with a HARD timeout.

    A wedged device tunnel makes jax.devices() hang forever, which must not
    take the whole bench down with it.  subprocess.run(capture_output=True)
    is NOT safe here: on timeout it kills the child but then blocks in
    communicate() waiting for the pipes to close — and the accelerator
    runtime forks helpers that inherit them, so the old implementation hung
    right after printing nothing (BENCH_r05 "probe hung").  Instead: no
    pipes at all, a fresh process group, and a group-wide SIGKILL on
    timeout so helper processes die with the probe."""
    import signal
    import subprocess

    if timeout_s is None:
        timeout_s = 30 if QUICK else 120
    proc = subprocess.Popen(
        [sys.executable, "-c", "import jax; jax.devices()"],
        stdin=subprocess.DEVNULL,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
        start_new_session=True,  # its own process group: killable as a unit
    )
    try:
        return proc.wait(timeout=timeout_s) == 0
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        try:
            proc.wait(timeout=5)
        except subprocess.TimeoutExpired:
            pass  # unreapable zombie: the skip still proceeds cleanly
        return False


def bench_transfer_plane():
    """The transfer-plane A/B rows (serial vs windowed pull on a latency-
    injected link, 1 vs 2 sources, f32 vs int8/bf16 quantized ring) as a
    BENCH-json block, so the trajectory captures the data-plane speedups
    from this round on.  Quick mode: the structural ratios are the point
    (speedups, occupancy, head RPCs/object), not absolute MB/s on this
    noisy host."""
    from cluster_anywhere_tpu.microbenchmark import run_transfer_plane

    rows = run_transfer_plane(quick=True)
    out = {}
    for name, value, _unit in rows:
        key = (
            name.replace(" ", "_").replace("(", "").replace(")", "")
            .replace(",", "").replace("=", "").replace("/", "_per_")
        )
        out[key] = round(value, 3)
    log(f"transferplane: {out}")
    return out


def bench_serve_plane():
    """Serving-plane envelope rows (open-loop SSE req/s + TTFT/p99, shedding
    and prefix-cache A/Bs, drain-under-load zero-drop proof) as a BENCH-json
    block, so the trajectory captures the serve path the way it captured the
    lease/owner/transfer planes."""
    from cluster_anywhere_tpu.microbenchmark import run_serve_plane

    rows = run_serve_plane(quick=True)
    out = {}
    for name, value, unit in rows:
        key = name.replace("serve ", "").replace(" ", "_").replace("-", "_")
        out[key] = round(value, 3)
    log(f"serveplane: {out}")
    return out


def bench_train_plane():
    """Preemption-elastic train rows (drain-aware proactive restart vs
    reactive poll-failure restart: warning->resumed latency + steps lost)
    as a BENCH-json block — the structural claim is proactive losing
    strictly fewer steps, not absolute latency on this noisy host."""
    from cluster_anywhere_tpu.microbenchmark import run_train_elastic

    rows = run_train_elastic(quick=True)
    out = {}
    for name, value, _unit in rows:
        key = name.replace("train-elastic ", "").replace(" ", "_").replace("-", "_")
        out[key] = round(value, 3)
    log(f"trainplane: {out}")
    return out


def bench_dag_plane():
    """Compiled-DAG plane rows (compiled tick vs RPC actor-call latency and
    throughput, 3-actor chain A/B, serve TTFT with the compiled stream on
    vs off) as a BENCH-json block.  The structural claim is the latency
    ratio (compiled tick >= 10x below the sync RPC path); absolute us on
    this shared host is context."""
    from cluster_anywhere_tpu.microbenchmark import run_dag_plane

    rows = run_dag_plane(quick=True)
    out = {}
    for name, value, _unit in rows:
        key = name.replace("dag ", "").replace(" ", "_").replace("-", "_")
        out[key] = round(value, 3)
    log(f"dagplane: {out}")
    return out


def bench_chaos_plane():
    """Partition-tolerance rows (head<->node blackhole mid-workload:
    detect->fence->heal timeline, at-most-once commit proof, zombie-grant
    audit, fresh-incarnation rejoin) as a BENCH-json block.  The structural
    claims are zero duplicate/missing commits and zero zombie grants; the
    detect/heal latencies are host-noisy context."""
    from cluster_anywhere_tpu.microbenchmark import run_partition_chaos

    rows = run_partition_chaos(quick=True)
    out = {}
    for name, value, _unit in rows:
        key = name.replace("partition ", "").replace("->", "_to_").replace(" ", "_")
        out[key] = round(value, 3)
    log(f"chaosplane: {out}")
    return out


def bench_obsplane():
    """Flight-recorder cost rows (armed record events/s, disabled-path gate
    rate, journal memory at the default ring cap, task throughput with the
    plane on vs off) as a BENCH-json block.  The structural claims: the
    disabled path is one attribute load + branch (tens of ns), and the
    on/off task-throughput ratio stays within host noise."""
    from cluster_anywhere_tpu.microbenchmark import run_obsplane

    rows = run_obsplane(quick=True)
    out = {}
    for name, value, _unit in rows:
        key = (
            name.replace("obsplane ", "").replace(" ", "_")
            .replace("/", "_per_")
        )
        out[key] = round(value, 3)
    log(f"obsplane: {out}")
    return out


def bench_ha_plane():
    """Head-failover rows (SIGKILL the active head with a warm standby
    subscribed: detect->promote->first-op latency, acked-KV loss, duplicate
    side effects, epoch bump) as a BENCH-json block.  The structural claims
    are loss = 0 and dup = 0; the failover latencies are host-noisy
    context."""
    from cluster_anywhere_tpu.microbenchmark import run_ha_plane

    rows = run_ha_plane(quick=True)
    out = {}
    for name, value, _unit in rows:
        key = (
            name.replace("ha ", "").replace("->", "_to_").replace(" ", "_")
        )
        out[key] = round(value, 3)
    log(f"haplane: {out}")
    return out


def main():
    _, best_actor, _, logplane, drainplane, ownerplane, metricsplane = bench_core()
    transferplane = {}
    try:
        transferplane = bench_transfer_plane()
    except Exception as e:
        log(f"transfer plane bench failed: {e!r}")
    serveplane = {}
    try:
        serveplane = bench_serve_plane()
    except Exception as e:
        log(f"serve plane bench failed: {e!r}")
    trainplane = {}
    try:
        trainplane = bench_train_plane()
    except Exception as e:
        log(f"train plane bench failed: {e!r}")
    dagplane = {}
    try:
        dagplane = bench_dag_plane()
    except Exception as e:
        log(f"dag plane bench failed: {e!r}")
    chaosplane = {}
    try:
        chaosplane = bench_chaos_plane()
    except Exception as e:
        log(f"chaos plane bench failed: {e!r}")
    obsplane = {}
    try:
        obsplane = bench_obsplane()
    except Exception as e:
        log(f"obs plane bench failed: {e!r}")
    haplane = {}
    try:
        haplane = bench_ha_plane()
    except Exception as e:
        log(f"ha plane bench failed: {e!r}")
    if _device_probe_ok():
        model_skip = bench_model()
    else:
        model_skip = "accelerator runtime unreachable (probe hung)"
        log(f"model bench skipped: {model_skip}")
    out = {
        "metric": "actor_calls_async_per_s",
        "value": round(best_actor, 1),
        "unit": "calls/s",
        "vs_baseline": round(best_actor / BASELINE_ACTOR_ASYNC, 3),
    }
    if logplane:
        out["logplane"] = logplane
    if drainplane:
        out["drainplane"] = drainplane
    if ownerplane:
        out["ownerplane"] = ownerplane
    if metricsplane:
        out["metricsplane"] = metricsplane
    if transferplane:
        out["transferplane"] = transferplane
    if serveplane:
        out["serveplane"] = serveplane
    if trainplane:
        out["trainplane"] = trainplane
    if dagplane:
        out["dagplane"] = dagplane
    if chaosplane:
        out["chaosplane"] = chaosplane
    if obsplane:
        out["obsplane"] = obsplane
    if haplane:
        out["haplane"] = haplane
    if model_skip is not None:
        # the skip reason travels in the json, not just stderr: a missing
        # model row must be distinguishable from a never-attempted one
        out["model_skipped_reason"] = model_skip
    print(json.dumps(out))


if __name__ == "__main__":
    main()
