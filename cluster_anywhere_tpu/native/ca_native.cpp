// Native runtime helpers for cluster_anywhere_tpu.
//
// TPU-native analogue of the reference's C++ data-plane fast paths
// (src/ray/object_manager/plasma/ memcpy paths and the futex-style
// semaphores of experimental mutable objects,
// src/ray/core_worker/experimental_mutable_object_manager.h):
//
//  - ca_parallel_copy: multi-threaded memcpy for large object payloads
//    (plasma splits big copies across threads the same way).
//  - ca_wait_u64_ge / ca_store_u64_wake: cross-process futex wait/notify on
//    8-byte shared-memory words — the blocking primitive under the shm
//    channels (no spin-polling, microsecond wakeups).
//
// Built with: g++ -O3 -shared -fPIC -pthread (see build.py).

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <thread>
#include <vector>

#include <linux/futex.h>
#include <sys/syscall.h>
#include <unistd.h>

extern "C" {

// ---------------------------------------------------------------- memcpy

// Copy n bytes with up to `max_threads` threads. Threading only pays off for
// large buffers; callers should gate on size (we also gate here).
void ca_parallel_copy(void* dst, const void* src, uint64_t n,
                      int max_threads) {
  constexpr uint64_t kMinPerThread = 4ull << 20;  // 4 MiB
  int nthreads = max_threads > 0 ? max_threads : 4;
  uint64_t want = (uint64_t)(n / kMinPerThread);
  if (want < (uint64_t)nthreads) nthreads = (int)want;
  if (nthreads <= 1 || n < 2 * kMinPerThread) {
    memcpy(dst, src, n);
    return;
  }
  uint64_t chunk = (n + nthreads - 1) / nthreads;
  std::vector<std::thread> ts;
  ts.reserve(nthreads - 1);
  for (int i = 1; i < nthreads; i++) {
    uint64_t off = (uint64_t)i * chunk;
    if (off >= n) break;
    uint64_t len = (off + chunk <= n) ? chunk : n - off;
    ts.emplace_back([=] { memcpy((char*)dst + off, (const char*)src + off, len); });
  }
  memcpy(dst, src, chunk <= n ? chunk : n);
  for (auto& t : ts) t.join();
}

// ----------------------------------------------------------------- futex

static long futex(uint32_t* uaddr, int op, uint32_t val,
                  const struct timespec* timeout) {
  return syscall(SYS_futex, uaddr, op, val, timeout, nullptr, 0);
}

// Wait until the u64 at `addr` (8-byte aligned, shared mapping) is >= min_val.
// timeout_ns < 0 means wait forever. Returns 0 on success, -1 on timeout.
//
// The futex sleeps on the LOW 32 bits (little-endian): every increment of the
// u64 changes them, so a sleeper is always woken by a publish.
int ca_wait_u64_ge(const volatile uint64_t* addr, uint64_t min_val,
                   int64_t timeout_ns) {
  auto* a = reinterpret_cast<const std::atomic<uint64_t>*>(
      const_cast<const uint64_t*>(addr));
  struct timespec deadline;
  if (timeout_ns >= 0) {
    clock_gettime(CLOCK_MONOTONIC, &deadline);
    deadline.tv_sec += timeout_ns / 1000000000ll;
    deadline.tv_nsec += timeout_ns % 1000000000ll;
    if (deadline.tv_nsec >= 1000000000l) {
      deadline.tv_sec += 1;
      deadline.tv_nsec -= 1000000000l;
    }
  }
  // brief spin first: channel handoffs are often sub-microsecond
  for (int i = 0; i < 64; i++) {
    if (a->load(std::memory_order_acquire) >= min_val) return 0;
#if defined(__x86_64__)
    __builtin_ia32_pause();
#else
    std::this_thread::yield();
#endif
  }
  while (true) {
    uint64_t v = a->load(std::memory_order_acquire);
    if (v >= min_val) return 0;
    struct timespec ts;
    const struct timespec* tp = nullptr;
    if (timeout_ns >= 0) {
      struct timespec now;
      clock_gettime(CLOCK_MONOTONIC, &now);
      int64_t ns = (deadline.tv_sec - now.tv_sec) * 1000000000ll +
                   (deadline.tv_nsec - now.tv_nsec);
      if (ns <= 0) return -1;
      ts.tv_sec = ns / 1000000000ll;
      ts.tv_nsec = ns % 1000000000ll;
      tp = &ts;
    }
    uint32_t low = (uint32_t)v;
    long rc = futex((uint32_t*)addr, FUTEX_WAIT, low, tp);
    if (rc == -1 && errno == ETIMEDOUT) return -1;
    // EAGAIN (value changed) / EINTR: loop and re-check
  }
}

// Release-store a u64 then wake all futex waiters on it.
void ca_store_u64_wake(volatile uint64_t* addr, uint64_t val) {
  auto* a = reinterpret_cast<std::atomic<uint64_t>*>(
      const_cast<uint64_t*>(addr));
  a->store(val, std::memory_order_release);
  futex((uint32_t*)addr, FUTEX_WAKE, INT32_MAX, nullptr);
}

// Wake all futex waiters WITHOUT storing — for close()-style nudges where a
// blind read-modify-store could roll back a concurrent publish.
void ca_wake_u64(volatile uint64_t* addr) {
  futex((uint32_t*)addr, FUTEX_WAKE, INT32_MAX, nullptr);
}

// Like ca_wait_u64_ge, but also watches a flag word: returns 2 as soon as
// (*flag_addr & flag_mask) != 0 (a close() that wakes this word is observed
// immediately instead of being re-slept through). 0 = value reached,
// -1 = timeout.
int ca_wait_u64_ge_flag(const volatile uint64_t* addr, uint64_t min_val,
                        const volatile uint64_t* flag_addr, uint64_t flag_mask,
                        int64_t timeout_ns) {
  auto* a = reinterpret_cast<const std::atomic<uint64_t>*>(
      const_cast<const uint64_t*>(addr));
  auto* fa = reinterpret_cast<const std::atomic<uint64_t>*>(
      const_cast<const uint64_t*>(flag_addr));
  struct timespec deadline;
  if (timeout_ns >= 0) {
    clock_gettime(CLOCK_MONOTONIC, &deadline);
    deadline.tv_sec += timeout_ns / 1000000000ll;
    deadline.tv_nsec += timeout_ns % 1000000000ll;
    if (deadline.tv_nsec >= 1000000000l) {
      deadline.tv_sec += 1;
      deadline.tv_nsec -= 1000000000l;
    }
  }
  for (int i = 0; i < 64; i++) {
    if (a->load(std::memory_order_acquire) >= min_val) return 0;
    if (fa->load(std::memory_order_acquire) & flag_mask) return 2;
#if defined(__x86_64__)
    __builtin_ia32_pause();
#else
    std::this_thread::yield();
#endif
  }
  while (true) {
    uint64_t v = a->load(std::memory_order_acquire);
    if (v >= min_val) return 0;
    if (fa->load(std::memory_order_acquire) & flag_mask) return 2;
    struct timespec ts;
    const struct timespec* tp = nullptr;
    if (timeout_ns >= 0) {
      struct timespec now;
      clock_gettime(CLOCK_MONOTONIC, &now);
      int64_t ns = (deadline.tv_sec - now.tv_sec) * 1000000000ll +
                   (deadline.tv_nsec - now.tv_nsec);
      if (ns <= 0) return -1;
      ts.tv_sec = ns / 1000000000ll;
      ts.tv_nsec = ns % 1000000000ll;
      tp = &ts;
    }
    futex((uint32_t*)addr, FUTEX_WAIT, (uint32_t)v, tp);
  }
}

// Plain acquire load (symmetry helper for the Python side).
uint64_t ca_load_u64(const volatile uint64_t* addr) {
  auto* a = reinterpret_cast<const std::atomic<uint64_t>*>(
      const_cast<const uint64_t*>(addr));
  return a->load(std::memory_order_acquire);
}

}  // extern "C"
