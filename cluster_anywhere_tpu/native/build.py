"""Build + load the native helper library (ca_native.cpp) via ctypes.

Compiled on first use with g++ into native/_build/, cached by source mtime.
Every consumer degrades gracefully to pure Python when the toolchain or a
Linux-only primitive is unavailable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "ca_native.cpp")
_BUILD_DIR = os.path.join(_HERE, "_build")
_SO = os.path.join(_BUILD_DIR, "libca_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_failed = False


def _compile(out: str = _SO, extra_flags: Optional[list] = None) -> bool:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    tmp = out + f".tmp{os.getpid()}"
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-pthread", "-std=c++17",
        *(extra_flags or []),
        _SRC, "-o", tmp,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)
        return True
    except (subprocess.SubprocessError, OSError):
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def build_sanitized(kind: str = "thread") -> Optional[str]:
    """Build a sanitizer-instrumented variant (TSAN/ASAN) of the native lib
    and return its path, or None if the toolchain can't.  Used by the race
    -detection tests (§5 sanitizer story): the instrumented .so is loaded in
    a subprocess with the sanitizer runtime LD_PRELOADed, never in-process.
    """
    assert kind in ("thread", "address")
    out = os.path.join(_BUILD_DIR, f"libca_native.{kind[0]}san.so")
    if (
        os.path.exists(out)
        and os.path.getmtime(out) >= os.path.getmtime(_SRC)
    ):
        return out
    flags = [f"-fsanitize={kind}", "-g", "-fno-omit-frame-pointer"]
    return out if _compile(out, flags) else None


def load() -> Optional[ctypes.CDLL]:
    """The shared library, building it if stale/missing. None if unavailable."""
    global _lib, _failed
    with _lock:
        if _lib is not None:
            return _lib
        if _failed:
            return None
        try:
            need_build = (
                not os.path.exists(_SO)
                or os.path.getmtime(_SO) < os.path.getmtime(_SRC)
            )
            if need_build and not _compile():
                _failed = True
                return None
            lib = ctypes.CDLL(_SO)
            lib.ca_parallel_copy.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int,
            ]
            lib.ca_parallel_copy.restype = None
            lib.ca_wait_u64_ge.argtypes = [
                ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int64,
            ]
            lib.ca_wait_u64_ge.restype = ctypes.c_int
            lib.ca_store_u64_wake.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
            lib.ca_store_u64_wake.restype = None
            lib.ca_wake_u64.argtypes = [ctypes.c_void_p]
            lib.ca_wake_u64.restype = None
            lib.ca_wait_u64_ge_flag.argtypes = [
                ctypes.c_void_p, ctypes.c_uint64,
                ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int64,
            ]
            lib.ca_wait_u64_ge_flag.restype = ctypes.c_int
            lib.ca_load_u64.argtypes = [ctypes.c_void_p]
            lib.ca_load_u64.restype = ctypes.c_uint64
            _lib = lib
            return _lib
        except OSError:
            _failed = True
            return None


def buffer_address(buf) -> int:
    """Base address of a writable buffer (mmap or memoryview)."""
    c = (ctypes.c_char * len(buf)).from_buffer(buf)
    return ctypes.addressof(c)
