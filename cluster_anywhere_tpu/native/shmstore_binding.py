"""ctypes binding for the shm-store fast path: threaded memcpy of large
payloads into mapped segments (plasma-style parallel writes)."""

from __future__ import annotations

import ctypes
import os

import numpy as np

from . import build

_THRESHOLD = 8 << 20  # below this a plain slice copy beats thread spawn


class _ShmNative:
    def __init__(self, lib: ctypes.CDLL):
        self._lib = lib
        self._threads = min(8, (os.cpu_count() or 4))

    def copy_into(self, dst_mv: memoryview, offset: int, src) -> None:
        """dst_mv[offset:offset+len(src)] = src, multithreaded when large."""
        src_mv = memoryview(src)
        if src_mv.ndim != 1 or src_mv.itemsize != 1:
            src_mv = src_mv.cast("B")
        n = len(src_mv)
        if n < _THRESHOLD:
            dst_mv[offset : offset + n] = src_mv
            return
        # numpy views expose raw addresses for writable AND readonly buffers
        dst_arr = np.frombuffer(dst_mv, dtype=np.uint8)
        src_arr = np.frombuffer(src_mv, dtype=np.uint8)
        self._lib.ca_parallel_copy(
            ctypes.c_void_p(dst_arr.ctypes.data + offset),
            ctypes.c_void_p(src_arr.ctypes.data),
            ctypes.c_uint64(n),
            self._threads,
        )


def load() -> _ShmNative:
    lib = build.load()
    if lib is None:
        raise RuntimeError("native library unavailable")
    return _ShmNative(lib)
