"""In-process multi-node cluster fixture (analogue of
python/ray/cluster_utils.py:135 `Cluster`).

Starts a head process plus any number of node-agent processes on this host,
each with its own shm namespace and resource pool, talking to the head over
TCP exactly as real remote hosts would.  This is how all distributed behavior
(scheduling spillover, node-to-node object transfer, node death, actor
restart across nodes) is tested without real multi-host hardware — the same
strategy the reference uses (cluster_utils.py:202,286 add_node/remove_node).

Usage:
    cluster = Cluster(head_resources={"CPU": 1})
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    cluster.connect()          # ca.init(address=...) as the driver
    ...
    cluster.remove_node(nid)   # SIGKILL the agent: simulates node power-off
    cluster.shutdown()
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from .core.config import CAConfig


class Cluster:
    def __init__(
        self,
        head_resources: Optional[Dict[str, float]] = None,
        config: Optional[CAConfig] = None,
        connect: bool = False,
    ):
        self.config = config or CAConfig()
        root = self.config.session_dir_root
        os.makedirs(root, exist_ok=True)
        self.session_dir = os.path.join(
            root, f"session_{int(time.time() * 1000)}_{os.getpid()}"
        )
        os.makedirs(self.session_dir, exist_ok=True)
        self._node_seq = 0
        self._agents: Dict[str, subprocess.Popen] = {}
        self._standbys: Dict[int, subprocess.Popen] = {}  # rank -> proc
        self._connected = False
        resources = dict(head_resources or {"CPU": 0.0})
        resources.setdefault("memory", float(self.config.object_store_memory))
        self._head_resources = resources
        self._spawn_head()
        self.head_tcp = open(os.path.join(self.session_dir, "head.addr")).read().strip()
        if connect:
            self.connect()

    def _spawn_head(self):
        env = self._base_env()
        env["CA_RESOURCES"] = json.dumps(self._head_resources)
        env["CA_HEAD_PERSIST"] = "1"  # fixture controls teardown, not drivers
        ready = os.path.join(self.session_dir, "head.ready")
        try:
            os.unlink(ready)
        except FileNotFoundError:
            pass
        head_log = open(os.path.join(self.session_dir, "head.log"), "ab")
        self._head_proc = subprocess.Popen(
            [sys.executable, "-m", "cluster_anywhere_tpu.core.head"],
            env=env,
            stdout=head_log,
            stderr=subprocess.STDOUT,
            start_new_session=True,
        )
        head_log.close()
        self._wait_for_file(ready, 30)

    # -------------------------------------------------------- fault injection
    def kill_head(self):
        """SIGKILL the head (control-plane crash; state survives in the
        snapshot, data plane keeps running)."""
        try:
            os.kill(self._head_proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        self._head_proc.wait(timeout=10)

    def restart_head(self):
        """Start a fresh head process for the same session: it loads the
        snapshot and re-adopts live workers, agents, and drivers."""
        self._spawn_head()

    # ---------------------------------------------------------------- HA plane
    def add_standby(self, rank: int = 0, env_overrides: Optional[Dict[str, str]] = None) -> str:
        """Start a warm-standby head at `rank` (promotion order: rank 0
        self-promotes first).  It subscribes to the active head's replication
        stream and holds the full registry in memory; returns its TCP addr."""
        env = self._base_env()
        env["CA_RESOURCES"] = json.dumps(self._head_resources)
        env["CA_HEAD_PERSIST"] = "1"
        env["CA_HEAD_STANDBY"] = "1"
        env["CA_HEAD_STANDBY_RANK"] = str(rank)
        env["CA_HEAD_ADDR"] = self.head_ring()
        if env_overrides:
            env.update(env_overrides)
        ready = os.path.join(self.session_dir, f"head.standby{rank}.ready")
        try:
            os.unlink(ready)
        except FileNotFoundError:
            pass
        log = open(
            os.path.join(self.session_dir, f"head.standby{rank}.log"), "ab"
        )
        proc = subprocess.Popen(
            [sys.executable, "-m", "cluster_anywhere_tpu.core.head"],
            env=env,
            stdout=log,
            stderr=subprocess.STDOUT,
            start_new_session=True,
        )
        log.close()
        self._standbys[rank] = proc
        self._wait_for_file(ready, 30)
        return self.standby_addr(rank)

    def standby_addr(self, rank: int = 0) -> str:
        return open(
            os.path.join(self.session_dir, f"head.standby{rank}.addr")
        ).read().strip()

    def head_ring(self) -> str:
        """Comma-separated head address list: active first, then standbys in
        rank order — the CA_HEAD_ADDR / init(address=...) failover spec."""
        addrs = [self.head_tcp]
        for rank in sorted(self._standbys):
            try:
                a = self.standby_addr(rank)
            except FileNotFoundError:
                continue
            if a and a not in addrs:
                addrs.append(a)
        return ",".join(addrs)

    def kill_standby(self, rank: int = 0):
        proc = self._standbys.pop(rank, None)
        if proc is None:
            raise ValueError(f"no standby at rank {rank}")
        try:
            os.kill(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        proc.wait(timeout=10)

    def promote_standby(self, rank: int = 0, timeout: float = 10) -> dict:
        """Explicitly promote the rank's standby (the `ca head promote`
        path); returns its ha_status afterwards.  With ha_auto_promote on,
        standbys promote themselves after the grace window and this is only
        needed for deterministic tests / manual failover."""
        from .core.protocol import BlockingClient

        c = BlockingClient(self.standby_addr(rank))
        c._sock.settimeout(timeout)
        try:
            return c.call("head_promote")
        finally:
            c.close()

    def wait_promoted(self, timeout: float = 30) -> str:
        """Block until a standby has claimed head.addr (promotion rewrites
        it); adopts the promoted process as the cluster's head proc and
        returns the new active addr."""
        deadline = time.monotonic() + timeout
        old = self.head_tcp
        addr_path = os.path.join(self.session_dir, "head.addr")
        while time.monotonic() < deadline:
            try:
                cur = open(addr_path).read().strip()
            except FileNotFoundError:
                cur = ""
            if cur and cur != old:
                self.head_tcp = cur
                for rank, proc in list(self._standbys.items()):
                    try:
                        if self.standby_addr(rank) == cur:
                            self._head_proc = self._standbys.pop(rank)
                    except FileNotFoundError:
                        pass
                return cur
            time.sleep(0.05)
        raise TimeoutError("no standby promoted within the window")

    def _base_env(self) -> dict:
        env = dict(os.environ)
        env["CA_SESSION_DIR"] = self.session_dir
        env["CA_CONFIG_JSON"] = self.config.to_json()
        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        return env

    @staticmethod
    def _wait_for_file(path: str, timeout: float):
        deadline = time.monotonic() + timeout
        while not os.path.exists(path):
            if time.monotonic() > deadline:
                raise TimeoutError(f"timed out waiting for {path}")
            time.sleep(0.01)

    # ------------------------------------------------------------------ nodes
    def add_node(
        self,
        num_cpus: float = 4,
        num_tpus: float = 0,
        resources: Optional[Dict[str, float]] = None,
        node_id: Optional[str] = None,
        labels: Optional[Dict[str, str]] = None,
        env_overrides: Optional[Dict[str, str]] = None,
    ) -> str:
        """Start a node-agent process and wait for it to join the cluster.
        `labels` become the node's scheduling labels; `env_overrides` lets a
        test simulate e.g. a TPU host's TPU_* environment on the agent."""
        self._node_seq += 1
        nid = node_id or f"node{self._node_seq}"
        shape: Dict[str, float] = {"CPU": float(num_cpus)}
        if num_tpus:
            shape["TPU"] = float(num_tpus)
        shape.setdefault("memory", float(self.config.object_store_memory))
        if resources:
            shape.update({k: float(v) for k, v in resources.items()})
        env = self._base_env()
        env["CA_HEAD_ADDR"] = self.head_ring()  # active first, then standbys
        env["CA_NODE_ID"] = nid
        env["CA_NODE_RESOURCES"] = json.dumps(shape)
        if labels:
            env["CA_NODE_LABELS"] = json.dumps(labels)
        if env_overrides:
            env.update(env_overrides)
        node_dir = os.path.join(self.session_dir, "nodes", nid)
        os.makedirs(node_dir, exist_ok=True)
        agent_log = open(os.path.join(node_dir, "agent.log"), "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "cluster_anywhere_tpu.core.nodeagent"],
            env=env,
            stdout=agent_log,
            stderr=subprocess.STDOUT,
            start_new_session=True,
        )
        agent_log.close()
        self._agents[nid] = proc
        self._wait_for_file(os.path.join(node_dir, "agent.ready"), 30)
        return nid

    def remove_node(self, node_id: str, graceful: bool = False):
        """Kill a node.  Default: SIGKILL the agent (simulated power-off;
        the head detects the death via connection drop / missed heartbeats
        and fences the node's workers).  graceful=True sends SIGTERM — the
        preemption warning — and the agent SELF-DRAINS through the head
        (evacuation, then a clean exit), so the wait below can take up to
        the drain deadline when the node is busy."""
        proc = self._agents.pop(node_id, None)
        if proc is None:
            raise ValueError(f"unknown node {node_id!r}")
        try:
            os.kill(proc.pid, signal.SIGTERM if graceful else signal.SIGKILL)
        except ProcessLookupError:
            pass
        proc.wait(timeout=(self.config.drain_deadline_s + 15) if graceful else 10)

    def nodes(self) -> List[dict]:
        from .core import api

        return api.nodes()

    def wait_for_nodes(self, n: int, timeout: float = 30) -> None:
        """Block until `n` nodes (including the head node) are alive."""
        from .core.worker import global_worker

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            alive = [x for x in self.nodes() if x["alive"]]
            if len(alive) >= n:
                return
            time.sleep(0.05)
        raise TimeoutError(f"cluster did not reach {n} alive nodes")

    # ----------------------------------------------------------------- driver
    def connect(self) -> dict:
        from .core import api

        # the driver must run the SAME plane configuration as the cluster it
        # joins (e.g. owner_plane off in an A/B) — a default-config driver
        # would settle its objects owner-resident against a centralized head
        info = api.init(address=self.session_dir, config=self.config)
        self._connected = True
        return info

    def shutdown(self):
        from .core import api

        if self._connected:
            try:
                api.shutdown()
            except Exception:
                pass
            self._connected = False
        for nid in list(self._agents):
            try:
                self.remove_node(nid)
            except Exception:
                pass
        for rank in list(self._standbys):
            try:
                self.kill_standby(rank)
            except Exception:
                pass
        if self._head_proc.poll() is None:
            try:
                os.kill(self._head_proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            self._head_proc.wait(timeout=10)
        import shutil

        shutil.rmtree(
            os.path.join("/dev/shm", os.path.basename(self.session_dir)),
            ignore_errors=True,
        )
        shutil.rmtree(self.session_dir, ignore_errors=True)
