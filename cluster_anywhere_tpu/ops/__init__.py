"""TPU kernel layer (Pallas).

Hand-written kernels for the ops where XLA's default lowering leaves MXU/HBM
performance on the table.  Everything degrades gracefully: on CPU (tests) the
kernels run in Pallas interpret mode or fall back to pure-jax references.
"""

from .attention import attention, flash_attention, merge_attention  # noqa: F401
