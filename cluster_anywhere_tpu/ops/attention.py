"""Flash attention as a Pallas TPU kernel (forward + backward).

Replaces the O(T^2)-memory dense softmax attention with the streaming-softmax
tiling that keeps the MXU busy from VMEM: per query block, K/V are consumed in
blocks with a running (max, normalizer, accumulator) — the [T, T] score matrix
never hits HBM.  Backward recomputes scores blockwise from the saved
log-sum-exp (no O(T^2) residuals), the standard flash-attention-2 scheme.

The reference framework has no attention kernels at all (it delegates model
math to torch; SURVEY.md §5 notes SP/CP absent in-tree) — this kernel is the
compute core of the TPU-native model stack: the dense transformer path calls
`attention()`, and ring attention merges per-block flash results with
`merge_attention` (parallel/ring_attention.py).

Layout contract: [B, T, H, D] inputs (time-major per head), fp32 accumulation
regardless of input dtype.  GQA callers repeat K/V heads first.

On non-TPU backends `attention()` uses the fused-jnp reference; the Pallas
kernels themselves also run under interpret mode for tests
(`flash_attention(..., interpret=True)` — exercised in tests/test_ops.py).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

try:  # pallas is part of jax, but keep import-failure graceful for CPU-only
    from jax.experimental import pallas as pl
except Exception:  # pragma: no cover
    pl = None

NEG_INF = -1e30


def _platform() -> str:
    try:
        return jax.devices()[0].platform
    except Exception:
        return "cpu"


# --------------------------------------------------------------------------
# forward kernel
# --------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, *refs, scale, causal, block_k, has_pad):
    if has_pad:
        pad_ref, o_ref, lse_ref = refs
        pad_val = pad_ref[0]
    else:
        (o_ref, lse_ref) = refs
        pad_val = None
    block_q, d = q_ref.shape
    t_kv = k_ref.shape[0]
    qi = pl.program_id(1)
    q = q_ref[...].astype(jnp.float32)

    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    if causal:
        # skip key blocks fully above the diagonal
        num_k = lax.div((qi + 1) * block_q + block_k - 1, block_k)
    else:
        num_k = t_kv // block_k

    def body(ki, carry):
        m, l, acc = carry
        k_blk = k_ref[pl.ds(ki * block_k, block_k), :].astype(
            jnp.float32
        )
        v_blk = v_ref[pl.ds(ki * block_k, block_k), :].astype(
            jnp.float32
        )
        s = (
            lax.dot_general(
                q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
            * scale
        )
        if causal or has_pad:
            k_pos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            ok = None
            if causal:
                q_pos = qi * block_q + lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0
                )
                ok = q_pos >= k_pos
            if has_pad:
                # left-padded rows: keys before pad_val are pad tokens
                k_ok = k_pos >= pad_val
                ok = k_ok if ok is None else (ok & k_ok)
            s = jnp.where(ok, s, NEG_INF)
        m_blk = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(s - m_new[:, None])
        if causal or has_pad:
            p = jnp.where(s <= NEG_INF, 0.0, p)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[:, None] + lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    m, l, acc = lax.fori_loop(0, num_k, body, (m0, l0, acc0))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o_ref[...] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # lse_ref is the full (1, t) row; each grid step writes its q-block slice
    block_q_ = q_ref.shape[0]
    lse_ref[0, pl.ds(qi * block_q_, block_q_)] = m + jnp.log(l_safe)


# --------------------------------------------------------------------------
# backward kernels (flash-attention-2: recompute p from lse, no O(T^2) saves)
# --------------------------------------------------------------------------


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *refs, scale, causal, block_k, has_pad
):
    if has_pad:
        pad_ref, dq_ref = refs
        pad_val = pad_ref[0]
    else:
        (dq_ref,) = refs
        pad_val = None
    block_q, d = q_ref.shape
    t_kv = k_ref.shape[0]
    qi = pl.program_id(1)
    q = q_ref[...].astype(jnp.float32)
    do = do_ref[...].astype(jnp.float32)
    lse = lse_ref[0, pl.ds(qi * block_q, block_q)].astype(jnp.float32)
    delta = delta_ref[0, pl.ds(qi * block_q, block_q)].astype(jnp.float32)

    if causal:
        num_k = lax.div((qi + 1) * block_q + block_k - 1, block_k)
    else:
        num_k = t_kv // block_k

    def body(ki, dq):
        k_blk = k_ref[pl.ds(ki * block_k, block_k), :].astype(
            jnp.float32
        )
        v_blk = v_ref[pl.ds(ki * block_k, block_k), :].astype(
            jnp.float32
        )
        s = (
            lax.dot_general(
                q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
            * scale
        )
        if causal or has_pad:
            k_pos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            ok = None
            if causal:
                q_pos = qi * block_q + lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0
                )
                ok = q_pos >= k_pos
            if has_pad:
                k_ok = k_pos >= pad_val
                ok = k_ok if ok is None else (ok & k_ok)
            s = jnp.where(ok, s, NEG_INF)
        p = jnp.exp(s - lse[:, None])
        if causal or has_pad:
            p = jnp.where(s <= NEG_INF, 0.0, p)
        dp = lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta[:, None])
        return dq + scale * lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    dq = lax.fori_loop(0, num_k, body, jnp.zeros((block_q, d), jnp.float32))
    dq_ref[...] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *refs, scale, causal, block_q, has_pad
):
    if has_pad:
        pad_ref, dk_ref, dv_ref = refs
        pad_val = pad_ref[0]
    else:
        dk_ref, dv_ref = refs
        pad_val = None
    block_k, d = k_ref.shape
    t_q = q_ref.shape[0]
    ki = pl.program_id(1)
    k = k_ref[...].astype(jnp.float32)
    v = v_ref[...].astype(jnp.float32)
    nq = t_q // block_q
    # causal: query blocks strictly before this key block contribute nothing
    lo = lax.div(ki * block_k, block_q) if causal else 0

    def body(qi, carry):
        dk, dv = carry
        q_blk = q_ref[pl.ds(qi * block_q, block_q), :].astype(
            jnp.float32
        )
        do_blk = do_ref[pl.ds(qi * block_q, block_q), :].astype(
            jnp.float32
        )
        lse_blk = lse_ref[0, pl.ds(qi * block_q, block_q)].astype(jnp.float32)
        delta_blk = delta_ref[0, pl.ds(qi * block_q, block_q)].astype(jnp.float32)
        s = (
            lax.dot_general(
                q_blk, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
            )
            * scale
        )
        if causal or has_pad:
            k_pos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            ok = None
            if causal:
                q_pos = qi * block_q + lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0
                )
                ok = q_pos >= k_pos
            if has_pad:
                k_ok = k_pos >= pad_val
                ok = k_ok if ok is None else (ok & k_ok)
            s = jnp.where(ok, s, NEG_INF)
        p = jnp.exp(s - lse_blk[:, None])  # [bq, bk]
        if causal or has_pad:
            p = jnp.where(s <= NEG_INF, 0.0, p)
        dv_new = dv + lax.dot_general(
            p, do_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = lax.dot_general(
            do_blk, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_blk[:, None])
        dk_new = dk + scale * lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk_new, dv_new

    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = lax.fori_loop(lo, nq, body, (dk0, dv0))
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


# --------------------------------------------------------------------------
# host-side wrappers
# --------------------------------------------------------------------------


def _to_bhtd(x):
    """[B, T, H, D] -> [B*H, T, D]."""
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _from_bhtd(x, b, h):
    bh, t, d = x.shape
    return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _pad_bh(pad, h):
    """[B] per-row left-pad counts -> [B*H, 1] int32 (one scalar per grid
    row, matching the B*H-flattened kernel grid)."""
    return jnp.repeat(pad.astype(jnp.int32), h)[:, None]


def _fwd_impl(q, k, v, pad, causal, scale, block_q, block_k, interpret):
    b, t, h, d = q.shape
    t_kv = k.shape[1]
    qf, kf, vf = _to_bhtd(q), _to_bhtd(k), _to_bhtd(v)
    bh = b * h
    nq = t // block_q
    grid = (bh, nq)
    has_pad = pad is not None
    in_specs = [
        pl.BlockSpec((None, block_q, d), lambda bi, qi: (bi, qi, 0)),
        pl.BlockSpec((None, t_kv, d), lambda bi, qi: (bi, 0, 0)),
        pl.BlockSpec((None, t_kv, d), lambda bi, qi: (bi, 0, 0)),
    ]
    args = [qf, kf, vf]
    if has_pad:
        in_specs.append(pl.BlockSpec((None, 1), lambda bi, qi: (bi, 0)))
        args.append(_pad_bh(pad, h))
    out, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, scale=scale, causal=causal, block_k=block_k, has_pad=has_pad
        ),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda bi, qi: (bi, qi, 0)),
            # (1, t) full-row blocks: TPU lowering requires the last two block
            # dims divisible by (8, 128) OR equal to the array dims
            pl.BlockSpec((None, 1, t), lambda bi, qi: (bi, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t, d), q.dtype),
            jax.ShapeDtypeStruct((bh, 1, t), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return _from_bhtd(out, b, h), lse.reshape(b, h, t)


def _bwd_impl(q, k, v, o, lse, do, pad, causal, scale, block_q, block_k, interpret, dlse=None):
    b, t, h, d = q.shape
    t_kv = k.shape[1]
    qf, kf, vf = _to_bhtd(q), _to_bhtd(k), _to_bhtd(v)
    dof, of = _to_bhtd(do), _to_bhtd(o)
    bh = b * h
    lsef = lse.reshape(bh, 1, t)
    # delta_i = rowsum(dO_i * O_i) — cheap elementwise, leave to XLA.  An lse
    # cotangent folds in with opposite sign: ds = p * (dp - (delta - dlse))
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32), axis=-1)
    if dlse is not None:
        delta = delta - dlse.reshape(bh, t).astype(jnp.float32)
    delta = delta.reshape(bh, 1, t)
    has_pad = pad is not None
    pad_arg = [_pad_bh(pad, h)] if has_pad else []
    pad_spec = [pl.BlockSpec((None, 1), lambda bi, qi: (bi, 0))] if has_pad else []

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal, block_k=block_k, has_pad=has_pad
        ),
        grid=(bh, t // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda bi, qi: (bi, qi, 0)),
            pl.BlockSpec((None, t_kv, d), lambda bi, qi: (bi, 0, 0)),
            pl.BlockSpec((None, t_kv, d), lambda bi, qi: (bi, 0, 0)),
            pl.BlockSpec((None, block_q, d), lambda bi, qi: (bi, qi, 0)),
            pl.BlockSpec((None, 1, t), lambda bi, qi: (bi, 0, 0)),
            pl.BlockSpec((None, 1, t), lambda bi, qi: (bi, 0, 0)),
        ] + pad_spec,
        out_specs=pl.BlockSpec((None, block_q, d), lambda bi, qi: (bi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, t, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, delta, *pad_arg)

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal, block_q=block_q, has_pad=has_pad
        ),
        grid=(bh, t_kv // block_k),
        in_specs=[
            pl.BlockSpec((None, t, d), lambda bi, ki: (bi, 0, 0)),
            pl.BlockSpec((None, block_k, d), lambda bi, ki: (bi, ki, 0)),
            pl.BlockSpec((None, block_k, d), lambda bi, ki: (bi, ki, 0)),
            pl.BlockSpec((None, t, d), lambda bi, ki: (bi, 0, 0)),
            pl.BlockSpec((None, 1, t), lambda bi, ki: (bi, 0, 0)),
            pl.BlockSpec((None, 1, t), lambda bi, ki: (bi, 0, 0)),
        ] + pad_spec,
        out_specs=[
            pl.BlockSpec((None, block_k, d), lambda bi, ki: (bi, ki, 0)),
            pl.BlockSpec((None, block_k, d), lambda bi, ki: (bi, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, t_kv, d), k.dtype),
            jax.ShapeDtypeStruct((bh, t_kv, d), v.dtype),
        ],
        interpret=interpret,
    )(qf, kf, vf, dof, lsef, delta, *pad_arg)
    return _from_bhtd(dq, b, h), _from_bhtd(dk, b, h), _from_bhtd(dv, b, h)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, pad, causal, scale, block_q, block_k, interpret):
    out, _ = _fwd_impl(q, k, v, pad, causal, scale, block_q, block_k, interpret)
    return out


def _flash_fwd(q, k, v, pad, causal, scale, block_q, block_k, interpret):
    out, lse = _fwd_impl(q, k, v, pad, causal, scale, block_q, block_k, interpret)
    return out, (q, k, v, pad, out, lse)


def _flash_bwd(causal, scale, block_q, block_k, interpret, res, do):
    q, k, v, pad, out, lse = res
    dq, dk, dv = _bwd_impl(
        q, k, v, out, lse, do, pad, causal, scale, block_q, block_k, interpret
    )
    return dq, dk, dv, None  # pad is integer-valued: no cotangent


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_with_lse(q, k, v, pad, causal, scale, block_q, block_k, interpret):
    return _fwd_impl(q, k, v, pad, causal, scale, block_q, block_k, interpret)


def _flash_with_lse_fwd(q, k, v, pad, causal, scale, block_q, block_k, interpret):
    out, lse = _fwd_impl(q, k, v, pad, causal, scale, block_q, block_k, interpret)
    return (out, lse), (q, k, v, pad, out, lse)


def _flash_with_lse_bwd(causal, scale, block_q, block_k, interpret, res, cts):
    """Cotangent of lse folds into the delta term: d(lse)/ds = p per row, so
    ds = p*(dp - delta + dlse) — pass (delta - dlse) where the kernels expect
    delta (the ring merge differentiates through lse)."""
    q, k, v, pad, out, lse = res
    do, dlse = cts
    dq, dk, dv = _bwd_impl(
        q, k, v, out, lse, do, pad, causal, scale, block_q, block_k, interpret,
        dlse=dlse,
    )
    return dq, dk, dv, None


_flash_with_lse.defvjp(_flash_with_lse_fwd, _flash_with_lse_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    scale: Optional[float] = None,
    pad: Optional[jax.Array] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: Optional[bool] = None,
    return_lse: bool = False,
):
    """Pallas flash attention.  q: [B, T, H, D]; k, v: [B, T_kv, H, D].

    pad: optional [B] int32 per-row LEFT-pad counts — keys at positions
    < pad[b] are masked out (the left-padded-prompt mask the LLM prefill
    needs; models/generate.py _prefill_block).

    block_q/block_k default to the largest power-of-two divisor of T / T_kv
    capped at 256 / 512 — measured best for fwd+bwd on v5e at d_head=64
    (vs 128/128: bigger K tiles amortize the half-empty 64-lane contraction
    and cut grid-step overhead; Q tiles above 256 pay more bwd recompute
    than they save).  Requires T % block_q == 0 and T_kv % block_k == 0 (the
    dispatcher `attention()` falls back to the jnp reference otherwise).
    With return_lse=True also returns the per-row log-sum-exp [B, H, T] —
    the carry ring attention needs to merge per-block results
    (merge_attention).
    """
    if scale is None:
        scale = q.shape[-1] ** -0.5
    if interpret is None:
        interpret = _platform() == "cpu"
    if block_q is None:
        block_q = _auto_block(q.shape[1], 256)
    if block_k is None:
        block_k = _auto_block(k.shape[1], 512)
    block_q = min(block_q, q.shape[1])
    block_k = min(block_k, k.shape[1])
    if return_lse:
        return _flash_with_lse(q, k, v, pad, causal, scale, block_q, block_k, interpret)
    return _flash(q, k, v, pad, causal, scale, block_q, block_k, interpret)


def _auto_block(t: int, cap: int) -> int:
    """Largest power-of-two divisor of t, capped.  When t has no power-of-two
    divisor >= 8, fall back to t itself (one full block — always valid:
    a block equal to the array dim satisfies the TPU tiling rule, whereas
    returning a non-divisor would leave grid-uncovered rows unwritten)."""
    b = cap
    while b > 8 and t % b != 0:
        b //= 2
    return b if t % b == 0 else t


def merge_attention(o1, lse1, o2, lse2):
    """Merge two normalized attention partials over disjoint key sets.

    o: [B, T, H, D]; lse: [B, H, T].  Returns (o, lse) of the union — the
    streaming-softmax combine that lets ring attention run flash per block.
    """
    m = jnp.maximum(lse1, lse2)
    # exp(-inf - -inf) guard: where both lse are -inf the row saw no keys
    w1 = jnp.where(lse1 == NEG_INF, 0.0, jnp.exp(lse1 - m))
    w2 = jnp.where(lse2 == NEG_INF, 0.0, jnp.exp(lse2 - m))
    tot = w1 + w2
    tot_safe = jnp.where(tot == 0.0, 1.0, tot)
    w1t = (w1 / tot_safe).transpose(0, 2, 1)[..., None].astype(o1.dtype)
    w2t = (w2 / tot_safe).transpose(0, 2, 1)[..., None].astype(o2.dtype)
    o = o1 * w1t + o2 * w2t
    lse = m + jnp.log(tot_safe)
    return o, jnp.where(tot == 0.0, NEG_INF, lse)


def reference_attention(q, k, v, causal=True, scale=None, pad=None):
    """Dense jnp attention (fallback + test oracle): [B,T,H,D] -> [B,T,H,D].
    pad: optional [B] left-pad counts (keys < pad[b] masked)."""
    d = q.shape[-1]
    if scale is None:
        scale = d ** -0.5
    s = (
        jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32))
        * scale
    )
    t_q, t_k = s.shape[-2], s.shape[-1]
    mask = None
    if causal:
        mask = jnp.tril(jnp.ones((t_q, t_k), dtype=bool))[None, None]
    if pad is not None:
        key_ok = (jnp.arange(t_k)[None, :] >= pad[:, None])[:, None, None, :]
        mask = key_ok if mask is None else (mask & key_ok)
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(q.dtype)


def attention(q, k, v, causal: bool = True, scale: Optional[float] = None, pad=None):
    """Dispatcher: Pallas flash kernel on TPU when shapes tile cleanly, else
    the jnp reference (XLA still fuses that well on CPU test meshes)."""
    t, t_kv = q.shape[1], k.shape[1]
    use_flash = (
        pl is not None
        and _platform() not in ("cpu",)
        and t % min(128, t) == 0
        and t_kv % min(128, t_kv) == 0
        and t >= 128
        and t_kv >= 128
    )
    if use_flash:
        return flash_attention(q, k, v, causal=causal, scale=scale, pad=pad)
    return reference_attention(q, k, v, causal=causal, scale=scale, pad=pad)
