"""cluster_anywhere_tpu: a TPU-native distributed computing framework.

Same capability surface as the reference system surveyed in SURVEY.md (tasks,
actors, a distributed object store, placement groups, and ML libraries for
data/train/tune/serve), designed TPU-first: the tensor plane is JAX/XLA —
sharded `jax.Array`s are first-class objects (DeviceRef) that never leave the
accelerator; parallelism strategies (DP/FSDP/TP/PP/SP/EP, ring attention,
Ulysses) are first-class in `cluster_anywhere_tpu.parallel`.

Keep this module import-light: jax is only imported when the tensor-plane
modules (`parallel`, `ops`, `models`) are used.
"""

from ._version import version as __version__
from .core import errors as exceptions
from .core.actor import ActorHandle, exit_actor, get_actor, kill, method
from .core.api import (
    available_resources,
    timeline,
    cancel,
    cluster_resources,
    cluster_stats,
    drain_node,
    get,
    init,
    is_initialized,
    nodes,
    put,
    remote,
    shutdown,
    wait,
)
from .core.errors import (
    ActorDiedError,
    ActorError,
    CAError,
    DagTimeoutError,
    DeadActorError,
    GetTimeoutError,
    ObjectLostError,
    TaskCancelledError,
    TaskError,
    WorkerCrashedError,
)
from .core.object_ref import DeviceRef, ObjectRef
from .core.placement import (
    PlacementGroup,
    placement_group,
    placement_group_table,
    remove_placement_group,
)
from .core.runtime_context import get_runtime_context
from .core.scheduling_strategies import (
    DoesNotExist,
    Exists,
    In,
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
    NotIn,
    PlacementGroupSchedulingStrategy,
    SpreadSchedulingStrategy,
)

__all__ = [
    "__version__",
    "init",
    "shutdown",
    "is_initialized",
    "put",
    "get",
    "wait",
    "cancel",
    "remote",
    "ObjectRef",
    "DeviceRef",
    "ActorHandle",
    "get_actor",
    "method",
    "kill",
    "exit_actor",
    "nodes",
    "cluster_resources",
    "available_resources",
    "cluster_stats",
    "drain_node",
    "timeline",
    "placement_group",
    "remove_placement_group",
    "placement_group_table",
    "PlacementGroup",
    "PlacementGroupSchedulingStrategy",
    "NodeAffinitySchedulingStrategy",
    "SpreadSchedulingStrategy",
    "NodeLabelSchedulingStrategy",
    "In",
    "NotIn",
    "Exists",
    "DoesNotExist",
    "get_runtime_context",
    "exceptions",
    "CAError",
    "TaskError",
    "ActorError",
    "ActorDiedError",
    "DeadActorError",
    "DagTimeoutError",
    "WorkerCrashedError",
    "ObjectLostError",
    "GetTimeoutError",
    "TaskCancelledError",
]
