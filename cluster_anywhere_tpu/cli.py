"""Command-line interface (analogue of the reference's python/ray/scripts/
scripts.py: ray start/stop/status/submit/memory/timeline/summary/logs/
microbenchmark).

Usage: python -m cluster_anywhere_tpu.cli <command> [...]
(or the `ca` console script when the package is installed).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _connect(args):
    import cluster_anywhere_tpu as ca

    # no log-stream subscription for one-shot CLI commands: live worker
    # echoes would interleave with (and for `ca logs --follow`, duplicate)
    # the command's own output
    ca.init(address=getattr(args, "address", None) or "auto", log_to_driver=False)
    return ca


def cmd_start(args):
    """Start a persistent head (survives driver disconnects) for other
    drivers/jobs to join via init(address=...)."""
    import cluster_anywhere_tpu as ca

    os.environ["CA_HEAD_PERSIST"] = "1"
    info = ca.init(num_cpus=args.num_cpus, num_tpus=args.num_tpus)
    print(f"started cluster at {info['session_dir']}")
    print(f"resources: {info['resources']}")
    print("connect with: cluster_anywhere_tpu.init(address='auto')")
    # detach without stopping the cluster
    from cluster_anywhere_tpu.core import api as _api
    from cluster_anywhere_tpu.core.worker import global_worker

    global_worker().shutdown(stop_cluster=False)
    _api._head_proc = None  # leave the head running


def cmd_join(args):
    """Join THIS host to a running cluster as a node (foreground agent) —
    the command an SSH/command-runner provider executes on each machine
    (reference `ray start --address=...` worker-node role).

        ca join --head tcp:headhost:6379 --num-cpus 8 \\
                --labels '{"zone": "a"}'
    """
    import json as _json
    import uuid as _uuid

    from cluster_anywhere_tpu.core.config import CAConfig

    node_id = args.node_id or f"host-{_uuid.uuid4().hex[:6]}"
    root = args.session_root or CAConfig().session_dir_root
    sdir = os.path.join(root, f"joined_{node_id}")
    os.makedirs(sdir, exist_ok=True)
    os.environ["CA_SESSION_DIR"] = sdir
    os.environ["CA_HEAD_ADDR"] = args.head
    os.environ["CA_NODE_ID"] = node_id
    shape = {"CPU": float(args.num_cpus)}
    if args.num_tpus:
        shape["TPU"] = float(args.num_tpus)
    if args.resources:
        shape.update({k: float(v) for k, v in _json.loads(args.resources).items()})
    shape.setdefault("memory", float(CAConfig().object_store_memory))
    os.environ["CA_NODE_RESOURCES"] = _json.dumps(shape)
    if args.labels:
        os.environ["CA_NODE_LABELS"] = args.labels
    os.environ.setdefault("CA_CONFIG_JSON", CAConfig().to_json())
    from cluster_anywhere_tpu.core.nodeagent import main as agent_main

    print(f"joining {args.head} as node {node_id} with {shape}")
    agent_main()


def cmd_up(args):
    """Bring up a cluster from a YAML config (reference `ray up` role: local
    provider by default, or a command-runner provider for real machines).

    Config shape:
        head: {num_cpus: 4, num_tpus: 0}
        provider:            # optional; omit for local agent nodes
          type: command      # ssh/command-runner seam
          hosts: [host-a, host-b]
          launch_cmd: "ssh {host} 'ca join --head {head_addr} --node-id {node_id} --resources {resources_json} --labels {labels_json}'"
          terminate_cmd: "..."   # optional
          quote_levels: 2        # shells the JSON traverses (2 for ssh)
        nodes:
          - {count: 2, num_cpus: 2, labels: {zone: a}}
          - {count: 1, num_cpus: 1, resources: {fast_disk: 1}}
    """
    import yaml

    import cluster_anywhere_tpu as ca
    from cluster_anywhere_tpu.autoscaler.provider import (
        AgentNodeProvider,
        CommandRunnerNodeProvider,
        NodeType,
    )

    with open(args.config) as f:
        cfg = yaml.safe_load(f) or {}
    head = cfg.get("head") or {}
    os.environ["CA_HEAD_PERSIST"] = "1"
    info = ca.init(
        num_cpus=head.get("num_cpus"), num_tpus=head.get("num_tpus")
    )
    print(f"head up at {info['session_dir']}")
    pspec = cfg.get("provider") or {}
    if pspec.get("type") == "command":
        provider = CommandRunnerNodeProvider(
            hosts=pspec["hosts"],
            launch_cmd=pspec["launch_cmd"],
            terminate_cmd=pspec.get("terminate_cmd"),
            wait_s=float(pspec.get("wait_s", 60)),
            quote_levels=int(pspec.get("quote_levels", 1)),
        )
    else:
        provider = AgentNodeProvider()
    n_started = 0
    for spec in cfg.get("nodes") or []:
        shape = {"CPU": float(spec.get("num_cpus", 2))}
        if spec.get("num_tpus"):
            shape["TPU"] = float(spec["num_tpus"])
        shape.update({k: float(v) for k, v in (spec.get("resources") or {}).items()})
        for _ in range(int(spec.get("count", 1))):
            node = provider.create_node(
                NodeType("yaml", shape, labels=spec.get("labels"))
            )
            n_started += 1
            print(f"node {node.node_id} up: {shape}")
    from cluster_anywhere_tpu.core.worker import global_worker

    w = global_worker()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        alive = [n for n in w.head_call("nodes")["nodes"] if n["alive"]]
        if len(alive) >= 1 + n_started:
            break
        time.sleep(0.2)
    print(f"cluster up: {len(alive)} nodes, resources {ca.cluster_resources()}")
    from cluster_anywhere_tpu.core import api as _api

    w.shutdown(stop_cluster=False)
    _api._head_proc = None  # persists until `ca down`


def cmd_down(args):
    """Tear down the running cluster (reference `ray down`): agents exit on
    head shutdown notification, the head cleans the shm namespace."""
    cmd_stop(args)


def cmd_serve(args):
    """`ca serve deploy <yaml>` / `ca serve status` (reference serve CLI)."""
    import cluster_anywhere_tpu as ca
    from cluster_anywhere_tpu import serve

    if args.action == "deploy" and not args.config:
        print("usage: ca serve deploy <config.yaml>", file=sys.stderr)
        sys.exit(2)
    ca.init(address=getattr(args, "address", None) or "auto")
    if args.action == "deploy":
        handles = serve.run_config(args.config)
        for name in handles:
            print(f"deployed application {name!r}")
    elif args.action == "status":
        print(json.dumps(serve.status(), indent=2, default=str))
    elif args.action == "shutdown":
        serve.shutdown()
        print("serve shut down")
    from cluster_anywhere_tpu.core import api as _api
    from cluster_anywhere_tpu.core.worker import global_worker

    global_worker().shutdown(stop_cluster=False)
    _api._head_proc = None


def cmd_stop(args):
    import cluster_anywhere_tpu as ca
    from cluster_anywhere_tpu.core.worker import global_worker

    try:
        ca.init(address=getattr(args, "address", None) or "auto", log_to_driver=False)
    except ConnectionError as e:
        print(e)
        return
    w = global_worker()
    print(f"stopping cluster at {w.session_dir}")
    w.shutdown(stop_cluster=True)


def cmd_drain(args):
    """Gracefully drain a node: evacuate actors/objects, let running tasks
    finish until the deadline, then let the provider reclaim the VM."""
    ca = _connect(args)
    try:
        kw = {"reason": args.reason}
        if args.deadline is not None:
            kw["deadline_s"] = args.deadline
        r = ca.drain_node(args.node, **kw)
    except Exception as e:
        print(f"drain failed: {e}")
        ca.shutdown()
        sys.exit(1)
    state = r.get("state")
    print(f"node {args.node}: {state}"
          + (f" (deadline {r['deadline_s']:g}s)" if "deadline_s" in r else ""))
    if args.wait and state == "draining":
        while True:
            time.sleep(0.2)
            rec = next(
                (n for n in ca.nodes() if n["node_id"] == args.node), None
            )
            if rec is None or rec.get("state") in ("drained", "dead"):
                print(f"node {args.node}: {rec['state'] if rec else 'gone'}")
                break
    ca.shutdown()


def cmd_chaos(args):
    """Network-chaos plane control: install/clear/inspect a cluster-wide
    per-link fault schedule (blackhole/delay/flap, seeded+deterministic).
    The head installs the spec locally and broadcasts it to every connected
    process, so both ends of each named link inject symmetrically."""
    from cluster_anywhere_tpu.core.worker import global_worker

    ca = _connect(args)
    try:
        w = global_worker()
        if args.action == "set":
            if not args.spec:
                print("usage: ca chaos set '<spec>'  (e.g. "
                      "'seed=7;n0<>node1:blackhole@0+8')")
                sys.exit(2)
            r = w.head_call(
                "net_chaos", spec=args.spec, epoch=args.epoch or time.time()
            )
            print(f"installed: {r.get('spec')}")
        elif args.action == "clear":
            w.head_call("net_chaos", spec="")
            print("cleared (reachable processes only — scheduled windows "
                  "heal partitioned ones)")
        else:  # status
            r = w.head_call("net_chaos")
            st = r.get("status") or {}
            if not st.get("active"):
                print("net chaos: inactive")
            else:
                print(f"net chaos: {st.get('spec')}")
                print(f"  seed={st.get('seed')} epoch={st.get('epoch'):.3f} "
                      f"local={st.get('local')}")
                print(f"  links: {', '.join(st.get('links') or [])}")
                for k, v in (st.get("stats") or {}).items():
                    print(f"  {k}: {v}")
                for ev in st.get("events") or []:
                    print(f"  event: {ev}")
    except Exception as e:
        print(f"chaos command failed: {e}")
        ca.shutdown()
        sys.exit(1)
    ca.shutdown()


def cmd_status(args):
    ca = _connect(args)
    total = ca.cluster_resources()
    avail = ca.available_resources()
    stats = ca.cluster_stats()
    print("== cluster status ==")
    for k in sorted(total):
        print(f"  {k}: {avail.get(k, 0):g} / {total[k]:g} available")
    for k, v in sorted(stats.items()):
        print(f"  {k}: {v}")
    # node states: draining nodes show their reason + remaining window so an
    # announced exit (preemption, downscale) is visible before it completes
    draining = [
        n for n in ca.nodes() if n.get("state") not in ("alive", None)
    ]
    if draining:
        print("== nodes not alive ==")
        for n in draining:
            d = n.get("drain") or {}
            extra = (
                f" reason={d.get('reason')} deadline_in={d.get('deadline_in_s')}s"
                if n.get("state") == "draining"
                else ""
            )
            print(f"  {n['node_id']}: {n.get('state')}{extra}")
    # lease plane: delegated vs used block capacity per node and pool, so an
    # exhausted block (every local grant denied -> head fallback) is
    # diagnosable without the dashboard
    nodes = ca.nodes()
    blocks = [
        (n["node_id"], p, b)
        for n in nodes
        if n["alive"]
        for p, b in (n.get("lease_blocks") or {}).items()
    ]
    if blocks:
        print("== lease plane (per-node delegated blocks) ==")
        for nid, pool, b in blocks:
            print(
                f"  {nid}/{pool}: {b.get('used', 0)}/{b.get('size', 0)} used/"
                f"delegated (granted {b.get('granted', 0)}, "
                f"denied {b.get('denied', 0)})"
            )
    # ownership plane: owner-resident vs head-fallback settlement volume —
    # the structural proof (or diagnosis) that object lifetime traffic
    # stays off the head in steady state
    try:
        from .util.state import owner_plane

        op = owner_plane()
        if op["counters"] or op["objects_released_by_owner"]:
            print("== ownership plane (cluster-aggregated) ==")
            for k, v in sorted(op["counters"].items()):
                print(f"  {k}: {v}")
            for k in (
                "objects_released_by_owner", "owners_adopted",
                "early_refs_expired", "head_obj_refs_rpcs",
            ):
                print(f"  {k}: {op[k]}")
    except Exception:
        pass  # pre-plane head (rolling upgrade): status stays usable
    # transfer plane: pull volume, window occupancy, failovers, and the
    # quantized ring's wire savings — the bulk-byte data plane at a glance
    try:
        from .util.state import transfer_plane

        tp = transfer_plane()
        if tp["counters"].get("pulls") or tp["counters"].get("quant_ops"):
            print("== transfer plane (cluster-aggregated) ==")
            for k, v in sorted(tp["counters"].items()):
                print(f"  {k}: {v}")
            print(f"  window_occupancy: {tp['window_occupancy']:.2f}")
            print(f"  objects_transferred: {tp['objects_transferred']}")
    except Exception:
        pass
    # serving plane: per-deployment target/actual replicas, last autoscale
    # decision, drain state, and the admission/prefix/backpressure counters
    try:
        from .util.state import serve_plane

        sp = serve_plane()
        if sp["deployments"] or sp["counters"]:
            print("== serving plane ==")
            for app, deps in sorted(sp["deployments"].items()):
                for dep, d in sorted(deps.items()):
                    drain_note = (
                        f" draining={len(d['draining_replicas'])}"
                        if d.get("draining_replicas") else ""
                    )
                    scale = d.get("last_scale")
                    scale_note = (
                        f" last_scale={scale['direction']} "
                        f"{scale['from']}->{scale['to']} "
                        f"(avg_ongoing={scale['avg_ongoing']})"
                        if scale else ""
                    )
                    print(
                        f"  {app}/{dep}: {d['actual_replicas']}/"
                        f"{d['target_replicas']} replicas ({d['status']})"
                        f"{drain_note}{scale_note}"
                    )
            for k, v in sorted(sp["counters"].items()):
                print(f"  {k}: {v}")
            for k, v in sorted(sp["quantiles"].items()):
                print(f"  {k}: {v:.4g}" if isinstance(v, float) else f"  {k}: {v}")
    except Exception:
        pass
    # compiled-DAG plane: execute/result volume, channel traffic, and the
    # failure-semantics counters (timeouts, actor deaths, recompiles) — the
    # hot path that bypasses RPC should be visible without the dashboard
    try:
        from .util.state import dag_plane

        dp = dag_plane()
        if dp["dag"].get("executions") or dp["channel"].get("writes"):
            print("== compiled DAG plane (cluster-aggregated) ==")
            for k, v in sorted(dp["dag"].items()):
                print(f"  dag_{k}: {v}")
            for k, v in sorted(dp["channel"].items()):
                print(f"  channel_{k}: {v}")
    except Exception:
        pass
    # train plane: active/recent runs (attempt, world size, last checkpoint)
    # and the elastic counters — a preemption mid-run should read as a
    # PREEMPTING->RUNNING transition with a fresh checkpoint, not a mystery
    try:
        from .util.state import train_plane

        tp = train_plane()
        if tp["runs"] or tp["counters"]:
            print("== train plane ==")
            for name, r in sorted(tp["runs"].items()):
                ck = r.get("last_checkpoint")
                ck_note = f" last_ckpt={os.path.basename(ck)}" if ck else ""
                pre = r.get("preempt_restarts") or 0
                pre_note = f" preempt_restarts={pre}" if pre else ""
                print(
                    f"  {name}: {r.get('status')} attempt={r.get('attempt')} "
                    f"world={r.get('world_size')}"
                    f"{pre_note}{ck_note}"
                )
            for k, v in sorted(tp["counters"].items()):
                print(f"  ca_train_{k}: {v}")
    except Exception:
        pass
    ca.shutdown()


def cmd_submit(args):
    from cluster_anywhere_tpu.jobs import JobSubmissionClient

    client = JobSubmissionClient(getattr(args, "address", None) or "auto")
    entry = " ".join(args.entrypoint)
    # run in the submitter's cwd so `ca submit -- python x.py` resolves
    # relative paths the way the user expects
    sid = client.submit_job(
        entrypoint=entry, runtime_env={"working_dir": args.working_dir or os.getcwd()}
    )
    print(f"submitted {sid}: {entry}")
    if args.no_wait:
        return
    for chunk in client.tail_job_logs(sid):
        sys.stdout.write(chunk)
        sys.stdout.flush()
    status = client.get_job_status(sid)
    print(f"\njob {sid} {status}")
    sys.exit(0 if status == "SUCCEEDED" else 1)


def cmd_jobs(args):
    from cluster_anywhere_tpu.jobs import JobSubmissionClient

    client = JobSubmissionClient(getattr(args, "address", None) or "auto")
    for info in client.list_jobs():
        dur = (info.end_time or time.time()) - info.start_time
        print(f"{info.submission_id}  {info.status:10s}  {dur:8.1f}s  {info.entrypoint}")


def cmd_memory(args):
    ca = _connect(args)
    from cluster_anywhere_tpu.util import state

    objs = state.list_objects()
    print(f"{len(objs)} objects, {sum(o['size'] for o in objs)} bytes")
    for o in objs[: args.limit]:
        loc = "shm" if o["in_shm"] else "inline"
        print(f"  {o['object_id'][:16]}  {o['size']:>12}  {loc:6}  holders={o['num_holders']}")
    ca.shutdown()


def cmd_timeline(args):
    ca = _connect(args)
    events = ca.timeline(args.output, limit=args.limit)
    n_flows = sum(1 for e in events if e.get("ph") == "s")
    n_procs = sum(1 for e in events if e.get("name") == "process_name")
    print(
        f"wrote {len(events)} events ({n_procs} processes, {n_flows} "
        f"submit→run flows) to {args.output}"
    )
    print("open in https://ui.perfetto.dev or chrome://tracing")
    ca.shutdown()


def cmd_summary(args):
    ca = _connect(args)
    from cluster_anywhere_tpu.util import state

    if args.kind == "tasks":
        out = state.summarize_tasks()
    elif args.kind == "actors":
        out = state.summarize_actors()
    else:
        out = state.summarize_objects()
    print(json.dumps(out, indent=2, default=str))
    ca.shutdown()


def cmd_list(args):
    ca = _connect(args)
    from cluster_anywhere_tpu.util import state

    fn = {
        "tasks": state.list_tasks,
        "actors": state.list_actors,
        "workers": state.list_workers,
        "nodes": state.list_nodes,
        "objects": state.list_objects,
        "placement-groups": state.list_placement_groups,
    }[args.kind]
    print(json.dumps(fn(), indent=2, default=str))
    ca.shutdown()


def _render_log_trace(data: str) -> str:
    """Pretty-print trace-filtered JSONL records as `[wid span] line`."""
    out = []
    for line in data.splitlines():
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        sid = (rec.get("trace") or {}).get("sid", "")
        out.append(f"[{rec.get('wid', '?')} {sid}] {rec.get('line', '')}")
    return "\n".join(out)


def cmd_logs(args):
    """`ca logs [<worker|task|actor|node|head>] [--tail N] [--follow]
    [--trace <id>]` — reads/tails wherever the log lives: the head proxies
    cross-node reads through the owning node's agent (no shared filesystem
    needed).  `--trace` keeps only lines whose print site ran under that
    trace id (span stamps from the structured capture)."""
    ca = _connect(args)
    from cluster_anywhere_tpu.core.worker import global_worker

    w = global_worker()
    trace = getattr(args, "trace", None)
    failed = False
    try:
        try:
            reply = w.head_call(
                "log_fetch", id=args.worker_id, tail=args.tail, trace=trace
            )
        except (FileNotFoundError, RuntimeError, ConnectionError) as e:
            print(f"ca logs: {e}", file=sys.stderr)
            failed = True
            return
        if reply["data"]:
            print(_render_log_trace(reply["data"]) if trace else reply["data"])
        if not args.follow:
            return
        off = reply["off"]
        try:
            while True:
                time.sleep(0.3)
                try:
                    reply = w.head_call(
                        "log_fetch", id=args.worker_id, off=off, trace=trace
                    )
                except FileNotFoundError:
                    continue  # rotated away: keep polling from the new file
                except (RuntimeError, ConnectionError) as e:
                    print(f"ca logs: {e}", file=sys.stderr)
                    failed = True
                    return
                if reply["data"]:
                    data = (
                        _render_log_trace(reply["data"]) + "\n"
                        if trace else reply["data"]
                    )
                    sys.stdout.write(data)
                    sys.stdout.flush()
                off = reply["off"]
        except KeyboardInterrupt:
            pass
    finally:
        ca.shutdown()
        if failed:
            sys.exit(1)


def _format_flight_event(e, t0=None):
    """One journal line: `+12.345s node/proc plane:event {fields}`."""
    ts = e.get("ts") or 0.0
    rel = f"+{ts - t0:8.3f}s" if t0 is not None else time.strftime(
        "%H:%M:%S", time.localtime(ts)
    )
    origin = f"{e.get('node') or '?'}/{e.get('proc') or '?'}"
    tr = (e.get("trace") or {}).get("tid")
    skip = {"ts", "seq", "plane", "event", "node", "proc", "trace"}
    fields = " ".join(
        f"{k}={v}" for k, v in e.items() if k not in skip
    )
    line = f"{rel}  {origin:24s} {e.get('plane', '?')}:{e.get('event', '?')}"
    if fields:
        line += f"  {fields}"
    if tr:
        line += f"  [trace {tr}]"
    return line


def cmd_events(args):
    """`ca events [--trace <id>] [--plane <p>] [--node <n>]` — the head's
    merged flight-recorder journal, newest-last."""
    ca = _connect(args)
    from cluster_anywhere_tpu.util import state

    try:
        r = state.flightrec_events(
            trace=args.trace, plane=args.plane, node=args.node,
            event=args.event, limit=args.limit,
        )
        if args.json:
            print(json.dumps(r, indent=2, default=str))
            return
        evs = r.get("events", [])
        if not r.get("enabled", True):
            print("flight recorder disabled (flightrec_plane=False)")
        print(f"== ca events: {len(evs)} shown / {r.get('total', 0)} in ring ==")
        for e in evs:
            print(_format_flight_event(e))
    finally:
        ca.shutdown()


def cmd_incident(args):
    """`ca incident` — reconstruct the causal cross-node timeline of the
    recent window: every plane's decision events in time order, with
    relative offsets from the first event (the incident trigger)."""
    ca = _connect(args)
    from cluster_anywhere_tpu.util import state

    try:
        r = state.incident(
            trace=args.trace, plane=args.plane, node=args.node,
            window_s=args.window, limit=args.limit,
        )
        if args.json:
            print(json.dumps(r, indent=2, default=str))
            return
        evs = r.get("events", [])
        if not r.get("enabled", True):
            print("flight recorder disabled (flightrec_plane=False)")
        if not evs:
            print(f"no flight-recorder events in the last {args.window:g}s")
            return
        planes = ", ".join(
            f"{p}={n}" for p, n in sorted(r.get("planes", {}).items())
        )
        print(
            f"== ca incident: {len(evs)} events over {r.get('span_s', 0):.1f}s "
            f"across {len(r.get('nodes', []))} node(s) =="
        )
        print(f"   planes: {planes}")
        t0 = evs[0].get("ts") or 0.0
        print(f"   t0 = {time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(t0))}")
        for e in evs:
            print(_format_flight_event(e, t0=t0))
    finally:
        ca.shutdown()


def _node_metrics_addr(args, node_id: str):
    """Resolve a node agent's HTTP scrape endpoint: addr files first
    (head-free, same-host — deliberately WITHOUT _find_session's
    head-liveness check, since scraping a node with the head dead is the
    point), then the head's node table."""
    import glob

    from cluster_anywhere_tpu.core.config import get_config

    addr_arg = getattr(args, "address", None) or "auto"
    candidates = []
    if os.path.isdir(addr_arg):
        candidates.append(addr_arg)
    elif addr_arg == "auto":
        # newest sessions first, head alive or not
        candidates.extend(sorted(
            glob.glob(os.path.join(get_config().session_dir_root, "session_*")),
            key=os.path.getmtime, reverse=True,
        ))
    for sdir in candidates:
        path = os.path.join(sdir, "nodes", node_id, "metrics.addr")
        if os.path.exists(path):
            return open(path).read().strip()
    ca = _connect(args)
    try:
        for n in ca.nodes():
            if n["node_id"] == node_id:
                return n.get("metrics_addr")
    finally:
        ca.shutdown()
    return None


def cmd_metrics(args):
    node_id = getattr(args, "node", None)
    if node_id:
        # scrape the node agent's HTTP endpoint directly — works with the
        # head dead (that is the metrics plane's whole point)
        import urllib.request

        try:
            addr = _node_metrics_addr(args, node_id)
        except (RuntimeError, ConnectionError, FileNotFoundError, TimeoutError) as e:
            print(f"ca metrics: {e}", file=sys.stderr)
            sys.exit(1)
        if not addr:
            print(
                f"ca metrics: no scrape endpoint known for node {node_id!r} "
                f"(node down, or metrics_plane disabled)",
                file=sys.stderr,
            )
            sys.exit(1)
        try:
            with urllib.request.urlopen(addr.rstrip("/") + "/metrics", timeout=10) as r:
                sys.stdout.write(r.read().decode())
        except OSError as e:
            print(f"ca metrics: scrape of {addr} failed: {e}", file=sys.stderr)
            sys.exit(1)
        return
    try:
        ca = _connect(args)
    except (RuntimeError, ConnectionError, FileNotFoundError, TimeoutError) as e:
        # friendly one-liner, not a traceback (the `ca logs` convention)
        print(f"ca metrics: {e}", file=sys.stderr)
        sys.exit(1)
    from cluster_anywhere_tpu.util import metrics

    if getattr(args, "grafana_out", None):
        from cluster_anywhere_tpu.util.grafana import write_grafana_dashboards

        snap = metrics.get_metrics_snapshot()
        for p in write_grafana_dashboards(args.grafana_out, snapshot=snap):
            print(p)
    else:
        print(metrics.prometheus_text(), end="")
    ca.shutdown()


def cmd_profile(args):
    """`ca profile <worker|actor|task|node|head> [--duration]`: trigger the
    target process's in-process stack sampler and print folded stacks (plus
    a hot-function summary); --speedscope saves the speedscope.app JSON."""
    try:
        ca = _connect(args)
    except (RuntimeError, ConnectionError, FileNotFoundError, TimeoutError) as e:
        print(f"ca profile: {e}", file=sys.stderr)
        sys.exit(1)
    from cluster_anywhere_tpu.core.worker import global_worker

    failed = False
    try:
        try:
            out = global_worker().head_call(
                "profile", id=args.target, duration=args.duration, hz=args.hz,
                timeout=args.duration + 30,
            )
        except (ValueError, RuntimeError, ConnectionError) as e:
            print(f"ca profile: {e}", file=sys.stderr)
            failed = True
            return
        from cluster_anywhere_tpu.util.profiler import top_functions

        print(
            f"# {out['target']} (node {out['node_id']}): {out['samples']} "
            f"samples over {out['duration_s']:.1f}s"
        )
        folded = {}
        for line in out["folded"].splitlines():
            stack, _, count = line.rpartition(" ")
            if stack:
                folded[stack] = int(count)
        for fn, n in top_functions(folded, limit=10):
            pct = 100.0 * n / max(out["samples"], 1)
            print(f"  {pct:5.1f}%  {fn}")
        if args.speedscope:
            with open(args.speedscope, "w") as f:
                json.dump(out["speedscope"], f)
            print(f"speedscope profile -> {args.speedscope}")
        if args.folded_out:
            with open(args.folded_out, "w") as f:
                f.write(out["folded"] + "\n")
            print(f"folded stacks -> {args.folded_out}")
        elif not args.speedscope:
            print(out["folded"])
    finally:
        ca.shutdown()
        if failed:
            sys.exit(1)


def cmd_top(args):
    """`ca top`: refreshing live cluster view — resource occupancy, node
    table, and metrics-plane RATES (tasks/s, objects/s, RPC msg/s, head
    loop lag) derived from the head's time-series store."""
    try:
        ca = _connect(args)
    except (RuntimeError, ConnectionError, FileNotFoundError, TimeoutError) as e:
        print(f"ca top: {e}", file=sys.stderr)
        sys.exit(1)
    from cluster_anywhere_tpu.core.worker import global_worker

    w = global_worker()
    rate_rows = [
        ("head_tasks_pushed", "tasks/s"),
        ("head_objects_created", "objects/s"),
        ("head_leases_granted", "head leases/s"),
        ("head_rpc_messages_recv", "head RPC msg/s"),
        ("head_actor_restarts", "actor restarts/s"),
        # post-PR-7 planes: compiled-DAG ticks, serve requests + sheds,
        # train reports, transfer pulls, flight-recorder events
        ("ca_dag_executions", "dag ticks/s"),
        ("ca_serve_request_latency_seconds_count", "serve reqs/s"),
        ("ca_serve_shed_total", "serve sheds/s"),
        ("ca_train_preempt_restarts_total", "train preempts/s"),
        ("ca_transfer_pulls", "transfer pulls/s"),
        ("ca_flightrec_recorded", "flightrec ev/s"),
    ]
    gauge_rows = [
        ("head_n_workers", "workers"),
        ("head_n_actors", "actors"),
        ("head_n_objects", "objects"),
        ("head_pending_leases", "pending leases"),
        ("head_nodes_draining", "nodes draining"),
        ("ca_head_loop_lag_seconds", "head loop lag (s)"),
    ]
    names = [n for n, _ in rate_rows + gauge_rows]
    it = 0
    try:
        while True:
            it += 1
            summary = w.head_call("stats")["stats"]
            ts = w.head_call("timeseries", names=names, rate=True)
            series = ts.get("series", {})

            def latest(name):
                tagged = series.get(name) or {}
                for rec in tagged.values():
                    if rec["points"]:
                        return rec["points"][-1][1]
                return None

            lines = ["== ca top =="]
            lines.append(
                f"nodes {summary.get('n_nodes', '?')}  "
                f"workers {summary.get('n_workers', '?')}  "
                f"actors {summary.get('n_actors', '?')}  "
                f"objects {summary.get('n_objects', '?')}"
            )
            lines.append("-- rates (tier-0 window) --")
            for name, label in rate_rows:
                v = latest(name)
                lines.append(
                    f"  {label:20s} {v:10.2f}" if v is not None
                    else f"  {label:20s}          -"
                )
            lines.append("-- levels --")
            for name, label in gauge_rows:
                # gauges pass rate=True through untouched
                v = latest(name)
                lines.append(
                    f"  {label:20s} {v:10.4g}" if v is not None
                    else f"  {label:20s}          -"
                )
            meta = ts.get("meta", {})
            lines.append(
                f"-- retention: {meta.get('n_series', 0)} series, "
                f"{meta.get('memory_bytes', 0) / 1024:.0f} KiB --"
            )
            if args.iterations and not args.no_clear:
                pass  # finite runs print consecutively (test/pipe friendly)
            elif not args.no_clear:
                sys.stdout.write("\x1b[2J\x1b[H")
            print("\n".join(lines), flush=True)
            if args.iterations and it >= args.iterations:
                return
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    finally:
        ca.shutdown()


def cmd_debug(args):
    """List active remote breakpoints and attach (reference `ray debug`)."""
    ca = _connect(args)
    from cluster_anywhere_tpu.core.worker import global_worker
    from cluster_anywhere_tpu.util import rpdb

    try:
        bps = rpdb.list_breakpoints(global_worker())
        if not bps:
            print("no active breakpoints")
            return
        for i, bp in enumerate(bps):
            print(f"[{i}] {bp['label']}  (pid {bp['pid']}, {bp['host']}:{bp['port']})")
        idx = args.index
        if idx is None:
            if len(bps) == 1:
                idx = 0
            else:
                idx = int(input("attach to which breakpoint? "))
        bp = bps[idx]
        print(f"attaching to {bp['label']} ... (Ctrl-D to detach)")
        rpdb.attach(bp["host"], bp["port"])
    finally:
        ca.shutdown()


def cmd_lint(args):
    """Static analysis over this checkout (no cluster needed): `ca lint`,
    `ca lint --update-baseline`, `ca lint --contract`, `ca lint --format
    json` — see cluster_anywhere_tpu/analysis/."""
    from cluster_anywhere_tpu.analysis.lint import main as lint_main

    rest = args.rest
    if rest and rest[0] == "--":
        rest = rest[1:]
    raise SystemExit(lint_main(rest))


def cmd_dashboard(args):
    """Print the running cluster's dashboard URL."""
    import os

    from cluster_anywhere_tpu.core.api import _find_session
    from cluster_anywhere_tpu.core.config import get_config

    sdir = _find_session(args.address or "auto", get_config().session_dir_root)
    path = os.path.join(sdir, "dashboard.addr")
    if not os.path.exists(path):
        raise SystemExit("no dashboard.addr in the session (head predates it?)")
    print(open(path).read().strip())


def cmd_head(args):
    """HA plane control: run a warm-standby head (foreground, like `ca
    join`), promote a standby to active, or print every head's role/epoch/
    replication watermark."""
    import glob as _glob
    import json as _json

    from cluster_anywhere_tpu.core.api import _find_session
    from cluster_anywhere_tpu.core.config import CAConfig
    from cluster_anywhere_tpu.core.protocol import BlockingClient

    if args.action == "standby":
        if args.head:
            # cross-host standby: its own session dir, replicating over TCP
            root = CAConfig().session_dir_root
            sdir = os.path.join(root, f"standby{args.rank}_{os.getpid()}")
            os.makedirs(sdir, exist_ok=True)
            head_addr = args.head
        else:
            sdir = _find_session(args.address or "auto", CAConfig().session_dir_root)
            head_addr = open(os.path.join(sdir, "head.addr")).read().strip()
        os.environ["CA_SESSION_DIR"] = sdir
        os.environ["CA_HEAD_ADDR"] = head_addr
        os.environ["CA_HEAD_STANDBY"] = "1"
        os.environ["CA_HEAD_STANDBY_RANK"] = str(args.rank)
        os.environ["CA_HEAD_PERSIST"] = "1"
        os.environ.setdefault("CA_CONFIG_JSON", CAConfig().to_json())
        from cluster_anywhere_tpu.core.head import main as head_main

        print(f"standby head (rank {args.rank}) replicating from {head_addr}")
        head_main()
        return

    sdir = _find_session(args.address or "auto", CAConfig().session_dir_root)

    def _ha_status(addr):
        c = BlockingClient(addr)
        c._sock.settimeout(5.0)
        try:
            r = c.call("ha_status")
        finally:
            c.close()
        return {k: v for k, v in r.items() if k not in ("i", "ok")}

    if args.action == "promote":
        path = os.path.join(sdir, f"head.standby{args.rank}.addr")
        if not os.path.exists(path):
            raise SystemExit(f"no standby at rank {args.rank} in {sdir}")
        addr = open(path).read().strip()
        c = BlockingClient(addr)
        c._sock.settimeout(30.0)
        try:
            r = c.call("head_promote")
        finally:
            c.close()
        print(
            f"promoted {addr}: epoch {r.get('epoch')} "
            f"(replicated seq {r.get('seq')}, watermark {r.get('watermark')})"
        )
        return

    # status: the active head plus every advertised standby
    rows = []
    try:
        active = open(os.path.join(sdir, "head.addr")).read().strip()
    except FileNotFoundError:
        active = ""
    if active:
        try:
            rows.append(_ha_status(active))
        except Exception as e:
            rows.append({"addr": active, "role": f"unreachable ({e})"})
    for path in sorted(_glob.glob(os.path.join(sdir, "head.standby*.addr"))):
        addr = open(path).read().strip()
        if any(r.get("addr") == addr for r in rows):
            continue  # a promoted standby already answered as the active
        try:
            rows.append(_ha_status(addr))
        except Exception as e:
            rows.append({"addr": addr, "role": f"unreachable ({e})"})
    if getattr(args, "json", False):
        print(_json.dumps(rows, indent=2, default=str))
        return
    for r in rows:
        role = r.get("role", "?")
        line = f"{r.get('addr', '?'):<28} {role:<9} epoch={r.get('epoch', '?')}"
        if role == "active":
            line += (
                f" seq={r.get('seq')} standbys={len(r.get('standbys') or [])}"
                f" repl_lag={r.get('repl_lag')}"
            )
        elif role == "standby":
            line += (
                f" rank={r.get('rank')} watermark={r.get('watermark')}"
                f" syncing_from={r.get('active_addr')}"
            )
        print(line)


def cmd_microbenchmark(args):
    """Single-node microbenchmarks (reference _private/ray_perf.py main):
    the canonical table — tasks/actors sync+async, put/get call rates, put
    bandwidth, placement-group churn — for comparison with BASELINE.md."""
    if getattr(args, "saturation", False):
        from .microbenchmark import head_saturation

        head_saturation(quick=getattr(args, "quick", False))
        return
    if getattr(args, "lease_plane", False):
        # owns its own multi-node clusters (local-grant vs head-grant A/B)
        from .microbenchmark import run_lease_plane

        run_lease_plane(quick=getattr(args, "quick", False))
        return
    if getattr(args, "owner_plane", False):
        # owns its own clusters (owner-resident vs centralized object A/B
        # plus the GC-with-the-head-down proof)
        from .microbenchmark import run_owner_plane

        run_owner_plane(quick=getattr(args, "quick", False))
        return
    if getattr(args, "metrics_plane", False):
        # owns its own clusters (node-scrape vs head-RPC metrics A/B plus
        # the scrape-with-the-head-down proof)
        from .microbenchmark import run_metrics_plane

        run_metrics_plane(quick=getattr(args, "quick", False))
        return
    if getattr(args, "transfer", False):
        # owns its own clusters (serial vs windowed pulls on a latency-
        # injected link, 1 vs 2 sources, f32 vs int8/bf16 quantized ring)
        from .microbenchmark import run_transfer_plane

        run_transfer_plane(quick=getattr(args, "quick", False))
        return
    if getattr(args, "serve_plane", False):
        # owns its own clusters (open-loop SSE envelope, shedding and
        # prefix-cache A/Bs, drain-under-load zero-drop proof)
        from .microbenchmark import run_serve_plane

        run_serve_plane(quick=getattr(args, "quick", False))
        return
    if getattr(args, "dag", False):
        # owns its own clusters (compiled-DAG vs RPC actor-call latency and
        # throughput, 3-actor chain A/B, serve TTFT on/off A/B)
        from .microbenchmark import run_dag_plane

        run_dag_plane(quick=getattr(args, "quick", False))
        return
    if getattr(args, "train_elastic", False):
        # owns its own clusters (drain-aware proactive restart vs reactive
        # poll-failure restart: warning->resumed latency + steps lost)
        from .microbenchmark import run_train_elastic

        run_train_elastic(quick=getattr(args, "quick", False))
        return
    if getattr(args, "partition", False):
        # owns its own clusters (head<->node blackhole mid-workload:
        # detect->fence->heal timeline + at-most-once commit proof)
        from .microbenchmark import run_partition_chaos

        run_partition_chaos(quick=getattr(args, "quick", False))
        return
    if getattr(args, "obsplane", False):
        # owns its own clusters (flight-recorder cost model: armed record
        # rate, disabled-path gate, journal memory, tasks/s on/off A/B)
        from .microbenchmark import run_obsplane

        run_obsplane(quick=getattr(args, "quick", False))
        return
    if getattr(args, "ha", False):
        # owns its own clusters (SIGKILL the active head mid-workload:
        # detect->promote->first-successful-op latency, acked-KV loss=0,
        # duplicate side effects=0, replication-lag ceiling)
        from .microbenchmark import run_ha_plane

        run_ha_plane(quick=getattr(args, "quick", False))
        return

    import cluster_anywhere_tpu as ca

    from . import microbenchmark as mb

    runner = mb.run_microbenchmarks
    if getattr(args, "multi", False):
        runner = mb.run_multiclient
    elif getattr(args, "scalability", False):
        runner = mb.run_scalability
    elif getattr(args, "collective", False):
        runner = mb.run_collective_bw

    ca.init(num_cpus=args.num_cpus)
    try:
        runner(quick=getattr(args, "quick", False))
    finally:
        ca.shutdown()


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv[:1] == ["lint"]:
        # hand the whole tail to the lint parser: argparse REMAINDER would
        # reject leading option tokens (`ca lint --format json`)
        from cluster_anywhere_tpu.analysis.lint import main as lint_main

        raise SystemExit(lint_main(argv[1:]))
    p = argparse.ArgumentParser(prog="ca", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    def addr(sp):
        sp.add_argument("--address", default=None, help="session dir (default: auto)")

    sp = sub.add_parser("start", help="start a persistent local cluster")
    sp.add_argument("--num-cpus", type=int, default=None)
    sp.add_argument("--num-tpus", type=int, default=None)
    sp.set_defaults(fn=cmd_start)

    sp = sub.add_parser("join", help="join this host to a cluster as a node")
    sp.add_argument("--head", required=True, help="head TCP address (tcp:host:port)")
    sp.add_argument("--node-id", default=None)
    sp.add_argument("--num-cpus", type=float, default=4)
    sp.add_argument("--num-tpus", type=float, default=0)
    sp.add_argument("--resources", default=None, help="extra resources, JSON")
    sp.add_argument("--labels", default=None, help="node labels, JSON")
    sp.add_argument("--session-root", default=None)
    sp.set_defaults(fn=cmd_join)

    sp = sub.add_parser("up", help="bring up a cluster from a YAML config")
    sp.add_argument("config", help="path to the cluster YAML")
    sp.set_defaults(fn=cmd_up)

    sp = sub.add_parser("down", help="tear down the running cluster")
    addr(sp)
    sp.set_defaults(fn=cmd_down)

    sp = sub.add_parser("serve", help="serve deploy <yaml> / status / shutdown")
    sp.add_argument("action", choices=["deploy", "status", "shutdown"])
    sp.add_argument("config", nargs="?", help="YAML for deploy")
    addr(sp)
    sp.set_defaults(fn=cmd_serve)

    sp = sub.add_parser("stop", help="stop the running cluster")
    addr(sp)
    sp.set_defaults(fn=cmd_stop)

    sp = sub.add_parser("status", help="cluster resources and stats")
    addr(sp)
    sp.set_defaults(fn=cmd_status)

    sp = sub.add_parser(
        "drain",
        help="gracefully drain a node (evacuate, then release to the provider)",
    )
    sp.add_argument("node", help="node id to drain (see ca status / ca list nodes)")
    sp.add_argument(
        "--reason",
        choices=("manual", "idle", "preemption"),
        default="manual",
        help="drain reason recorded in events/metrics (default: manual)",
    )
    sp.add_argument(
        "--deadline",
        type=float,
        default=None,
        help="evacuation window in seconds (default: cluster drain_deadline_s)",
    )
    sp.add_argument(
        "--wait", action="store_true",
        help="block until the node reaches drained/dead",
    )
    addr(sp)
    sp.set_defaults(fn=cmd_drain)

    sp = sub.add_parser(
        "chaos",
        help="network-chaos plane: install/clear/inspect a per-link "
        "blackhole/delay/flap schedule cluster-wide",
    )
    addr(sp)
    sp.add_argument("action", choices=["set", "clear", "status"])
    sp.add_argument(
        "spec", nargs="?", default=None,
        help="chaos spec for `set`, e.g. 'seed=7;n0<>node1:blackhole@0+8'",
    )
    sp.add_argument(
        "--epoch", type=float, default=None,
        help="wall-clock anchor for window offsets (default: now)",
    )
    sp.set_defaults(fn=cmd_chaos)

    sp = sub.add_parser("submit", help="submit a job: ca submit -- python x.py")
    addr(sp)
    sp.add_argument("--no-wait", action="store_true")
    sp.add_argument("--working-dir", default=None)
    sp.add_argument("entrypoint", nargs=argparse.REMAINDER)
    sp.set_defaults(fn=cmd_submit)

    sp = sub.add_parser("jobs", help="list submitted jobs")
    addr(sp)
    sp.set_defaults(fn=cmd_jobs)

    sp = sub.add_parser("memory", help="object store contents")
    addr(sp)
    sp.add_argument("--limit", type=int, default=50)
    sp.set_defaults(fn=cmd_memory)

    sp = sub.add_parser(
        "timeline",
        help="export a Chrome-trace/Perfetto timeline of task lifecycles",
    )
    sp.add_argument("--limit", type=int, default=100_000,
                    help="max task events to assemble")
    addr(sp)
    sp.add_argument("--output", "-o", default="timeline.json")
    sp.set_defaults(fn=cmd_timeline)

    sp = sub.add_parser("summary", help="summarize tasks/actors/objects")
    addr(sp)
    sp.add_argument("kind", choices=["tasks", "actors", "objects"])
    sp.set_defaults(fn=cmd_summary)

    sp = sub.add_parser("list", help="list cluster entities")
    addr(sp)
    sp.add_argument(
        "kind",
        choices=["tasks", "actors", "workers", "nodes", "objects", "placement-groups"],
    )
    sp.set_defaults(fn=cmd_list)

    sp = sub.add_parser(
        "logs", help="read/tail head/worker/task/actor logs across nodes"
    )
    addr(sp)
    sp.add_argument(
        "worker_id", nargs="?", default=None,
        help="worker/task/actor/node id, or 'head' (default)",
    )
    sp.add_argument("--tail", type=int, default=200)
    sp.add_argument(
        "--follow", "-f", action="store_true",
        help="keep streaming new lines (Ctrl-C to stop)",
    )
    sp.add_argument(
        "--trace", default=None, metavar="TRACE_ID",
        help="only lines printed under this trace id (structured capture)",
    )
    sp.set_defaults(fn=cmd_logs)

    sp = sub.add_parser(
        "events",
        help="flight recorder: cross-node control-plane decision events",
    )
    addr(sp)
    sp.add_argument("--trace", default=None, help="filter by trace id")
    sp.add_argument(
        "--plane", default=None,
        help="filter by plane (fence/drain/chaos/dag/serve/train/transfer/"
        "ownership/node/actor/ha)",
    )
    sp.add_argument("--node", default=None, help="filter by node id")
    sp.add_argument("--event", default=None, help="filter by event substring")
    sp.add_argument("--limit", type=int, default=200, help="newest N events")
    sp.add_argument("--json", action="store_true", help="raw JSON output")
    sp.set_defaults(fn=cmd_events)

    sp = sub.add_parser(
        "incident",
        help="causal incident timeline from the flight recorder "
        "(fence → cancel → heal → rejoin, cross-node)",
    )
    addr(sp)
    sp.add_argument("--trace", default=None, help="follow one trace id")
    sp.add_argument("--plane", default=None, help="restrict to one plane")
    sp.add_argument("--node", default=None, help="restrict to one node")
    sp.add_argument(
        "--window", type=float, default=600.0,
        help="look back this many seconds (default 600)",
    )
    sp.add_argument("--limit", type=int, default=2000)
    sp.add_argument("--json", action="store_true", help="raw JSON output")
    sp.set_defaults(fn=cmd_incident)

    sp = sub.add_parser("metrics", help="Prometheus metrics snapshot")
    addr(sp)
    sp.add_argument(
        "--grafana-out", default=None, metavar="DIR",
        help="write Grafana dashboard JSON + provisioning stub to DIR",
    )
    sp.add_argument(
        "--node", default=None, metavar="NODE_ID",
        help="scrape that node agent's /metrics endpoint directly "
        "(head-free: works with the head down)",
    )
    sp.set_defaults(fn=cmd_metrics)

    sp = sub.add_parser(
        "profile",
        help="sampling profiler: fold a live process's stacks (ca profile "
        "<worker|actor|task|node|head>)",
    )
    addr(sp)
    sp.add_argument(
        "target", nargs="?", default="head",
        help="worker/actor/task/node id, or 'head' (default)",
    )
    sp.add_argument("--duration", type=float, default=2.0, help="seconds to sample")
    sp.add_argument("--hz", type=float, default=100.0, help="sampling frequency")
    sp.add_argument(
        "--speedscope", default=None, metavar="FILE",
        help="write speedscope.app JSON to FILE",
    )
    sp.add_argument(
        "--folded-out", default=None, metavar="FILE",
        help="write folded stacks to FILE instead of stdout",
    )
    sp.set_defaults(fn=cmd_profile)

    sp = sub.add_parser(
        "top", help="live cluster view: occupancy + metrics-plane rates"
    )
    addr(sp)
    sp.add_argument("--interval", type=float, default=2.0, help="refresh period")
    sp.add_argument(
        "--iterations", type=int, default=0,
        help="render N frames then exit (0 = until Ctrl-C)",
    )
    sp.add_argument(
        "--no-clear", action="store_true",
        help="append frames instead of clearing the screen (pipes/logs)",
    )
    sp.set_defaults(fn=cmd_top)

    sp = sub.add_parser(
        "lint",
        help="static analysis: RPC contract checker + asyncio hazard "
        "analyzer (see `ca lint --help`)",
    )
    sp.add_argument("rest", nargs=argparse.REMAINDER)
    sp.set_defaults(fn=cmd_lint)

    sp = sub.add_parser("debug", help="attach to a remote breakpoint (rpdb)")
    addr(sp)
    sp.add_argument("index", nargs="?", type=int, default=None)
    sp.set_defaults(fn=cmd_debug)

    sp = sub.add_parser("dashboard", help="print the dashboard URL")
    addr(sp)
    sp.set_defaults(fn=cmd_dashboard)

    sp = sub.add_parser(
        "head",
        help="HA plane: run a warm-standby head / promote a standby / "
        "show head roles+epochs",
    )
    sp.add_argument("action", choices=["standby", "promote", "status"])
    addr(sp)
    sp.add_argument(
        "--rank", type=int, default=0,
        help="standby rank (promotion order; rank 0 self-promotes first)",
    )
    sp.add_argument(
        "--head", default=None,
        help="active head TCP address for a cross-host standby "
        "(tcp:host:port[,tcp:host2:port2...])",
    )
    sp.add_argument("--json", action="store_true", help="raw JSON status")
    sp.set_defaults(fn=cmd_head)

    sp = sub.add_parser("microbenchmark", help="single-node perf microbenchmarks")
    sp.add_argument("--quick", action="store_true", help="scaled-down run")
    sp.add_argument(
        "--saturation", action="store_true",
        help="head-saturation sweep: control-plane ops/s vs clients and nodes",
    )
    sp.add_argument(
        "--multi", action="store_true",
        help="multi-client aggregate rows (client actors drive concurrently)",
    )
    sp.add_argument(
        "--scalability", action="store_true",
        help="envelope probes: many-args/returns/gets + queued-task flood",
    )
    sp.add_argument(
        "--collective", action="store_true",
        help="p2p host allreduce bandwidth + head-traffic proof",
    )
    sp.add_argument(
        "--lease-plane", dest="lease_plane", action="store_true",
        help="node-local vs head lease granting tasks/s + head-RPC proof",
    )
    sp.add_argument(
        "--owner-plane", dest="owner_plane", action="store_true",
        help="owner-resident vs centralized object settlement A/B + "
        "head-down GC proof",
    )
    sp.add_argument(
        "--metrics-plane", dest="metrics_plane", action="store_true",
        help="node-scrape vs head-RPC metrics A/B: head metric traffic "
        "per scrape + head-down scrape proof",
    )
    sp.add_argument(
        "--transfer", action="store_true",
        help="bulk-transfer A/B: serial vs windowed pulls (latency-injected "
        "link), 1 vs 2 sources, f32 vs int8/bf16 quantized ring",
    )
    sp.add_argument(
        "--serve", dest="serve_plane", action="store_true",
        help="serving-plane envelope: open-loop SSE req/s + TTFT/p99, "
        "admission shedding A/B, prefix-cache A/B, drain-under-load proof",
    )
    sp.add_argument(
        "--dag", action="store_true",
        help="compiled-DAG plane A/B: compiled tick vs RPC actor-call "
        "latency/throughput, 3-actor chain, serve TTFT on/off",
    )
    sp.add_argument(
        "--train-elastic", dest="train_elastic", action="store_true",
        help="preemption-elastic train A/B: drain-aware proactive restart "
        "vs reactive poll-failure restart (warning->resumed latency, "
        "steps lost, max_failures consumed)",
    )
    sp.add_argument(
        "--partition", action="store_true",
        help="partition-tolerance chaos: head<->node blackhole mid-workload "
        "(detect->fence->heal timeline, at-most-once side effects, "
        "zombie-free rejoin at a fresh incarnation)",
    )
    sp.add_argument(
        "--obsplane", action="store_true",
        help="flight-recorder cost model: armed record events/s, disabled "
        "gate rate, journal memory at cap, tasks/s with the plane on/off",
    )
    sp.add_argument(
        "--ha", action="store_true",
        help="HA-plane failover chaos: SIGKILL the active head mid-workload "
        "(detect->promote->first-op latency, acked-KV loss=0, duplicate "
        "side effects=0)",
    )
    sp.add_argument("--num-cpus", type=int, default=None)
    sp.set_defaults(fn=cmd_microbenchmark)

    args = p.parse_args(argv)
    if getattr(args, "entrypoint", None) and args.entrypoint and args.entrypoint[0] == "--":
        args.entrypoint = args.entrypoint[1:]
    args.fn(args)


if __name__ == "__main__":
    main()
