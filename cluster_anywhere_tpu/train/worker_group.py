"""WorkerGroup: a gang of TrainWorker actors scheduled via a placement group.

Analogue of the reference's train/_internal/worker_group.py:102 — but the
worker actor here hosts the training thread AND the session, and the
driver polls reports instead of using a results queue actor.
"""

from __future__ import annotations

import os
import socket
import threading
import traceback
from typing import Any, Callable, Dict, List, Optional

import cluster_anywhere_tpu as ca

from .checkpoint import Checkpoint
from .session import TrainContext, _Session, _set_session


class TrainWorker:
    """Actor hosting one training process' session + train thread."""

    def __init__(self):
        self._thread: Optional[threading.Thread] = None
        self._session = None
        self._error: Optional[str] = None
        self._done = False

    def node_info(self) -> Dict[str, Any]:
        return {
            "hostname": socket.gethostname(),
            "pid": os.getpid(),
            "node_id": ca.get_runtime_context().node_id,
        }

    def free_port(self) -> int:
        s = socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def set_env(self, env: Dict[str, str]) -> None:
        os.environ.update(env)

    def start_training(
        self,
        train_fn: Callable,
        train_fn_config: Optional[Dict[str, Any]],
        context_kwargs: Dict[str, Any],
        dataset_shards: Optional[Dict[str, Any]],
        resume_checkpoint_path: Optional[str],
    ) -> None:
        if self._thread is not None and self._thread.is_alive():
            raise RuntimeError("training already running on this worker")
        ctx = TrainContext(**context_kwargs)
        os.makedirs(ctx.trial_dir, exist_ok=True)
        resume = (
            Checkpoint(resume_checkpoint_path) if resume_checkpoint_path else None
        )
        self._session = _Session(ctx, dataset_shards, resume)
        self._error = None
        self._done = False
        _set_session(self._session)

        def _run():
            try:
                if train_fn_config is not None:
                    train_fn(train_fn_config)
                else:
                    train_fn()
            except BaseException:
                self._error = traceback.format_exc()
            finally:
                self._done = True
                self._session.finished.set()

        self._thread = threading.Thread(target=_run, daemon=True, name="ca-train")
        self._thread.start()

    def poll(self) -> Dict[str, Any]:
        s = self._session
        # checkpoint-on-preempt barrier: True once this rank reported a
        # checkpoint after the controller's request_checkpoint.  Read BEFORE
        # draining: the ack is set after its report is queued, so an ack
        # observed here guarantees the checkpoint entry rides this (or an
        # earlier) drain — the controller tears the group down on it
        acked = bool(s.ckpt_acked) if s else False
        return {
            "reports": s.drain_reports() if s else [],
            "done": self._done,
            "error": self._error,
            "ckpt_acked": acked,
        }

    def request_checkpoint(self) -> bool:
        """Controller->session control channel: ask the training loop to
        checkpoint at its next step boundary (train.should_checkpoint()).
        Returns False when no session is running (nothing to barrier on)."""
        s = self._session
        if s is None or self._done:
            return False
        s.ckpt_acked = False
        s.ckpt_request.set()
        return True

    def join(self, timeout: Optional[float] = None) -> bool:
        if self._thread is None:
            return True
        self._thread.join(timeout)
        return not self._thread.is_alive()

    def execute(self, fn: Callable, *args, **kwargs):
        """Run an arbitrary function in the worker process (backend setup)."""
        return fn(*args, **kwargs)


def _node_sorted_permutation(node_infos: List[Dict[str, Any]]) -> List[int]:
    """Stable permutation grouping workers by first-seen node: ranks on the
    same node become contiguous (and keep their relative order), which is
    what local_ranks()/node_ranks() assume.  Raw placement order can
    interleave nodes (e.g. SPREAD, or PACK across partially-full nodes),
    which would hand two workers of one node non-consecutive local ranks."""
    order: Dict[str, int] = {}
    for info in node_infos:
        order.setdefault(info["node_id"], len(order))
    return sorted(
        range(len(node_infos)),
        key=lambda i: (order[node_infos[i]["node_id"]], i),
    )


class WorkerGroup:
    """N TrainWorker actors gang-scheduled through one placement group."""

    def __init__(
        self,
        num_workers: int,
        bundle: Dict[str, float],
        placement_strategy: str = "PACK",
        max_restarts: int = 0,
        label_selector=None,
    ):
        self.num_workers = num_workers
        self._pg = ca.placement_group(
            [dict(bundle) for _ in range(num_workers)],
            strategy=placement_strategy,
            # slice targeting: every bundle carries the gang's hard selector
            bundle_label_selectors=(
                [label_selector] * num_workers if label_selector else None
            ),
        )
        self._pg.wait(timeout_seconds=60)
        cls = ca.remote(TrainWorker)
        custom = {
            k: v for k, v in bundle.items() if k not in ("CPU", "TPU", "memory")
        }
        self.workers: List[Any] = [
            cls.options(
                max_concurrency=4,
                max_restarts=max_restarts,
                placement_group=self._pg,
                placement_group_bundle_index=i,
                num_cpus=bundle.get("CPU", 0),
                num_tpus=bundle.get("TPU", 0),
                resources=custom,
                # the TrainController handles node drains app-aware
                # (checkpoint barrier + group rebuild on survivors); the
                # head's generic drain evacuation restarting a TrainWorker
                # elsewhere would race the barrier and lose the training
                # thread's state anyway
                drain_migration=False,
            ).remote()
            for i in range(num_workers)
        ]
        # sorted by node for stable local_rank assignment: workers and their
        # infos are reordered TOGETHER so rank i always maps to workers[i]
        infos = ca.get([w.node_info.remote() for w in self.workers])
        perm = _node_sorted_permutation(infos)
        self.workers = [self.workers[i] for i in perm]
        self.node_infos = [infos[i] for i in perm]

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        return ca.get(self.execute_async(fn, *args, **kwargs))

    def execute_async(self, fn: Callable, *args, **kwargs) -> List[Any]:
        return [w.execute.remote(fn, *args, **kwargs) for w in self.workers]

    def execute_single(self, index: int, fn: Callable, *args, **kwargs) -> Any:
        return ca.get(self.workers[index].execute.remote(fn, *args, **kwargs))

    def local_ranks(self) -> List[int]:
        counts: Dict[str, int] = {}
        ranks = []
        for info in self.node_infos:
            nid = info["node_id"]
            ranks.append(counts.get(nid, 0))
            counts[nid] = counts.get(nid, 0) + 1
        return ranks

    def node_ranks(self) -> List[int]:
        order: Dict[str, int] = {}
        ranks = []
        for info in self.node_infos:
            nid = info["node_id"]
            if nid not in order:
                order[nid] = len(order)
            ranks.append(order[nid])
        return ranks

    def node_ids(self) -> List[str]:
        """Per-rank node ids — what the controller intersects with the
        drain plane's draining_node_ids() to spot a preemption warning."""
        return [info["node_id"] for info in self.node_infos]

    def shutdown(self):
        from ..core.ownership import warn_ratelimited
        from ..core.worker import TRAIN_STATS

        for rank, w in enumerate(self.workers):
            try:
                ca.kill(w)
            except Exception as e:
                # a worker that is already gone (preempted node) is normal
                # here, but it must stay visible: a kill that fails for any
                # OTHER reason leaks an actor slot for the group's lifetime
                TRAIN_STATS["shutdown_errors_total"] += 1
                warn_ratelimited(
                    "train_wg_kill",
                    f"train worker group: killing rank {rank} failed: {e!r}",
                )
        self.workers = []
        try:
            ca.remove_placement_group(self._pg)
        except Exception as e:
            TRAIN_STATS["shutdown_errors_total"] += 1
            warn_ratelimited(
                "train_wg_pg",
                f"train worker group: removing placement group failed: {e!r}",
            )
