"""TrainController: the v2-style run loop with pluggable scaling + failure
policies (analogue of reference train/v2/_internal/execution/controller/
controller.py:91).

State machine per attempt:
  INIT -> STARTING (worker group up, backend bootstrapped)
       -> RUNNING  (polling worker reports)
       -> PREEMPTING (drain warning for a gang node: checkpoint barrier)
       -> FINISHED | ERRORED
On worker failure, FailurePolicy decides RETRY (rebuild the group, resume
from the latest registered checkpoint) or RAISE.  ScalingPolicy decides
the world size of each (re)start — ElasticScalingPolicy shrinks to what
the cluster can actually place, enabling elastic training.

Preemption elasticity (drain_aware, the default): the controller watches
the drain plane's warnings (`worker.draining_node_ids()`, fed by the head's
`drain` pubs with zero extra RPCs — the same surface the serve controller
uses) and reacts BEFORE the kill instead of waiting for a poll failure:
request a checkpoint at every rank's next step boundary
(`train.should_checkpoint()`), wait for the barrier, register rank 0's
checkpoint, tear the group down, and rebuild on survivors — with sharded
checkpoints resharding onto whatever mesh the shrunk world forms.
Preemption-caused attempts are budget-exempt: FailureKind.PREEMPTION never
consumes failure_config.max_failures (the drain plane's budget-exempt task
retry, applied to whole training attempts).
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..util import flightrec
from .backend_executor import BackendExecutor
from .checkpoint import Checkpoint, CheckpointManager
from .config import (
    BackendConfig,
    RunConfig,
    ScalingConfig,
    TrainingFailedError,
)


class RunAttemptStatus(Enum):
    INIT = "INIT"
    STARTING = "STARTING"
    RUNNING = "RUNNING"
    PREEMPTING = "PREEMPTING"  # drain warning: checkpoint barrier in flight
    FINISHED = "FINISHED"
    ERRORED = "ERRORED"


@dataclass
class Result:
    """What fit() returns (reference air Result)."""

    metrics: Dict[str, Any] = field(default_factory=dict)
    checkpoint: Optional[Checkpoint] = None
    path: Optional[str] = None
    error: Optional[BaseException] = None
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)
    best_checkpoints: List[Any] = field(default_factory=list)


class ScalingPolicy:
    def target_num_workers(self, scaling_config: ScalingConfig, attempt: int) -> int:
        return scaling_config.num_workers


class FixedScalingPolicy(ScalingPolicy):
    pass


class ElasticScalingPolicy(ScalingPolicy):
    """Shrink the group to what the cluster can place, within
    [min_workers, max_workers]."""

    def target_num_workers(self, scaling_config: ScalingConfig, attempt: int) -> int:
        import cluster_anywhere_tpu as ca

        lo = scaling_config.min_workers or 1
        hi = scaling_config.max_workers or scaling_config.num_workers
        bundle = scaling_config.bundle()
        avail = ca.available_resources()
        fit = hi
        for key, per in bundle.items():
            if per > 0:
                fit = min(fit, int(avail.get(key, 0) // per))
        return max(lo, min(hi, fit))


class FailureDecision(Enum):
    RETRY = "RETRY"
    RAISE = "RAISE"


class FailureKind(Enum):
    """Why an attempt ended early.  WORKER failures consume the
    max_failures budget; PREEMPTION (a death or proactive restart inside an
    announced drain window) is the system's fault and never does —
    mirroring the drain plane's budget-exempt task retry."""

    WORKER = "worker"
    PREEMPTION = "preemption"


class FailurePolicy:
    def __init__(self, max_failures: int = 0):
        self.max_failures = max_failures

    def decide(
        self,
        failure_count: int,
        error: str,
        kind: FailureKind = FailureKind.WORKER,
    ) -> FailureDecision:
        if kind == FailureKind.PREEMPTION:
            return FailureDecision.RETRY  # budget-exempt: announced exit
        if self.max_failures < 0 or failure_count <= self.max_failures:
            return FailureDecision.RETRY
        return FailureDecision.RAISE


@dataclass
class _RunHandle:
    """Trial-shaped handle for run_config.callbacks on the Train path."""

    trial_id: str
    config: Dict[str, Any]
    local_dir: str


class TrainController:
    def __init__(
        self,
        train_fn: Callable,
        train_fn_config: Optional[Dict[str, Any]],
        scaling_config: ScalingConfig,
        run_config: RunConfig,
        backend_config: BackendConfig,
        datasets: Optional[Dict[str, Any]] = None,
        experiment_name: Optional[str] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
        scaling_policy: Optional[ScalingPolicy] = None,
        poll_interval_s: float = 0.02,
    ):
        self.train_fn = train_fn
        self.train_fn_config = train_fn_config
        self.scaling_config = scaling_config
        self.run_config = run_config
        self.backend_config = backend_config
        self.datasets = datasets
        self.experiment_name = experiment_name or run_config.name or (
            f"train_run_{int(time.time())}"
        )
        self.checkpoint_manager = CheckpointManager(run_config.checkpoint_config)
        self.failure_policy = FailurePolicy(run_config.failure_config.max_failures)
        if scaling_policy is None:
            elastic = (
                scaling_config.min_workers is not None
                or scaling_config.max_workers is not None
            )
            scaling_policy = ElasticScalingPolicy() if elastic else FixedScalingPolicy()
        self.scaling_policy = scaling_policy
        self.poll_interval_s = poll_interval_s
        self.status = RunAttemptStatus.INIT
        self._resume_checkpoint = resume_from_checkpoint
        self._latest_metrics: Dict[str, Any] = {}
        self._metrics_history: List[Dict[str, Any]] = []
        # run_config.callbacks (tune/callback.py hook surface) fire here too:
        # the whole train run presents as one "trial" to the loggers
        self._run_handle = _RunHandle(
            trial_id=self.experiment_name,
            config=dict(train_fn_config or {}),
            local_dir=os.path.join(
                run_config.resolved_storage_path(), self.experiment_name
            ),
        )
        self.drain_aware = run_config.failure_config.drain_aware
        self._attempt = 0
        self._world_size = 0
        self._failure_count = 0
        self._preempt_restarts = 0
        self._last_pub = 0.0

    def _cb(self, hook: str, *args):
        from ..core.ownership import warn_ratelimited
        from ..core.worker import TRAIN_STATS

        for cb in self.run_config.callbacks:
            try:
                getattr(cb, hook)(self._run_handle, *args)
            except Exception as e:
                # logging must never take down the run — but a silently
                # broken logger invalidates every experiment it "recorded"
                TRAIN_STATS["callback_errors_total"] += 1
                warn_ratelimited(
                    f"train_cb_{hook}",
                    f"train run {self.experiment_name!r}: callback "
                    f"{type(cb).__name__}.{hook} raised {e!r}",
                )

    # -- drain plane ------------------------------------------------------
    def _draining_node_ids(self) -> set:
        """Nodes inside an announced drain window.  The head pushes `drain`
        pubs to every client — including this controller's process — so
        the read is a local dict lookup, zero RPCs (serve controller idiom,
        serve/controller.py)."""
        if not self.drain_aware:
            return set()
        try:
            from ..core.worker import global_worker

            return global_worker().draining_node_ids()
        except Exception:
            return set()

    def _preempt_barrier(self, executor: BackendExecutor) -> bool:
        """Checkpoint-on-preempt: ask every rank to checkpoint at its next
        step boundary, then poll until all live ranks acked (reported a
        checkpoint), finished, or the barrier window closes.  Reports keep
        being ingested throughout, so rank 0's barrier checkpoint registers
        before the caller tears the group down.  Returns True when every
        rank answered inside the window."""
        from ..core.worker import TRAIN_STATS

        self.status = RunAttemptStatus.PREEMPTING
        self._publish_digest(force=True)
        timeout = self.run_config.failure_config.preempt_barrier_timeout_s
        deadline = time.monotonic() + timeout
        accepted = executor.request_checkpoint()
        if flightrec.REC is not None:
            flightrec.REC.record(
                "train", "train_preempt_barrier", phase="requested",
                run=self.experiment_name, attempt=self._attempt,
                accepted=sum(bool(a) for a in accepted), ranks=len(accepted),
                timeout_s=timeout,
            )
        if not any(accepted):
            # no rank had a running session to barrier on (the warning
            # raced group bring-up, or every loop already returned):
            # nothing can ever ack — rebuild now rather than burning the
            # shrinking warning window on a provably futile wait
            return False
        acked = False
        died = False
        while time.monotonic() < deadline:
            try:
                polls = executor.poll()
            except Exception:
                died = True  # a rank died inside the window: keep what we have
                break
            self._ingest_reports(polls)
            if any(p["error"] for p in polls):
                died = True
                break
            # a rank that could not take the request (no session yet /
            # unreachable on the dying node) will never ack: wait only on
            # the ranks that accepted
            if all(
                p["ckpt_acked"] or p["done"] or not accepted[i]
                for i, p in enumerate(polls)
            ):
                acked = True
                break
            time.sleep(self.poll_interval_s)
        if flightrec.REC is not None:
            flightrec.REC.record(
                "train", "train_preempt_barrier",
                phase=("acked" if acked else "rank_died" if died else "timeout"),
                run=self.experiment_name, attempt=self._attempt,
            )
        if acked:
            TRAIN_STATS["preempt_barrier_acked_total"] += 1
        elif not died:
            # only a genuinely expired window counts as a timeout — the
            # counter tunes preempt_barrier_timeout_s, and a node dying 1s
            # into a 15s window says nothing about the window being short
            # (deaths surface through the attempt error path instead)
            TRAIN_STATS["preempt_barrier_timeout_total"] += 1
        return acked

    def _pick_resume_checkpoint(self) -> Optional[Checkpoint]:
        """Newest RESUMABLE checkpoint: a sharded dir whose ranks were
        killed mid-save (e.g. the reactive drain-deadline kill landing
        during a periodic save) fails its coverage check — retrying into it
        would burn every max_failures slot on the same ValueError.  Skip to
        the previous registered checkpoint instead, loudly."""
        from ..core.ownership import warn_ratelimited

        for ck in self.checkpoint_manager.checkpoints_newest_first():
            if ck.is_sharded() and not ck.sharded_complete():
                warn_ratelimited(
                    "train_resume_incomplete",
                    f"train run {self.experiment_name!r}: skipping "
                    f"incomplete sharded checkpoint {ck.path} (a rank's "
                    "shards never landed); resuming from the previous one",
                )
                continue
            return ck
        return self._resume_checkpoint

    # -- one attempt -----------------------------------------------------
    def _run_attempt(
        self, attempt: int
    ) -> Tuple[FailureKind, Optional[str]]:
        """Returns (kind, None) on success, or (kind, error string) when the
        attempt must be rebuilt — kind=PREEMPTION when the cause was an
        announced node exit (budget-exempt)."""
        from ..core.worker import TRAIN_STATS

        n = self.scaling_policy.target_num_workers(self.scaling_config, attempt)
        executor = BackendExecutor(
            self.backend_config,
            self.scaling_config,
            self.run_config,
            self.experiment_name,
        )
        self.status = RunAttemptStatus.STARTING
        self._attempt = attempt
        self._world_size = n
        self._publish_digest(force=True)
        if flightrec.REC is not None:
            flightrec.REC.record(
                "train", "train_attempt_start", run=self.experiment_name,
                attempt=attempt, world_size=n,
            )

        def _kind() -> FailureKind:
            gang = set(executor.worker_node_ids())
            draining = self._draining_node_ids()
            if gang:
                return (
                    FailureKind.PREEMPTION
                    if gang & draining
                    else FailureKind.WORKER
                )
            # the group died before its node map existed (placement /
            # node_info raced the exit): with a drain window open anywhere,
            # the announced exit is the likeliest cause — exempt it.  The
            # exemption is bounded: drain windows expire, after which a
            # persistent start failure counts against the budget again
            return FailureKind.PREEMPTION if draining else FailureKind.WORKER

        try:
            try:
                executor.start(num_workers=n)
                resume = self._pick_resume_checkpoint()
                executor.start_training(
                    self.train_fn,
                    self.train_fn_config,
                    self.datasets,
                    resume,
                    attempt=attempt,
                )
            except Exception as e:
                # group bring-up raced a node exit (placement, env push):
                # classify like any other death so a drain-window loss of
                # the half-built gang retries budget-exempt
                return (_kind(), f"worker group start failed: {e!r}")
            self.status = RunAttemptStatus.RUNNING
            self._publish_digest(force=True)
            while True:
                try:
                    polls = executor.poll()
                except Exception as e:  # a worker actor died mid-poll
                    return (_kind(), f"worker group failure: {e!r}")
                self._ingest_reports(polls)
                for rank, p in enumerate(polls):
                    if p["error"]:
                        return (
                            _kind(),
                            f"rank {rank} failed: {p['error']}",
                        )
                # done wins over a concurrent drain warning: a run whose
                # ranks all finished must return FINISHED, not be rebuilt
                # because its (now idle) node is being downscaled
                if all(p["done"] for p in polls):
                    self.status = RunAttemptStatus.FINISHED
                    return (FailureKind.WORKER, None)
                gang_draining = sorted(
                    self._draining_node_ids()
                    & set(executor.worker_node_ids())
                )
                if gang_draining:
                    # preemption warning for a gang member: checkpoint at
                    # the next step boundary and rebuild BEFORE the kill
                    TRAIN_STATS["preempt_restarts_total"] += 1
                    self._preempt_restarts += 1
                    if flightrec.REC is not None:
                        flightrec.REC.record(
                            "train", "train_preempt_detected",
                            run=self.experiment_name, attempt=attempt,
                            draining_nodes=gang_draining,
                        )
                    self._preempt_barrier(executor)
                    return (
                        FailureKind.PREEMPTION,
                        f"node(s) {gang_draining} entered a preemption "
                        "drain window: proactive checkpoint + restart",
                    )
                self._publish_digest()
                time.sleep(self.poll_interval_s)
        finally:
            executor.shutdown()

    def _ingest_reports(self, polls: List[Dict[str, Any]]):
        # rank 0 is authoritative for metrics + checkpoint registration
        for rank, poll in enumerate(polls):
            for rep in poll["reports"]:
                if rank == 0:
                    self._latest_metrics = rep["metrics"]
                    self._metrics_history.append(rep["metrics"])
                    self._cb("on_trial_result", rep["metrics"])
                    if "checkpoint_path" in rep:
                        self.checkpoint_manager.register(
                            Checkpoint(rep["checkpoint_path"]), rep["metrics"]
                        )

    # -- observability ---------------------------------------------------
    _DIGEST_RETENTION_S = 3600.0  # finished-run digests kept this long

    def _prune_stale_digests(self):
        """Head-KV hygiene: digests have no TTL head-side, so without this
        every run ever executed would accumulate in the KV (and in
        `ca status` output) for the head's lifetime.  Each starting
        controller sweeps digests of runs that reached a terminal state
        more than _DIGEST_RETENTION_S ago — recently finished runs stay
        visible, the store stays bounded by the active set + a 1h tail."""
        try:
            from ..core.worker import global_worker

            w = global_worker()
            cutoff = time.time() - self._DIGEST_RETENTION_S
            for key in w.head_call("kv_keys", prefix="train:run:")["keys"]:
                raw = w.head_call("kv_get", key=key).get("value")
                if not raw:
                    continue
                try:
                    info = json.loads(raw)
                except ValueError:
                    w.head_call("kv_del", key=key)  # undecodable: drop
                    continue
                if (
                    info.get("status")
                    in (
                        RunAttemptStatus.FINISHED.value,
                        RunAttemptStatus.ERRORED.value,
                    )
                    and info.get("updated_at", 0) < cutoff
                ):
                    w.head_call("kv_del", key=key)
        except Exception:
            pass  # hygiene only: never block a run on it

    def _publish_digest(self, force: bool = False):
        """~1s head-KV digest (`train:run:<name>`): `ca status`, the
        dashboard, and util.state.train_plane() see every active run's
        attempt / world size / last checkpoint without reaching into the
        driver process (serve controller's plane-digest idiom)."""
        now = time.monotonic()
        if not force and now - self._last_pub < 1.0:
            return
        self._last_pub = now
        try:
            from ..core.worker import global_worker

            latest = self.checkpoint_manager.latest_checkpoint
            info = {
                "status": self.status.value,
                "attempt": self._attempt,
                "world_size": self._world_size,
                "failure_count": self._failure_count,
                "preempt_restarts": self._preempt_restarts,
                "last_checkpoint": latest.path if latest else None,
                "last_metrics": {
                    k: v
                    for k, v in self._latest_metrics.items()
                    if isinstance(v, (int, float, str, bool))
                },
                "updated_at": time.time(),
            }
            global_worker().head_call(
                "kv_put",
                key=f"train:run:{self.experiment_name}",
                value=json.dumps(info, default=str).encode(),
            )
        except Exception:
            pass  # head briefly unreachable / not connected: next tick

    # -- full run --------------------------------------------------------
    def run(self) -> Result:
        from ..core.worker import TRAIN_STATS

        failure_count = 0
        attempt = 0
        final_error: Optional[BaseException] = None
        self._prune_stale_digests()
        self._cb("on_trial_start")
        while True:
            kind, error = self._run_attempt(attempt)
            attempt += 1
            if error is None:
                break
            if kind == FailureKind.PREEMPTION:
                # announced exit: the restart is the system's to absorb
                TRAIN_STATS["budget_exempt_attempts_total"] += 1
            else:
                failure_count += 1
            self._failure_count = failure_count
            decision = self.failure_policy.decide(
                failure_count, error, kind=kind
            )
            if decision != FailureDecision.RETRY:
                self.status = RunAttemptStatus.ERRORED
                final_error = TrainingFailedError(message=error)
                break
        self._cb("on_trial_error" if final_error is not None else "on_trial_complete")
        # every attempt's worker group is down: safe to reclaim evictions
        # the write-grace window deferred
        self.checkpoint_manager.finalize()
        self._publish_digest(force=True)
        return Result(
            metrics=self._latest_metrics,
            checkpoint=self.checkpoint_manager.latest_checkpoint,
            path=os.path.join(
                self.run_config.resolved_storage_path(), self.experiment_name
            ),
            error=final_error,
            metrics_history=self._metrics_history,
            best_checkpoints=self.checkpoint_manager.best_checkpoints(),
        )
