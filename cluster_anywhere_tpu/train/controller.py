"""TrainController: the v2-style run loop with pluggable scaling + failure
policies (analogue of reference train/v2/_internal/execution/controller/
controller.py:91).

State machine per attempt:
  INIT -> STARTING (worker group up, backend bootstrapped)
       -> RUNNING  (polling worker reports)
       -> FINISHED | ERRORED
On worker failure, FailurePolicy decides RETRY (rebuild the group, resume
from the latest registered checkpoint) or RAISE.  ScalingPolicy decides
the world size of each (re)start — ElasticScalingPolicy shrinks to what
the cluster can actually place, enabling elastic training.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Callable, Dict, List, Optional

from .backend_executor import BackendExecutor
from .checkpoint import Checkpoint, CheckpointManager
from .config import (
    BackendConfig,
    RunConfig,
    ScalingConfig,
    TrainingFailedError,
)


class RunAttemptStatus(Enum):
    INIT = "INIT"
    STARTING = "STARTING"
    RUNNING = "RUNNING"
    FINISHED = "FINISHED"
    ERRORED = "ERRORED"


@dataclass
class Result:
    """What fit() returns (reference air Result)."""

    metrics: Dict[str, Any] = field(default_factory=dict)
    checkpoint: Optional[Checkpoint] = None
    path: Optional[str] = None
    error: Optional[BaseException] = None
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)
    best_checkpoints: List[Any] = field(default_factory=list)


class ScalingPolicy:
    def target_num_workers(self, scaling_config: ScalingConfig, attempt: int) -> int:
        return scaling_config.num_workers


class FixedScalingPolicy(ScalingPolicy):
    pass


class ElasticScalingPolicy(ScalingPolicy):
    """Shrink the group to what the cluster can place, within
    [min_workers, max_workers]."""

    def target_num_workers(self, scaling_config: ScalingConfig, attempt: int) -> int:
        import cluster_anywhere_tpu as ca

        lo = scaling_config.min_workers or 1
        hi = scaling_config.max_workers or scaling_config.num_workers
        bundle = scaling_config.bundle()
        avail = ca.available_resources()
        fit = hi
        for key, per in bundle.items():
            if per > 0:
                fit = min(fit, int(avail.get(key, 0) // per))
        return max(lo, min(hi, fit))


class FailureDecision(Enum):
    RETRY = "RETRY"
    RAISE = "RAISE"


class FailurePolicy:
    def __init__(self, max_failures: int = 0):
        self.max_failures = max_failures

    def decide(self, failure_count: int, error: str) -> FailureDecision:
        if self.max_failures < 0 or failure_count <= self.max_failures:
            return FailureDecision.RETRY
        return FailureDecision.RAISE


@dataclass
class _RunHandle:
    """Trial-shaped handle for run_config.callbacks on the Train path."""

    trial_id: str
    config: Dict[str, Any]
    local_dir: str


class TrainController:
    def __init__(
        self,
        train_fn: Callable,
        train_fn_config: Optional[Dict[str, Any]],
        scaling_config: ScalingConfig,
        run_config: RunConfig,
        backend_config: BackendConfig,
        datasets: Optional[Dict[str, Any]] = None,
        experiment_name: Optional[str] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
        scaling_policy: Optional[ScalingPolicy] = None,
        poll_interval_s: float = 0.02,
    ):
        self.train_fn = train_fn
        self.train_fn_config = train_fn_config
        self.scaling_config = scaling_config
        self.run_config = run_config
        self.backend_config = backend_config
        self.datasets = datasets
        self.experiment_name = experiment_name or run_config.name or (
            f"train_run_{int(time.time())}"
        )
        self.checkpoint_manager = CheckpointManager(run_config.checkpoint_config)
        self.failure_policy = FailurePolicy(run_config.failure_config.max_failures)
        if scaling_policy is None:
            elastic = (
                scaling_config.min_workers is not None
                or scaling_config.max_workers is not None
            )
            scaling_policy = ElasticScalingPolicy() if elastic else FixedScalingPolicy()
        self.scaling_policy = scaling_policy
        self.poll_interval_s = poll_interval_s
        self.status = RunAttemptStatus.INIT
        self._resume_checkpoint = resume_from_checkpoint
        self._latest_metrics: Dict[str, Any] = {}
        self._metrics_history: List[Dict[str, Any]] = []
        # run_config.callbacks (tune/callback.py hook surface) fire here too:
        # the whole train run presents as one "trial" to the loggers
        self._run_handle = _RunHandle(
            trial_id=self.experiment_name,
            config=dict(train_fn_config or {}),
            local_dir=os.path.join(
                run_config.resolved_storage_path(), self.experiment_name
            ),
        )

    def _cb(self, hook: str, *args):
        for cb in self.run_config.callbacks:
            try:
                getattr(cb, hook)(self._run_handle, *args)
            except Exception:
                pass  # logging must never take down the run

    # -- one attempt -----------------------------------------------------
    def _run_attempt(self, attempt: int) -> Optional[str]:
        """Returns None on success, or an error string on worker failure."""
        n = self.scaling_policy.target_num_workers(self.scaling_config, attempt)
        executor = BackendExecutor(
            self.backend_config,
            self.scaling_config,
            self.run_config,
            self.experiment_name,
        )
        self.status = RunAttemptStatus.STARTING
        try:
            executor.start(num_workers=n)
            resume = (
                self.checkpoint_manager.latest_checkpoint or self._resume_checkpoint
            )
            executor.start_training(
                self.train_fn, self.train_fn_config, self.datasets, resume
            )
            self.status = RunAttemptStatus.RUNNING
            while True:
                try:
                    polls = executor.poll()
                except Exception as e:  # a worker actor died mid-poll
                    return f"worker group failure: {e!r}"
                self._ingest_reports(polls)
                errors = [p["error"] for p in polls if p["error"]]
                if errors:
                    return errors[0]
                if all(p["done"] for p in polls):
                    self.status = RunAttemptStatus.FINISHED
                    return None
                time.sleep(self.poll_interval_s)
        finally:
            executor.shutdown()

    def _ingest_reports(self, polls: List[Dict[str, Any]]):
        # rank 0 is authoritative for metrics + checkpoint registration
        for rank, poll in enumerate(polls):
            for rep in poll["reports"]:
                if rank == 0:
                    self._latest_metrics = rep["metrics"]
                    self._metrics_history.append(rep["metrics"])
                    self._cb("on_trial_result", rep["metrics"])
                    if "checkpoint_path" in rep:
                        self.checkpoint_manager.register(
                            Checkpoint(rep["checkpoint_path"]), rep["metrics"]
                        )

    # -- full run --------------------------------------------------------
    def run(self) -> Result:
        failure_count = 0
        attempt = 0
        final_error: Optional[BaseException] = None
        self._cb("on_trial_start")
        while True:
            error = self._run_attempt(attempt)
            attempt += 1
            if error is None:
                break
            failure_count += 1
            if self.failure_policy.decide(failure_count, error) != FailureDecision.RETRY:
                self.status = RunAttemptStatus.ERRORED
                final_error = TrainingFailedError(message=error)
                break
        self._cb("on_trial_error" if final_error is not None else "on_trial_complete")
        return Result(
            metrics=self._latest_metrics,
            checkpoint=self.checkpoint_manager.latest_checkpoint,
            path=os.path.join(
                self.run_config.resolved_storage_path(), self.experiment_name
            ),
            error=final_error,
            metrics_history=self._metrics_history,
            best_checkpoints=self.checkpoint_manager.best_checkpoints(),
        )
