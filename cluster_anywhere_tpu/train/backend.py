"""Framework backends: per-worker process-group setup hooks.

Analogue of the reference's `_TorchBackend` (train/torch/config.py:66-153,
which calls torch.distributed.init_process_group) — except the TPU-native
backend wires up JAX: rank env vars always; `jax.distributed.initialize`
when the config asks for a true multi-host runtime (TPU pod / multi-proc
CPU). Single-host JAX needs no collective bootstrap at all: a Mesh over
locally visible chips is enough, XLA emits the ICI collectives.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from .config import BackendConfig, JaxConfig

if TYPE_CHECKING:
    from .worker_group import WorkerGroup


class Backend:
    """No-op base backend."""

    def on_start(self, worker_group: "WorkerGroup", backend_config: BackendConfig):
        pass

    def on_training_start(
        self, worker_group: "WorkerGroup", backend_config: BackendConfig
    ):
        pass

    def on_shutdown(self, worker_group: "WorkerGroup", backend_config: BackendConfig):
        pass


def ensure_cpu_collectives():
    """Select Gloo for CPU cross-process collectives.  Must run BEFORE the
    runtime initializes (newer jaxlibs default to "none" and every
    multi-process computation raises).  The knob only affects the CPU
    backend, so it is set unconditionally — probing the platform here would
    initialize backends ahead of distributed.initialize and pin the mesh
    local; TPU/GPU runtimes keep their native ICI/DCN paths regardless."""
    import jax

    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass  # older jax: gloo is the baked-in default


def _init_jax_distributed(coordinator: str, num_processes: int, process_id: int):
    import jax

    ensure_cpu_collectives()
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


class JaxBackend(Backend):
    def on_start(self, worker_group: "WorkerGroup", backend_config: JaxConfig):
        n = worker_group.num_workers
        local_ranks = worker_group.local_ranks()
        node_ranks = worker_group.node_ranks()
        import cluster_anywhere_tpu as ca

        coordinator = None
        if backend_config.init_jax_distributed:
            port = backend_config.coordinator_port or ca.get(
                worker_group.workers[0].free_port.remote()
            )
            host = worker_group.node_infos[0]["hostname"]
            coordinator = f"{host}:{port}"

        refs = []
        for rank, w in enumerate(worker_group.workers):
            env = {
                "CA_WORLD_SIZE": str(n),
                "CA_WORLD_RANK": str(rank),
                "CA_LOCAL_RANK": str(local_ranks[rank]),
                "CA_NODE_RANK": str(node_ranks[rank]),
            }
            if coordinator:
                env["CA_COORDINATOR"] = coordinator
            refs.append(w.set_env.remote(env))
        ca.get(refs)

        if coordinator:
            ca.get(
                [
                    w.execute.remote(_init_jax_distributed, coordinator, n, rank)
                    for rank, w in enumerate(worker_group.workers)
                ]
            )


def _init_torch_pg(master_addr: str, master_port: int, world_size: int, rank: int,
                   backend: str, timeout_s: float):
    import datetime
    import os as _os

    import torch.distributed as dist

    _os.environ["MASTER_ADDR"] = master_addr
    _os.environ["MASTER_PORT"] = str(master_port)
    dist.init_process_group(
        backend=backend,
        world_size=world_size,
        rank=rank,
        timeout=datetime.timedelta(seconds=timeout_s),
    )


def _destroy_torch_pg():
    import torch.distributed as dist

    if dist.is_initialized():
        dist.destroy_process_group()


class TorchBackend(Backend):
    """torch.distributed process group across the worker group (reference
    _TorchBackend, train/torch/config.py:66-153): rank-0's node hosts the
    TCP store; every worker joins with its rank envs, enabling DDP/FSDP
    training loops unchanged (gloo on CPU hosts, nccl where tenable)."""

    def on_start(self, worker_group: "WorkerGroup", backend_config):
        import cluster_anywhere_tpu as ca

        n = worker_group.num_workers
        local_ranks = worker_group.local_ranks()
        node_ranks = worker_group.node_ranks()
        port = backend_config.port or ca.get(
            worker_group.workers[0].free_port.remote()
        )
        host = worker_group.node_infos[0]["hostname"]
        refs = []
        for rank, w in enumerate(worker_group.workers):
            env = {
                "CA_WORLD_SIZE": str(n),
                "CA_WORLD_RANK": str(rank),
                "CA_LOCAL_RANK": str(local_ranks[rank]),
                "CA_NODE_RANK": str(node_ranks[rank]),
                "MASTER_ADDR": host,
                "MASTER_PORT": str(port),
            }
            refs.append(w.set_env.remote(env))
        ca.get(refs)
        ca.get(
            [
                w.execute.remote(
                    _init_torch_pg, host, port, n, rank,
                    backend_config.backend, backend_config.timeout_s,
                )
                for rank, w in enumerate(worker_group.workers)
            ]
        )

    def on_shutdown(self, worker_group: "WorkerGroup", backend_config):
        import cluster_anywhere_tpu as ca

        try:
            ca.get([w.execute.remote(_destroy_torch_pg) for w in worker_group.workers])
        except Exception:
            pass
