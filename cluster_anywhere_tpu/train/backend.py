"""Framework backends: per-worker process-group setup hooks.

Analogue of the reference's `_TorchBackend` (train/torch/config.py:66-153,
which calls torch.distributed.init_process_group) — except the TPU-native
backend wires up JAX: rank env vars always; `jax.distributed.initialize`
when the config asks for a true multi-host runtime (TPU pod / multi-proc
CPU). Single-host JAX needs no collective bootstrap at all: a Mesh over
locally visible chips is enough, XLA emits the ICI collectives.
"""

from __future__ import annotations

from typing import TYPE_CHECKING

from .config import BackendConfig, JaxConfig

if TYPE_CHECKING:
    from .worker_group import WorkerGroup


class Backend:
    """No-op base backend."""

    def on_start(self, worker_group: "WorkerGroup", backend_config: BackendConfig):
        pass

    def on_training_start(
        self, worker_group: "WorkerGroup", backend_config: BackendConfig
    ):
        pass

    def on_shutdown(self, worker_group: "WorkerGroup", backend_config: BackendConfig):
        pass


def _init_jax_distributed(coordinator: str, num_processes: int, process_id: int):
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )


class JaxBackend(Backend):
    def on_start(self, worker_group: "WorkerGroup", backend_config: JaxConfig):
        n = worker_group.num_workers
        local_ranks = worker_group.local_ranks()
        node_ranks = worker_group.node_ranks()
        import cluster_anywhere_tpu as ca

        coordinator = None
        if backend_config.init_jax_distributed:
            port = backend_config.coordinator_port or ca.get(
                worker_group.workers[0].free_port.remote()
            )
            host = worker_group.node_infos[0]["hostname"]
            coordinator = f"{host}:{port}"

        refs = []
        for rank, w in enumerate(worker_group.workers):
            env = {
                "CA_WORLD_SIZE": str(n),
                "CA_WORLD_RANK": str(rank),
                "CA_LOCAL_RANK": str(local_ranks[rank]),
                "CA_NODE_RANK": str(node_ranks[rank]),
            }
            if coordinator:
                env["CA_COORDINATOR"] = coordinator
            refs.append(w.set_env.remote(env))
        ca.get(refs)

        if coordinator:
            ca.get(
                [
                    w.execute.remote(_init_jax_distributed, coordinator, n, rank)
                    for rank, w in enumerate(worker_group.workers)
                ]
            )
