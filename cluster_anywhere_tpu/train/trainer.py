"""Trainers: user-facing entry points.

`DataParallelTrainer` is the analogue of the reference's
train/data_parallel_trainer.py:26 (`fit` at base_trainer.py:649);
`JaxTrainer` specialises it with the JAX backend, mirroring how
TorchTrainer binds `_TorchBackend` (train/torch/torch_trainer.py:11).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from .checkpoint import Checkpoint
from .config import BackendConfig, JaxConfig, RunConfig, ScalingConfig
from .controller import Result, TrainController


class DataParallelTrainer:
    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict[str, Any]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        backend_config: Optional[BackendConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.backend_config = backend_config or BackendConfig()
        self.datasets = datasets
        self.resume_from_checkpoint = resume_from_checkpoint

    def fit(self) -> Result:
        controller = TrainController(
            train_fn=self.train_loop_per_worker,
            train_fn_config=self.train_loop_config,
            scaling_config=self.scaling_config,
            run_config=self.run_config,
            backend_config=self.backend_config,
            datasets=self.datasets,
            resume_from_checkpoint=self.resume_from_checkpoint,
        )
        result = controller.run()
        if result.error is not None:
            raise result.error
        return result


class JaxTrainer(DataParallelTrainer):
    """DataParallelTrainer with the JAX backend bound by default."""

    def __init__(self, *args, **kwargs):
        kwargs.setdefault("backend_config", JaxConfig())
        super().__init__(*args, **kwargs)
