"""Per-worker training session: the in-loop API.

Analogue of the reference's train/_internal/session.py — `train.report`,
`train.get_checkpoint`, `train.get_dataset_shard`, `train.get_context()`.

The session lives inside a TrainWorker actor. `report()` persists any
checkpoint to storage (worker-side upload, like the reference's
StorageContext train/_internal/storage.py) and enqueues the report for
the driver to poll. By default it does NOT block the training thread.
"""

from __future__ import annotations

import os
import shutil
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from ..util import flightrec
from ..util import tracing as _tracing
from .checkpoint import Checkpoint


@dataclass
class TrainContext:
    world_size: int
    world_rank: int
    local_rank: int
    node_rank: int
    experiment_name: str
    storage_path: str
    trial_dir: str
    # controller-assigned attempt number, identical on every rank of the
    # gang — what keys rank-shared sharded checkpoint dirs so a retry that
    # re-runs a step never re-saves into a previous attempt's directory
    attempt: int = 0

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_trial_dir(self) -> str:
        return self.trial_dir


class _Session:
    def __init__(
        self,
        context: TrainContext,
        dataset_shards: Optional[Dict[str, Any]] = None,
        resume_checkpoint: Optional[Checkpoint] = None,
    ):
        self.context = context
        self.dataset_shards = dataset_shards or {}
        self.resume_checkpoint = resume_checkpoint
        self.reports: deque = deque()
        self.lock = threading.Lock()
        self.report_seq = 0
        self.finished = threading.Event()
        # checkpoint-on-preempt barrier (controller -> session control
        # channel): the controller sets ckpt_request on every rank when a
        # gang node enters a drain window; the training loop observes it via
        # train.should_checkpoint() and answers by reporting a checkpoint at
        # its next step boundary, which flips ckpt_acked for the driver's
        # barrier poll.  Resume then loses at most ONE step, not one
        # checkpoint interval.
        self.ckpt_request = threading.Event()
        self.ckpt_acked = False
        # distinguishes checkpoint dirs across retry attempts: report_seq
        # restarts at 0 in a new session, and a colliding path would let the
        # driver's keep-K eviction of the old attempt's entry delete the new
        # attempt's data
        self.attempt_token = uuid.uuid4().hex[:8]
        # step-span clock: report() boundaries delimit train:step spans in
        # `ca timeline` (the loop itself is user code we cannot wrap)
        self._step_t0 = time.time()

    def report(
        self, metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None
    ) -> None:
        entry: Dict[str, Any] = {"metrics": dict(metrics), "seq": self.report_seq}
        if checkpoint is not None:
            if checkpoint.is_sharded():
                # rank-cooperative sharded checkpoint: every rank wrote its
                # own shards into ONE shared dir (shared_checkpoint_dir) —
                # register it in place; a per-rank copy would capture only
                # the shards that happened to have landed at copy time
                entry["checkpoint_path"] = checkpoint.path
            else:
                # Persist into the trial dir so it survives the worker
                # process.  Only rank 0's copy is registered by the driver,
                # but every rank may pass a checkpoint (they are rank-tagged
                # to avoid collision).
                dest = os.path.join(
                    self.context.trial_dir,
                    f"checkpoint_{self.attempt_token}_{self.report_seq:06d}"
                    f"_rank{self.context.world_rank}",
                )
                if os.path.abspath(checkpoint.path) != dest:
                    os.makedirs(os.path.dirname(dest), exist_ok=True)
                    shutil.copytree(checkpoint.path, dest, dirs_exist_ok=True)
                entry["checkpoint_path"] = dest
        barrier_ack = False
        with self.lock:
            self.reports.append(entry)
            self.report_seq += 1
            if checkpoint is not None and self.ckpt_request.is_set():
                # the barrier is answered by the FIRST checkpoint-carrying
                # report after the request, whatever triggered the save.
                # Acked strictly AFTER the entry is queued (and inside the
                # lock): the controller's poll must never observe the ack
                # without also draining the checkpoint report it acks —
                # it tears the group down on the strength of that ack
                self.ckpt_request.clear()
                self.ckpt_acked = True
                barrier_ack = True
        if barrier_ack and flightrec.REC is not None:
            # rank-side half of the preemption barrier: pairs with the
            # controller's train_preempt_barrier phases in `ca incident`
            flightrec.REC.record(
                "train", "train_ckpt_barrier_ack",
                rank=self.context.world_rank, seq=entry["seq"],
                attempt=getattr(self.context, "attempt", None),
            )
        now = time.time()
        tr = _tracing.current()
        if tr is not None or _tracing.is_enabled():
            ctx = (
                {"tid": tr["tid"], "sid": _tracing.new_span_id(), "psid": tr["sid"]}
                if tr is not None
                else {"tid": _tracing.new_trace_id(), "sid": _tracing.new_span_id()}
            )
            w = _tracing._current_worker()
            _tracing.record_task_event(
                "", f"train:step:{entry['seq']}", "span", "SPAN",
                trace=ctx,
                worker_id=w.client_id if w is not None else None,
                node_id=w.node_id if w is not None else None,
                start=self._step_t0, end=now,
                rank=self.context.world_rank,
            )
        self._step_t0 = now

    def drain_reports(self) -> List[Dict[str, Any]]:
        with self.lock:
            out = list(self.reports)
            self.reports.clear()
            return out

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self.resume_checkpoint

    def get_dataset_shard(self, name: str = "train"):
        return self.dataset_shards.get(name)


_session_lock = threading.Lock()
_session: Optional[_Session] = None


def _set_session(s: Optional[_Session]):
    global _session
    with _session_lock:
        _session = s


def _get_session() -> _Session:
    if _session is None:
        raise RuntimeError(
            "No training session active: this API must be called from inside "
            "a train_loop_per_worker launched by a Trainer."
        )
    return _session


# ---- public in-loop API (mirrors `ray.train.*`) -------------------------

def report(metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None) -> None:
    _get_session().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return _get_session().get_checkpoint()


def get_dataset_shard(name: str = "train"):
    return _get_session().get_dataset_shard(name)


def get_context() -> TrainContext:
    return _get_session().context


def make_temp_checkpoint_dir() -> str:
    """A scratch dir for building a checkpoint before report()."""
    d = os.path.join(
        _get_session().context.trial_dir, f"_tmp_ckpt_{uuid.uuid4().hex[:8]}"
    )
    os.makedirs(d, exist_ok=True)
    return d


def should_checkpoint() -> bool:
    """Has the controller asked this rank to checkpoint at the next step
    boundary?  Set when a node hosting a gang member enters a preemption
    drain window; answer by reporting a checkpoint (the report clears the
    flag and acks the barrier).  Ranks of a multi-process mesh should agree
    on the boundary by reducing the flag across the mesh (max) before
    branching — the request lands on every rank, but not atomically between
    steps (see ARCHITECTURE.md "Elastic train plane")."""
    return _get_session().ckpt_request.is_set()


def shared_checkpoint_dir(tag: Any) -> str:
    """The rank-SHARED directory for a cooperative sharded checkpoint:
    every rank calling with the same `tag` (use the step number) resolves
    the same trial-dir path, writes its own shards there
    (Checkpoint.save_pytree_sharded), and reports it; the session registers
    sharded checkpoints in place instead of making per-rank copies.  The
    path is keyed by the controller-assigned attempt too: a retry that
    re-runs a step must save into a FRESH dir — a kill mid-re-save into the
    previous attempt's dir would leave a mix of old and new shards that
    passes the coverage check and restores inconsistent state."""
    ctx = _get_session().context
    d = os.path.join(ctx.trial_dir, f"shard_ckpt_a{ctx.attempt}_{tag}")
    os.makedirs(d, exist_ok=True)
    return d
