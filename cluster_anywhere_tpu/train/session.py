"""Per-worker training session: the in-loop API.

Analogue of the reference's train/_internal/session.py — `train.report`,
`train.get_checkpoint`, `train.get_dataset_shard`, `train.get_context()`.

The session lives inside a TrainWorker actor. `report()` persists any
checkpoint to storage (worker-side upload, like the reference's
StorageContext train/_internal/storage.py) and enqueues the report for
the driver to poll. By default it does NOT block the training thread.
"""

from __future__ import annotations

import os
import shutil
import threading
import uuid
from collections import deque
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from .checkpoint import Checkpoint


@dataclass
class TrainContext:
    world_size: int
    world_rank: int
    local_rank: int
    node_rank: int
    experiment_name: str
    storage_path: str
    trial_dir: str

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.world_rank

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_trial_dir(self) -> str:
        return self.trial_dir


class _Session:
    def __init__(
        self,
        context: TrainContext,
        dataset_shards: Optional[Dict[str, Any]] = None,
        resume_checkpoint: Optional[Checkpoint] = None,
    ):
        self.context = context
        self.dataset_shards = dataset_shards or {}
        self.resume_checkpoint = resume_checkpoint
        self.reports: deque = deque()
        self.lock = threading.Lock()
        self.report_seq = 0
        self.finished = threading.Event()
        # distinguishes checkpoint dirs across retry attempts: report_seq
        # restarts at 0 in a new session, and a colliding path would let the
        # driver's keep-K eviction of the old attempt's entry delete the new
        # attempt's data
        self.attempt_token = uuid.uuid4().hex[:8]

    def report(
        self, metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None
    ) -> None:
        entry: Dict[str, Any] = {"metrics": dict(metrics), "seq": self.report_seq}
        if checkpoint is not None:
            # Persist into the trial dir so it survives the worker process.
            # Only rank 0's copy is registered by the driver, but every rank
            # may pass a checkpoint (they are rank-tagged to avoid collision).
            dest = os.path.join(
                self.context.trial_dir,
                f"checkpoint_{self.attempt_token}_{self.report_seq:06d}"
                f"_rank{self.context.world_rank}",
            )
            if os.path.abspath(checkpoint.path) != dest:
                os.makedirs(os.path.dirname(dest), exist_ok=True)
                shutil.copytree(checkpoint.path, dest, dirs_exist_ok=True)
            entry["checkpoint_path"] = dest
        with self.lock:
            self.reports.append(entry)
            self.report_seq += 1

    def drain_reports(self) -> List[Dict[str, Any]]:
        with self.lock:
            out = list(self.reports)
            self.reports.clear()
            return out

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self.resume_checkpoint

    def get_dataset_shard(self, name: str = "train"):
        return self.dataset_shards.get(name)


_session_lock = threading.Lock()
_session: Optional[_Session] = None


def _set_session(s: Optional[_Session]):
    global _session
    with _session_lock:
        _session = s


def _get_session() -> _Session:
    if _session is None:
        raise RuntimeError(
            "No training session active: this API must be called from inside "
            "a train_loop_per_worker launched by a Trainer."
        )
    return _session


# ---- public in-loop API (mirrors `ray.train.*`) -------------------------

def report(metrics: Dict[str, Any], checkpoint: Optional[Checkpoint] = None) -> None:
    _get_session().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return _get_session().get_checkpoint()


def get_dataset_shard(name: str = "train"):
    return _get_session().get_dataset_shard(name)


def get_context() -> TrainContext:
    return _get_session().context


def make_temp_checkpoint_dir() -> str:
    """A scratch dir for building a checkpoint before report()."""
    d = os.path.join(
        _get_session().context.trial_dir, f"_tmp_ckpt_{uuid.uuid4().hex[:8]}"
    )
    os.makedirs(d, exist_ok=True)
    return d
