"""Train configuration dataclasses.

Analogues of the reference's air/config.py (`RunConfig`/`ScalingConfig`/
`FailureConfig`, reference python/ray/air/config.py) and
CheckpointConfig (keep-K by score) — re-stated TPU-first: ScalingConfig
speaks in workers x chips and an optional mesh spec instead of GPUs.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class ScalingConfig:
    """How many training workers to launch and what each needs.

    num_workers: number of SPMD training worker processes (one per host in a
        real TPU pod; the driver assigns consecutive ranks).
    use_tpu: request one "TPU" resource per worker (plus `chips_per_worker-1`
        extra) so the scheduler lands workers on TPU hosts.
    resources_per_worker: extra custom resources per worker.
    placement_strategy: PACK | SPREAD | STRICT_PACK | STRICT_SPREAD for the
        placement group that gangs the workers.
    """

    num_workers: int = 1
    use_tpu: bool = False
    chips_per_worker: int = 1
    cpus_per_worker: float = 1.0
    resources_per_worker: Dict[str, float] = field(default_factory=dict)
    placement_strategy: str = "PACK"
    # Pin the whole gang to label-matching nodes (every bundle gets this
    # hard selector) — on TPU clusters the auto-populated topology labels
    # make this the slice-targeting knob, e.g.
    # {"ca.io/tpu-slice-name": In("pod-a")} or
    # {"ca.io/tpu-generation": In("v5e")}.
    label_selector: Optional[Dict[str, Any]] = None
    # Elastic bounds (Train-v2 style); None disables elasticity.
    min_workers: Optional[int] = None
    max_workers: Optional[int] = None

    def bundle(self) -> Dict[str, float]:
        b: Dict[str, float] = {"CPU": float(self.cpus_per_worker)}
        if self.use_tpu:
            b["TPU"] = float(self.chips_per_worker)
        b.update(self.resources_per_worker)
        return b


@dataclass
class CheckpointConfig:
    """Keep-K checkpoint retention (reference train/_internal/checkpoint_manager.py)."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"  # "max" | "min"

    def __post_init__(self):
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")
        if self.num_to_keep is not None and self.num_to_keep <= 0:
            raise ValueError("num_to_keep must be positive or None")


@dataclass
class FailureConfig:
    """max_failures: worker-group restarts allowed before the run fails.
    -1 = unlimited (reference air/config.py FailureConfig).

    Preemption elasticity: with drain_aware on (default), the controller
    watches the drain plane's preemption warnings (`worker.draining_node_ids`)
    and, when a node hosting a gang member enters its drain window, asks
    every rank to checkpoint at the next step boundary
    (`train.should_checkpoint()`), waits up to preempt_barrier_timeout_s for
    the barrier, and rebuilds the group on survivors BEFORE the kill lands.
    Preemption-caused attempts never consume max_failures — an announced
    exit is the system's fault, not the application's (mirrors the drain
    plane's budget-exempt task retry)."""

    max_failures: int = 0
    drain_aware: bool = True
    preempt_barrier_timeout_s: float = 15.0


@dataclass
class RunConfig:
    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    verbose: int = 0
    # experiment callbacks (tune/callback.py): logger integrations etc.
    callbacks: List[Any] = field(default_factory=list)

    def resolved_storage_path(self) -> str:
        return os.path.expanduser(
            self.storage_path or os.environ.get("CA_STORAGE_PATH", "~/ca_results")
        )


@dataclass
class BackendConfig:
    """Base class for framework backend configs (reference train/backend/backend.py)."""

    def backend_cls(self):
        from .backend import Backend

        return Backend


@dataclass
class JaxConfig(BackendConfig):
    """JAX backend: optionally bootstrap `jax.distributed` across the worker
    group (multi-host TPU pods); on a single host it only exports rank env
    vars and lets each worker use its locally-visible chips.
    """

    init_jax_distributed: bool = False
    coordinator_port: int = 0  # 0 = pick a free port on rank-0's node

    def backend_cls(self):
        from .backend import JaxBackend

        return JaxBackend


@dataclass
class TrainingFailedError(Exception):
    """Raised by trainer.fit() when training failed after exhausting retries."""

    message: str = ""
    worker_errors: Any = None

    def __str__(self):
        return self.message or "training failed"


@dataclass
class TorchConfig(BackendConfig):
    """torch.distributed backend (reference train/torch/config.py
    TorchConfig): gloo for CPU hosts; init timeout mirrors the reference's
    default."""

    backend: str = "gloo"
    port: int = 0  # 0 = pick a free port on rank-0's node
    timeout_s: float = 1800.0

    def backend_cls(self):
        from .backend import TorchBackend

        return TorchBackend
