"""BackendExecutor: drives a WorkerGroup through one training run attempt.

Analogue of the reference's train/_internal/backend_executor.py:69
(`start`, `start_training`) — the driver-side polling loop lives in the
TrainController, this class owns group lifecycle + per-attempt start.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional

from .backend import Backend
from .checkpoint import Checkpoint
from .config import BackendConfig, RunConfig, ScalingConfig
from .worker_group import WorkerGroup


def _split_dataset(ds: Any, n: int) -> List[Any]:
    """Split a dataset-ish object into n per-worker shards."""
    if ds is None:
        return [None] * n
    if hasattr(ds, "streaming_split"):
        return ds.streaming_split(n)
    if hasattr(ds, "split"):
        return ds.split(n)
    if isinstance(ds, (list, tuple)):
        return [list(ds[i::n]) for i in range(n)]
    return [ds] * n


class BackendExecutor:
    def __init__(
        self,
        backend_config: BackendConfig,
        scaling_config: ScalingConfig,
        run_config: RunConfig,
        experiment_name: str,
    ):
        self.backend_config = backend_config
        self.backend: Backend = backend_config.backend_cls()()
        self.scaling_config = scaling_config
        self.run_config = run_config
        self.experiment_name = experiment_name
        self.worker_group: Optional[WorkerGroup] = None

    def start(self, num_workers: Optional[int] = None):
        n = num_workers or self.scaling_config.num_workers
        self.worker_group = WorkerGroup(
            num_workers=n,
            bundle=self.scaling_config.bundle(),
            placement_strategy=self.scaling_config.placement_strategy,
            label_selector=self.scaling_config.label_selector,
        )
        self.backend.on_start(self.worker_group, self.backend_config)

    def start_training(
        self,
        train_fn: Callable,
        train_fn_config: Optional[Dict[str, Any]],
        datasets: Optional[Dict[str, Any]],
        resume_checkpoint: Optional[Checkpoint],
        attempt: int = 0,
    ):
        wg = self.worker_group
        assert wg is not None, "call start() first"
        n = wg.num_workers
        storage = self.run_config.resolved_storage_path()
        trial_dir = os.path.join(storage, self.experiment_name)
        os.makedirs(trial_dir, exist_ok=True)
        shards: Dict[str, List[Any]] = {
            name: _split_dataset(ds, n) for name, ds in (datasets or {}).items()
        }
        local_ranks = wg.local_ranks()
        node_ranks = wg.node_ranks()
        self.backend.on_training_start(wg, self.backend_config)
        import cluster_anywhere_tpu as ca

        refs = []
        for rank, w in enumerate(wg.workers):
            ctx = dict(
                world_size=n,
                world_rank=rank,
                local_rank=local_ranks[rank],
                node_rank=node_ranks[rank],
                experiment_name=self.experiment_name,
                storage_path=storage,
                trial_dir=trial_dir,
                attempt=attempt,
            )
            refs.append(
                w.start_training.remote(
                    train_fn,
                    train_fn_config,
                    ctx,
                    {name: s[rank] for name, s in shards.items()},
                    resume_checkpoint.path if resume_checkpoint else None,
                )
            )
        ca.get(refs)

    def poll(self) -> List[Dict[str, Any]]:
        """Per-rank poll results; a dead rank yields a synthetic error
        entry instead of raising.  Resolving the batch with one ca.get
        would discard every SURVIVING rank's already-drained reports when
        any single ref raises — their poll() executed remotely (emptying
        the session deque) before the batch get failed, losing e.g. the
        barrier checkpoint report the preempt ack protocol just delivered."""
        import cluster_anywhere_tpu as ca

        assert self.worker_group is not None
        refs = [w.poll.remote() for w in self.worker_group.workers]
        out = []
        for ref in refs:
            try:
                out.append(ca.get(ref))
            except Exception as e:
                out.append(
                    {
                        "reports": [],
                        "done": False,
                        "error": f"worker actor lost: {e!r}",
                        "ckpt_acked": False,
                    }
                )
        return out

    def worker_node_ids(self) -> List[str]:
        """Per-rank node ids of the running group ([] before start())."""
        if self.worker_group is None:
            return []
        return self.worker_group.node_ids()

    def request_checkpoint(self) -> List[bool]:
        """Fan the checkpoint-on-preempt request out to every rank's
        session.  All requests launch up front and are gathered under ONE
        shared 2s window (ca.wait), not a per-rank timeout: N unreachable
        ranks on the dying node must cost 2s total, not 2s each — every
        second spent here comes out of the barrier window."""
        import cluster_anywhere_tpu as ca

        assert self.worker_group is not None
        refs = [w.request_checkpoint.remote() for w in self.worker_group.workers]
        ready, _ = ca.wait(refs, num_returns=len(refs), timeout=2.0)
        ready_set = set(ready)
        out = []
        for ref in refs:
            if ref not in ready_set:
                out.append(False)  # rank unreachable inside the window
                continue
            try:
                out.append(bool(ca.get(ref)))
            except Exception:
                out.append(False)
        return out

    def shutdown(self):
        if self.worker_group is not None:
            self.backend.on_shutdown(self.worker_group, self.backend_config)
            self.worker_group.shutdown()
            self.worker_group = None
