"""cluster_anywhere_tpu.train: distributed training on the actor runtime.

Same capability surface as the reference's Ray Train (v1 trainer API +
v2 controller/scaling/failure policies), TPU-first: the framework backend
is JAX — single-host needs no process-group bootstrap (a Mesh over local
chips suffices), multi-host bootstraps `jax.distributed`.

In-loop API (inside train_loop_per_worker):
    from cluster_anywhere_tpu import train
    train.report(metrics, checkpoint=...)
    train.get_checkpoint(); train.get_dataset_shard("train")
    train.get_context().get_world_rank()
"""

from .checkpoint import Checkpoint, CheckpointManager
from .config import (
    BackendConfig,
    CheckpointConfig,
    FailureConfig,
    JaxConfig,
    TorchConfig,
    RunConfig,
    ScalingConfig,
    TrainingFailedError,
)
from .controller import (
    ElasticScalingPolicy,
    FailureDecision,
    FailureKind,
    FailurePolicy,
    FixedScalingPolicy,
    Result,
    ScalingPolicy,
    TrainController,
)
from .session import (
    TrainContext,
    get_checkpoint,
    get_context,
    get_dataset_shard,
    make_temp_checkpoint_dir,
    report,
    shared_checkpoint_dir,
    should_checkpoint,
)
from .trainer import DataParallelTrainer, JaxTrainer
from .worker_group import TrainWorker, WorkerGroup

__all__ = [
    "Checkpoint",
    "CheckpointManager",
    "CheckpointConfig",
    "BackendConfig",
    "JaxConfig",
    "TorchConfig",
    "FailureConfig",
    "RunConfig",
    "ScalingConfig",
    "TrainingFailedError",
    "Result",
    "TrainController",
    "ScalingPolicy",
    "FixedScalingPolicy",
    "ElasticScalingPolicy",
    "FailurePolicy",
    "FailureDecision",
    "FailureKind",
    "TrainContext",
    "report",
    "get_checkpoint",
    "get_context",
    "get_dataset_shard",
    "make_temp_checkpoint_dir",
    "shared_checkpoint_dir",
    "should_checkpoint",
    "DataParallelTrainer",
    "JaxTrainer",
    "TrainWorker",
    "WorkerGroup",
]
