"""Checkpoint: a directory of files, referenced by path.

Analogue of the reference's `ray.train.Checkpoint`
(python/ray/train/_checkpoint.py) and `_CheckpointManager`
(train/_internal/checkpoint_manager.py: keep-K by score attribute).

TPU-first notes: model state is a JAX pytree; `save_pytree`/`load_pytree`
store it with numpy .npz + a structure pickle so checkpoints are
host-portable and never require the saving mesh to reload (arrays are
fetched to host with `jax.device_get`).
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
import threading
import time
import uuid
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .config import CheckpointConfig

_METADATA_FILE = ".ca_checkpoint_metadata.json"


def _atomic_write(path: str, write_fn, mode: str = "wb") -> None:
    """Write via unique tmp + rename, unlinking the tmp on failure: a
    preemption kill mid-write must never leave a truncated shard (or tmp
    litter) where a restore expects a file."""
    tmp = os.path.join(
        os.path.dirname(path), f".{os.path.basename(path)}.{uuid.uuid4().hex[:6]}.tmp"
    )
    try:
        with open(tmp, mode) as f:
            write_fn(f)
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def _atomic_savez(path: str, arrays: Dict[str, Any]) -> None:
    import numpy as np

    _atomic_write(path, lambda f: np.savez(f, **arrays))


def _atomic_write_json(path: str, obj: Any) -> None:
    _atomic_write(path, lambda f: json.dump(obj, f), mode="w")


class Checkpoint:
    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def to_directory(self, path: Optional[str] = None) -> str:
        """Copy checkpoint contents into `path` (default: a temp dir)."""
        dest = path or os.path.join(
            tempfile.gettempdir(), f"ca_ckpt_{uuid.uuid4().hex[:8]}"
        )
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    @contextmanager
    def as_directory(self):
        """Context manager yielding a local directory with the contents.
        Local-fs checkpoints are yielded in place (no copy)."""
        yield self.path

    # -- metadata --------------------------------------------------------
    def get_metadata(self) -> Dict[str, Any]:
        p = os.path.join(self.path, _METADATA_FILE)
        if os.path.exists(p):
            with open(p) as f:
                return json.load(f)
        return {}

    def set_metadata(self, metadata: Dict[str, Any]) -> None:
        with open(os.path.join(self.path, _METADATA_FILE), "w") as f:
            json.dump(metadata, f)

    # -- pytree helpers (TPU-first) --------------------------------------
    def save_pytree(self, tree: Any, name: str = "state") -> None:
        """Store a JAX/numpy pytree: leaves as .npz, structure pickled."""
        import numpy as np

        try:
            import jax

            leaves, treedef = jax.tree_util.tree_flatten(tree)
            leaves = [np.asarray(jax.device_get(x)) for x in leaves]
        except ImportError:  # numpy-only environments
            leaves, treedef = [np.asarray(tree)], None
        np.savez(
            os.path.join(self.path, f"{name}.npz"),
            **{f"leaf_{i}": leaf for i, leaf in enumerate(leaves)},
        )
        with open(os.path.join(self.path, f"{name}.treedef.pkl"), "wb") as f:
            pickle.dump(treedef, f)

    def load_pytree(self, name: str = "state") -> Any:
        import numpy as np

        with np.load(os.path.join(self.path, f"{name}.npz")) as z:
            leaves = [z[f"leaf_{i}"] for i in range(len(z.files))]
        with open(os.path.join(self.path, f"{name}.treedef.pkl"), "rb") as f:
            treedef = pickle.load(f)
        if treedef is None:
            return leaves[0]
        import jax

        return jax.tree_util.tree_unflatten(treedef, leaves)

    # -- sharded pytree helpers (multi-process / topology-portable) -------
    #
    # `save_pytree` gathers the WHOLE state onto every host (jax.device_get),
    # which cannot run under a multi-process mesh where each process
    # addresses only its own devices' shards — and it forces the restoring
    # mesh to fit the full state per host.  The sharded variant writes, per
    # process, only the shards that process can address:
    #
    #   {name}.shard<p>.npz   chunk arrays (this process's replica-0 shards)
    #   {name}.shard<p>.json  chunk -> (leaf index, global index box)
    #   {name}.index.json     world size + per-leaf global shape/dtype (rank 0)
    #   {name}.treedef.pkl    pytree structure (rank 0)
    #
    # Restore stitches any target sharding from whatever chunking the SAVING
    # mesh used (parallel/sharding.py extract_region), so a checkpoint
    # written by an 8-process world reshards onto the 6-process mesh the
    # surviving nodes form — optimizer state re-laid-out included (cf.
    # automatic cross-replica sharding, arxiv 2004.13336).

    def is_sharded(self, name: Optional[str] = None) -> bool:
        """Does this checkpoint hold a rank-cooperative sharded pytree?
        With name=None (the session's register-in-place check) ANY sharded
        save counts, whatever it was named; any rank's shard manifest
        suffices — rank 0's index may not have landed yet while the barrier
        is still draining."""
        try:
            files = os.listdir(self.path)
        except OSError:
            return False
        if name is None:
            return any(
                f.endswith(".index.json")
                or (".shard" in f and f.endswith(".json"))
                for f in files
            )
        return any(
            f == f"{name}.index.json"
            or (f.startswith(f"{name}.shard") and f.endswith(".json"))
            for f in files
        )

    def save_pytree_sharded(
        self,
        tree: Any,
        name: str = "state",
        process_index: Optional[int] = None,
        num_processes: Optional[int] = None,
    ) -> None:
        """Store this process's addressable shards of a (possibly only
        partially addressable) global pytree.  Every rank of a gang calls
        this against the SAME directory; each jax.Array leaf contributes its
        replica-0 device shards with their global index boxes, non-array
        leaves are written whole by process 0.  Writes are atomic
        (tmp + rename) so a preemption kill mid-save never leaves a
        half-written shard for the restore to trip on."""
        import numpy as np

        from ..parallel.sharding import index_box

        try:
            import jax

            leaves, treedef = jax.tree_util.tree_flatten(tree)
            if process_index is None:
                process_index = jax.process_index()
            if num_processes is None:
                num_processes = jax.process_count()
        except ImportError:  # numpy-only environments
            arr = np.asarray(tree)
            if arr.dtype == object:
                # np.asarray on a dict/mixed tree yields an object array:
                # savez would happily pickle it, but np.load(allow_pickle=
                # False) on restore cannot read it back — the data would be
                # unrecoverable.  Fail at save time, not resume time.
                raise TypeError(
                    "save_pytree_sharded without jax supports only a "
                    "single array-like tree; got a structure that "
                    "numpy can only represent as an object array"
                )
            leaves, treedef = [arr], None
            process_index = process_index or 0
            num_processes = num_processes or 1
        chunks: Dict[str, Any] = {}
        meta: List[Dict[str, Any]] = []
        leaf_specs: List[Dict[str, Any]] = []

        def _add(leaf_i: int, box: list, data) -> None:
            key = f"l{leaf_i}c{len(meta)}"
            chunks[key] = data
            meta.append({"leaf": leaf_i, "key": key, "box": box})

        for i, leaf in enumerate(leaves):
            shards = getattr(leaf, "addressable_shards", None)
            # attribute reads only: np.asarray on a partially-addressable
            # global array would try to fetch remote shards and raise
            if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
                shape = tuple(leaf.shape)
                dtype = str(leaf.dtype)
            else:
                arr = np.asarray(leaf)
                shape, dtype = arr.shape, str(arr.dtype)
            leaf_specs.append({"shape": list(shape), "dtype": dtype})
            if shards is not None:
                for sh in shards:
                    if sh.replica_id != 0:
                        continue  # one writer per distinct global shard
                    _add(i, index_box(sh.index, shape), np.asarray(sh.data))
            elif process_index == 0:
                arr = np.asarray(leaf)
                if arr.dtype == object:
                    # savez would pickle an object array silently, but
                    # np.load(allow_pickle=False) on restore can never
                    # read it back — and sharded_complete (manifest-only)
                    # would keep steering resume into the poisoned dir.
                    # Fail at save time, not resume time.
                    raise TypeError(
                        f"save_pytree_sharded: leaf {i} is not array-like "
                        "(numpy can only represent it as an object array, "
                        "which a pickle-free restore cannot read)"
                    )
                _add(i, [[0, d] for d in arr.shape], arr)
        _atomic_savez(
            os.path.join(self.path, f"{name}.shard{process_index}.npz"), chunks
        )
        _atomic_write_json(
            os.path.join(self.path, f"{name}.shard{process_index}.json"),
            {"process_index": process_index, "chunks": meta},
        )
        if process_index == 0:
            _atomic_write_json(
                os.path.join(self.path, f"{name}.index.json"),
                {
                    "version": 1,
                    "world_size": num_processes,
                    "num_leaves": len(leaves),
                    "leaves": leaf_specs,
                },
            )
            _atomic_write(
                os.path.join(self.path, f"{name}.treedef.pkl"),
                lambda f: pickle.dump(treedef, f),
            )
            # overwriting a dir saved by a LARGER world leaves stale
            # high-rank shards behind whose boxes would double-cover the
            # leaves and fail the restore's coverage check — sweep them
            for fn in os.listdir(self.path):
                pref = f"{name}.shard"
                if not fn.startswith(pref):
                    continue
                rank_str = fn[len(pref):].split(".", 1)[0]
                if rank_str.isdigit() and int(rank_str) >= num_processes:
                    try:
                        os.unlink(os.path.join(self.path, fn))
                    except OSError:
                        pass

    def _read_shard_directory(self, name: str = "state"):
        """Read a sharded checkpoint's manifests (no array loads): returns
        (index, per-leaf chunk directory) and raises ValueError when the
        chunk boxes do not fully cover every leaf — the coverage check that
        keeps a missing rank's shard from silently zero-filling a restore."""
        import glob as _glob

        from ..parallel.sharding import boxes_cover

        with open(os.path.join(self.path, f"{name}.index.json")) as f:
            index = json.load(f)
        if not os.path.exists(os.path.join(self.path, f"{name}.treedef.pkl")):
            # rank 0 writes the treedef LAST: an index without it means the
            # save was killed between the two writes — restorable data but
            # no structure to unflatten into, so the dir is incomplete
            raise ValueError(
                f"incomplete sharded checkpoint {self.path!r}: "
                f"{name}.treedef.pkl never landed"
            )
        # chunk directory: leaf -> [(box, shard_npz_path, key)]
        per_leaf: List[List[Tuple[list, str, str]]] = [
            [] for _ in range(index["num_leaves"])
        ]
        for mpath in sorted(
            _glob.glob(os.path.join(self.path, f"{name}.shard*.json"))
        ):
            with open(mpath) as f:
                m = json.load(f)
            if int(m.get("process_index", 0)) >= index["world_size"]:
                # stale shard from an earlier larger-world save into this
                # dir (save-side sweep may not have run against it)
                continue
            npz = mpath[: -len(".json")] + ".npz"
            for c in m["chunks"]:
                if not 0 <= c["leaf"] < index["num_leaves"]:
                    # a manifest left over from a save with a DIFFERENT
                    # tree structure: corrupt, not merely incomplete
                    raise ValueError(
                        f"sharded checkpoint {self.path!r}: manifest "
                        f"{os.path.basename(mpath)} references leaf "
                        f"{c['leaf']} but the index has "
                        f"{index['num_leaves']} leaves"
                    )
                per_leaf[c["leaf"]].append((c["box"], npz, c["key"]))
        for i, spec in enumerate(index["leaves"]):
            if not boxes_cover([b for b, _, _ in per_leaf[i]], spec["shape"]):
                raise ValueError(
                    f"incomplete sharded checkpoint {self.path!r}: leaf {i} "
                    f"(shape {spec['shape']}) is not fully covered by the "
                    f"saved shards — a rank's shard file is missing"
                )
        return index, per_leaf

    def sharded_complete(self, name: Optional[str] = None) -> bool:
        """Cheap (manifest-only) validity probe: does every leaf have full
        shard coverage?  False for a dir where a rank's save never landed
        (killed mid-write) — the controller skips such checkpoints when
        picking a resume point instead of retrying into the same error.
        name=None validates every sharded save in the dir (whatever names
        the loop used); a dir with shard files but no index (rank 0 never
        finished) is incomplete by definition."""
        try:
            if name is None:
                names = [
                    f[: -len(".index.json")]
                    for f in os.listdir(self.path)
                    if f.endswith(".index.json")
                ]
                if not names:
                    return False  # no index landed: not restorable at all
            else:
                names = [name]
            for nm in names:
                self._read_shard_directory(nm)
            return True
        except (OSError, ValueError, KeyError, json.JSONDecodeError):
            return False

    def load_pytree_sharded(
        self,
        name: str = "state",
        mesh: Any = None,
        specs: Any = None,
    ) -> Any:
        """Restore a sharded pytree, resharding onto `mesh`.

        mesh=None: assemble full host (numpy) arrays — the single-process /
        inspection path.  With a mesh: `specs` gives the target layout (a
        matching pytree of PartitionSpec, one spec for every leaf, or None =
        fully replicated) and each leaf materializes via
        jax.make_array_from_callback, so every process reads only the saved
        chunks overlapping ITS addressable shards.  The saving and restoring
        world sizes are independent: coverage is validated from the chunk
        boxes, and a missing rank's shard raises instead of zero-filling."""
        import numpy as np

        from ..parallel.sharding import extract_region

        index, per_leaf = self._read_shard_directory(name)
        with open(os.path.join(self.path, f"{name}.treedef.pkl"), "rb") as f:
            treedef = pickle.load(f)
        files: Dict[str, Any] = {}

        def _load(npz: str, key: str):
            if npz not in files:
                files[npz] = np.load(npz)
            return files[npz][key]

        def _chunks(leaf_i: int, want: Optional[list] = None) -> List[Tuple[list, Any]]:
            """Materialize leaf chunks — only the ones intersecting `want`
            when given.  npz members decompress per key, so a process
            restoring onto a mesh reads ONLY the saved bytes overlapping
            its own shards, not the whole global array."""
            out = []
            for box, npz, key in per_leaf[leaf_i]:
                if want is not None and any(
                    max(b[0], w[0]) >= min(b[1], w[1])
                    for b, w in zip(box, want)
                ):
                    continue  # no (non-empty) intersection with the request
                out.append((box, _load(npz, key)))
            return out

        try:
            if mesh is None:
                from ..parallel.sharding import box_volume

                # zero-sized leaves rebuild from the index's recorded
                # shape/dtype alone — they may have no chunk at all (a
                # zero-volume leaf passes coverage vacuously), and there
                # are no elements to read anyway
                leaves = [
                    np.empty(tuple(spec["shape"]), dtype=spec["dtype"])
                    if spec["shape"]
                    and box_volume([[0, d] for d in spec["shape"]]) == 0
                    else extract_region(
                        [[0, d] for d in spec["shape"]], _chunks(i)
                    )
                    for i, spec in enumerate(index["leaves"])
                ]
            else:
                import jax
                from jax.sharding import NamedSharding, PartitionSpec

                from ..parallel.sharding import index_box

                n = index["num_leaves"]
                if specs is None:
                    spec_list = [PartitionSpec()] * n
                elif isinstance(specs, PartitionSpec):
                    spec_list = [specs] * n
                else:
                    spec_list, _ = jax.tree_util.tree_flatten(
                        specs,
                        is_leaf=lambda x: x is None
                        or isinstance(x, PartitionSpec),
                    )
                    if len(spec_list) != n:
                        raise ValueError(
                            f"specs pytree has {len(spec_list)} leaves, "
                            f"checkpoint has {n}"
                        )
                from ..parallel.sharding import box_shape, box_volume

                def _region(idx, leaf_i, shape, dtype):
                    box = index_box(idx, shape)
                    if box_volume(box) == 0:
                        # an empty target shard has nothing to read — the
                        # index records shape/dtype, no chunk IO needed
                        return np.empty(box_shape(box), dtype=dtype)
                    return extract_region(box, _chunks(leaf_i, want=box))

                leaves = []
                for i, spec in enumerate(index["leaves"]):
                    shape, dtype = tuple(spec["shape"]), spec["dtype"]
                    sharding = NamedSharding(
                        mesh, spec_list[i] or PartitionSpec()
                    )
                    leaves.append(
                        jax.make_array_from_callback(
                            shape,
                            sharding,
                            lambda idx, _i=i, _s=shape, _d=dtype: _region(
                                idx, _i, _s, _d
                            ),
                        )
                    )
        finally:
            for z in files.values():
                z.close()
        if treedef is None:
            return leaves[0]
        import jax

        return jax.tree_util.tree_unflatten(treedef, leaves)

    def __repr__(self):
        return f"Checkpoint(path={self.path!r})"

    def __reduce__(self):
        return (Checkpoint, (self.path,))


@dataclass
class _TrackedCheckpoint:
    checkpoint: Checkpoint
    index: int
    metrics: Dict[str, Any] = field(default_factory=dict)


class CheckpointManager:
    """Registers reported checkpoints, retains the top-K by the configured
    score attribute, deletes evicted checkpoint directories."""

    def __init__(self, config: Optional[CheckpointConfig] = None):
        self.config = config or CheckpointConfig()
        self._lock = threading.Lock()
        self._checkpoints: List[_TrackedCheckpoint] = []
        self._pending_delete: List[Checkpoint] = []
        self._next_index = 0

    def register(
        self, checkpoint: Checkpoint, metrics: Optional[Dict[str, Any]] = None
    ) -> _TrackedCheckpoint:
        with self._lock:
            tracked = _TrackedCheckpoint(checkpoint, self._next_index, metrics or {})
            self._next_index += 1
            # a re-registered path supersedes its older entry (dropped
            # WITHOUT deleting: they share the directory).  Rank-shared
            # sharded dirs are keyed by step, so a retry attempt that
            # re-runs a step re-saves into — and re-registers — the same
            # dir; two tracked entries aliasing one path would let keep-K
            # eviction of the stale entry rmtree the live checkpoint
            self._checkpoints = [
                t
                for t in self._checkpoints
                if t.checkpoint.path != checkpoint.path
            ]
            self._checkpoints.append(tracked)
            self._evict_locked()
            return tracked

    def _score(self, t: _TrackedCheckpoint) -> Tuple[float, int]:
        attr = self.config.checkpoint_score_attribute
        sign = 1.0 if self.config.checkpoint_score_order == "max" else -1.0
        if attr is None or attr not in t.metrics:
            # fall back to recency so unscored checkpoints behave FIFO
            return (float("-inf"), t.index)
        return (sign * float(t.metrics[attr]), t.index)

    # sharded dirs written to this recently may have a lagging rank still
    # mid-save (register-in-place: every rank writes the SAME dir, and the
    # driver registers on rank 0's report, not on all ranks finishing) —
    # deleting under the writer would error that rank and charge the
    # attempt to max_failures for an eviction race
    _SHARDED_EVICT_GRACE_S = 60.0

    def _evict_locked(self):
        # retry deferred deletions FIRST, even when nothing new gets
        # evicted this pass — the early return below must not strand them
        pending, self._pending_delete = self._pending_delete, []
        for ck in pending:
            self._delete_or_defer(ck)
        k = self.config.num_to_keep
        if k is None or len(self._checkpoints) <= k:
            return
        latest = self._checkpoints[-1]
        ranked = sorted(self._checkpoints, key=self._score, reverse=True)
        keep = ranked[:k]
        if latest not in keep:  # the latest is always kept for resume
            keep = keep[: k - 1] + [latest]
        keep_paths = {t.checkpoint.path for t in keep}
        for t in self._checkpoints:
            if t not in keep and t.checkpoint.path not in keep_paths:
                self._delete_or_defer(t.checkpoint)
        self._checkpoints = [t for t in self._checkpoints if t in keep]

    def finalize(self):
        """Run teardown: force-delete evictions still deferred by the
        write-grace window.  The grace protects lagging ranks mid-save;
        once the worker group is down there are no writers left, and
        leaving the dirs would quietly turn keep-K into keep-K-plus-tail
        (multi-GB state per leaked dir)."""
        with self._lock:
            pending, self._pending_delete = self._pending_delete, []
            for ck in pending:
                shutil.rmtree(ck.path, ignore_errors=True)

    def _delete_or_defer(self, ck: Checkpoint) -> None:
        """rmtree an evicted checkpoint dir, unless it is a sharded dir
        whose files changed within the grace window (a lagging rank may
        still be writing) — those go to the pending list and are retried
        on the next eviction."""
        try:
            if ck.is_sharded() and (
                time.time() - os.path.getmtime(ck.path)
                < self._SHARDED_EVICT_GRACE_S
            ):
                self._pending_delete.append(ck)
                return
        except OSError:
            pass  # already gone / unreadable: fall through to rmtree
        shutil.rmtree(ck.path, ignore_errors=True)

    @property
    def best_checkpoint(self) -> Optional[Checkpoint]:
        with self._lock:
            if not self._checkpoints:
                return None
            return max(self._checkpoints, key=self._score).checkpoint

    @property
    def latest_checkpoint(self) -> Optional[Checkpoint]:
        with self._lock:
            if not self._checkpoints:
                return None
            return self._checkpoints[-1].checkpoint

    def checkpoints_newest_first(self) -> List[Checkpoint]:
        """Registration order, newest first — the controller walks this to
        find the newest RESUMABLE checkpoint (skipping sharded dirs whose
        ranks were killed mid-save)."""
        with self._lock:
            return [t.checkpoint for t in reversed(self._checkpoints)]

    def best_checkpoints(self) -> List[Tuple[Checkpoint, Dict[str, Any]]]:
        with self._lock:
            ranked = sorted(self._checkpoints, key=self._score, reverse=True)
            return [(t.checkpoint, dict(t.metrics)) for t in ranked]
