"""Checkpoint: a directory of files, referenced by path.

Analogue of the reference's `ray.train.Checkpoint`
(python/ray/train/_checkpoint.py) and `_CheckpointManager`
(train/_internal/checkpoint_manager.py: keep-K by score attribute).

TPU-first notes: model state is a JAX pytree; `save_pytree`/`load_pytree`
store it with numpy .npz + a structure pickle so checkpoints are
host-portable and never require the saving mesh to reload (arrays are
fetched to host with `jax.device_get`).
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import tempfile
import threading
import uuid
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .config import CheckpointConfig

_METADATA_FILE = ".ca_checkpoint_metadata.json"


class Checkpoint:
    def __init__(self, path: str):
        self.path = os.path.abspath(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def to_directory(self, path: Optional[str] = None) -> str:
        """Copy checkpoint contents into `path` (default: a temp dir)."""
        dest = path or os.path.join(
            tempfile.gettempdir(), f"ca_ckpt_{uuid.uuid4().hex[:8]}"
        )
        if os.path.abspath(dest) != self.path:
            shutil.copytree(self.path, dest, dirs_exist_ok=True)
        return dest

    @contextmanager
    def as_directory(self):
        """Context manager yielding a local directory with the contents.
        Local-fs checkpoints are yielded in place (no copy)."""
        yield self.path

    # -- metadata --------------------------------------------------------
    def get_metadata(self) -> Dict[str, Any]:
        p = os.path.join(self.path, _METADATA_FILE)
        if os.path.exists(p):
            with open(p) as f:
                return json.load(f)
        return {}

    def set_metadata(self, metadata: Dict[str, Any]) -> None:
        with open(os.path.join(self.path, _METADATA_FILE), "w") as f:
            json.dump(metadata, f)

    # -- pytree helpers (TPU-first) --------------------------------------
    def save_pytree(self, tree: Any, name: str = "state") -> None:
        """Store a JAX/numpy pytree: leaves as .npz, structure pickled."""
        import numpy as np

        try:
            import jax

            leaves, treedef = jax.tree_util.tree_flatten(tree)
            leaves = [np.asarray(jax.device_get(x)) for x in leaves]
        except ImportError:  # numpy-only environments
            leaves, treedef = [np.asarray(tree)], None
        np.savez(
            os.path.join(self.path, f"{name}.npz"),
            **{f"leaf_{i}": leaf for i, leaf in enumerate(leaves)},
        )
        with open(os.path.join(self.path, f"{name}.treedef.pkl"), "wb") as f:
            pickle.dump(treedef, f)

    def load_pytree(self, name: str = "state") -> Any:
        import numpy as np

        with np.load(os.path.join(self.path, f"{name}.npz")) as z:
            leaves = [z[f"leaf_{i}"] for i in range(len(z.files))]
        with open(os.path.join(self.path, f"{name}.treedef.pkl"), "rb") as f:
            treedef = pickle.load(f)
        if treedef is None:
            return leaves[0]
        import jax

        return jax.tree_util.tree_unflatten(treedef, leaves)

    def __repr__(self):
        return f"Checkpoint(path={self.path!r})"

    def __reduce__(self):
        return (Checkpoint, (self.path,))


@dataclass
class _TrackedCheckpoint:
    checkpoint: Checkpoint
    index: int
    metrics: Dict[str, Any] = field(default_factory=dict)


class CheckpointManager:
    """Registers reported checkpoints, retains the top-K by the configured
    score attribute, deletes evicted checkpoint directories."""

    def __init__(self, config: Optional[CheckpointConfig] = None):
        self.config = config or CheckpointConfig()
        self._lock = threading.Lock()
        self._checkpoints: List[_TrackedCheckpoint] = []
        self._next_index = 0

    def register(
        self, checkpoint: Checkpoint, metrics: Optional[Dict[str, Any]] = None
    ) -> _TrackedCheckpoint:
        with self._lock:
            tracked = _TrackedCheckpoint(checkpoint, self._next_index, metrics or {})
            self._next_index += 1
            self._checkpoints.append(tracked)
            self._evict_locked()
            return tracked

    def _score(self, t: _TrackedCheckpoint) -> Tuple[float, int]:
        attr = self.config.checkpoint_score_attribute
        sign = 1.0 if self.config.checkpoint_score_order == "max" else -1.0
        if attr is None or attr not in t.metrics:
            # fall back to recency so unscored checkpoints behave FIFO
            return (float("-inf"), t.index)
        return (sign * float(t.metrics[attr]), t.index)

    def _evict_locked(self):
        k = self.config.num_to_keep
        if k is None or len(self._checkpoints) <= k:
            return
        latest = self._checkpoints[-1]
        ranked = sorted(self._checkpoints, key=self._score, reverse=True)
        keep = ranked[:k]
        if latest not in keep:  # the latest is always kept for resume
            keep = keep[: k - 1] + [latest]
        for t in self._checkpoints:
            if t not in keep:
                shutil.rmtree(t.checkpoint.path, ignore_errors=True)
        self._checkpoints = [t for t in self._checkpoints if t in keep]

    @property
    def best_checkpoint(self) -> Optional[Checkpoint]:
        with self._lock:
            if not self._checkpoints:
                return None
            return max(self._checkpoints, key=self._score).checkpoint

    @property
    def latest_checkpoint(self) -> Optional[Checkpoint]:
        with self._lock:
            if not self._checkpoints:
                return None
            return self._checkpoints[-1].checkpoint

    def best_checkpoints(self) -> List[Tuple[Checkpoint, Dict[str, Any]]]:
        with self._lock:
            ranked = sorted(self._checkpoints, key=self._score, reverse=True)
            return [(t.checkpoint, dict(t.metrics)) for t in ranked]
