"""Compiled graphs: a static DAG of actor-method calls executed as per-actor
loops with shared-memory channel I/O instead of per-call RPC (analogue of the
reference's ray.dag — dag_node.py / compiled_dag_node.py:767 CompiledDAG).

Usage:
    with InputNode() as inp:
        x = a.step.bind(inp)
        y = b.step.bind(x)
    dag = y  # or MultiOutputNode([x, y])
    out_ref = dag.execute(5)            # eager: per-call task submission
    compiled = dag.experimental_compile()
    fut = compiled.execute(5)           # channel-driven, driver out of hot loop
    fut.get()
"""

from .node import (
    ClassMethodNode,
    DAGNode,
    FunctionNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
)
from ..core.errors import DagTimeoutError, DeadActorError
from .compiled import DAG_STATS, CompiledDAG, CompiledDAGRef

__all__ = [
    "DagTimeoutError",
    "DeadActorError",
    "DAGNode",
    "InputNode",
    "InputAttributeNode",
    "FunctionNode",
    "ClassMethodNode",
    "MultiOutputNode",
    "CompiledDAG",
    "CompiledDAGRef",
]
