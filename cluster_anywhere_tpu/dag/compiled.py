"""CompiledDAG: turn a DAG of actor-method nodes into per-actor executable
loops wired with shared-memory channels (analogue of the reference's
dag/compiled_dag_node.py:767 CompiledDAG + :446 ExecutableTask; hot-path
semantics per §3.6 of SURVEY.md — the driver leaves the per-call RPC loop).

Compilation:
  - every compute node must be a ClassMethodNode (actor-owned);
  - edges between different processes become BufferedShmChannels
    (num_buffers = max_inflight_executions, giving pipelined backpressure);
  - same-actor edges pass values in memory within a tick;
  - the driver writes one input channel per execute() and reads the output
    channels; errors are forwarded through the graph as _DagError payloads.
"""

from __future__ import annotations

import traceback
from typing import Any, Dict, List, Optional, Tuple

from ..channel.shm_channel import (
    BufferedShmChannel,
    ChannelClosedError,
    open_channel,
)
from ..core.errors import DagTimeoutError, DeadActorError
from ..util import flightrec
from .node import (
    ClassMethodNode,
    DAGNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
)

# driver-side DAG-plane counters (plain ints, same contract as
# channel.shm_channel.CHANNEL_STATS): util/metrics delta-ships them as
# ca_dag_* cluster counters; `ca status` and util.state.dag_plane() read the
# aggregate
DAG_STATS = {
    "compiles": 0,            # CompiledDAG graphs compiled (incl. recompiles)
    "recompiles": 0,          # rebuilds after an actor restart
    "executions": 0,          # execute() submissions
    "results": 0,             # ticks whose outputs the driver consumed
    "backpressure_waits": 0,  # execute() blocked at max_inflight_executions
    "timeouts": 0,            # DagTimeoutError raised
    "actor_deaths": 0,        # DeadActorError raised (loop died mid-execute)
    "teardowns": 0,           # teardown() completions
}

# driver poll slice while waiting on channels: short enough that actor death
# surfaces promptly, long enough that a healthy tick never pays for it (the
# futex read wakes on publish, not at the slice boundary)
_DEATH_POLL_S = 0.2


class _TraceEnv:
    """Trace context riding a channel payload (tentpole: span id in channel
    meta).  The driver wraps the input payload with its ambient context, each
    actor re-wraps its cross-process writes with its own tick span, so a
    compiled-DAG tick renders in `ca timeline` as one connected trace.  Only
    minted while a trace is active — untraced ticks ship bare payloads and
    pay nothing but one isinstance on the read side."""

    __slots__ = ("tr", "value")

    def __init__(self, tr, value):
        self.tr = tr
        self.value = value

    def __reduce__(self):
        return (_TraceEnv, (self.tr, self.value))


class _DagError:
    """An execution error traveling through channels."""

    def __init__(self, exc: BaseException):
        self.exc = exc
        self.tb = traceback.format_exc()

    def raise_(self):
        raise self.exc


def _extract_input(input_payload, key):
    """input_payload is (args tuple, kwargs dict)."""
    args, kwargs = input_payload
    if key is None:
        if not kwargs and len(args) == 1:
            return args[0]
        return (args, kwargs) if kwargs else tuple(args)
    if isinstance(key, int):
        return args[key]
    if key in kwargs:
        return kwargs[key]
    raise KeyError(key)


def _dag_actor_loop(instance, schedule: List[tuple], node_ops: Dict[int, dict],
                    reader_specs: Dict[int, Tuple[dict, int]],
                    writer_specs: Dict[int, dict], timeout: float):
    """Runs inside the actor (via the __ca_exec__ builtin): loop until the
    input side closes, executing this actor's operation schedule each tick.

    `schedule` is this actor's projection of the global operation schedule
    (dag/operation.py, reference dag_node_operation.py): an ordered list of
    ("read", channel_id) / ("compute", node_id) / ("write", node_id) ops.
    Scheduled reads replace lazy ones — the schedule is a slice of one
    global topological order, so a blocking read here can never deadlock
    against another actor's schedule, and each channel is read exactly once
    per tick (readers desynchronize from writers otherwise)."""
    readers = {nid: open_channel(spec, ridx) for nid, (spec, ridx) in reader_specs.items()}
    writers = {nid: open_channel(spec) for nid, spec in writer_specs.items()}
    tensor_chans = {nid for nid, (spec, _) in reader_specs.items() if spec.get("tensor")}
    tensor_writers = {nid for nid, spec in writer_specs.items() if spec.get("tensor")}

    def _to_device(v):
        """with_tensor_transport consumer side: DeviceEnvelopes land their
        shards directly on local devices under the producer's sharding;
        legacy plain-ndarray payloads re-enter the default device."""
        import jax
        import numpy as _np

        from ..channel.device_transport import DeviceEnvelope, unpack_device_value

        if isinstance(v, DeviceEnvelope):
            return unpack_device_value(v)
        return jax.tree.map(
            lambda x: jax.device_put(x) if isinstance(x, _np.ndarray) else x, v
        )

    def _pack_tensor(v):
        """with_tensor_transport producer side: decompose array leaves into
        per-shard zero-copy buffer borrows (no host assembly, no pickle of
        array bytes; sharding metadata rides along)."""
        from ..channel.device_transport import pack_device_value

        return pack_device_value(v)

    try:
        from ..util import tracing as _trc
    except Exception:  # pragma: no cover — tracing must never kill the loop
        _trc = None
    import time as _time

    ticks = 0
    try:
        while True:
            chan_vals: Dict[int, Any] = {}
            tick_vals: Dict[int, Any] = {}
            err: Optional[_DagError] = None
            closed = False
            tick_tok = None  # trace token: set by the first enveloped read
            tick_t0 = 0.0

            def resolve(spec):
                kind, ref = spec
                if kind == "const":
                    return ref
                if kind == "chan":
                    return chan_vals[ref]
                if kind == "local":
                    return tick_vals[ref]
                if kind == "input":
                    payload = chan_vals[ref[0]]
                    if isinstance(payload, _DagError):
                        return payload
                    return _extract_input(payload, ref[1])
                raise ValueError(kind)

            for kind, ref in schedule:
                try:
                    if kind == "read":
                        # block without deadline: teardown closes the channel
                        # to wake us
                        v = readers[ref].read(None)
                        if isinstance(v, _TraceEnv):
                            # channel meta: adopt the upstream trace for this
                            # tick (first envelope wins) before touching the
                            # payload, so tensor landing runs inside the span
                            if tick_tok is None and _trc is not None:
                                tick_tok = _trc.push_execution(v.tr)
                                tick_t0 = _time.time()
                            v = v.value
                        if ref in tensor_chans and not isinstance(v, _DagError):
                            try:
                                v = _to_device(v)
                            except BaseException as e:  # noqa: BLE001
                                # a bad landing (device OOM, shard-spec
                                # mismatch) is this tick's error, not the
                                # loop's death: forward it to the driver
                                v = _DagError(e)
                                err = err or v
                        chan_vals[ref] = v
                    elif kind == "compute":
                        op = node_ops[ref]
                        if err is not None:
                            # actor-local poisoning: once an op on this actor
                            # fails in a tick, later ops forward the error so
                            # the driver sees the root cause, not knock-ons
                            result = err
                        else:
                            try:
                                args = [resolve(s) for s in op["args"]]
                                kwargs = {k: resolve(s) for k, s in op["kwargs"].items()}
                                bad = next((a for a in args + list(kwargs.values())
                                            if isinstance(a, _DagError)), None)
                                if bad is not None:
                                    result = bad
                                else:
                                    result = getattr(instance, op["method"])(*args, **kwargs)
                            except BaseException as e:  # noqa: BLE001 — forwarded to driver
                                result = _DagError(e)
                                err = result
                        tick_vals[ref] = result
                    else:  # write
                        out = tick_vals[ref]
                        if ref in tensor_writers and not isinstance(out, _DagError):
                            try:
                                out = _pack_tensor(out)
                            except BaseException as e:  # noqa: BLE001 — surfaced to driver
                                out = _DagError(e)
                                err = err or out
                        if tick_tok is not None:
                            # re-wrap under THIS actor's tick span: the next
                            # hop (actor or driver) parents on it, chaining
                            # the channel ops into one causal trace
                            cur = _trc.current()
                            if cur is not None:
                                out = _TraceEnv(
                                    {"tid": cur["tid"], "sid": cur["sid"]}, out
                                )
                        writers[ref].write(out, timeout)
                except ChannelClosedError:
                    closed = True
                    break
            if tick_tok is not None:
                if not closed:
                    cur = _trc.current()
                    w = _trc._current_worker()
                    _trc.record_task_event(
                        "", "dag:tick", "span", "SPAN",
                        trace=cur,
                        worker_id=w.client_id if w is not None else None,
                        node_id=w.node_id if w is not None else None,
                        start=tick_t0,
                        end=_time.time(),
                    )
                _trc.pop_execution(tick_tok)
            if closed:
                break
            ticks += 1
    finally:
        for w in writers.values():
            w.close()
    return {"ticks": ticks}


class CompiledDAGRef:
    """Future for one execute() (reference: CompiledDAGRef)."""

    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq
        self._consumed = False

    def get(self, timeout: Optional[float] = None):
        if self._consumed:
            raise ValueError("CompiledDAGRef.get() may only be called once")
        result = self._dag._read_result(self._seq, timeout)
        # only mark consumed on success: a TimeoutError leaves the ref
        # retryable (the DAG's partial-read state keeps channels aligned)
        self._consumed = True
        return result

    def __repr__(self):
        return f"CompiledDAGRef(seq={self._seq})"

    async def get_async(self, timeout: Optional[float] = None):
        """Awaitable result read; the blocking channel read runs off-loop."""
        import asyncio

        return await asyncio.to_thread(self.get, timeout)

    def __await__(self):
        return self.get_async().__await__()


class CompiledDAG:
    def __init__(self, root: DAGNode, max_inflight_executions: int = 2,
                 buffer_size: Optional[int] = None,
                 execute_timeout_s: Optional[float] = None):
        from ..core.config import get_config

        self._root = root
        self._max_inflight = max(1, max_inflight_executions)
        self._buffer_size = buffer_size or 8 * 1024 * 1024
        self._timeout = (
            execute_timeout_s if execute_timeout_s is not None
            else get_config().dag_execute_timeout_s
        )
        self._torn_down = False
        self._dead: Optional[DeadActorError] = None
        self._exec_seq = 0
        self._read_seq = 0
        self._result_cache: Dict[int, Any] = {}
        self._compile()

    # ------------------------------------------------------------------ build

    def _compile(self):
        nodes = self._root._walk()
        self._input_node: Optional[InputNode] = None
        compute: List[ClassMethodNode] = []
        output_leaves: List[DAGNode] = []
        root = self._root
        if isinstance(root, MultiOutputNode):
            output_leaves = list(root._upstream())
        else:
            output_leaves = [root]
        for n in nodes:
            if isinstance(n, InputNode):
                if self._input_node is not None and n is not self._input_node:
                    raise ValueError("compiled DAGs support a single InputNode")
                self._input_node = n
            elif isinstance(n, (InputAttributeNode, MultiOutputNode)):
                pass
            elif isinstance(n, ClassMethodNode):
                compute.append(n)
            else:
                raise TypeError(
                    f"compiled DAGs require actor-method nodes; got {n._label()} "
                    "(tasks run via DAGNode.execute())"
                )
        for leaf in output_leaves:
            if not isinstance(leaf, ClassMethodNode):
                raise TypeError("compiled DAG outputs must be actor-method nodes")

        # node -> owning actor key
        def owner(n: ClassMethodNode):
            return n._actor.actor_id.hex()

        actors: Dict[str, List[ClassMethodNode]] = {}
        handles: Dict[str, Any] = {}
        for n in compute:
            actors.setdefault(owner(n), []).append(n)
            handles[owner(n)] = n._actor
        if not actors:
            raise ValueError("compiled DAG has no actor-method nodes")

        # which (producer node, consumer actor) edges cross processes, and
        # whether the driver consumes the producer
        consumers: Dict[int, set] = {}  # producer node_id -> set of actor keys ("<driver>" for driver)
        input_consumers: set = set()

        def record_edge(dep: DAGNode, consumer_key: str):
            if isinstance(dep, (InputNode, InputAttributeNode)):
                input_consumers.add(consumer_key)
            elif isinstance(dep, ClassMethodNode):
                if owner(dep) != consumer_key:
                    consumers.setdefault(dep._id, set()).add(consumer_key)
            elif isinstance(dep, MultiOutputNode):
                raise TypeError("MultiOutputNode must be the DAG root")

        for n in compute:
            for dep in n._upstream():
                record_edge(dep, owner(n))
        for leaf in output_leaves:
            consumers.setdefault(leaf._id, set()).add("<driver>")

        if self._input_node is None and input_consumers:
            raise ValueError("InputAttributeNode without InputNode")

        # allocate channels; assign reader indices deterministically
        self._channels: Dict[int, BufferedShmChannel] = {}
        reader_index: Dict[Tuple[int, str], int] = {}
        INPUT_ID = -1
        if self._input_node is not None:
            if not input_consumers:
                raise ValueError("InputNode is never consumed")
            chan = BufferedShmChannel(
                num_readers=len(input_consumers),
                num_buffers=self._max_inflight,
                buffer_size=self._buffer_size,
            )
            self._channels[INPUT_ID] = chan
            for i, key in enumerate(sorted(input_consumers)):
                reader_index[(INPUT_ID, key)] = i
        for nid, cons in consumers.items():
            chan = BufferedShmChannel(
                num_readers=len(cons),
                num_buffers=self._max_inflight,
                buffer_size=self._buffer_size,
            )
            self._channels[nid] = chan
            for i, key in enumerate(sorted(cons)):
                reader_index[(nid, key)] = i

        # per-actor operation schedules from the global operation graph
        # (dag/operation.py; reference dag_node_operation.py).  The schedule
        # decides when each channel read happens, so multi-stage actors
        # front-load shallow-stage work instead of blocking a whole tick on
        # a deeper stage's upstream — GPipe-style microbatch pipelining.
        from .operation import build_operation_graph, generate_actor_schedules

        channel_node_ids = {nid for nid in self._channels if nid != INPUT_ID}
        ops, op_edges = build_operation_graph(
            compute, owner, channel_node_ids, INPUT_ID
        )
        raw_schedules = generate_actor_schedules(ops, op_edges)

        self._loop_refs = []
        self._loop_actors: List[str] = []  # parallel to _loop_refs
        self._handles = handles
        # node labels for typed errors: "<method> (node <id>)" and the set
        # of nodes each actor hosts (DeadActorError names the failed ones)
        self._node_methods = {n._id: n._method_name for n in compute}
        self._actor_nodes = {
            key: tuple(
                f"{n._method_name} (node {n._id})"
                for n in compute if owner(n) == key
            )
            for key in handles
        }
        self._actor_schedules: Dict[str, List[tuple]] = {}
        for key, handle in handles.items():
            node_ops: Dict[int, dict] = {}
            reader_specs: Dict[int, Tuple[dict, int]] = {}
            writer_specs: Dict[int, dict] = {}
            for n in compute:
                if owner(n) != key:
                    continue

                def chan_spec(nid, producer):
                    spec = dict(self._channels[nid].spec())
                    if getattr(producer, "_tensor_transport", False):
                        spec["tensor"] = True
                    return spec

                def arg_spec(dep):
                    if isinstance(dep, InputNode):
                        reader_specs[INPUT_ID] = (
                            chan_spec(INPUT_ID, self._input_node),
                            reader_index[(INPUT_ID, key)],
                        )
                        return ("input", (INPUT_ID, None))
                    if isinstance(dep, InputAttributeNode):
                        reader_specs[INPUT_ID] = (
                            chan_spec(INPUT_ID, self._input_node),
                            reader_index[(INPUT_ID, key)],
                        )
                        return ("input", (INPUT_ID, dep._key))
                    if isinstance(dep, ClassMethodNode):
                        if owner(dep) == key:
                            return ("local", dep._id)
                        reader_specs[dep._id] = (
                            chan_spec(dep._id, dep),
                            reader_index[(dep._id, key)],
                        )
                        return ("chan", dep._id)
                    return ("const", dep)

                node_ops[n._id] = {
                    "method": n._method_name,
                    "args": [
                        arg_spec(a) if isinstance(a, DAGNode) else ("const", a)
                        for a in n._bound_args
                    ],
                    "kwargs": {
                        k: arg_spec(v) if isinstance(v, DAGNode) else ("const", v)
                        for k, v in n._bound_kwargs.items()
                    },
                }
                if n._id in self._channels:
                    wspec = dict(self._channels[n._id].spec())
                    if getattr(n, "_tensor_transport", False):
                        wspec["tensor"] = True
                    writer_specs[n._id] = wspec

            # project the actor's OpIds into loop ops: READ carries the
            # channel id, COMPUTE/WRITE carry the node id
            schedule: List[tuple] = []
            for opid in raw_schedules.get(key, []):
                kind, ref = opid
                schedule.append(("read", ref[0]) if kind == "read" else (kind, ref))
            self._actor_schedules[key] = schedule

            # no_resend: the loop is incarnation-bound.  If the actor dies
            # the ref must resolve with ActorDiedError (feeding _check_loops)
            # instead of being transparently re-sent to the restarted
            # incarnation, whose re-run loop would reopen these channels at
            # stale stream positions and never produce the lost tick.
            ref = handle._submit(
                "__ca_exec__",
                (_dag_actor_loop, schedule, node_ops, reader_specs,
                 writer_specs, self._timeout),
                {},
                {"num_returns": 1, "no_resend": True},
            )
            self._loop_refs.append(ref)
            self._loop_actors.append(key)

        # driver-side reader handles for outputs; duplicate leaves in a
        # MultiOutputNode share one channel that is read once per tick
        self._driver_readers = {}
        self._driver_read_order: List[int] = []
        for leaf in output_leaves:
            if leaf._id in self._driver_readers:
                continue
            spec = self._channels[leaf._id].spec()
            self._driver_readers[leaf._id] = open_channel(
                spec, reader_index[(leaf._id, "<driver>")]
            )
            self._driver_read_order.append(leaf._id)
        self._output_leaves = output_leaves
        self._multi_output = isinstance(root, MultiOutputNode)
        self._INPUT_ID = INPUT_ID
        # partially-read tick state (survives a TimeoutError so channel
        # streams never misalign): node_id -> value for the current tick
        self._partial_vals: Dict[int, Any] = {}
        DAG_STATS["compiles"] += 1
        from ..util.metrics import _ensure_flusher

        _ensure_flusher()  # stats dicts only ship while the flusher runs

    # ----------------------------------------------------------- fault watch

    def _check_loops(self):
        """Distinguish infrastructure death from a slow tick: a loop ref only
        resolves when its actor loop EXITS, which before teardown means the
        actor died (or the loop crashed outside user code).  App errors never
        come this way — they travel through the channels as _DagError.
        Raises DeadActorError (after tearing the DAG down) on death."""
        if not self._loop_refs:
            return
        from ..core import api as ca

        try:
            done, _ = ca.wait(
                self._loop_refs, num_returns=len(self._loop_refs), timeout=0
            )
        except Exception:
            return  # wait plumbing unavailable: the deadline still bounds us
        if not done:
            return
        ref = done[0]
        key = self._loop_actors[self._loop_refs.index(ref)]
        detail = "actor loop exited mid-execute"
        try:
            ca.get(ref)
        except BaseException as e:  # noqa: BLE001 — folded into the typed error
            detail = repr(e)
        # record BEFORE constructing the error: DeadActorError snapshots the
        # recent dag-plane events into .flight_events, and this one is the
        # root cause the incident view must lead with
        if flightrec.REC is not None:
            flightrec.REC.record(
                "dag", "dag_actor_death", actor=key, detail=detail,
                nodes=list(self._actor_nodes.get(key, ())),
            )
        err = DeadActorError(key, self._actor_nodes.get(key, ()), detail)
        DAG_STATS["actor_deaths"] += 1
        self._dead = err
        self.teardown()
        raise err

    def _raise_if_unusable(self):
        if self._dead is not None:
            raise self._dead
        if self._torn_down:
            raise RuntimeError("compiled DAG has been torn down")

    # ---------------------------------------------------------------- execute

    def execute(self, *args, **kwargs) -> CompiledDAGRef:
        import contextlib
        import time as _time

        from ..util import tracing as _trc

        self._raise_if_unusable()
        if self._input_node is not None:
            payload = (tuple(args), kwargs)
            if getattr(self._input_node, "_tensor_transport", False):
                from ..channel.device_transport import pack_device_value

                payload = pack_device_value(payload)
            chan = self._channels[self._INPUT_ID]
            # trace propagation (tentpole): the input write carries the
            # driver's span in the channel meta; actor ticks parent on it.
            # Untraced path: one contextvar read + one branch.
            traced = _trc.is_enabled() or _trc.current() is not None
            span_cm = (
                _trc.span("dag:execute") if traced
                else contextlib.nullcontext(None)
            )
            with span_cm as sctx:
                if sctx is not None:
                    payload = _TraceEnv(
                        {"tid": sctx["tid"], "sid": sctx["sid"]}, payload
                    )
                deadline = _time.monotonic() + self._timeout
                waited = False
                # sliced write: at max_inflight the input channel blocks on
                # the slowest reader's ack (backpressure); slicing keeps
                # actor death from turning that into a silent hang
                while True:
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        DAG_STATS["timeouts"] += 1
                        if flightrec.REC is not None:
                            flightrec.REC.record(
                                "dag", "dag_timeout", node="InputNode",
                                phase="execute", timeout_s=self._timeout,
                            )
                        raise DagTimeoutError(
                            "InputNode (backpressure)", self._timeout,
                            phase="execute",
                        )
                    try:
                        chan.write(payload, min(_DEATH_POLL_S, remaining))
                        break
                    except TimeoutError:
                        if not waited:
                            waited = True
                            DAG_STATS["backpressure_waits"] += 1
                        self._check_loops()
        DAG_STATS["executions"] += 1
        ref = CompiledDAGRef(self, self._exec_seq)
        self._exec_seq += 1
        return ref

    async def execute_async(self, *args, **kwargs) -> CompiledDAGRef:
        """Async submission (reference compiled_dag_node.py:2336): the input
        -channel write (which blocks under backpressure at max inflight)
        runs off-loop; `await ref.get_async()` or `await ref` reads."""
        import asyncio

        return await asyncio.to_thread(self.execute, *args, **kwargs)

    def _read_one(self, nid: int, deadline: float, timeout_s: float):
        """Read one output channel in death-aware slices: a healthy tick
        wakes on publish (futex), a dead producer surfaces as DeadActorError
        from _check_loops, and the deadline surfaces as a typed error naming
        the stalled node — never a bare hang."""
        import time as _time

        reader = self._driver_readers[nid]
        while True:
            # clamp to 0 rather than pre-raising: a 0-timeout read still
            # returns a value that is already published (poll semantics)
            remaining = max(0.0, deadline - _time.monotonic())
            try:
                v = reader.read(min(_DEATH_POLL_S, remaining))
                if isinstance(v, _TraceEnv):
                    v = v.value  # driver consumes; trace ends here
                return v
            except TimeoutError:
                self._check_loops()
                if _time.monotonic() >= deadline:
                    DAG_STATS["timeouts"] += 1
                    node = f"{self._node_methods.get(nid, '?')} (node {nid})"
                    if flightrec.REC is not None:
                        flightrec.REC.record(
                            "dag", "dag_timeout", node=node, phase="read",
                            timeout_s=timeout_s,
                        )
                    raise DagTimeoutError(node, timeout_s) from None

    def _read_result(self, seq: int, timeout: Optional[float]):
        import time as _time

        self._raise_if_unusable()
        t = self._timeout if timeout is None else timeout
        deadline = _time.monotonic() + t
        while self._read_seq <= seq:
            for nid in self._driver_read_order:
                if nid in self._partial_vals:
                    continue  # already read before an earlier timeout
                v = self._read_one(nid, deadline, t)
                if not isinstance(v, _DagError):
                    from ..channel.device_transport import maybe_unpack

                    v = maybe_unpack(v)
                self._partial_vals[nid] = v
            outs = [self._partial_vals[leaf._id] for leaf in self._output_leaves]
            self._partial_vals = {}
            self._result_cache[self._read_seq] = outs
            self._read_seq += 1
            DAG_STATS["results"] += 1
        outs = self._result_cache.pop(seq)
        for o in outs:
            if isinstance(o, _DagError):
                o.raise_()
        return outs if self._multi_output else outs[0]

    # ---------------------------------------------------------------- teardown

    def teardown(self):
        if self._torn_down:
            return
        self._torn_down = True
        for chan in self._channels.values():
            try:
                chan.close()
            except Exception:
                pass
        for r in getattr(self, "_driver_readers", {}).values():
            try:
                r.close()
            except Exception:
                pass
        from ..core import api as ca

        try:
            ca.wait(self._loop_refs, num_returns=len(self._loop_refs), timeout=10)
        except Exception:
            pass
        for chan in self._channels.values():
            try:
                chan.release()
            except Exception:
                pass
        for r in getattr(self, "_driver_readers", {}).values():
            try:
                r.release()
            except Exception:
                pass
        DAG_STATS["teardowns"] += 1

    def recompile(self):
        """Rebuild channels and actor loops against the CURRENT incarnation
        of every actor — recovery path after DeadActorError when the failed
        actor has a restart budget (max_restarts).  In-flight executions are
        lost (their results died with the old loops); sequence numbers reset
        so fresh executes read fresh channels."""
        self.teardown()
        self._torn_down = False
        self._dead = None
        self._exec_seq = 0
        self._read_seq = 0
        self._result_cache = {}
        DAG_STATS["recompiles"] += 1
        if flightrec.REC is not None:
            flightrec.REC.record(
                "dag", "dag_recompile", actors=len(self._handles),
                channels=len(self._channels),
            )
        self._compile()

    def __del__(self):
        try:
            self.teardown()
        except Exception:
            pass

    def visualize(self) -> str:
        return self._root.visualize()

    def actor_schedules(self) -> Dict[str, List[tuple]]:
        """The per-actor operation schedules this DAG executes (reference:
        CompiledDAG.actor_to_execution_schedule).  Read-only introspection
        for tests and debugging."""
        return {k: list(v) for k, v in self._actor_schedules.items()}
