"""Per-actor operation schedules for compiled DAGs (analogue of the
reference's dag/dag_node_operation.py: _DAGNodeOperation /
_DAGOperationGraphNode / _generate_actor_to_execution_schedule).

Each compute node decomposes into up to three operations:

  READ(channel, actor)  — pull one value from a cross-process channel
                          (one op per (channel, actor) pair, because a
                          channel must be read exactly once per tick no
                          matter how many of the actor's nodes consume it);
  COMPUTE(node)         — run the bound method;
  WRITE(node)           — push the result into the node's output channel.

The global operation graph links READ -> COMPUTE -> WRITE within a node,
WRITE(producer) -> READ(channel, consumer-actor) across processes, and
COMPUTE(producer) -> COMPUTE(consumer) for same-actor in-memory edges.
Schedules are produced by a deterministic Kahn traversal prioritised by
*stage depth* (longest path from the DAG input), so that when one actor
hosts nodes from several pipeline stages — the interleaved-pipeline shape,
e.g. actor A holding stages 0 and 2 with actor B holding stage 1 — every
microbatch's stage-0 work is scheduled before A blocks on B's stage-1
output.  A naive depth-first program order would serialise the microbatches
(A cannot start microbatch 1 until microbatch 0 has come back from B);
the schedule turns the same DAG into a GPipe-style pipeline.

Because every per-actor schedule is a projection of one global topological
order, scheduled blocking reads cannot deadlock against each other; a cycle
in the operation graph is detected here and raised at compile time instead
of hanging an actor loop at runtime.
"""

from __future__ import annotations

import heapq
from typing import Any, Dict, List, Set, Tuple

READ = "read"
COMPUTE = "compute"
WRITE = "write"
_KIND_ORDER = {READ: 0, COMPUTE: 1, WRITE: 2}

# Op identity: (READ, (channel_id, actor_key)) | (COMPUTE, node_id) |
# (WRITE, node_id).  Keys never mix types within a kind, so OpIds are
# totally ordered and usable as deterministic heap tie-breakers.
OpId = Tuple[str, Any]


class ScheduleError(ValueError):
    """The operation graph admits no schedule (cyclic dependencies)."""


def node_depths(compute_nodes) -> Dict[int, int]:
    """Longest-path-from-input depth per compute node id.  Input nodes sit
    at depth 0; a node is one deeper than its deepest DAGNode argument."""
    from .node import ClassMethodNode

    depth: Dict[int, int] = {}
    for n in compute_nodes:  # already in topological order (deps first)
        d = 0
        for dep in n._upstream():
            if isinstance(dep, ClassMethodNode):
                d = max(d, depth[dep._id] + 1)
            else:
                d = max(d, 1)
        depth[n._id] = d
    return depth


def build_operation_graph(
    compute_nodes,
    owner_of,
    channel_ids: Set[int],
    input_id: int,
):
    """Return (ops, edges) of the global operation graph.

    ops: OpId -> {"actor": key, "depth": int, "order": int}
    edges: OpId -> set of successor OpIds
    """
    from .node import ClassMethodNode, InputAttributeNode, InputNode

    depths = node_depths(compute_nodes)
    ops: Dict[OpId, Dict[str, Any]] = {}
    edges: Dict[OpId, Set[OpId]] = {}

    def add_op(opid: OpId, actor: str, depth: int, order: int):
        if opid not in ops:
            ops[opid] = {"actor": actor, "depth": depth, "order": order}
            edges[opid] = set()
        else:
            # a READ shared by several of the actor's nodes runs as early as
            # its earliest consumer needs it
            ops[opid]["depth"] = min(ops[opid]["depth"], depth)
            ops[opid]["order"] = min(ops[opid]["order"], order)

    for n in compute_nodes:
        key = owner_of(n)
        comp: OpId = (COMPUTE, n._id)
        add_op(comp, key, depths[n._id], n._id)
        for dep in n._upstream():
            if isinstance(dep, (InputNode, InputAttributeNode)):
                rd: OpId = (READ, (input_id, key))
                add_op(rd, key, depths[n._id], n._id)
                edges[rd].add(comp)
            elif isinstance(dep, ClassMethodNode):
                if owner_of(dep) == key:
                    edges[(COMPUTE, dep._id)].add(comp)
                else:
                    rd = (READ, (dep._id, key))
                    add_op(rd, key, depths[n._id], n._id)
                    edges[rd].add(comp)
                    if dep._id in channel_ids:
                        wr: OpId = (WRITE, dep._id)
                        # producer WRITE op is added when the producer node
                        # is visited; deps-first topo order guarantees it
                        # exists by now
                        edges[wr].add(rd)
        if n._id in channel_ids:
            wr = (WRITE, n._id)
            add_op(wr, key, depths[n._id], n._id)
            edges[comp].add(wr)
    return ops, edges


def generate_actor_schedules(ops, edges) -> Dict[str, List[OpId]]:
    """Deterministic priority-Kahn linearisation of the operation graph,
    projected onto each actor (reference:
    _generate_actor_to_execution_schedule, dag_node_operation.py:360).

    Priority = (stage depth, node creation order, READ < COMPUTE < WRITE):
    shallow-stage work schedules first, which is exactly the interleaving
    that keeps every pipeline stage busy.  Raises ScheduleError on a cycle.
    """
    indeg: Dict[OpId, int] = {o: 0 for o in ops}
    for a, succs in edges.items():
        for b in succs:
            indeg[b] += 1

    def push(o: OpId):
        meta = ops[o]
        heapq.heappush(heap, (meta["depth"], meta["order"], _KIND_ORDER[o[0]], o))

    heap: list = []
    for o, d in indeg.items():
        if d == 0:
            push(o)
    schedules: Dict[str, List[OpId]] = {}
    done = 0
    while heap:
        _, _, _, o = heapq.heappop(heap)
        schedules.setdefault(ops[o]["actor"], []).append(o)
        done += 1
        for b in edges[o]:
            indeg[b] -= 1
            if indeg[b] == 0:
                push(b)
    if done != len(ops):
        stuck = sorted(o for o, d in indeg.items() if d > 0)
        raise ScheduleError(
            f"compiled DAG operation graph has a cycle; unschedulable ops: {stuck[:8]}"
        )
    return schedules
