"""DAG node types (analogue of the reference's ray.dag dag_node.py /
input_node.py / class_node.py / function_node.py / output_node.py)."""

from __future__ import annotations

import itertools
from typing import Any, Dict, List, Optional, Tuple

_node_counter = itertools.count()


class DAGNode:
    """Base: an operation whose bound args may include other DAGNodes."""

    def __init__(self, args: Tuple = (), kwargs: Optional[Dict[str, Any]] = None):
        self._bound_args = tuple(args)
        self._bound_kwargs = dict(kwargs or {})
        self._id = next(_node_counter)
        self._tensor_transport = False

    def with_tensor_transport(self) -> "DAGNode":
        """Mark this node's output as tensor data: array leaves cross the
        channel as per-shard zero-copy buffer borrows with sharding
        metadata (channel/device_transport), and land shard-by-shard on the
        consumer's devices under a reconstructed NamedSharding — the full
        array is never assembled on the host and never passes through
        pickle bytes.

        TPU-native counterpart of the reference's
        experimental/channel/torch_tensor_nccl_channel.py:44 transport
        annotation: separate jax processes cannot share one ICI runtime, so
        the shm channel scatter-writes the device shard buffers directly
        (one memcpy per side — the physical minimum for a process hop);
        in-graph transfers inside jit/shard_map ride ICI collectives and
        never come through here."""
        self._tensor_transport = True
        return self

    # -- graph introspection ------------------------------------------------

    def _upstream(self) -> List["DAGNode"]:
        ups = []
        for a in list(self._bound_args) + list(self._bound_kwargs.values()):
            if isinstance(a, DAGNode):
                ups.append(a)
        return ups

    def _walk(self, seen=None) -> List["DAGNode"]:
        """Topological order (deps first)."""
        if seen is None:
            seen = {}
        if self._id in seen:
            return []
        seen[self._id] = self
        out = []
        for u in self._upstream():
            out.extend(u._walk(seen))
        out.append(self)
        return out

    # -- eager execution ----------------------------------------------------

    def execute(self, *input_args, **input_kwargs):
        """Recursively execute by submitting tasks/actor calls; DAGNode args
        are passed as ObjectRefs so the runtime chains them without a driver
        round-trip per hop."""
        cache: Dict[int, Any] = {}

        def run(node: DAGNode):
            if node._id in cache:
                return cache[node._id]
            args = [run(a) if isinstance(a, DAGNode) else a for a in node._bound_args]
            kwargs = {
                k: run(v) if isinstance(v, DAGNode) else v
                for k, v in node._bound_kwargs.items()
            }
            cache[node._id] = node._execute_impl(args, kwargs, input_args, input_kwargs)
            return cache[node._id]

        return run(self)

    def _execute_impl(self, args, kwargs, input_args, input_kwargs):
        raise NotImplementedError

    def experimental_compile(self, *, max_inflight_executions: int = 2,
                             buffer_size: Optional[int] = None,
                             execute_timeout_s: Optional[float] = None):
        from .compiled import CompiledDAG

        return CompiledDAG(
            self, max_inflight_executions=max_inflight_executions,
            buffer_size=buffer_size, execute_timeout_s=execute_timeout_s,
        )

    def visualize(self) -> str:
        """ASCII rendering of the graph (reference: dag/vis_utils.py)."""
        lines = []
        for n in self._walk():
            ups = ", ".join(str(u._id) for u in n._upstream()) or "-"
            lines.append(f"[{n._id}] {n._label()}  <- {ups}")
        return "\n".join(lines)

    def _label(self) -> str:
        return type(self).__name__


class InputNode(DAGNode):
    """The DAG's input placeholder; supports `with InputNode() as inp:` and
    `inp[0]` / `inp.key` attribute access (reference: dag/input_node.py)."""

    def __init__(self):
        super().__init__()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __getitem__(self, key):
        return InputAttributeNode(self, key)

    def __getattr__(self, key):
        if key.startswith("_"):
            raise AttributeError(key)
        return InputAttributeNode(self, key)

    def _execute_impl(self, args, kwargs, input_args, input_kwargs):
        if input_kwargs or len(input_args) != 1:
            return tuple(input_args) if not input_kwargs else (input_args, input_kwargs)
        return input_args[0]

    def _label(self):
        return "Input"


class InputAttributeNode(DAGNode):
    def __init__(self, input_node: InputNode, key):
        super().__init__(args=(input_node,))
        self._key = key

    def _execute_impl(self, args, kwargs, input_args, input_kwargs):
        if isinstance(self._key, int):
            return input_args[self._key]
        if self._key in input_kwargs:
            return input_kwargs[self._key]
        return args[0][self._key]

    def _label(self):
        return f"Input[{self._key!r}]"


class FunctionNode(DAGNode):
    """A task node, from RemoteFunction.bind()."""

    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn

    def _execute_impl(self, args, kwargs, input_args, input_kwargs):
        return self._remote_fn.remote(*args, **kwargs)

    def _label(self):
        return f"task:{self._remote_fn.underlying.__name__}"


class ClassMethodNode(DAGNode):
    """An actor-method node, from ActorMethod.bind()."""

    def __init__(self, actor_handle, method_name: str, args, kwargs):
        super().__init__(args, kwargs)
        self._actor = actor_handle
        self._method_name = method_name

    def _execute_impl(self, args, kwargs, input_args, input_kwargs):
        return getattr(self._actor, self._method_name).remote(*args, **kwargs)

    def _label(self):
        return f"{self._actor!r}.{self._method_name}"


class MultiOutputNode(DAGNode):
    """Aggregates several leaf nodes into a tuple output (reference:
    dag/output_node.py)."""

    def __init__(self, outputs: List[DAGNode]):
        super().__init__(args=tuple(outputs))

    def _execute_impl(self, args, kwargs, input_args, input_kwargs):
        return list(args)

    def _label(self):
        return "MultiOutput"
