"""`ca microbenchmark` — the reference's `ray microbenchmark`
(python/ray/_private/ray_perf.py:93) surface: one command printing the
canonical single-node micro numbers so users can compare environments
against BASELINE.md's published table.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np


def _rate(n: int, fn: Callable[[], None]) -> float:
    t0 = time.perf_counter()
    fn()
    return n / (time.perf_counter() - t0)


def run_microbenchmarks(quick: bool = False) -> List[Tuple[str, float, str]]:
    """Returns [(metric, value, unit)] and prints them as it goes."""
    from .core import api as ca

    owns = not ca.is_initialized()
    if owns:
        ca.init(num_cpus=4)
    results: List[Tuple[str, float, str]] = []

    def record(name: str, value: float, unit: str):
        results.append((name, value, unit))
        print(f"{name}: {value:,.1f} {unit}")

    scale = 0.2 if quick else 1.0

    @ca.remote
    def noop():
        return None

    # warm the pool AND wait out prestarted-worker registration: interpreter
    # startups compete with the head for the core and poison early numbers
    ca.get([noop.remote() for _ in range(50)])
    from .core.worker import global_worker

    w = global_worker()
    deadline = time.monotonic() + 10
    want = int(ca.cluster_resources().get("CPU", 1))
    while time.monotonic() < deadline:
        alive = [
            x for x in w.head_call("list_workers")["workers"]
            if x.get("state") in ("idle", "leased")
        ]
        if len(alive) >= want:
            break
        time.sleep(0.2)
    time.sleep(0.5)

    n = int(5000 * scale)
    record(
        "single client tasks async",
        _rate(n, lambda: ca.get([noop.remote() for _ in range(n)])),
        "/s",
    )

    n = int(500 * scale)

    def sync_tasks():
        for _ in range(n):
            ca.get(noop.remote())

    record("single client tasks sync", _rate(n, sync_tasks), "/s")

    @ca.remote
    class A:
        def ping(self):
            return None

    a = A.remote()
    ca.get(a.ping.remote())
    n = int(5000 * scale)
    record(
        "1:1 actor calls async",
        _rate(n, lambda: ca.get([a.ping.remote() for _ in range(n)])),
        "/s",
    )
    n = int(500 * scale)

    def sync_actor():
        for _ in range(n):
            ca.get(a.ping.remote())

    record("1:1 actor calls sync", _rate(n, sync_actor), "/s")
    from .core.actor import kill as _kill

    _kill(a)

    # puts: value churn through the object store
    n = int(1000 * scale)
    small = np.arange(16)
    record(
        "single client put calls",
        _rate(n, lambda: [ca.put(small) for _ in range(n)]),
        "/s",
    )
    n = int(2000 * scale)
    refs = [ca.put(small) for _ in range(n)]
    record(
        "single client get calls",
        _rate(n, lambda: [ca.get(r) for r in refs]),
        "/s",
    )
    del refs

    size = 64 * 1024 * 1024 if quick else 256 * 1024 * 1024
    arr = np.frombuffer(np.random.bytes(size), dtype=np.uint8)
    reps = 2 if quick else 4
    warm = [ca.put(arr) for _ in range(reps)]
    del warm
    time.sleep(0.5)
    t0 = time.perf_counter()
    big = [ca.put(arr) for _ in range(reps)]
    record(
        "single client put gigabytes",
        reps * size / (time.perf_counter() - t0) / 1e9,
        "GB/s",
    )
    del big

    # placement group create/remove churn.  Earlier phases' task leases
    # idle-return after ~1s; wait for full capacity or the first PG goes
    # PENDING and the average collapses to the service-tick cadence.
    from .core.placement import placement_group, remove_placement_group

    total_cpu = ca.cluster_resources().get("CPU", 0)
    deadline = time.monotonic() + 10
    while (
        ca.available_resources().get("CPU", 0) < total_cpu
        and time.monotonic() < deadline
    ):
        time.sleep(0.1)

    n = int(100 * scale)

    def pg_churn():
        for _ in range(n):
            pg = placement_group([{"CPU": 1}])
            pg.wait(10)
            remove_placement_group(pg)

    record("placement group create/removal", _rate(n, pg_churn), "/s")

    # wait over a 1k-ref frontier (ray_perf "single client wait 1k refs")
    refs1k = [ca.put(small) for _ in range(1000)]
    n = max(3, int(10 * scale))

    def wait_1k():
        for _ in range(n):
            ready, _ = ca.wait(refs1k, num_returns=1000, timeout=60)
            assert len(ready) == 1000

    record("single client wait 1k refs", _rate(n, wait_1k), "/s")
    del refs1k

    # container deserialization fan-out (ray_perf "get containing 10k refs")
    refs10k = [ca.put(i) for i in range(10000)]
    container = ca.put(refs10k)
    n = max(3, int(10 * scale))
    record(
        "get object containing 10k refs",
        _rate(n, lambda: [ca.get(container) for _ in range(n)]),
        "/s",
    )
    del container, refs10k

    if owns:
        ca.shutdown()
    return results


def run_multiclient(quick: bool = False) -> List[Tuple[str, float, str]]:
    """The multi-client aggregate rows (ray_perf.py multi-client variants):
    K client ACTORS drive submissions concurrently — same shape as the
    reference, which uses worker processes as clients.  On this 1-core host
    the clients, their targets, the head, and the pool workers all share one
    core, so these aggregate numbers are a lower bound (co-tenancy caveat
    recorded in SCALE.md)."""
    from .core import api as ca

    owns = not ca.is_initialized()
    if owns:
        ca.init(num_cpus=4)
    results: List[Tuple[str, float, str]] = []

    def record(name: str, value: float, unit: str):
        results.append((name, value, unit))
        print(f"{name}: {value:,.1f} {unit}")

    scale = 0.2 if quick else 1.0
    k = 4

    @ca.remote(num_cpus=0)
    class Client:
        """A driver-role actor (num_cpus=0: clients must not occupy the CPU
        slots their own submitted tasks need — the reference's multi-client
        rows likewise run the drivers outside the worker pool)."""

        def __init__(self):
            import cluster_anywhere_tpu as ca2

            @ca2.remote
            def noop():
                return None

            self._noop = noop

        def tasks_async(self, n):
            import cluster_anywhere_tpu as ca2

            noop = self._noop
            t0 = time.perf_counter()
            ca2.get([noop.remote() for _ in range(n)])
            return n / (time.perf_counter() - t0)

        def drive_actor(self, target, n):
            import cluster_anywhere_tpu as ca2

            t0 = time.perf_counter()
            ca2.get([target.ping.remote() for _ in range(n)])
            return n / (time.perf_counter() - t0)

        def puts(self, n, nbytes):
            import numpy as _np

            import cluster_anywhere_tpu as ca2

            arr = _np.frombuffer(_np.random.bytes(nbytes), dtype=_np.uint8)
            t0 = time.perf_counter()
            refs = [ca2.put(arr) for _ in range(n)]
            dt = time.perf_counter() - t0
            del refs
            return n * nbytes / dt

    @ca.remote(num_cpus=0)
    class Target:
        def ping(self):
            return None

    clients = [Client.remote() for _ in range(k)]
    n = int(2000 * scale)
    # warmup: client-side pools spin up
    ca.get([c.tasks_async.remote(50) for c in clients])
    t0 = time.perf_counter()
    ca.get([c.tasks_async.remote(n) for c in clients], timeout=600)
    record(
        "multi client tasks async",
        k * n / (time.perf_counter() - t0),
        "/s",
    )

    targets = [Target.remote() for _ in range(k)]
    ca.get([t.ping.remote() for t in targets])
    n = int(2000 * scale)
    t0 = time.perf_counter()
    ca.get(
        [c.drive_actor.remote(t, n) for c, t in zip(clients, targets)],
        timeout=600,
    )
    record("n:n actor calls async", k * n / (time.perf_counter() - t0), "/s")

    nbytes = 16 * 1024 * 1024 if quick else 64 * 1024 * 1024
    reps = 2 if quick else 4
    ca.get([c.puts.remote(1, nbytes) for c in clients])  # warm arenas
    t0 = time.perf_counter()
    ca.get([c.puts.remote(reps, nbytes) for c in clients], timeout=600)
    record(
        "multi client put gigabytes",
        k * reps * nbytes / (time.perf_counter() - t0) / 1e9,
        "GB/s",
    )

    from .core.actor import kill as _kill

    for h in clients + targets:
        _kill(h)
    if owns:
        ca.shutdown()
    return results


def run_scalability(quick: bool = False) -> List[Tuple[str, float, str]]:
    """Scalability-envelope probes (release/perf_metrics/scalability/
    single_node.json rows, honestly scaled to this host and labeled with
    their sizes): many-args, many-returns, many-gets, and a bounded
    queued-task flood."""
    from .core import api as ca

    owns = not ca.is_initialized()
    if owns:
        ca.init(num_cpus=4)
    results: List[Tuple[str, float, str]] = []

    def record(name: str, value: float, unit: str):
        results.append((name, value, unit))
        print(f"{name}: {value:,.2f} {unit}")

    n_args = 2000 if quick else 10000
    refs = [ca.put(i) for i in range(n_args)]

    @ca.remote
    def consume_many(*args):
        return len(args)

    t0 = time.perf_counter()
    got = ca.get(consume_many.remote(*refs), timeout=600)
    assert got == n_args
    record(f"{n_args} object args to one task", time.perf_counter() - t0, "s")
    del refs

    n_ret = 600 if quick else 3000

    @ca.remote
    def many_returns():
        return tuple(range(n_ret))

    t0 = time.perf_counter()
    out = ca.get(
        many_returns.options(num_returns=n_ret).remote(), timeout=600
    )
    assert len(out) == n_ret
    record(f"{n_ret} returns from one task", time.perf_counter() - t0, "s")

    n_get = 2000 if quick else 10000

    @ca.remote
    def make_refs(k):
        import cluster_anywhere_tpu as ca2

        return [ca2.put(i) for i in range(k)]

    # the refs are owned by a WORKER: the driver's get exercises the real
    # resolution path (borrowed-ref seeding against the owner's directory),
    # not its own local value cache
    refs = ca.get(make_refs.remote(n_get), timeout=300)
    t0 = time.perf_counter()
    vals = ca.get(refs, timeout=600)
    assert len(vals) == n_get and vals[1] == 1
    record(f"get of {n_get} worker-owned objects", time.perf_counter() - t0, "s")
    del refs, vals

    # queued-task flood: 100k on this host (the reference's 1M row ran on an
    # m4.16xlarge; the claim under test — the submission/lease pipeline keeps
    # absorbing tasks far beyond pool capacity without collapse — scales down)
    n_flood = 20000 if quick else 100000

    @ca.remote
    def tiny():
        return None

    t0 = time.perf_counter()
    flood = [tiny.remote() for _ in range(n_flood)]
    submit_dt = time.perf_counter() - t0
    ca.get(flood, timeout=1200)
    total_dt = time.perf_counter() - t0
    record(f"{n_flood} queued tasks submit", submit_dt, "s")
    record(f"{n_flood} queued tasks drain", total_dt, "s")

    if owns:
        ca.shutdown()
    return results


def run_collective_bw(quick: bool = False) -> List[Tuple[str, float, str]]:
    """Host (out-of-graph) allreduce bandwidth over the p2p backend, with
    proof that no per-op traffic landed on the head (r4 weak #2/#3)."""
    from .core import api as ca

    owns = not ca.is_initialized()
    if owns:
        ca.init(num_cpus=4)
    results: List[Tuple[str, float, str]] = []

    def record(name: str, value: float, unit: str):
        results.append((name, value, unit))
        print(f"{name}: {value:,.2f} {unit}")

    from .parallel import collectives as coll

    @ca.remote
    class Rank(coll.CollectiveActorMixin):
        def warm(self, nbytes, group):
            import numpy as _np

            # peer resolution + connection setup + first-op buffers
            coll.allreduce(_np.zeros(nbytes // 4, _np.float32), group_name=group)
            return True

        def bench(self, nbytes, reps, group):
            import numpy as _np

            arr = _np.frombuffer(_np.random.bytes(nbytes), dtype=_np.float32)
            t0 = time.perf_counter()
            for _ in range(reps):
                out = coll.allreduce(arr, group_name=group)
            dt = time.perf_counter() - t0
            assert out.shape == arr.shape
            return reps * nbytes / dt

    from .core.actor import kill as _kill
    from .core.worker import global_worker

    nbytes = 8 * 1024 * 1024 if quick else 64 * 1024 * 1024
    reps = 3 if quick else 5
    for world in (2, 4):
        ranks = [Rank.remote() for _ in range(world)]
        coll.create_collective_group(
            ranks, world, list(range(world)), group_name=f"bw{world}"
        )
        ca.get([r.warm.remote(nbytes, f"bw{world}") for r in ranks], timeout=120)
        before = global_worker().head_call("stats").get("rpc_counts", {})
        per_rank = ca.get(
            [r.bench.remote(nbytes, reps, f"bw{world}") for r in ranks], timeout=600
        )
        after = global_worker().head_call("stats").get("rpc_counts", {})
        # input-size bandwidth per rank (the ring moves 2(N-1)/N x input
        # bytes on the wire; this is the user-visible "allreduce of X bytes
        # took T")
        record(
            f"host allreduce ({world} ranks, {nbytes >> 20} MB)",
            min(per_rank) / 1e9,
            "GB/s per rank",
        )
        head_delta = sum(
            after.get(m, 0) - before.get(m, 0)
            for m in ("kv_get", "kv_put", "kv_keys", "obj_locate")
        )
        record(
            f"head KV/locate ops during allreduce loop ({world} ranks)",
            head_delta,
            "ops",
        )
        coll.destroy_group_on(ranks, f"bw{world}")
        for r in ranks:
            _kill(r)
    if owns:
        ca.shutdown()
    return results


def run_lease_plane(quick: bool = False) -> List[Tuple[str, float, str]]:
    """`ca microbenchmark --lease-plane`: A/B the lease plane.  A task flood
    against a multi-node cluster with node-local granting ON (agents grant
    out of head-delegated lease blocks) vs OFF (every lease crosses the
    head's loop), with the head's request_lease RPC delta printed as the
    structural proof — local granting should leave it ~0 in steady state."""
    from .cluster_utils import Cluster
    from .core import api as ca
    from .core.config import CAConfig
    from .core.worker import LEASE_STATS, global_worker

    results: List[Tuple[str, float, str]] = []

    def record(name: str, value: float, unit: str):
        results.append((name, value, unit))
        print(f"{name}: {value:,.1f} {unit}")

    n = 1000 if quick else 4000

    def flood(delegation: bool):
        cfg = CAConfig()
        cfg.lease_delegation = delegation
        cluster = Cluster(head_resources={"CPU": 0}, config=cfg)
        cluster.add_node(num_cpus=2)
        cluster.add_node(num_cpus=2)
        cluster.connect()
        try:
            @ca.remote
            def noop():
                return None

            w = global_worker()
            ca.get([noop.remote() for _ in range(100)], timeout=120)
            # let the warm leases idle-return so the measured flood actually
            # exercises the grant path (and, with delegation on, gives the
            # head a beat to hand the freed idle workers to the agents)
            deadline = time.monotonic() + 15
            while time.monotonic() < deadline:
                stats = w.head_call("stats")["stats"]
                if not delegation or stats.get("lease_delegated_slots", 0) >= 2:
                    if stats.get("idle_workers", 0) or stats.get(
                        "lease_delegated_slots", 0
                    ):
                        break
                time.sleep(0.2)
            local0 = LEASE_STATS["local_grants"]
            before = w.head_call("stats")["rpc_counts"].get("request_lease", 0)
            t0 = time.perf_counter()
            ca.get([noop.remote() for _ in range(n)], timeout=300)
            dt = time.perf_counter() - t0
            after = w.head_call("stats")["rpc_counts"].get("request_lease", 0)
            rate = n / dt
            # bursty phase: bursts separated by > the lease idle timeout, so
            # EVERY burst re-acquires leases — the lease-churn traffic class
            # the delegation moves off the head (a steady warm flood hides
            # it behind lease reuse).  Per-burst head lease ops is the
            # structural number: ~0 local vs several per burst central.
            bursts = 4 if quick else 8
            lease_ops = ("request_lease", "return_lease")
            rc0 = w.head_call("stats")["rpc_counts"]
            b0 = sum(rc0.get(m, 0) for m in lease_ops)
            for _ in range(bursts):
                time.sleep(1.3)  # leases idle-return between bursts
                ca.get([noop.remote() for _ in range(100)], timeout=120)
            rc1 = w.head_call("stats")["rpc_counts"]
            per_burst = (sum(rc1.get(m, 0) for m in lease_ops) - b0) / bursts
            return rate, after - before, LEASE_STATS["local_grants"] - local0, per_burst
        finally:
            cluster.shutdown()

    rate, head_rpcs, local, per_burst = flood(True)
    record("lease plane local-grant tasks", rate, "/s")
    print(f"  head request_lease RPCs during flood: {head_rpcs} "
          f"(local grants: {local})")
    record("lease plane head lease-ops/burst (local)", per_burst, "ops")
    rate_off, head_rpcs_off, _, per_burst_off = flood(False)
    record("lease plane head-grant tasks", rate_off, "/s")
    print(f"  head request_lease RPCs during flood: {head_rpcs_off}")
    record("lease plane head lease-ops/burst (central)", per_burst_off, "ops")
    return results


def run_owner_plane(quick: bool = False) -> List[Tuple[str, float, str]]:
    """`ca microbenchmark --owner-plane`: A/B the ownership plane.  A
    steady-state object workload — driver creates shm objects, workers
    borrow them (inline holder lists smuggle the refs: transit pins +
    borrower registration + release, the lease-plane test pattern extended
    to objects) — with owner-resident settlement ON vs OFF.  The structural
    proof is the head's per-object obj_refs message count: ~0 with the
    plane on (inc/dec/pins/acks settle at owner ledgers over direct
    connections) vs >= 1 centralized.  A final phase kills the head
    mid-workload and shows cluster-wide GC still completing (owner ledgers
    are the lifetime authority; the head is only the registry)."""
    import numpy as np

    from .cluster_utils import Cluster
    from .core import api as ca
    from .core.config import CAConfig
    from .core.worker import global_worker

    results: List[Tuple[str, float, str]] = []

    def record(name: str, value: float, unit: str):
        results.append((name, value, unit))
        print(f"{name}: {value:,.2f} {unit}")

    n = 150 if quick else 600
    arr = np.arange(4000)  # ~32KB: shm-backed, registered at the head
    want = int(arr.sum())

    def arena_bytes(w) -> int:
        return sum(
            a.size - sum(sz for _, sz in a.free)
            for a in w.shm_store._arenas.values()
        )

    def workload(owner_plane: bool):
        cfg = CAConfig()
        cfg.owner_plane = owner_plane
        cluster = Cluster(head_resources={"CPU": 4}, config=cfg)
        cluster.connect()
        try:
            @ca.remote
            def borrow(holder):
                return int(ca.get(holder[0]).sum())

            # warm the pool + connections
            ca.get(
                [borrow.remote([ca.put(arr)]) for _ in range(20)], timeout=120
            )
            w = global_worker()
            time.sleep(1.0)  # let warmup refcounts settle before counting
            ops = ("obj_refs", "transit_done")
            rc0 = w.head_call("stats")["rpc_counts"]
            t0 = time.perf_counter()
            refs = [ca.put(arr) for _ in range(n)]
            outs = ca.get([borrow.remote([r]) for r in refs], timeout=600)
            assert all(o == want for o in outs)
            del refs, outs
            # settlement proof: every arena slice reclaimed, not just fast
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline and arena_bytes(w) > 0:
                time.sleep(0.2)
            leaked = arena_bytes(w)
            dt = time.perf_counter() - t0
            rc1 = w.head_call("stats")["rpc_counts"]
            per_obj = {
                m: (rc1.get(m, 0) - rc0.get(m, 0)) / n for m in ops
            }
            return n / dt, per_obj, leaked
        finally:
            cluster.shutdown()

    rate_on, per_on, leaked_on = workload(True)
    record("owner plane objects (ledger)", rate_on, "obj/s")
    record("owner plane head obj_refs/object (ledger)", per_on["obj_refs"], "ops")
    record(
        "owner plane head transit_done/object (ledger)",
        per_on["transit_done"], "ops",
    )
    print(f"  leaked arena bytes after settle: {leaked_on}")
    rate_off, per_off, leaked_off = workload(False)
    record("owner plane objects (centralized)", rate_off, "obj/s")
    record(
        "owner plane head obj_refs/object (centralized)",
        per_off["obj_refs"], "ops",
    )
    record(
        "owner plane head transit_done/object (centralized)",
        per_off["transit_done"], "ops",
    )
    print(f"  leaked arena bytes after settle: {leaked_off}")

    # --- GC with the head down mid-workload (ownership plane only) --------
    cluster = Cluster(head_resources={"CPU": 2})
    cluster.connect()
    try:
        w = global_worker()
        big = np.zeros(200_000)  # 1.6MB: shm-backed from the first put
        refs = [ca.put(big) for _ in range(20)]
        assert arena_bytes(w) > 0
        cluster.kill_head()
        time.sleep(0.5)
        del refs
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and arena_bytes(w) > 0:
            time.sleep(0.2)
        leaked = arena_bytes(w)
        record("owner plane GC with head down (leaked bytes)", leaked, "B")
        cluster.restart_head()
    finally:
        cluster.shutdown()
    return results


def run_metrics_plane(quick: bool = False) -> List[Tuple[str, float, str]]:
    """`ca microbenchmark --metrics-plane`: A/B the metrics plane.  With the
    plane ON, agent-node workers ship metric deltas to their node agent
    (piggybacked head-ward on node_sync) and Prometheus scrapes the agents'
    HTTP endpoints — a scrape costs the head ZERO RPCs.  With it OFF, every
    worker reports straight to the head each flush and a scrape is a
    `metrics_snapshot` head RPC.  The structural rows are head metrics-RPC
    traffic per scrape in each mode; the final phase kills the head and
    shows the node endpoint still serving exposition text (scrape survives
    a dead head)."""
    import urllib.request

    from .cluster_utils import Cluster
    from .core import api as ca
    from .core.config import CAConfig
    from .core.worker import global_worker

    results: List[Tuple[str, float, str]] = []

    def record(name: str, value: float, unit: str):
        results.append((name, value, unit))
        print(f"{name}: {value:,.2f} {unit}")

    n_scrapes = 5 if quick else 20
    scrape_gap = 0.25  # leaves room for flush ticks between scrapes

    def node_scrape(cluster, nid: str) -> str:
        addr = open(
            os.path.join(cluster.session_dir, "nodes", nid, "metrics.addr")
        ).read().strip()
        with urllib.request.urlopen(addr + "/metrics", timeout=10) as r:
            return r.read().decode()

    def workload(plane_on: bool):
        cfg = CAConfig()
        cfg.metrics_plane = plane_on
        cluster = Cluster(head_resources={"CPU": 1}, config=cfg)
        nid = cluster.add_node(num_cpus=2)
        cluster.connect()
        try:
            @ca.remote
            def noisy(i):
                from cluster_anywhere_tpu.util.metrics import Counter

                Counter("mb_metricsplane_total", "a/b traffic source").inc()
                return i

            ca.get([noisy.remote(i) for i in range(40)], timeout=120)
            time.sleep(2.0)  # a couple of flush ticks settle the pipeline
            w = global_worker()
            rc0 = w.head_call("stats")["rpc_counts"]
            for _ in range(n_scrapes):
                if plane_on:
                    text = node_scrape(cluster, nid)
                    assert "ca_node_agent" in text
                else:
                    w.head_call("metrics_snapshot")
                time.sleep(scrape_gap)
            rc1 = w.head_call("stats")["rpc_counts"]
            per_scrape = {
                m: (rc1.get(m, 0) - rc0.get(m, 0)) / n_scrapes
                for m in ("metrics_snapshot", "metrics_report")
            }
            return per_scrape, cluster, nid
        except BaseException:
            cluster.shutdown()
            raise

    per_on, cluster_on, nid_on = workload(True)
    record(
        "metrics plane head snapshot RPCs/scrape (node scrape)",
        per_on["metrics_snapshot"], "ops",
    )
    record(
        "metrics plane head report RPCs/scrape (node scrape)",
        per_on["metrics_report"], "ops",
    )
    # --- scrape with the head DOWN (the plane's reason to exist) ----------
    try:
        cluster_on.kill_head()
        time.sleep(0.5)
        text = node_scrape(cluster_on, nid_on)
        ok = 1.0 if ("ca_node_agent_scrapes_total" in text and "# TYPE" in text) else 0.0
        record("metrics plane scrape with head down (1=ok)", ok, "")
        cluster_on.restart_head()
    finally:
        cluster_on.shutdown()

    per_off, cluster_off, _ = workload(False)
    try:
        record(
            "metrics plane head snapshot RPCs/scrape (head RPC)",
            per_off["metrics_snapshot"], "ops",
        )
        record(
            "metrics plane head report RPCs/scrape (head RPC)",
            per_off["metrics_report"], "ops",
        )
    finally:
        cluster_off.shutdown()
    return results


def run_transfer_plane(quick: bool = False) -> List[Tuple[str, float, str]]:
    """`ca microbenchmark --transfer`: A/B the bulk-transfer data plane.

    (1) Serial vs windowed object pulls on a LATENCY-INJECTED link
    (config.testing_transfer_delay_s per served chunk, so the number
    measures pipelining, not this host's memcpy speed), with the structural
    columns — window occupancy (avg per-pull peak in-flight pull_chunk
    RPCs) and head RPCs per pulled object (must not grow with the window).
    (2) 1-source vs 2-source pulls of an object with two live copies.
    (3) f32 vs int8/bf16 quantized host collective ring at 64 MB
    (effective bytes/s = input bytes reduced per second)."""
    from .cluster_utils import Cluster
    from .core import api as ca
    from .core.config import CAConfig
    from .core.scheduling_strategies import NodeAffinitySchedulingStrategy
    from .core.worker import TRANSFER_STATS, global_worker

    results: List[Tuple[str, float, str]] = []

    def record(name: str, value: float, unit: str):
        results.append((name, value, unit))
        print(f"{name}: {value:,.2f} {unit}")

    delay = 0.02
    chunk = 256 * 1024
    nobj = 2 if quick else 4
    size = 4 * 1024**2 if quick else 8 * 1024**2

    def pull_bench(window: int, two_sources: bool = False, multi: bool = True):
        cfg = CAConfig()
        cfg.transfer_window = window
        cfg.transfer_chunk_bytes = chunk
        cfg.testing_transfer_delay_s = delay
        cfg.transfer_multi_source = multi
        cluster = Cluster(head_resources={"CPU": 1}, config=cfg)
        n1 = cluster.add_node(num_cpus=2)
        n2 = cluster.add_node(num_cpus=2) if two_sources else None
        cluster.connect()
        cluster.wait_for_nodes(3 if two_sources else 2)
        try:
            @ca.remote
            def produce(n):
                import numpy as _np

                return _np.frombuffer(_np.random.bytes(n), dtype=_np.uint8)

            @ca.remote
            def touch(a):
                return int(a[0]) + int(a[-1])

            na = NodeAffinitySchedulingStrategy
            refs = [
                produce.options(scheduling_strategy=na(n1)).remote(size)
                for _ in range(nobj)
            ]
            ca.wait(refs, num_returns=len(refs), timeout=300)
            if two_sources:
                # a consumer on n2 pulls each object once: the directory now
                # lists two live copies per object
                ca.get(
                    [
                        touch.options(scheduling_strategy=na(n2)).remote(r)
                        for r in refs
                    ],
                    timeout=600,
                )
                time.sleep(1.0)  # obj_copy notifies land
            w = global_worker()
            rc0 = w.head_call("stats")["rpc_counts"]
            s0 = dict(TRANSFER_STATS)
            t0 = time.perf_counter()
            outs = ca.get(refs, timeout=600)  # the driver pulls each object
            dt = time.perf_counter() - t0
            assert len(outs) == nobj and all(o.nbytes == size for o in outs)
            rc1 = w.head_call("stats")["rpc_counts"]
            d = {k: TRANSFER_STATS[k] - s0[k] for k in TRANSFER_STATS}
            head_per_obj = sum(
                rc1.get(m, 0) - rc0.get(m, 0)
                for m in ("obj_locate", "obj_pin")
            ) / nobj
            occupancy = d["window_peak_sum"] / max(1, d["pulls"])
            return nobj * size / dt, occupancy, head_per_obj, d
        finally:
            cluster.shutdown()

    bps, occ, head_rpc, _ = pull_bench(window=1)
    record("transfer pull serial (window=1)", bps / 1e6, "MB/s")
    record("transfer pull serial window occupancy", occ, "rpcs")
    record("transfer pull serial head RPCs/object", head_rpc, "ops")
    bps_w, occ_w, head_rpc_w, _ = pull_bench(window=4)
    record("transfer pull windowed (window=4)", bps_w / 1e6, "MB/s")
    record("transfer pull windowed window occupancy", occ_w, "rpcs")
    record("transfer pull windowed head RPCs/object", head_rpc_w, "ops")
    record("transfer pull windowed speedup", bps_w / bps, "x")
    bps_1, _, _, d1 = pull_bench(window=4, two_sources=True, multi=False)
    record("transfer pull 1-source (2 copies live)", bps_1 / 1e6, "MB/s")
    bps_2, _, _, d2 = pull_bench(window=4, two_sources=True, multi=True)
    record("transfer pull 2-source (2 copies live)", bps_2 / 1e6, "MB/s")
    record("transfer pull multi-source speedup", bps_2 / bps_1, "x")
    record(
        "transfer pull 2-source pulls drawing from both holders",
        d2["multi_source_pulls"], "pulls",
    )

    # --- quantized collective ring (f32 vs int8 vs bf16) ------------------
    from .parallel import collectives as coll

    owns = not ca.is_initialized()
    if owns:
        ca.init(num_cpus=4)

    @ca.remote
    class Rank(coll.CollectiveActorMixin):
        def bench(self, nbytes, reps, group, quantize):
            import numpy as _np

            arr = _np.frombuffer(_np.random.bytes(nbytes), dtype=_np.float32)
            coll.allreduce(arr, group_name=group, quantize=quantize)  # warm
            t0 = time.perf_counter()
            for _ in range(reps):
                out = coll.allreduce(arr, group_name=group, quantize=quantize)
            dt = time.perf_counter() - t0
            assert out.shape == arr.shape
            return reps * nbytes / dt

    from .core.actor import kill as _kill

    # quick still runs 32 MB: below ~16 MB the per-hop fixed costs (loop
    # latency, frame handling) flatten the quantized-vs-f32 ratio into noise
    nbytes = 32 * 1024**2 if quick else 64 * 1024**2
    reps = 2 if quick else 3
    ratios = {}
    for world in (2,) if quick else (2, 4):
        ranks = [Rank.remote() for _ in range(world)]
        coll.create_collective_group(
            ranks, world, list(range(world)), group_name=f"tq{world}"
        )
        base = None
        for qmode in (None, "int8", "bf16"):
            per_rank = ca.get(
                [
                    r.bench.remote(nbytes, reps, f"tq{world}", qmode)
                    for r in ranks
                ],
                timeout=900,
            )
            eff = min(per_rank)
            label = qmode or "f32"
            record(
                f"ring allreduce {label} ({world} ranks, {nbytes >> 20} MB)",
                eff / 1e9, "GB/s per rank",
            )
            if qmode is None:
                base = eff
            else:
                ratios[(world, qmode)] = eff / base
                record(
                    f"ring allreduce {label} speedup vs f32 ({world} ranks)",
                    eff / base, "x",
                )
        coll.destroy_group_on(ranks, f"tq{world}")
        for r in ranks:
            _kill(r)
    if owns:
        ca.shutdown()
    return results


def _sse_request(
    host: str,
    port: int,
    path: str,
    body: Optional[dict] = None,
    timeout: float = 120.0,
) -> Tuple[int, Optional[float], float, int]:
    """One open-loop SSE request over a raw socket.  Returns
    (status, ttft_s or None, total_s, n_events).  TTFT = first `data:` line
    on the wire — what an LLM user actually waits for."""
    import json as _json
    import socket

    t0 = time.perf_counter()
    payload = _json.dumps(body or {}).encode()
    req = (
        f"POST {path} HTTP/1.1\r\nHost: x\r\nAccept: text/event-stream\r\n"
        f"Content-Type: application/json\r\nContent-Length: {len(payload)}\r\n\r\n"
    ).encode() + payload
    s = socket.create_connection((host, port), timeout=timeout)
    try:
        s.settimeout(timeout)
        s.sendall(req)
        buf = b""
        status = 0
        ttft = None
        n_events = 0
        scanned = 0  # resume `data:` counting where the last scan stopped
        while True:
            try:
                chunk = s.recv(65536)
            except OSError:
                break
            if not chunk:
                break
            buf += chunk
            if status == 0 and b"\r\n" in buf:
                try:
                    status = int(buf.split(b"\r\n", 1)[0].split()[1])
                except (IndexError, ValueError):
                    status = 599
                if status != 200:
                    break  # shed/error responses are small: headers+json body
            if ttft is None and b"data:" in buf:
                ttft = time.perf_counter() - t0
            n_events += buf.count(b"data:", scanned)
            # keep a 4-byte overlap: a `data:` straddling two recv()s must
            # count once it completes (an undercount here reads as a
            # dropped request in the drain zero-drop proof)
            scanned = max(0, len(buf) - 4)
        return status, ttft, time.perf_counter() - t0, n_events
    finally:
        s.close()


def _open_loop(
    host: str,
    port: int,
    path: str,
    make_body,
    rate_hz: float,
    duration_s: float,
) -> Tuple[List[Tuple[float, int, Optional[float], float, int]], float]:
    """Open-loop load: requests START at the arrival schedule no matter how
    slow completions are (closed-loop clients would self-throttle and hide
    the saturation knee).  Returns ([(start_s, status, ttft, total,
    n_events)], wall_s) — start_s relative to the trial start, wall_s the
    time until the LAST completion (the honest divisor for served/s when a
    backlog outlives the arrival window)."""
    import threading as _th

    results: List = []
    lock = _th.Lock()
    threads: List[_th.Thread] = []
    t0 = time.perf_counter()

    def one(i: int, start_s: float):
        try:
            r = _sse_request(host, port, path, make_body(i))
        except Exception:
            r = (598, None, 0.0, 0)  # connect/transport failure
        with lock:
            results.append((start_s,) + r)

    i = 0
    while True:
        due = i / rate_hz
        now = time.perf_counter() - t0
        if due > duration_s:
            break
        if now < due:
            time.sleep(due - now)
        t = _th.Thread(
            target=one, args=(i, time.perf_counter() - t0), daemon=True
        )
        t.start()
        threads.append(t)
        i += 1
    for t in threads:
        t.join(timeout=150)
    return results, time.perf_counter() - t0


def _pct(xs: List[float], q: float) -> float:
    if not xs:
        return 0.0
    return float(np.percentile(np.asarray(xs), q * 100))


def run_serve_plane(quick: bool = False) -> List[Tuple[str, float, str]]:
    """`ca microbenchmark --serve`: the serving-plane envelope.

    (1) Open-loop SSE load through proxy -> router -> ContinuousLLMServer at
        increasing arrival rates: requests/s served, TTFT p50/p99, total p99.
    (2) Admission A/B at ~2x the knee: with the gate ON the proxy sheds
        (429/503 + Retry-After) and the SERVED requests' p99 stays bounded;
        OFF, everything queues and p99 grows with the backlog.
    (3) Prefix-cache A/B: shared-system-prompt traffic vs distinct prompts —
        hits skip the prefix prefill, measured as the TTFT drop.
    (4) Drain-under-load: 2-node cluster, drain the replica-hosting node
        mid-traffic — zero dropped requests, replacement replicas spawn, and
        TTFT p99 during the drain stays within ~2x steady state."""
    import socket

    from . import serve
    from .core import api as ca
    from .core.actor import get_actor
    from .llm.processor import ProcessorConfig
    from .llm.serve_llm import build_continuous_llm_deployment
    from .serve.config import AdmissionPolicy
    from .serve.controller import CONTROLLER_NAME

    results: List[Tuple[str, float, str]] = []

    def record(name: str, value: float, unit: str):
        results.append((name, value, unit))
        print(f"{name}: {value:,.2f} {unit}")

    def free_port() -> int:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    host = "127.0.0.1"

    # ---------------- phase 1+2: envelope + shedding (single-node) --------
    owns = not ca.is_initialized()
    if owns:
        ca.init(num_cpus=4)
    port = free_port()
    serve.start(host=host, port=port)
    slots = 4
    mnt = 8 if quick else 16
    cfg = ProcessorConfig(max_prompt_len=64, max_new_tokens=mnt)
    app = build_continuous_llm_deployment(
        cfg, slots=slots, num_replicas=1, sse_ingress=True,
        admission=AdmissionPolicy(max_queue_depth=2 * slots),
    )
    serve.run(app, name="llmserve", route_prefix="/llmserve")
    time.sleep(1.0)  # proxy route refresh picks up the admission policy

    def body(i: int) -> dict:
        return {"prompt": f"request {i:04d} " + "x" * 16, "max_new_tokens": mnt}

    # warmup: compile prefill/decode programs before any timing
    for i in range(2):
        st, _, _, _ = _sse_request(host, port, "/llmserve", body(i))
        assert st == 200, f"warmup request failed: HTTP {st}"
    # knee estimate from a short closed-loop burst
    t0 = time.perf_counter()
    n_burst = 6
    for i in range(n_burst):
        _sse_request(host, port, "/llmserve", body(100 + i))
    svc = n_burst / (time.perf_counter() - t0)  # closed-loop service rate
    # continuous batching shares one decode loop, so capacity is closer to
    # the closed-loop rate than to slots x it; "below knee" = ~0.7x that
    base_rate = max(0.5, svc * 0.7)
    dur = 5.0 if quick else 8.0

    def trial(rate: float, label: str):
        rs, wall = _open_loop(host, port, "/llmserve", body, rate, dur)
        ok = [r for r in rs if r[1] == 200]
        shed = [r for r in rs if r[1] in (429, 503)]
        err = [r for r in rs if r[1] not in (200, 429, 503)]
        ttfts = [r[2] for r in ok if r[2] is not None]
        record(f"serve {label} offered", rate, "req/s")
        record(f"serve {label} served", len(ok) / max(wall, 1e-9), "req/s")
        record(f"serve {label} shed", float(len(shed)), "req")
        record(f"serve {label} errors", float(len(err)), "req")
        record(f"serve {label} TTFT p50", _pct(ttfts, 0.5) * 1e3, "ms")
        record(f"serve {label} TTFT p99", _pct(ttfts, 0.99) * 1e3, "ms")
        record(
            f"serve {label} total p99",
            _pct([r[3] for r in ok], 0.99) * 1e3, "ms",
        )
        return rs

    trial(base_rate, "below-knee")
    over = max(2.0, svc * 2.5)
    trial(over, "overload admission-on")
    # admission OFF at the same overload: same code, config-only redeploy
    app_off = build_continuous_llm_deployment(
        cfg, slots=slots, num_replicas=1, sse_ingress=True, admission=None,
    )
    serve.run(app_off, name="llmserve", route_prefix="/llmserve")
    time.sleep(1.5)  # proxy refresh drops the policy
    trial(over, "overload admission-off")

    # ---------------- phase 3: prefix-cache A/B ---------------------------
    pfx_cfg = ProcessorConfig(
        max_prompt_len=256, max_new_tokens=8, prefix_cache_entries=8,
        prefix_block=16,
    )
    pfx_app = build_continuous_llm_deployment(
        pfx_cfg, slots=slots, num_replicas=1, sse_ingress=True,
        name="LLMPrefix",
    )
    serve.run(pfx_app, name="llmpfx", route_prefix="/llmpfx")
    system = "You are a terse assistant. " * 9  # ~240 chars -> 240 byte-tokens
    n_seq = 6 if quick else 12

    def seq_ttft(mk_body) -> List[float]:
        out = []
        for i in range(n_seq):
            st, ttft, _, _ = _sse_request(host, port, "/llmpfx", mk_body(i))
            if st == 200 and ttft is not None:
                out.append(ttft)
        return out

    # warm the programs AND seed the cache with the shared prefix
    seq_ttft(lambda i: {"prompt": system + f"warm {i}", "max_new_tokens": 8})
    shared = seq_ttft(lambda i: {"prompt": system + f"q{i:03d}", "max_new_tokens": 8})
    distinct = seq_ttft(
        lambda i: {"prompt": f"{i:03d} " * 60 + f"q{i}", "max_new_tokens": 8}
    )
    record("serve prefix shared TTFT p50", _pct(shared, 0.5) * 1e3, "ms")
    record("serve prefix distinct TTFT p50", _pct(distinct, 0.5) * 1e3, "ms")
    if shared and distinct:
        record(
            "serve prefix TTFT speedup",
            _pct(distinct, 0.5) / max(_pct(shared, 0.5), 1e-9), "x",
        )
    try:
        from .util.state import serve_plane

        time.sleep(2.5)  # engine-metrics sync + flush tick
        counters = serve_plane()["counters"]
        record(
            "serve prefix cache hits",
            float(counters.get("prefix_hits_total", 0)), "req",
        )
        record(
            "serve prefix tokens reused",
            float(counters.get("prefix_tokens_reused_total", 0)), "tok",
        )
    except Exception as e:
        print(f"(prefix counters unavailable: {e!r})")
    serve.delete("llmpfx")
    serve.delete("llmserve")
    serve.shutdown()
    if owns:
        ca.shutdown()

    # ---------------- phase 4: drain under load (multi-node) --------------
    from .cluster_utils import Cluster

    c = Cluster(head_resources={"CPU": 1})
    c.add_node(num_cpus=3)
    c.add_node(num_cpus=3)
    c.connect()
    c.wait_for_nodes(3)
    try:
        port2 = free_port()
        serve.start(host=host, port=port2)

        @serve.deployment(num_replicas=2, max_ongoing_requests=8)
        class TokenStream:
            def __call__(self, request):
                n = 20
                for i in range(n):
                    time.sleep(0.05)
                    yield {"token": i}

        serve.run(TokenStream.bind(), name="drainapp", route_prefix="/drainapp")
        time.sleep(1.0)
        # warm
        st, _, _, ne = _sse_request(host, port2, "/drainapp", {})
        assert st == 200 and ne >= 20, f"warmup stream failed: {st}/{ne}"

        ctrl = get_actor(CONTROLLER_NAME)
        info = ca.get(ctrl.serve_plane_info.remote(), timeout=10)
        reps = info["drainapp"]["TokenStream"]["replicas"]
        victim = next(
            n for n in (r["node_id"] for r in reps.values()) if n and n != "n0"
        )

        rate = 4.0 if quick else 6.0
        dur2 = 10.0 if quick else 14.0
        drain_at = 3.0
        drained = {}

        def drainer():
            time.sleep(drain_at)
            drained["t"] = time.perf_counter()
            ca.drain_node(victim, reason="preemption", deadline_s=30.0)

        import threading as _th

        th = _th.Thread(target=drainer, daemon=True)
        t_start = time.perf_counter()
        th.start()
        rs, _wall = _open_loop(host, port2, "/drainapp", lambda i: {}, rate, dur2)
        th.join()
        ok = [r for r in rs if r[1] == 200 and r[4] >= 20]
        bad = [r for r in rs if r not in ok]
        # split steady-state vs during-drain by request START time
        cut = drained["t"] - t_start
        steady = [r[2] for r in ok if r[2] is not None and r[0] < cut]
        during = [r[2] for r in ok if r[2] is not None and r[0] >= cut]
        record("serve drain requests", float(len(rs)), "req")
        record("serve drain dropped/errored", float(len(bad)), "req")
        record("serve drain TTFT p99 steady", _pct(steady, 0.99) * 1e3, "ms")
        record("serve drain TTFT p99 during", _pct(during, 0.99) * 1e3, "ms")
        if steady and during:
            record(
                "serve drain TTFT p99 ratio",
                _pct(during, 0.99) / max(_pct(steady, 0.99), 1e-9), "x",
            )
        info = ca.get(ctrl.serve_plane_info.remote(), timeout=10)
        d = info["drainapp"]["TokenStream"]
        record(
            "serve drain final active replicas",
            float(d["actual_replicas"] - len(d["draining_replicas"])), "replicas",
        )
        serve.delete("drainapp")
        serve.shutdown()
    finally:
        c.shutdown()
    return results


def run_dag_plane(quick: bool = False) -> List[Tuple[str, float, str]]:
    """`ca microbenchmark --dag`: compiled-DAG plane A/B.

    (1) Actor-call A/B on one actor: per-call RPC latency (sync p50) and
        async throughput vs compiled-DAG tick latency over pre-opened shm
        channels (driver write -> futex wake -> compute -> futex wake ->
        driver read; zero RPCs in steady state) and pipelined throughput at
        max_inflight_executions.
    (2) 3-actor chain A/B: chained RPC per item vs one compiled graph.
    (3) Serve TTFT A/B: ContinuousLLMServer SSE below the knee with
        config.serve_compiled_dag OFF vs ON — a fresh cluster per mode,
        env-toggled so the proxy process inherits the setting."""
    import socket

    from .core import api as ca
    from .dag import InputNode

    results: List[Tuple[str, float, str]] = []

    def record(name: str, value: float, unit: str):
        results.append((name, value, unit))
        print(f"{name}: {value:,.2f} {unit}")

    # ---------------- phase 1+2: actor-call / chain A/B -------------------
    owns = not ca.is_initialized()
    if owns:
        ca.init(num_cpus=4)

    @ca.remote
    class Relay:
        def step(self, x):
            return x

    actors = [Relay.remote() for _ in range(3)]
    a = actors[0]
    ca.get([x.step.remote(0) for x in actors])

    n_lat = 200 if quick else 1000
    n_thru = 2000 if quick else 10000

    def sync_p50(fn) -> float:
        lats = []
        for i in range(n_lat):
            t0 = time.perf_counter()
            fn(i)
            lats.append(time.perf_counter() - t0)
        return _pct(lats, 0.5)

    rpc_p50 = sync_p50(lambda i: ca.get(a.step.remote(i)))
    record("dag rpc actor-call sync p50", rpc_p50 * 1e6, "us")
    record(
        "dag rpc actor-call async",
        _rate(n_thru, lambda: ca.get([a.step.remote(i) for i in range(n_thru)])),
        "/s",
    )

    inflight = 8
    with InputNode() as inp:
        node = a.step.bind(inp)
    cd = node.experimental_compile(max_inflight_executions=inflight)
    assert cd.execute(0).get() == 0  # warm channels + loop
    dag_p50 = sync_p50(lambda i: cd.execute(i).get())
    record("dag compiled tick sync p50", dag_p50 * 1e6, "us")
    record("dag compiled vs rpc sync latency", rpc_p50 / max(dag_p50, 1e-9), "x")

    def pipelined():
        refs = []
        for i in range(n_thru):
            refs.append(cd.execute(i))
            if len(refs) >= inflight:
                refs.pop(0).get()
        while refs:
            refs.pop(0).get()

    record("dag compiled pipelined", _rate(n_thru, pipelined), "/s")
    cd.teardown()

    # 3-hop chain: driver -> a -> b -> c -> driver
    rpc3_p50 = sync_p50(
        lambda i: ca.get(
            actors[2].step.remote(actors[1].step.remote(actors[0].step.remote(i)))
        )
    )
    record("dag rpc 3-actor chain sync p50", rpc3_p50 * 1e6, "us")
    with InputNode() as inp:
        x = actors[0].step.bind(inp)
        x = actors[1].step.bind(x)
        x = actors[2].step.bind(x)
    cd3 = x.experimental_compile(max_inflight_executions=inflight)
    assert cd3.execute(0).get() == 0
    dag3_p50 = sync_p50(lambda i: cd3.execute(i).get())
    record("dag compiled 3-actor chain sync p50", dag3_p50 * 1e6, "us")
    record(
        "dag compiled vs rpc 3-actor latency", rpc3_p50 / max(dag3_p50, 1e-9), "x"
    )
    cd3.teardown()
    from .core.actor import kill as _kill

    for x in actors:
        _kill(x)
    if owns:
        ca.shutdown()

    # ---------------- phase 3: serve TTFT A/B -----------------------------
    if not owns:
        print("(serve TTFT A/B skipped: caller owns the cluster; the A/B "
              "needs a fresh cluster per mode)")
        return results
    from . import serve
    from .llm.processor import ProcessorConfig
    from .llm.serve_llm import build_continuous_llm_deployment

    def free_port() -> int:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    host = "127.0.0.1"
    mnt = 8 if quick else 16
    n_req = 8 if quick else 16
    prev = os.environ.get("CA_SERVE_COMPILED_DAG")
    try:
        for label, flag in (("rpc-stream", "0"), ("compiled", "1")):
            # env-toggled BEFORE init so the proxy's process inherits it
            os.environ["CA_SERVE_COMPILED_DAG"] = flag
            ca.init(num_cpus=4)
            port = free_port()
            serve.start(host=host, port=port)
            cfg = ProcessorConfig(max_prompt_len=64, max_new_tokens=mnt)
            app = build_continuous_llm_deployment(
                cfg, slots=4, num_replicas=1, sse_ingress=True,
            )
            serve.run(app, name="llmdag", route_prefix="/llmdag")
            time.sleep(1.0)

            def body(i: int) -> dict:
                return {
                    "prompt": f"request {i:04d} " + "x" * 16,
                    "max_new_tokens": mnt,
                }

            for i in range(2):  # compile prefill/decode before timing
                st, _, _, _ = _sse_request(host, port, "/llmdag", body(i))
                assert st == 200, f"warmup request failed: HTTP {st}"
            ttfts, events = [], 0
            for i in range(n_req):
                st, ttft, _, ne = _sse_request(host, port, "/llmdag", body(10 + i))
                if st == 200 and ttft is not None:
                    ttfts.append(ttft)
                    events += ne
            record(f"dag serve {label} TTFT p50", _pct(ttfts, 0.5) * 1e3, "ms")
            record(f"dag serve {label} TTFT p99", _pct(ttfts, 0.99) * 1e3, "ms")
            record(f"dag serve {label} events", float(events), "ev")
            serve.delete("llmdag")
            serve.shutdown()
            ca.shutdown()
    finally:
        if prev is None:
            os.environ.pop("CA_SERVE_COMPILED_DAG", None)
        else:
            os.environ["CA_SERVE_COMPILED_DAG"] = prev
    return results


def run_partition_chaos(quick: bool = False, seed: int = 1234) -> List[Tuple[str, float, str]]:
    """`ca microbenchmark --partition`: the partition-tolerance timeline.

    A head<->node blackhole lands mid-workload (side-effect tasks that
    commit a uniquely-keyed KV write per ATTEMPT).  Measured: how long the
    head takes to DETECT the silent node (heartbeat timeout -> death
    verdict), how many stale-incarnation RPCs the FENCE refused, and how
    long after the scheduled HEAL the node is back alive at a fresh
    incarnation.  Structural proofs: every logical task committed exactly
    once (zombie commits were fenced, not duplicated), and the healed node
    carries zero grants minted before the verdict."""
    from .cluster_utils import Cluster
    from .core import api as ca
    from .core.config import CAConfig
    from .core.worker import global_worker
    from .util.chaos import NetworkPartition

    results: List[Tuple[str, float, str]] = []

    def record(name: str, value: float, unit: str):
        results.append((name, value, unit))
        print(f"{name}: {value:,.2f} {unit}")

    print(f"partition chaos seed={seed} (replay: CA_PARTITION_SEED={seed})")
    cfg = CAConfig()
    cfg.health_check_period_s = 0.5
    cfg.health_check_failure_threshold = 3
    n_tasks = 6 if quick else 10
    duration = 6.0 if quick else 8.0
    c = Cluster(head_resources={"CPU": 2}, config=cfg)
    nid = c.add_node(num_cpus=2)
    c.connect()
    try:
        c.wait_for_nodes(2)
        w = global_worker()

        def node_row():
            return next(
                (n for n in ca.nodes() if n["node_id"] == nid), None
            )

        inc0 = node_row()["incarnation"]

        @ca.remote(max_retries=5)
        def commit(i, sleep_s):
            import os as _os
            import time as _t

            from cluster_anywhere_tpu.core.worker import global_worker as _gw

            _t.sleep(sleep_s)
            # the side effect: a fenced, attempt-keyed KV commit — a zombie
            # attempt's stamp is stale after the verdict, so it is REFUSED
            _gw().head_call(
                "kv_put", ns="chaos_se",
                key=f"{i}:{_os.urandom(4).hex()}", value=b"1",
            )
            return i

        refs = [commit.remote(i, 3.0) for i in range(n_tasks)]
        time.sleep(0.3)  # tasks land on both nodes before the cut
        part = NetworkPartition(nid, "n0", duration_s=duration, seed=seed).start()
        t_cut = part.epoch + part.start_after_s
        # --- detect: heartbeat silence -> death verdict -------------------
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            row = node_row()
            if row is None or not row["alive"]:
                break
            time.sleep(0.05)
        t_detect = time.time()
        record("partition detect", t_detect - t_cut, "s")
        # --- resubmit: the workload survives on the other side ------------
        assert ca.get(refs, timeout=120) == list(range(n_tasks))
        # --- heal: schedule re-opens the link; node rejoins fresh ---------
        part.wait_heal()
        deadline = time.monotonic() + 30
        row = None
        while time.monotonic() < deadline:
            row = node_row()
            if row is not None and row["alive"] and row["incarnation"] > inc0:
                break
            time.sleep(0.1)
        assert row is not None and row["incarnation"] > inc0, (
            f"node never rejoined fresh (seed={seed}): {row}"
        )
        record("partition heal->rejoin", time.time() - part.heals_at(), "s")
        record("partition incarnation delta", row["incarnation"] - inc0, "x")
        stats = w.head_call("stats")["stats"]
        record("partition fenced RPCs", float(stats.get("fenced_rpcs", 0)), "ops")
        # --- at-most-once: one commit per logical task --------------------
        keys = w.head_call("kv_keys", ns="chaos_se")["keys"]
        per_task = [len([k for k in keys if k.startswith(f"{i}:")]) for i in range(n_tasks)]
        dups = sum(max(0, n - 1) for n in per_task)
        missing = sum(1 for n in per_task if n == 0)
        record("partition duplicate commits", float(dups), "tasks")
        record("partition missing commits", float(missing), "tasks")
        # --- zombie grants: the healed node's blocks start empty ----------
        used = sum(
            b.get("used", 0) for b in (row.get("lease_blocks") or {}).values()
        )
        record("partition zombie grants after heal", float(used), "grants")
        part.clear()
    finally:
        c.shutdown()
    return results


def run_ha_plane(quick: bool = False) -> List[Tuple[str, float, str]]:
    """`ca microbenchmark --ha`: the head-failover timeline.

    A warm standby replicates the active head's registry; the active head is
    SIGKILLed mid-workload (side-effect tasks in flight, synchronously
    replicated "acked" KV writes committed beforehand).  Measured: how long
    from the kill until a standby promotes (detect -> promote), and until
    the driver's first successful operation against the successor.
    Structural proofs: every acked KV write survives (loss = 0), every
    logical side-effect task committed exactly once (dup = 0), and the
    successor's epoch is strictly above the dead head's."""
    from .cluster_utils import Cluster
    from .core import api as ca
    from .core.config import CAConfig
    from .core.worker import global_worker

    results: List[Tuple[str, float, str]] = []

    def record(name: str, value: float, unit: str):
        results.append((name, value, unit))
        print(f"{name}: {value:,.3f} {unit}")

    cfg = CAConfig()
    cfg.health_check_period_s = 0.5
    cfg.health_check_failure_threshold = 3
    cfg.ha_failover_grace_s = 1.0
    n_keys = 20 if quick else 50
    n_tasks = 6 if quick else 10
    c = Cluster(head_resources={"CPU": 2}, config=cfg)
    nid = c.add_node(num_cpus=2)
    c.add_standby(rank=0)
    c.connect()
    try:
        c.wait_for_nodes(2)
        w = global_worker()
        # wait for the standby to subscribe: only then are KV puts "acked"
        # (synchronously standby-resident before the reply)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            if w.head_call("ha_status").get("standbys"):
                break
            time.sleep(0.05)
        else:
            raise TimeoutError("standby never subscribed to the repl stream")
        for i in range(n_keys):
            w.head_call("kv_put", ns="ha_acked", key=f"k{i}", value=b"v")

        @ca.remote(max_retries=5)
        def commit(i, sleep_s):
            import os as _os
            import time as _t

            from cluster_anywhere_tpu.core.worker import global_worker as _gw

            _t.sleep(sleep_s)
            # attempt-keyed side effect: a duplicate execution would show up
            # as a second key with the same logical prefix
            _gw().head_call(
                "kv_put", ns="ha_se",
                key=f"{i}:{_os.urandom(4).hex()}", value=b"1",
            )
            return i

        refs = [commit.remote(i, 2.0) for i in range(n_tasks)]
        time.sleep(0.3)  # tasks are in flight when the head dies
        # --- SIGKILL the active head; the standby detects and promotes ----
        t_kill = time.time()
        c.kill_head()
        c.wait_promoted(timeout=45)
        record("ha detect->promote", time.time() - t_kill, "s")
        # --- first successful driver op through the failover ring ---------
        deadline = time.monotonic() + 45
        while True:
            try:
                w.head_call("kv_get", ns="ha_acked", key="k0")
                break
            except Exception:
                if time.monotonic() > deadline:
                    raise
                time.sleep(0.05)
        record("ha detect->promote->first op", time.time() - t_kill, "s")
        # --- acked-KV loss: every replicated write survived ----------------
        keys = w.head_call("kv_keys", ns="ha_acked")["keys"]
        lost = sum(1 for i in range(n_keys) if f"k{i}" not in keys)
        record("ha acked KV loss", float(lost), "keys")
        # --- the workload drains to completion on the successor ------------
        assert sorted(ca.get(refs, timeout=120)) == list(range(n_tasks))
        se = w.head_call("kv_keys", ns="ha_se")["keys"]
        per_task = [
            len([k for k in se if k.startswith(f"{i}:")]) for i in range(n_tasks)
        ]
        record(
            "ha duplicate side effects",
            float(sum(max(0, n - 1) for n in per_task)), "tasks",
        )
        record(
            "ha missing side effects",
            float(sum(1 for n in per_task if n == 0)), "tasks",
        )
        st = w.head_call("ha_status")
        record("ha promotion epoch bump", float(st["epoch"] - 1), "x")
        record("ha repl lag", float(st.get("repl_lag") or 0), "records")
        assert st["role"] == "active" and st["epoch"] >= 2
        # keep the surviving node honest: it must still be schedulable
        assert any(
            n["node_id"] == nid and n["alive"] for n in ca.nodes()
        ), "agent never re-anchored to the promoted head"
    finally:
        c.shutdown()
    return results


def head_saturation(quick: bool = False) -> List[Tuple[str, float, str]]:
    """`ca microbenchmark --saturation`: find where the single head's asyncio
    loop saturates (VERDICT r3 weak #6 — the directory/refcount/lease/pubsub
    planes all ride one loop; this records the envelope so round N+1 knows
    whether ownership needs distributing).

    Two sweeps:
    - control-plane ops/s vs concurrent driver connections (KV round-trips:
      the cheapest RPC, so the number is the loop's dispatch ceiling);
    - the same at the knee while K idle agent nodes heartbeat, measuring how
      much node-table upkeep steals from the dispatch budget.
    """
    import threading

    from .cluster_utils import Cluster
    from .core.protocol import BlockingClient

    results: List[Tuple[str, float, str]] = []

    def record(name: str, value: float, unit: str):
        results.append((name, value, unit))
        print(f"{name}: {value:,.1f} {unit}")

    cluster = Cluster(head_resources={"CPU": 2})
    try:
        n_per = 200 if quick else 1000

        def hammer(out, i):
            conn = BlockingClient(cluster.head_tcp)
            try:
                # "probe" role: served like a client but without driver-exit
                # or worker-table semantics
                conn.call("register", role="probe", client_id=f"sat{i}")
                t0 = time.perf_counter()
                for k in range(n_per):
                    conn.call("kv_put", key=f"sat{i}/{k % 8}", value=b"x")
                out[i] = n_per / (time.perf_counter() - t0)
            finally:
                conn.close()

        def sweep(m: int) -> float:
            out = [0.0] * m
            threads = [
                threading.Thread(target=hammer, args=(out, i)) for i in range(m)
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            elapsed = time.perf_counter() - t0
            if not all(out):
                # a dead hammer thread exactly at the knee would otherwise be
                # silently credited with its full op count
                raise RuntimeError(f"{out.count(0.0)} of {m} probe clients failed")
            return m * n_per / elapsed

        for m in (1, 2, 4, 8, 16):
            record(f"head kv ops ({m} clients)", sweep(m), "/s")

        # node-scale: idle agents heartbeating while 8 clients hammer
        def wait_nodes(n):
            probe = BlockingClient(cluster.head_tcp)
            try:
                probe.call("register", role="probe", client_id="satwait")
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline:
                    alive = [
                        x for x in probe.call("nodes")["nodes"] if x["alive"]
                    ]
                    if len(alive) >= n:
                        return
                    time.sleep(0.1)
                raise TimeoutError(f"cluster did not reach {n} nodes")
            finally:
                probe.close()

        for k in (4, 16):
            for _ in range(k - (len(cluster._agents))):
                cluster.add_node(num_cpus=1)
            wait_nodes(k + 1)
            record(f"head kv ops (8 clients, {k} nodes heartbeating)", sweep(8), "/s")
    finally:
        cluster.shutdown()
    return results


def run_train_elastic(quick: bool = False) -> List[Tuple[str, float, str]]:
    """`ca microbenchmark --train-elastic`: A/B the preemption-elastic
    train plane.

    Both arms run the SAME training loop (periodic checkpoint every
    `ckpt_every` steps, cooperative `train.should_checkpoint()` saves) as a
    2-worker gang across two 1-CPU nodes, and preempt one worker node
    mid-run (`ca.drain_node(reason="preemption")`):

    - proactive (drain_aware=True, max_failures=0): the controller sees the
      warning, barriers a checkpoint at the next step boundary, and rebuilds
      on the survivor — budget-exempt, so max_failures=0 still succeeds.
    - reactive (drain_aware=False, max_failures=1): the controller only
      learns at the drain-deadline kill (poll failure) and resumes from the
      last PERIODIC checkpoint, re-running every step since it.

    Rows: preempt-warning -> training-resumed latency and steps lost
    (re-executed) per arm.  Steps lost counts from delivered reports, so it
    is a floor for the reactive arm (reports between the last poll and the
    kill die with the worker)."""
    import tempfile
    import threading

    from .cluster_utils import Cluster
    from .core import api as ca

    results: List[Tuple[str, float, str]] = []

    def record(name: str, value: float, unit: str):
        results.append((name, value, unit))
        print(f"{name}: {value:,.2f} {unit}")

    total = 40 if quick else 70
    step_s = 0.15
    ckpt_every = 10
    preempt_at = 8
    reactive_deadline_s = 3.0

    def loop(config):
        import time as _time

        import numpy as _np

        from cluster_anywhere_tpu import train
        from cluster_anywhere_tpu.train import Checkpoint

        ctx = train.get_context()
        ck = train.get_checkpoint()
        start = 0
        if ck is not None:
            start = int(ck.load_pytree_sharded()["step"]) + 1
        resumed = start > 0
        for step in range(start, config["total"]):
            _time.sleep(config["step_s"])  # the "compute"
            if (
                step == config["preempt_at"]
                and ctx.get_world_rank() == 0
                and not resumed
            ):
                open(config["go"], "w").close()  # arm the preempter
            save = (
                train.should_checkpoint()
                or step % config["ckpt_every"] == config["ckpt_every"] - 1
                or step == config["total"] - 1
            )
            metrics = {"step": step, "t": _time.time(), "resumed": resumed}
            if save:
                c = Checkpoint(train.shared_checkpoint_dir(step))
                c.save_pytree_sharded(
                    {"step": _np.int64(step)},
                    process_index=ctx.get_world_rank(),
                    num_processes=ctx.get_world_size(),
                )
                train.report(metrics, checkpoint=c)
            else:
                train.report(metrics)

    def arm(drain_aware: bool) -> Tuple[float, float]:
        from .train import (
            DataParallelTrainer,
            FailureConfig,
            RunConfig,
            ScalingConfig,
        )

        cluster = Cluster(head_resources={"CPU": 0})
        n1 = cluster.add_node(num_cpus=1)
        cluster.add_node(num_cpus=1)
        cluster.connect()
        try:
            cluster.wait_for_nodes(3)
            tmp = tempfile.mkdtemp(prefix="ca_train_elastic_")
            go = os.path.join(tmp, "go")
            warn_t: Dict[str, float] = {}

            def preempter():
                while not os.path.exists(go):
                    time.sleep(0.02)
                warn_t["t"] = time.time()
                ca.drain_node(
                    n1,
                    reason="preemption",
                    deadline_s=30.0 if drain_aware else reactive_deadline_s,
                )

            th = threading.Thread(target=preempter, daemon=True)
            th.start()
            res = DataParallelTrainer(
                loop,
                train_loop_config={
                    "total": total,
                    "step_s": step_s,
                    "ckpt_every": ckpt_every,
                    "preempt_at": preempt_at,
                    "go": go,
                },
                scaling_config=ScalingConfig(
                    num_workers=2, min_workers=1, max_workers=2
                ),
                run_config=RunConfig(
                    name="proactive" if drain_aware else "reactive",
                    storage_path=tmp,
                    failure_config=FailureConfig(
                        max_failures=0 if drain_aware else 1,
                        drain_aware=drain_aware,
                    ),
                ),
            ).fit()
            th.join(timeout=10)
            hist = res.metrics_history
            pre = [m for m in hist if not m["resumed"]]
            post = [m for m in hist if m["resumed"]]
            if not pre or not post:
                raise RuntimeError(
                    f"arm drain_aware={drain_aware}: no restart observed "
                    f"(pre={len(pre)}, post={len(post)})"
                )
            latency = min(m["t"] for m in post) - warn_t["t"]
            steps_lost = max(m["step"] for m in pre) - (
                min(m["step"] for m in post) - 1
            )
            return latency, float(max(0, steps_lost))
        finally:
            cluster.shutdown()

    lat_a, lost_a = arm(drain_aware=True)
    record("train-elastic proactive restart latency", lat_a, "s")
    record("train-elastic proactive steps lost", lost_a, "steps")
    lat_b, lost_b = arm(drain_aware=False)
    record("train-elastic reactive restart latency", lat_b, "s")
    record("train-elastic reactive steps lost", lost_b, "steps")
    return results


def run_obsplane(quick: bool = False) -> List[Tuple[str, float, str]]:
    """`ca microbenchmark --obsplane`: the flight-recorder cost model.

    Process-local rows: armed `record()` events/s (the full cost — dict
    build, trace probe, lock, ring append), the disabled-path gate rate
    (`REC is None`: one attribute load + branch, the off switch's whole
    cost), and the journal's memory footprint with the default ring at
    cap.  Cluster rows: simple-task round-trip throughput with
    flightrec_plane on vs off — the acceptance A/B: disabled within
    noise, enabled cost bounded by the journal's own record rate."""
    from .cluster_utils import Cluster
    from .core import api as ca
    from .core.config import CAConfig
    from .util import flightrec

    results: List[Tuple[str, float, str]] = []

    def record(name: str, value: float, unit: str):
        results.append((name, value, unit))
        print(f"{name}: {value:,.1f} {unit}")

    # --- process-local: the record path and the off switch ---------------
    n = 50_000 if quick else 400_000
    saved = flightrec.REC
    try:
        rec = flightrec.FlightRecorder(cap=4096, node_id="bench", proc="mb")
        flightrec.REC = rec
        t0 = time.perf_counter()
        for i in range(n):
            rec.record("dag", "dag_tick", idx=i)
        dt = time.perf_counter() - t0
        record("obsplane armed record events/s", n / dt, "/s")
        # the ring rotated many times over: this is the steady-state
        # footprint of a FULL default-cap journal
        record(
            "obsplane journal memory at cap", float(rec.memory_bytes()),
            "bytes",
        )
        st = rec.stats()
        assert st["len"] == st["cap"] and st["dropped"] == n - st["cap"]

        flightrec.REC = None
        acc = 0
        t0 = time.perf_counter()
        for i in range(n):
            if flightrec.REC is not None:  # the disabled hot-path gate
                flightrec.REC.record("dag", "dag_tick", idx=i)
            acc += i
        dt_off = time.perf_counter() - t0
        record("obsplane disabled gate checks/s", n / dt_off, "/s")
        record(
            "obsplane disabled ns/check", dt_off / n * 1e9, "ns",
        )
    finally:
        flightrec.REC = saved

    # --- cluster A/B: task throughput with the plane on vs off -----------
    def tput(plane_on: bool) -> float:
        cfg = CAConfig()
        cfg.flightrec_plane = plane_on
        cluster = Cluster(head_resources={"CPU": 2}, config=cfg)
        cluster.connect()
        try:
            @ca.remote
            def echo(i):
                return i

            ca.get([echo.remote(i) for i in range(20)], timeout=120)
            m = 200 if quick else 1000
            t0 = time.perf_counter()
            ca.get([echo.remote(i) for i in range(m)], timeout=300)
            return m / (time.perf_counter() - t0)
        finally:
            cluster.shutdown()

    # two alternating rounds, best-of-each: the FIRST cluster a process
    # starts pays one-time warmup (imports, forkserver) that would be
    # misread as plane overhead if one arm always went first
    on = max(tput(True), tput(True))
    off = max(tput(False), tput(False))
    record("obsplane tasks/s flightrec on", on, "/s")
    record("obsplane tasks/s flightrec off", off, "/s")
    record("obsplane off/on throughput ratio", off / max(on, 1e-9), "")
    return results


def main(
    quick: bool = False,
    saturation: bool = False,
    multiclient: bool = False,
    scalability: bool = False,
    collective: bool = False,
    lease_plane: bool = False,
    owner_plane: bool = False,
    transfer: bool = False,
    serve_plane: bool = False,
    train_elastic: bool = False,
    partition: bool = False,
    obsplane: bool = False,
):
    if saturation:
        head_saturation(quick=quick)
    elif multiclient:
        run_multiclient(quick=quick)
    elif scalability:
        run_scalability(quick=quick)
    elif collective:
        run_collective_bw(quick=quick)
    elif lease_plane:
        run_lease_plane(quick=quick)
    elif owner_plane:
        run_owner_plane(quick=quick)
    elif transfer:
        run_transfer_plane(quick=quick)
    elif serve_plane:
        run_serve_plane(quick=quick)
    elif train_elastic:
        run_train_elastic(quick=quick)
    elif partition:
        run_partition_chaos(quick=quick)
    elif obsplane:
        run_obsplane(quick=quick)
    else:
        run_microbenchmarks(quick=quick)


if __name__ == "__main__":
    import sys

    main(
        quick="--quick" in sys.argv,
        saturation="--saturation" in sys.argv,
        multiclient="--multi" in sys.argv,
        scalability="--scalability" in sys.argv,
        collective="--collective" in sys.argv,
        lease_plane="--lease-plane" in sys.argv,
        owner_plane="--owner-plane" in sys.argv,
        transfer="--transfer" in sys.argv,
        serve_plane="--serve" in sys.argv,
        train_elastic="--train-elastic" in sys.argv,
        partition="--partition" in sys.argv,
        obsplane="--obsplane" in sys.argv,
    )
