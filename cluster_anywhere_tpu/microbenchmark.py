"""`ca microbenchmark` — the reference's `ray microbenchmark`
(python/ray/_private/ray_perf.py:93) surface: one command printing the
canonical single-node micro numbers so users can compare environments
against BASELINE.md's published table.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Tuple

import numpy as np


def _rate(n: int, fn: Callable[[], None]) -> float:
    t0 = time.perf_counter()
    fn()
    return n / (time.perf_counter() - t0)


def run_microbenchmarks(quick: bool = False) -> List[Tuple[str, float, str]]:
    """Returns [(metric, value, unit)] and prints them as it goes."""
    from .core import api as ca

    owns = not ca.is_initialized()
    if owns:
        ca.init(num_cpus=4)
    results: List[Tuple[str, float, str]] = []

    def record(name: str, value: float, unit: str):
        results.append((name, value, unit))
        print(f"{name}: {value:,.1f} {unit}")

    scale = 0.2 if quick else 1.0

    @ca.remote
    def noop():
        return None

    # warm the pool AND wait out prestarted-worker registration: interpreter
    # startups compete with the head for the core and poison early numbers
    ca.get([noop.remote() for _ in range(50)])
    from .core.worker import global_worker

    w = global_worker()
    deadline = time.monotonic() + 10
    want = int(ca.cluster_resources().get("CPU", 1))
    while time.monotonic() < deadline:
        alive = [
            x for x in w.head_call("list_workers")["workers"]
            if x.get("state") in ("idle", "leased")
        ]
        if len(alive) >= want:
            break
        time.sleep(0.2)
    time.sleep(0.5)

    n = int(5000 * scale)
    record(
        "single client tasks async",
        _rate(n, lambda: ca.get([noop.remote() for _ in range(n)])),
        "/s",
    )

    n = int(500 * scale)

    def sync_tasks():
        for _ in range(n):
            ca.get(noop.remote())

    record("single client tasks sync", _rate(n, sync_tasks), "/s")

    @ca.remote
    class A:
        def ping(self):
            return None

    a = A.remote()
    ca.get(a.ping.remote())
    n = int(5000 * scale)
    record(
        "1:1 actor calls async",
        _rate(n, lambda: ca.get([a.ping.remote() for _ in range(n)])),
        "/s",
    )
    n = int(500 * scale)

    def sync_actor():
        for _ in range(n):
            ca.get(a.ping.remote())

    record("1:1 actor calls sync", _rate(n, sync_actor), "/s")
    from .core.actor import kill as _kill

    _kill(a)

    # puts: value churn through the object store
    n = int(1000 * scale)
    small = np.arange(16)
    record(
        "single client put calls",
        _rate(n, lambda: [ca.put(small) for _ in range(n)]),
        "/s",
    )
    n = int(2000 * scale)
    refs = [ca.put(small) for _ in range(n)]
    record(
        "single client get calls",
        _rate(n, lambda: [ca.get(r) for r in refs]),
        "/s",
    )
    del refs

    size = 64 * 1024 * 1024 if quick else 256 * 1024 * 1024
    arr = np.frombuffer(np.random.bytes(size), dtype=np.uint8)
    reps = 2 if quick else 4
    warm = [ca.put(arr) for _ in range(reps)]
    del warm
    time.sleep(0.5)
    t0 = time.perf_counter()
    big = [ca.put(arr) for _ in range(reps)]
    record(
        "single client put gigabytes",
        reps * size / (time.perf_counter() - t0) / 1e9,
        "GB/s",
    )
    del big

    # placement group create/remove churn.  Earlier phases' task leases
    # idle-return after ~1s; wait for full capacity or the first PG goes
    # PENDING and the average collapses to the service-tick cadence.
    from .core.placement import placement_group, remove_placement_group

    total_cpu = ca.cluster_resources().get("CPU", 0)
    deadline = time.monotonic() + 10
    while (
        ca.available_resources().get("CPU", 0) < total_cpu
        and time.monotonic() < deadline
    ):
        time.sleep(0.1)

    n = int(100 * scale)

    def pg_churn():
        for _ in range(n):
            pg = placement_group([{"CPU": 1}])
            pg.wait(10)
            remove_placement_group(pg)

    record("placement group create/removal", _rate(n, pg_churn), "/s")

    if owns:
        ca.shutdown()
    return results


def head_saturation(quick: bool = False) -> List[Tuple[str, float, str]]:
    """`ca microbenchmark --saturation`: find where the single head's asyncio
    loop saturates (VERDICT r3 weak #6 — the directory/refcount/lease/pubsub
    planes all ride one loop; this records the envelope so round N+1 knows
    whether ownership needs distributing).

    Two sweeps:
    - control-plane ops/s vs concurrent driver connections (KV round-trips:
      the cheapest RPC, so the number is the loop's dispatch ceiling);
    - the same at the knee while K idle agent nodes heartbeat, measuring how
      much node-table upkeep steals from the dispatch budget.
    """
    import threading

    from .cluster_utils import Cluster
    from .core.protocol import BlockingClient

    results: List[Tuple[str, float, str]] = []

    def record(name: str, value: float, unit: str):
        results.append((name, value, unit))
        print(f"{name}: {value:,.1f} {unit}")

    cluster = Cluster(head_resources={"CPU": 2})
    try:
        n_per = 200 if quick else 1000

        def hammer(out, i):
            conn = BlockingClient(cluster.head_tcp)
            try:
                # "probe" role: served like a client but without driver-exit
                # or worker-table semantics
                conn.call("register", role="probe", client_id=f"sat{i}")
                t0 = time.perf_counter()
                for k in range(n_per):
                    conn.call("kv_put", key=f"sat{i}/{k % 8}", value=b"x")
                out[i] = n_per / (time.perf_counter() - t0)
            finally:
                conn.close()

        def sweep(m: int) -> float:
            out = [0.0] * m
            threads = [
                threading.Thread(target=hammer, args=(out, i)) for i in range(m)
            ]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            elapsed = time.perf_counter() - t0
            if not all(out):
                # a dead hammer thread exactly at the knee would otherwise be
                # silently credited with its full op count
                raise RuntimeError(f"{out.count(0.0)} of {m} probe clients failed")
            return m * n_per / elapsed

        for m in (1, 2, 4, 8, 16):
            record(f"head kv ops ({m} clients)", sweep(m), "/s")

        # node-scale: idle agents heartbeating while 8 clients hammer
        def wait_nodes(n):
            probe = BlockingClient(cluster.head_tcp)
            try:
                probe.call("register", role="probe", client_id="satwait")
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline:
                    alive = [
                        x for x in probe.call("nodes")["nodes"] if x["alive"]
                    ]
                    if len(alive) >= n:
                        return
                    time.sleep(0.1)
                raise TimeoutError(f"cluster did not reach {n} nodes")
            finally:
                probe.close()

        for k in (4, 16):
            for _ in range(k - (len(cluster._agents))):
                cluster.add_node(num_cpus=1)
            wait_nodes(k + 1)
            record(f"head kv ops (8 clients, {k} nodes heartbeating)", sweep(8), "/s")
    finally:
        cluster.shutdown()
    return results


def main(quick: bool = False, saturation: bool = False):
    if saturation:
        head_saturation(quick=quick)
    else:
        run_microbenchmarks(quick=quick)


if __name__ == "__main__":
    import sys

    main(quick="--quick" in sys.argv, saturation="--saturation" in sys.argv)
