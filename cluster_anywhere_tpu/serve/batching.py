"""@serve.batch: transparent request batching inside replicas (analogue of
python/ray/serve/batching.py).

Decorates an async method taking a list of inputs and returning a list of
outputs; concurrent callers are coalesced into batches of up to
max_batch_size, waiting at most batch_wait_timeout_s for the batch to fill.
"""

from __future__ import annotations

import asyncio
import functools
from typing import Any, Callable, List, Optional

from ..util.aio import spawn_logged


class _BatchQueue:
    def __init__(self, fn, max_batch_size: int, batch_wait_timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout_s = batch_wait_timeout_s
        self.queue: List = []  # [(item, future)]
        self._flusher: Optional[asyncio.Task] = None

    async def submit(self, item: Any):
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        self.queue.append((item, fut))
        if len(self.queue) >= self.max_batch_size:
            self._flush()
        elif self._flusher is None or self._flusher.done():
            self._flusher = loop.create_task(self._wait_then_flush())
        return await fut

    async def _wait_then_flush(self):
        await asyncio.sleep(self.timeout_s)
        self._flush()

    def _flush(self):
        if not self.queue:
            return
        batch, self.queue = self.queue, []
        # _run settles every batch future itself; spawn_logged guards the
        # residual failure modes (a fut.set_* race) from vanishing silently
        spawn_logged(self._run(batch), "serve-batch-run")

    async def _run(self, batch):
        items = [item for item, _ in batch]
        try:
            outs = await self.fn(items)
            if not isinstance(outs, list) or len(outs) != len(items):
                raise TypeError(
                    f"@serve.batch function must return a list of {len(items)} "
                    f"results, got {type(outs).__name__}"
                )
            for (_, fut), out in zip(batch, outs):
                if not fut.done():
                    fut.set_result(out)
        except asyncio.CancelledError as e:
            # fail the waiters, then stay cancelled: swallowing here would
            # wedge replica shutdown with a batch forever "in flight"
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)
            raise
        except BaseException as e:
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)


def batch(
    _fn: Optional[Callable] = None,
    *,
    max_batch_size: int = 8,
    batch_wait_timeout_s: float = 0.01,
):
    """Usage:
        @serve.batch(max_batch_size=32, batch_wait_timeout_s=0.05)
        async def handle_batch(self, inputs: list) -> list: ...
    """

    def deco(fn):
        # per-method attribute name: queues live ON the instance so their
        # lifetime matches it (a module-level id()-keyed dict would pin every
        # instance forever)
        attr = f"__ca_batch_queue_{fn.__qualname__.replace('.', '_')}"
        free_q: List[Optional[_BatchQueue]] = [None]

        @functools.wraps(fn)
        async def wrapper(*args, **kwargs):
            if kwargs:
                raise TypeError("@serve.batch calls must use positional args")
            if len(args) == 2:  # bound method: (self, item)
                self_obj, item = args
                q = getattr(self_obj, attr, None)
                if q is None:
                    q = _BatchQueue(
                        lambda items: fn(self_obj, items), max_batch_size, batch_wait_timeout_s
                    )
                    setattr(self_obj, attr, q)
            elif len(args) == 1:  # free function: (item,)
                (item,) = args
                if free_q[0] is None:
                    free_q[0] = _BatchQueue(fn, max_batch_size, batch_wait_timeout_s)
                q = free_q[0]
            else:
                raise TypeError("@serve.batch functions take exactly one request arg")
            return await q.submit(item)

        wrapper._is_serve_batch = True
        return wrapper

    if _fn is not None:
        return deco(_fn)
    return deco
