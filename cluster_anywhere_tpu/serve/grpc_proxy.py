"""gRPC ingress for Serve (reference serve/_private/proxy.py gRPCProxy:532).

Two surfaces on one port:

- TYPED (protos/serve.proto — compile it in any language):
  ``CAServeUserService/Call`` takes a CallRequest{application, payload}
  where payload is msgpack-encoded [args, kwargs] and returns a
  CallResponse{payload} of the msgpack-encoded result — no Python pickle
  anywhere, so non-Python clients are first-class.
  ``CAServeAPIService/{ListApplications,Healthz}`` is the management
  surface (reference RayServeAPIService analogue).
- LEGACY pickle: ``Ingress/Call`` with pickled (args, kwargs), app routing
  by metadata — kept for in-process Python callers shipping arbitrary
  objects.

Both route through the same controller-synced table the HTTP proxy uses.
Client side: ``grpc_call`` (pickle) / ``grpc_call_typed`` (proto+msgpack).
"""

from __future__ import annotations

import pickle
import threading
import time
from typing import Any, Dict, Optional

from . import proto_wire

SERVICE = "cluster_anywhere_tpu.serve.Ingress"
METHOD = f"/{SERVICE}/Call"
USER_CALL = "/cluster_anywhere_tpu.serve.CAServeUserService/Call"
API_LIST = "/cluster_anywhere_tpu.serve.CAServeAPIService/ListApplications"
API_HEALTHZ = "/cluster_anywhere_tpu.serve.CAServeAPIService/Healthz"

_MAX_CALL_S = 60.0


def _deadline_s(context) -> float:
    """Block no longer than the client's RPC deadline (capped): a handler
    still waiting after the client gave up would pin one of the server's
    pool threads and starve Healthz/ListApplications."""
    remaining = context.time_remaining()
    if remaining is None:
        return _MAX_CALL_S
    return max(0.1, min(_MAX_CALL_S, remaining))


class GrpcProxyActor:
    """Serve's gRPC ingress: one generic unary-unary method, app routing by
    metadata, replica scheduling through DeploymentHandle."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        import grpc

        self._apps: Dict[str, Any] = {}  # app name -> DeploymentHandle
        self._lock = threading.Lock()

        outer = self

        class _Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                method = handler_call_details.method
                if method == METHOD:
                    md = dict(handler_call_details.invocation_metadata or ())
                    app = md.get("application", "default")

                    def _unary(request_bytes, context):
                        handle = outer._handle_for(app)
                        if handle is None:
                            context.abort(
                                grpc.StatusCode.NOT_FOUND,
                                f"no serve application {app!r}",
                            )
                        try:
                            args, kwargs = pickle.loads(request_bytes)
                            result = handle.remote(*args, **kwargs).result(
                                timeout_s=_deadline_s(context)
                            )
                            return pickle.dumps(result)
                        except Exception as e:  # noqa: BLE001 — surfaced as status
                            context.abort(grpc.StatusCode.INTERNAL, repr(e))

                elif method == USER_CALL:

                    def _unary(request_bytes, context):
                        import msgpack

                        try:
                            app, payload = proto_wire.decode_call_request(request_bytes)
                            args, kwargs = msgpack.unpackb(payload, raw=False)
                        except (ValueError, TypeError, msgpack.UnpackException) as e:
                            # malformed bytes from a non-Python client must
                            # say so, not surface as UNKNOWN with no detail
                            context.abort(
                                grpc.StatusCode.INVALID_ARGUMENT,
                                f"bad CallRequest: {e}",
                            )
                        handle = outer._handle_for(app or "default")
                        if handle is None:
                            context.abort(
                                grpc.StatusCode.NOT_FOUND,
                                f"no serve application {app!r}",
                            )
                        try:
                            result = handle.remote(*args, **kwargs).result(
                                timeout_s=_deadline_s(context)
                            )
                            return proto_wire.encode_call_response(
                                msgpack.packb(result, use_bin_type=True)
                            )
                        except Exception as e:  # noqa: BLE001 — surfaced as status
                            context.abort(grpc.StatusCode.INTERNAL, repr(e))

                elif method == API_LIST:

                    def _unary(request_bytes, context):
                        with outer._lock:
                            names = sorted(outer._apps)
                        return proto_wire.encode_list_applications_response(names)

                elif method == API_HEALTHZ:

                    def _unary(request_bytes, context):
                        return proto_wire.encode_healthz_response("success")

                else:
                    return None

                return grpc.unary_unary_rpc_method_handler(
                    _unary,
                    request_deserializer=None,  # raw bytes in
                    response_serializer=None,  # raw bytes out
                )

        from concurrent.futures import ThreadPoolExecutor

        self._server = grpc.server(ThreadPoolExecutor(max_workers=8))
        self._server.add_generic_rpc_handlers((_Handler(),))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        self.host = host
        self._server.start()
        self._refresher = threading.Thread(
            target=self._refresh_loop, daemon=True, name="grpc-proxy-routes"
        )
        self._refresher.start()

    def ready(self) -> str:
        return f"{self.host}:{self.port}"

    def _handle_for(self, app: str) -> Optional[Any]:
        with self._lock:
            return self._apps.get(app)

    def _refresh_loop(self):
        from ..core import api as ca
        from ..core.actor import get_actor
        from .controller import CONTROLLER_NAME
        from .router import DeploymentHandle

        while True:
            try:
                ctrl = get_actor(CONTROLLER_NAME)
                routes = ca.get(ctrl.list_routes.remote(), timeout=10)
                new = {
                    app: DeploymentHandle(app, info["ingress"])
                    for app, info in routes.items()
                    if info["ingress"]
                }
                with self._lock:
                    for app, h in new.items():
                        cur = self._apps.get(app)
                        if cur is None or cur.deployment != h.deployment:
                            self._apps[app] = h
                    for app in list(self._apps):
                        if app not in new:
                            del self._apps[app]
            except Exception:
                pass
            time.sleep(0.5)

    def stop(self):
        self._server.stop(grace=1.0)


def grpc_call(target: str, application: str, *args, timeout: float = 60.0, **kwargs):
    """Invoke a serve application through the gRPC ingress (legacy pickle)."""
    import grpc

    with grpc.insecure_channel(target) as channel:
        fn = channel.unary_unary(METHOD)
        out = fn(
            pickle.dumps((args, kwargs)),
            metadata=(("application", application),),
            timeout=timeout,
        )
        return pickle.loads(out)


def grpc_call_typed(target: str, application: str, *args, timeout: float = 60.0, **kwargs):
    """Invoke through the TYPED service (protos/serve.proto + msgpack) —
    exactly what a non-Python client would send after compiling the proto."""
    import grpc
    import msgpack

    with grpc.insecure_channel(target) as channel:
        fn = channel.unary_unary(USER_CALL)
        out = fn(
            proto_wire.encode_call_request(
                application,
                msgpack.packb([list(args), kwargs], use_bin_type=True),
            ),
            timeout=timeout,
        )
        return msgpack.unpackb(proto_wire.decode_call_response(out), raw=False)


def grpc_list_applications(target: str, timeout: float = 10.0):
    import grpc

    with grpc.insecure_channel(target) as channel:
        out = channel.unary_unary(API_LIST)(b"", timeout=timeout)
        return proto_wire.decode_list_applications_response(out)


def grpc_healthz(target: str, timeout: float = 10.0) -> str:
    import grpc

    with grpc.insecure_channel(target) as channel:
        out = channel.unary_unary(API_HEALTHZ)(b"", timeout=timeout)
        return proto_wire.decode_healthz_response(out)
