"""gRPC ingress for Serve (reference serve/_private/proxy.py gRPCProxy:532).

Proto-free design: a generic handler serves
``/cluster_anywhere_tpu.serve.Ingress/Call`` unary-unary with pickled
payloads, routing by the ``application`` request metadatum to that app's
ingress deployment — the same controller-synced route table the HTTP proxy
uses.  No .proto compilation step, no per-model service definitions; typed
protos can layer on top by pickling their own bytes.

Client side: ``grpc_call(target, application, *args, **kwargs)``.
"""

from __future__ import annotations

import pickle
import threading
import time
from typing import Any, Dict, Optional

SERVICE = "cluster_anywhere_tpu.serve.Ingress"
METHOD = f"/{SERVICE}/Call"


class GrpcProxyActor:
    """Serve's gRPC ingress: one generic unary-unary method, app routing by
    metadata, replica scheduling through DeploymentHandle."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        import grpc

        self._apps: Dict[str, Any] = {}  # app name -> DeploymentHandle
        self._lock = threading.Lock()

        outer = self

        class _Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                if handler_call_details.method != METHOD:
                    return None
                md = dict(handler_call_details.invocation_metadata or ())
                app = md.get("application", "default")

                def _unary(request_bytes, context):
                    handle = outer._handle_for(app)
                    if handle is None:
                        context.abort(
                            grpc.StatusCode.NOT_FOUND,
                            f"no serve application {app!r}",
                        )
                    try:
                        args, kwargs = pickle.loads(request_bytes)
                        result = handle.remote(*args, **kwargs).result(timeout_s=60)
                        return pickle.dumps(result)
                    except Exception as e:  # noqa: BLE001 — surfaced as status
                        context.abort(grpc.StatusCode.INTERNAL, repr(e))

                return grpc.unary_unary_rpc_method_handler(
                    _unary,
                    request_deserializer=None,  # raw bytes in
                    response_serializer=None,  # raw bytes out
                )

        from concurrent.futures import ThreadPoolExecutor

        self._server = grpc.server(ThreadPoolExecutor(max_workers=8))
        self._server.add_generic_rpc_handlers((_Handler(),))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        self.host = host
        self._server.start()
        self._refresher = threading.Thread(
            target=self._refresh_loop, daemon=True, name="grpc-proxy-routes"
        )
        self._refresher.start()

    def ready(self) -> str:
        return f"{self.host}:{self.port}"

    def _handle_for(self, app: str) -> Optional[Any]:
        with self._lock:
            return self._apps.get(app)

    def _refresh_loop(self):
        from ..core import api as ca
        from ..core.actor import get_actor
        from .controller import CONTROLLER_NAME
        from .router import DeploymentHandle

        while True:
            try:
                ctrl = get_actor(CONTROLLER_NAME)
                routes = ca.get(ctrl.list_routes.remote(), timeout=10)
                new = {
                    app: DeploymentHandle(app, info["ingress"])
                    for app, info in routes.items()
                    if info["ingress"]
                }
                with self._lock:
                    for app, h in new.items():
                        cur = self._apps.get(app)
                        if cur is None or cur.deployment != h.deployment:
                            self._apps[app] = h
                    for app in list(self._apps):
                        if app not in new:
                            del self._apps[app]
            except Exception:
                pass
            time.sleep(0.5)

    def stop(self):
        self._server.stop(grace=1.0)


def grpc_call(target: str, application: str, *args, timeout: float = 60.0, **kwargs):
    """Invoke a serve application through the gRPC ingress."""
    import grpc

    with grpc.insecure_channel(target) as channel:
        fn = channel.unary_unary(METHOD)
        out = fn(
            pickle.dumps((args, kwargs)),
            metadata=(("application", application),),
            timeout=timeout,
        )
        return pickle.loads(out)
