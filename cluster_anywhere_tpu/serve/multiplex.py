"""Model multiplexing (analogue of python/ray/serve/multiplex.py
_ModelMultiplexWrapper + serve.get_multiplexed_model_id): one replica serves
many models, loading on demand with LRU eviction.
"""

from __future__ import annotations

import asyncio
import functools
from collections import OrderedDict
from typing import Any, Callable, Optional

from .replica import get_request_context


def get_multiplexed_model_id() -> str:
    return get_request_context().multiplexed_model_id


def multiplexed(_fn: Optional[Callable] = None, *, max_num_models_per_replica: int = 3):
    """Decorate an async model loader: `async def load(self, model_id): ...`.
    Calls are cached per model id with LRU eviction."""

    def deco(fn):
        # cache+lock live on the instance (a module-level id()-keyed dict
        # would pin every instance forever); free functions get one shared slot
        attr = f"__ca_mux_{fn.__qualname__.replace('.', '_')}"
        free_state: dict = {}

        @functools.wraps(fn)
        async def wrapper(*args):
            if len(args) == 2:
                self_obj, model_id = args
                state = getattr(self_obj, attr, None)
                if state is None:
                    state = {"cache": OrderedDict(), "locks": {}}
                    setattr(self_obj, attr, state)
            else:
                (model_id,) = args
                self_obj = None
                if not free_state:
                    free_state.update(cache=OrderedDict(), locks={})
                state = free_state
            cache = state["cache"]
            if model_id in cache:  # cache hits never wait behind a load
                cache.move_to_end(model_id)
                return cache[model_id]
            # loads serialize per model id only: a slow load of model B must
            # not block requests for cached model A or a parallel load of C
            lock = state["locks"].setdefault(model_id, asyncio.Lock())
            async with lock:
                if model_id in cache:
                    cache.move_to_end(model_id)
                    return cache[model_id]
                try:
                    model = await (
                        fn(self_obj, model_id) if self_obj is not None else fn(model_id)
                    )
                except BaseException:
                    # never cached: drop the lock entry too, or a stream of
                    # failing ids grows the dict forever
                    state["locks"].pop(model_id, None)
                    raise
                cache[model_id] = model
                while len(cache) > max_num_models_per_replica:
                    old_id, _ = cache.popitem(last=False)  # LRU; refcount GC cleans up
                    state["locks"].pop(old_id, None)
                return model

        wrapper._is_serve_multiplexed = True
        return wrapper

    if _fn is not None:
        return deco(_fn)
    return deco
