"""Hand-rolled protobuf wire codec for the four tiny messages in
protos/serve.proto.

Why not generated code: this image ships protoc 3.21 but a protobuf 6.x
Python runtime, which refuses 3.x-generated modules.  The messages are all
length-delimited scalar fields, whose wire format is trivial and frozen by
the protobuf spec — encoding them by hand keeps the TYPED service (callable
from any language that compiles serve.proto) without a codegen dependency.
Interop is pinned by tests that decode bytes produced by the real
google.protobuf runtime.

Wire format recap: each field is (field_number << 3 | wire_type) varint,
then for wire type 2 (len-delimited: strings, bytes, embedded) a varint
length + that many bytes.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

_LEN_TYPE = 2


def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    shift = 0
    result = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


def _field(num: int, data: bytes) -> bytes:
    return _varint(num << 3 | _LEN_TYPE) + _varint(len(data)) + data


def _parse_fields(buf: bytes) -> Dict[int, List[bytes]]:
    """All len-delimited fields by number; other wire types are skipped
    (forward compatibility with clients sending unknown scalar fields)."""
    out: Dict[int, List[bytes]] = {}
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        num, wt = key >> 3, key & 0x7
        if wt == _LEN_TYPE:
            ln, pos = _read_varint(buf, pos)
            if pos + ln > len(buf):
                raise ValueError("truncated field")
            out.setdefault(num, []).append(buf[pos : pos + ln])
            pos += ln
        elif wt == 0:  # varint scalar: skip
            _, pos = _read_varint(buf, pos)
        elif wt == 5:  # fixed32
            pos += 4
            if pos > len(buf):
                raise ValueError("truncated fixed32 field")
        elif wt == 1:  # fixed64
            pos += 8
            if pos > len(buf):
                raise ValueError("truncated fixed64 field")
        else:
            raise ValueError(f"unsupported wire type {wt}")
    return out


# -- CallRequest { string application = 1; bytes payload = 2; } -------------


def encode_call_request(application: str, payload: bytes) -> bytes:
    return _field(1, application.encode()) + _field(2, payload)


def decode_call_request(buf: bytes) -> Tuple[str, bytes]:
    f = _parse_fields(buf)
    app = f.get(1, [b""])[-1].decode()
    payload = f.get(2, [b""])[-1]
    return app, payload


# -- CallResponse { bytes payload = 1; } ------------------------------------


def encode_call_response(payload: bytes) -> bytes:
    return _field(1, payload)


def decode_call_response(buf: bytes) -> bytes:
    return _parse_fields(buf).get(1, [b""])[-1]


# -- ListApplicationsResponse { repeated string application_names = 1; } ----


def encode_list_applications_response(names: List[str]) -> bytes:
    return b"".join(_field(1, n.encode()) for n in names)


def decode_list_applications_response(buf: bytes) -> List[str]:
    return [b.decode() for b in _parse_fields(buf).get(1, [])]


# -- HealthzResponse { string message = 1; } --------------------------------


def encode_healthz_response(message: str) -> bytes:
    return _field(1, message.encode())


def decode_healthz_response(buf: bytes) -> str:
    return _parse_fields(buf).get(1, [b""])[-1].decode()
