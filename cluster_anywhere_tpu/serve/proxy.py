"""HTTP proxy actor (analogue of python/ray/serve/_private/proxy.py
HTTPProxy/ProxyActor): a minimal asyncio HTTP/1.1 server that routes requests
by route prefix to application ingress deployments.
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
import traceback
from typing import Any, Dict, Optional
from urllib.parse import parse_qs, unquote, urlparse

from ..util import flightrec
from ..util import tracing as _tracing
from ..util.aio import drain, spawn_logged

_proxy_metrics = {}


def _shed_metrics():
    """Admission-control + stream-lifecycle series (lazy like the replica's
    request metrics): ca_serve_shed_total{deployment,reason} counts requests
    refused at the gate; ca_serve_stream_abandoned_total{deployment} counts
    SSE streams whose client vanished mid-stream (their replica-side
    generators get cancelled, not left decoding)."""
    if not _proxy_metrics:
        from ..util import metrics as m

        _proxy_metrics["shed"] = m.Counter(
            "ca_serve_shed_total", "serve requests shed at the admission gate",
            tag_keys=("deployment", "reason"),
        )
        _proxy_metrics["abandoned"] = m.Counter(
            "ca_serve_stream_abandoned_total",
            "serve SSE streams abandoned by their client mid-stream",
            tag_keys=("deployment",),
        )
    return _proxy_metrics


class _Shed(Exception):
    """Admission refusal: HTTP code + reason + the Retry-After hint."""

    def __init__(self, code: int, reason: str, retry_after: float, limit: int):
        super().__init__(reason)
        self.code = code
        self.reason = reason
        self.retry_after = retry_after
        self.limit = limit


class _AdmissionState:
    """Per-deployment admission bookkeeping in THIS proxy: in-flight request
    count and summed token-cost estimate, gated by the deployment's
    AdmissionPolicy (refreshed with the route table)."""

    __slots__ = ("policy", "replicas", "max_ongoing", "inflight", "tokens")

    def __init__(self):
        self.policy = None  # dict from AdmissionPolicy.to_wire(), or None
        self.replicas = 1
        self.max_ongoing = 8
        self.inflight = 0
        self.tokens = 0


class Request:
    """What ingress callables receive for HTTP requests (a compact stand-in
    for the reference's starlette.requests.Request)."""

    def __init__(self, method: str, path: str, query_params: Dict[str, str], headers: Dict[str, str], body: bytes):
        self.method = method
        self.path = path
        self.query_params = query_params
        self.headers = headers
        self._body = body

    def body(self) -> bytes:
        return self._body

    def json(self) -> Any:
        return json.loads(self._body or b"null")

    def text(self) -> str:
        return self._body.decode("utf-8", "replace")


class ProxyActor:
    def __init__(self, host: str, port: int):
        from ..core.worker import global_worker

        self.host = host
        self.port = port
        self._routes: Dict[str, Any] = {}  # route_prefix -> DeploymentHandle
        self._admission: Dict[str, _AdmissionState] = {}  # route_prefix ->
        self._routes_lock = threading.Lock()
        self._miss_lock = threading.Lock()
        # deployment -> False once a dag_stream handshake failed (no such
        # method, or a replica whose shm segment this proxy can't map);
        # avoids paying a doomed extra RPC on every subsequent SSE request
        self._dag_stream_ok: Dict[str, bool] = {}
        self._refresh_gen = 0
        self._loop = global_worker().loop
        self._server = None
        self._started = threading.Event()
        self._start_error: Optional[str] = None
        fut = asyncio.run_coroutine_threadsafe(self._start_server(), self._loop)
        fut.result(timeout=30)
        self._refresher = threading.Thread(
            target=self._refresh_routes_loop, daemon=True, name="proxy-routes"
        )
        self._refresher.start()

    async def _start_server(self):
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port
        )

    def ready(self) -> str:
        return f"http://{self.host}:{self.port}"

    # ------------------------------------------------------------ route sync
    def _refresh_routes_loop(self):
        while True:
            self._refresh_routes_once()
            time.sleep(0.5)

    def _miss_refresh(self):
        # true single-flight via a generation counter: a waiter whose miss
        # preceded a refresh that has since COMPLETED skips its own RPC —
        # a 404 burst costs one controller round-trip total, while the
        # serve.run() -> immediate-request race still gets a refresh that
        # finished after its miss.  Short RPC timeout: a dead controller
        # costs a miss ~2s, not 10.
        my_gen = self._refresh_gen
        with self._miss_lock:
            if self._refresh_gen != my_gen:
                return
            self._refresh_routes_once(rpc_timeout=2)
            self._refresh_gen += 1

    def _refresh_routes_once(self, rpc_timeout: float = 10):
        from ..core import api as ca
        from ..core.actor import get_actor
        from .controller import CONTROLLER_NAME
        from .router import DeploymentHandle

        try:
            ctrl = get_actor(CONTROLLER_NAME)
            routes = ca.get(ctrl.list_routes.remote(), timeout=rpc_timeout)
            new = {}
            for app, info in routes.items():
                if info["ingress"]:
                    new[info["route_prefix"]] = (DeploymentHandle(app, info["ingress"]), info)
            with self._routes_lock:
                # keep existing handles (their routers have warm caches)
                for prefix, (h, info) in new.items():
                    if prefix not in self._routes or (
                        self._routes[prefix].app != h.app
                        or self._routes[prefix].deployment != h.deployment
                    ):
                        self._routes[prefix] = h
                    # admission state rides the refresh: the policy is
                    # deployment config, capacity tracks the autoscaler
                    adm = self._admission.get(prefix)
                    if adm is None:
                        adm = self._admission[prefix] = _AdmissionState()
                    adm.policy = info.get("admission")
                    adm.replicas = int(info.get("replicas", 1) or 1)
                    adm.max_ongoing = int(info.get("max_ongoing_requests", 8))
                for prefix in list(self._routes):
                    if prefix not in new:
                        del self._routes[prefix]
                        self._admission.pop(prefix, None)
        except Exception:
            pass

    def _match(self, path: str):
        with self._routes_lock:
            best = None
            for prefix, handle in self._routes.items():
                norm = prefix.rstrip("/") or ""
                if path == norm or path.startswith(norm + "/") or prefix == "/":
                    if best is None or len(prefix) > len(best[0]):
                        best = (prefix, handle)
            return best

    # ---------------------------------------------------------- http server
    async def _handle_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        """One request per connection (responses carry Connection: close)."""
        req = None
        try:
            req = await self._read_request(reader)
        except asyncio.CancelledError:
            try:
                writer.close()
            except Exception:
                pass
            raise  # proxy shutdown: release the socket, stay cancelled
        except Exception:
            pass
        if req is None:
            # malformed/empty request: close or the fd leaks per connection
            try:
                writer.close()
            except Exception:
                pass
            return
        spawn_logged(self._dispatch(req, writer), "serve-proxy-dispatch")

    # request-size guards (ADVICE r1: unbounded header/body reads let a
    # client exhaust proxy memory); generous defaults, overridable per proxy
    MAX_HEADER_LINE = 16 * 1024
    MAX_HEADERS = 128
    MAX_BODY = 64 * 1024 * 1024
    # ... and a time guard: a client that dials and then goes silent must
    # not pin a proxy coroutine (and its fd) forever.  TimeoutError rides
    # the same close-and-drop path as a malformed request.
    READ_TIMEOUT_S = 30.0

    async def _read_request(self, reader) -> Optional[Request]:
        try:
            line = await asyncio.wait_for(reader.readline(), self.READ_TIMEOUT_S)
        except (asyncio.LimitOverrunError, ValueError):
            return None
        if not line or len(line) > self.MAX_HEADER_LINE:
            return None
        try:
            method, target, _ = line.decode("latin1").split(" ", 2)
        except ValueError:
            return None
        headers: Dict[str, str] = {}
        n_lines = 0  # count lines, not dict keys: repeated names must still trip the cap
        while True:
            try:
                h = await asyncio.wait_for(reader.readline(), self.READ_TIMEOUT_S)
            except (asyncio.LimitOverrunError, ValueError):
                return None
            if h in (b"\r\n", b"\n", b""):
                break
            n_lines += 1
            if len(h) > self.MAX_HEADER_LINE or n_lines > self.MAX_HEADERS:
                return None
            k, _, v = h.decode("latin1").partition(":")
            headers[k.strip().lower()] = v.strip()
        body = b""
        try:
            n = int(headers.get("content-length", 0) or 0)
        except ValueError:
            return None
        if n < 0 or n > self.MAX_BODY:
            return None
        if n:
            body = await asyncio.wait_for(reader.readexactly(n), self.READ_TIMEOUT_S)
        parsed = urlparse(target)
        query = {k: v[0] for k, v in parse_qs(parsed.query).items()}
        return Request(method.upper(), unquote(parsed.path), query, headers, body)

    # ------------------------------------------------------------- admission
    @staticmethod
    def _estimate_tokens(policy: Dict[str, Any], req: Request) -> int:
        """Token-cost estimate for the budget gate: prompt chars/4 +
        max_new_tokens when the body (or query) carries them, else the
        policy's default.  Deliberately cheap and rough — the gate bounds
        aggregate decode work, it doesn't meter exact usage."""
        body: Dict[str, Any] = {}
        default = int(policy.get("default_request_tokens") or 64)
        if len(req._body) > 256 * 1024:
            # don't json-parse megabyte prompts on the event loop just for
            # an estimate: for a body this large the prompt dominates —
            # charge its size directly
            return max(1, default + len(req._body) // 4)
        try:
            if req.method == "POST" and req._body[:1] in (b"{", b"["):
                parsed = json.loads(req._body)
                if isinstance(parsed, dict):
                    body = parsed
            elif req.query_params:
                body = dict(req.query_params)
        except Exception:
            pass
        try:
            new_toks = int(body["max_new_tokens"]) if "max_new_tokens" in body else None
        except (TypeError, ValueError):
            new_toks = None
        prompt = body.get("prompt")
        prompt_toks = len(str(prompt)) // 4 if isinstance(prompt, (str, bytes)) else 0
        if new_toks is None and not prompt_toks:
            return default
        return max(1, (new_toks if new_toks is not None else default) + prompt_toks)

    def _try_admit(self, prefix: str, req: Request):
        """Admission gate.  Returns (None, 0) when no policy applies,
        (adm, tokens) when admitted, or raises _Shed with the refusal.
        The token estimate (a json.loads of the body) runs OUTSIDE the
        routes lock — holding it there would serialize every concurrent
        dispatch/release/refresh behind one request's body parse; the
        verdict + reservation then re-check under the lock atomically."""
        with self._routes_lock:
            adm = self._admission.get(prefix)
            pol = adm.policy if adm is not None else None
        if pol is None:
            return None, 0
        tokens = (
            self._estimate_tokens(pol, req)
            if pol.get("max_tokens_in_flight") is not None
            else 0
        )
        with self._routes_lock:
            adm = self._admission.get(prefix)
            if adm is None or adm.policy is None:
                return None, 0  # route/policy changed mid-check: admit
            pol = adm.policy
            depth = pol.get("max_queue_depth")
            if depth is None:
                depth = max(
                    1,
                    int(
                        float(pol.get("queue_depth_factor") or 2.0)
                        * max(1, adm.replicas) * adm.max_ongoing
                    ),
                )
            retry = float(pol.get("retry_after_s") or 1.0)
            if adm.inflight >= depth:
                raise _Shed(503, "queue_depth", retry, depth)
            budget = pol.get("max_tokens_in_flight")
            if budget is not None:
                if adm.tokens + tokens > int(budget):
                    raise _Shed(429, "token_budget", retry, int(budget))
            else:
                tokens = 0
            adm.inflight += 1
            adm.tokens += tokens
            return adm, tokens

    def _release(self, adm, tokens: int):
        if adm is not None:
            with self._routes_lock:
                adm.inflight -= 1
                adm.tokens -= tokens

    async def _dispatch(self, req: Request, writer: asyncio.StreamWriter):
        admitted = None
        # cross-plane trace (tentpole): adopt the client's W3C traceparent
        # header, or mint a root when tracing is enabled — the request span
        # parents every downstream task/stream, so `ca timeline` renders
        # proxy -> replica -> channel ops as one connected trace
        tr_in = _tracing.parse_traceparent(req.headers.get("traceparent"))
        if tr_in is not None:
            tr_req = {
                "tid": tr_in["tid"], "sid": _tracing.new_span_id(),
                "psid": tr_in["sid"],
            }
        elif _tracing.is_enabled():
            tr_req = {"tid": _tracing.new_trace_id(), "sid": _tracing.new_span_id()}
        else:
            tr_req = None
        wire = {"tid": tr_req["tid"], "sid": tr_req["sid"]} if tr_req else None
        tr_hdr = {"traceparent": _tracing.format_traceparent(wire)} if wire else None
        t0 = time.time()
        try:
            match = self._match(req.path)
            if match is None:
                # a route deployed milliseconds ago may not have reached the
                # 0.5s poller yet: EVERY miss gets one fresh look at the
                # controller before 404ing, serialized through one lock so a
                # 404 burst (scanners, favicon probes) queues behind a
                # single in-flight RPC instead of flooding the controller
                loop = asyncio.get_running_loop()
                await loop.run_in_executor(None, self._miss_refresh)
                match = self._match(req.path)
            if match is None:
                await self._respond(writer, 404, {"error": f"no route for {req.path}"})
                return
            prefix, handle = match
            dep_tag = {"deployment": f"{handle.app}/{handle.deployment}"}
            try:
                admitted = self._try_admit(prefix, req)
            except _Shed as s:
                # load-shedding: refuse NOW with Retry-After instead of
                # queueing unboundedly — past the saturation knee a bounded
                # queue is the only way p99 stays bounded
                _shed_metrics()["shed"].inc(1, tags={**dep_tag, "reason": s.reason})
                if flightrec.REC is not None:
                    flightrec.REC.record(
                        "serve", "serve_shed",
                        deployment=dep_tag["deployment"], reason=s.reason,
                        code=s.code, limit=s.limit, path=req.path,
                        **({"trace": wire} if wire else {}),
                    )
                await self._respond(
                    writer, s.code,
                    {"error": "request shed", "reason": s.reason, "limit": s.limit},
                    extra_headers={"Retry-After": f"{s.retry_after:g}", **(tr_hdr or {})},
                )
                return
            loop = asyncio.get_running_loop()
            if "text/event-stream" in req.headers.get("accept", ""):
                # SSE: iterate the deployment's generator, one event per item
                # (reference proxy StreamingResponse path; LLM token streams)
                await self._respond_sse(
                    writer, handle, req, loop, dep_tag, wire=wire, tr_hdr=tr_hdr
                )
                return

            # handle.remote() blocks briefly (routing) and result() blocks
            # until done — run both off the event loop.  run_in_executor does
            # NOT propagate contextvars, so the request trace is installed
            # inside the worker thread, around the submission.
            def _call():
                if wire is None:
                    return handle.remote(req).result(timeout_s=60)
                tok = _tracing.push_execution(wire)
                try:
                    return handle.remote(req).result(timeout_s=60)
                finally:
                    _tracing.pop_execution(tok)

            result = await loop.run_in_executor(None, _call)
            await self._respond(writer, 200, result, extra_headers=tr_hdr)
        except asyncio.CancelledError:
            try:
                writer.close()
            except Exception:
                pass
            raise  # proxy shutdown: don't dress cancellation up as a 500
        except Exception as e:
            traceback.print_exc()
            await self._respond(writer, 500, {"error": repr(e)})
        finally:
            if admitted is not None:
                self._release(*admitted)
            if tr_req is not None:
                w = _tracing._current_worker()
                _tracing.record_task_event(
                    "", f"serve:{req.method} {req.path}", "span", "SPAN",
                    trace=tr_req,
                    worker_id=w.client_id if w is not None else None,
                    node_id=w.node_id if w is not None else None,
                    start=t0, end=time.time(),
                )

    async def _open_stream(self, handle, req: Request, loop, wire=None):
        """Pick the token transport for one SSE request.

        Compiled-DAG path (config.serve_compiled_dag, default on): ONE RPC
        handshake asks the replica's `dag_stream` for a pre-opened shm
        channel spec, then every token travels writer->futex->reader with
        no RPC at all (see serve/dag_stream.py).  Falls back to the
        per-token streaming-RPC path when the deployment has no dag_stream
        method or the segment can't be mapped (cross-host replica), and
        remembers the failure per deployment.
        """
        from ..core.config import get_config

        def _traced(fn):
            # executor threads start with a fresh context: install the
            # request trace around the submission so the replica-side spans
            # chain under the proxy's span
            if wire is None:
                return fn

            def wrapped():
                tok = _tracing.push_execution(wire)
                try:
                    return fn()
                finally:
                    _tracing.pop_execution(tok)

            return wrapped

        dep_key = f"{handle.app}/{handle.deployment}"
        if get_config().serve_compiled_dag and self._dag_stream_ok.get(dep_key, True):
            try:
                spec = await loop.run_in_executor(
                    None,
                    _traced(
                        lambda: handle.options(method_name="dag_stream")
                        .remote(req)
                        .result(timeout_s=30)
                    ),
                )
                from .dag_stream import open_dag_stream

                return open_dag_stream(spec)
            except asyncio.CancelledError:
                raise
            except Exception:
                self._dag_stream_ok[dep_key] = False
        return await loop.run_in_executor(
            None, _traced(lambda: handle.options(stream=True).remote(req))
        )

    async def _respond_sse(self, writer, handle, req: Request, loop, dep_tag=None,
                           wire=None, tr_hdr=None):
        import json as _json
        import queue as _queue

        extras = "".join(f"{k}: {v}\r\n" for k, v in (tr_hdr or {}).items())
        writer.write(
            b"HTTP/1.1 200 OK\r\nContent-Type: text/event-stream\r\n"
            b"Cache-Control: no-cache\r\n" + extras.encode()
            + b"Connection: close\r\n\r\n"
        )
        await drain(writer)
        q: _queue.Queue = _queue.Queue(maxsize=64)
        _END = object()
        abandoned = threading.Event()
        resp_gen = await self._open_stream(handle, req, loop, wire=wire)

        def qput(item) -> bool:
            # abandonment-aware put: a dead consumer stops reading the
            # queue, so a plain put() would block this thread forever once
            # the buffer fills — but a merely SLOW consumer must still get
            # every item (especially _END: dropping it would hang the
            # consumer and leak its admission slot), so keep trying until
            # delivered or abandoned.
            while not abandoned.is_set():
                try:
                    q.put(item, timeout=0.2)
                    return True
                except _queue.Full:
                    continue
            return False

        def pump():
            try:
                for item in resp_gen:
                    if not qput(item):
                        return
            except Exception as e:  # noqa: BLE001 — forwarded as an event
                qput({"error": repr(e)})
            finally:
                qput(_END)

        loop.run_in_executor(None, pump)
        try:
            while True:
                item = await loop.run_in_executor(None, q.get)
                if item is _END:
                    break
                if isinstance(item, bytes):
                    data = item.decode("utf-8", "replace")
                elif isinstance(item, str):
                    data = item
                else:
                    data = _json.dumps(item, default=str)
                try:
                    writer.write(f"data: {data}\n\n".encode())
                    # bounded: a consumer that stops reading mid-stream must
                    # not pin this coroutine (or the replica's generator)
                    await drain(writer)
                except asyncio.CancelledError:
                    raise
                except Exception:
                    # client went away mid-stream: cancel the replica-side
                    # generator — the bounded buffer only protected MEMORY;
                    # without this the replica keeps decoding tokens nobody
                    # will ever read.  cancel() can block briefly on an
                    # unresolved routing future, so it runs off-loop.
                    abandoned.set()
                    loop.run_in_executor(None, resp_gen.cancel)
                    _shed_metrics()["abandoned"].inc(
                        1, tags=dep_tag or {"deployment": f"{handle.app}/{handle.deployment}"}
                    )
                    if flightrec.REC is not None:
                        flightrec.REC.record(
                            "serve", "serve_stream_abandoned",
                            deployment=(dep_tag or {}).get(
                                "deployment", f"{handle.app}/{handle.deployment}"
                            ),
                            path=req.path,
                            **({"trace": wire} if wire else {}),
                        )
                    return
        except asyncio.CancelledError:
            # proxy shutdown: stop the upstream too, then stay cancelled
            abandoned.set()
            loop.run_in_executor(None, resp_gen.cancel)
            raise
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _respond(self, writer, code: int, payload: Any, extra_headers=None):
        try:
            if isinstance(payload, bytes):
                body, ctype = payload, "application/octet-stream"
            elif isinstance(payload, str):
                body, ctype = payload.encode(), "text/plain; charset=utf-8"
            else:
                body, ctype = json.dumps(_json_default(payload)).encode(), "application/json"
            status = {
                200: "OK",
                404: "Not Found",
                429: "Too Many Requests",
                500: "Internal Server Error",
                503: "Service Unavailable",
            }.get(code, "OK")
            extras = "".join(
                f"{k}: {v}\r\n" for k, v in (extra_headers or {}).items()
            )
            writer.write(
                f"HTTP/1.1 {code} {status}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(body)}\r\n"
                f"{extras}"
                f"Connection: close\r\n\r\n".encode() + body
            )
            await drain(writer)
            writer.close()
        except asyncio.CancelledError:
            try:
                writer.close()
            except Exception:
                pass
            raise
        except Exception:
            pass


def _json_default(obj):
    import numpy as np

    if isinstance(obj, dict):
        return {k: _json_default(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_default(v) for v in obj]
    if isinstance(obj, np.generic):
        return obj.item()
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    return obj
