"""ServeController: the reconciliation control loop (analogue of
python/ray/serve/_private/controller.py ServeController +
deployment_state.py DeploymentStateManager).

A detached named actor. Holds desired state (applications -> deployments ->
target replica counts), reconciles actual replica actors toward it on a
background thread, runs autoscaling from replica queue-length metrics,
replaces dead replicas, and bumps a version counter per deployment that
routers poll (the long-poll analogue of serve/_private/long_poll.py).

Threading: all methods are sync and run on the actor's executor pool
(max_concurrency > 1); the reconcile loop is a dedicated thread. Blocking
`ca.get` is safe on these threads (the process's IO loop is separate); it
would deadlock on the loop itself, so nothing here is async.
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Any, Dict, List

from ..core import api as ca
from ..core.actor import get_actor, kill
from .config import DeploymentConfig, DeploymentStatus
from .replica import Replica

CONTROLLER_NAME = "SERVE_CONTROLLER"


class _DeploymentState:
    def __init__(self, app: str, name: str, deployment_def, init_args, init_kwargs, cfg: DeploymentConfig):
        self.app = app
        self.name = name
        self.deployment_def = deployment_def
        self.init_args = init_args
        self.init_kwargs = init_kwargs
        self.cfg = cfg
        self.target = (
            cfg.autoscaling_config.min_replicas
            if cfg.autoscaling_config
            else cfg.num_replicas
        )
        self.replicas: Dict[str, Any] = {}  # replica_id -> actor handle
        self.version = 0
        self.replica_counter = 0
        self.status = "UPDATING"
        self.message = ""
        self.payload_digest: str = ""
        # generation disambiguates replica actor names across redeploys;
        # retired tells a mid-flight reconcile pass to stop touching this state
        self.generation = 0
        self.retired = False
        self._last_scale_t = 0.0

    def key(self) -> str:
        return f"{self.app}/{self.name}"


class ServeController:
    def __init__(self):
        self.apps: Dict[str, Dict[str, _DeploymentState]] = {}
        self.route_prefixes: Dict[str, str] = {}  # app -> route_prefix
        self.ingress: Dict[str, str] = {}  # app -> ingress deployment name
        self._lock = threading.RLock()
        self._stopped = False
        self._thread = threading.Thread(
            target=self._reconcile_loop, daemon=True, name="serve-reconcile"
        )
        self._thread.start()

    # ------------------------------------------------------------ deploy API
    def deploy_application(
        self,
        app_name: str,
        route_prefix: str,
        ingress: str,
        deployments: List[Dict[str, Any]],
    ) -> str:
        import pickle

        with self._lock:
            app = self.apps.setdefault(app_name, {})
            wanted = set()
            for spec in deployments:
                name = spec["name"]
                wanted.add(name)
                cfg: DeploymentConfig = pickle.loads(spec["config"])
                d_def, init_args, init_kwargs = pickle.loads(spec["payload"])
                old = app.get(name)
                st = _DeploymentState(app_name, name, d_def, init_args, init_kwargs, cfg)
                st.payload_digest = __import__("hashlib").sha256(spec["payload"]).hexdigest()
                if old is not None:
                    old.retired = True  # a mid-flight reconcile must stop
                    st.replica_counter = old.replica_counter
                    st.generation = old.generation + 1
                    st.version = old.version + 1
                    if st.payload_digest == getattr(old, "payload_digest", None):
                        # same code: keep live replicas, push config deltas
                        st.replicas = old.replicas
                        st.generation = old.generation
                        if cfg.user_config is not None and old.cfg.user_config != cfg.user_config:
                            for h in st.replicas.values():
                                try:
                                    h.reconfigure.remote(cfg.user_config)
                                except Exception:
                                    pass
                    else:
                        # code/init-args changed: old replicas must not keep
                        # serving stale code — replace them
                        self._teardown_deployment(old)
                app[name] = st
            for name in list(app):
                if name not in wanted:
                    app[name].retired = True
                    self._teardown_deployment(app[name])
                    del app[name]
            self.route_prefixes[app_name] = route_prefix
            self.ingress[app_name] = ingress
        return "ok"

    def wait_ready(self, app_name: str, timeout_s: float = 60.0) -> str:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                app = dict(self.apps.get(app_name, {}))
                statuses = {n: (st.status, st.message) for n, st in app.items()}
            if statuses and all(s == "HEALTHY" for s, _ in statuses.values()):
                return "ok"
            for n, (s, msg) in statuses.items():
                if s == "UNHEALTHY":
                    raise RuntimeError(f"deployment {app_name}/{n} unhealthy: {msg}")
            time.sleep(0.05)
        raise TimeoutError(f"app {app_name!r} not ready after {timeout_s}s")

    def delete_application(self, app_name: str) -> str:
        with self._lock:
            app = self.apps.pop(app_name, None)
            self.route_prefixes.pop(app_name, None)
            self.ingress.pop(app_name, None)
            if app:
                for st in app.values():
                    st.retired = True
        if app:
            for st in app.values():
                self._teardown_deployment(st)
        return "ok"

    def shutdown(self) -> str:
        with self._lock:
            apps, self.apps = self.apps, {}
            self._stopped = True
        for app in apps.values():
            for st in app.values():
                self._teardown_deployment(st)
        return "ok"

    def _teardown_deployment(self, st: _DeploymentState):
        for h in st.replicas.values():
            try:
                kill(h)
            except Exception:
                pass
        st.replicas.clear()

    # ----------------------------------------------------------- router API
    def get_deployment_info(self, app: str, deployment: str) -> Dict[str, Any]:
        with self._lock:
            st = self._state(app, deployment)
            return {
                "version": st.version,
                "max_ongoing_requests": st.cfg.max_ongoing_requests,
                "replicas": [
                    {"replica_id": rid, "actor_name": self._replica_actor_name(st, rid)}
                    for rid in st.replicas
                ],
            }

    def poll_deployment_info(
        self, app: str, deployment: str, known_version: int, timeout_s: float = 10.0
    ) -> Dict[str, Any]:
        """Long-poll: returns when version != known_version or timeout."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                st = self._state(app, deployment)
                if st.version != known_version:
                    break
            time.sleep(0.05)
        return self.get_deployment_info(app, deployment)

    def get_app_route(self, app: str) -> Dict[str, str]:
        with self._lock:
            return {
                "route_prefix": self.route_prefixes.get(app, "/"),
                "ingress": self.ingress.get(app, ""),
            }

    def list_routes(self) -> Dict[str, Dict[str, str]]:
        with self._lock:
            return {
                app: {"route_prefix": self.route_prefixes.get(app, "/"), "ingress": ing}
                for app, ing in self.ingress.items()
            }

    def status(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {}
            for app_name, app in self.apps.items():
                out[app_name] = {
                    name: DeploymentStatus(
                        name=name,
                        status=st.status,
                        replica_states={"RUNNING": len(st.replicas)},
                        message=st.message,
                    ).__dict__
                    for name, st in app.items()
                }
            return out

    def ping(self) -> str:
        return "pong"

    def _state(self, app: str, deployment: str) -> _DeploymentState:
        try:
            return self.apps[app][deployment]
        except KeyError:
            raise KeyError(f"unknown deployment {app}/{deployment}")

    # ------------------------------------------------------------- reconcile
    def _replica_actor_name(self, st: _DeploymentState, rid: str) -> str:
        # generation-qualified: replicas of a retired deploy can never collide
        # with the names the replacement state will use
        return f"SERVE_REPLICA::{st.app}::{st.name}::g{st.generation}::{rid}"

    def _reconcile_loop(self):
        while not self._stopped:
            try:
                with self._lock:
                    states = [
                        st for app in self.apps.values() for st in app.values()
                    ]
                for st in states:
                    self._reconcile_deployment(st)
                    self._autoscale(st)
            except Exception:
                traceback.print_exc()
            time.sleep(0.1)

    def _bump_version(self, st: _DeploymentState):
        with self._lock:
            st.version += 1

    def _reconcile_deployment(self, st: _DeploymentState):
        if st.retired:
            return
        # replace dead replicas
        dead = []
        for rid, h in list(st.replicas.items()):
            try:
                ca.get(h.check_health.remote(), timeout=30)
            except Exception:
                dead.append(rid)
        for rid in dead:
            try:
                kill(st.replicas[rid])
            except Exception:
                pass
            with self._lock:
                st.replicas.pop(rid, None)
        if dead:
            self._bump_version(st)
        changed = False
        while len(st.replicas) < st.target and not self._stopped and not st.retired:
            with self._lock:
                rid = f"r{st.replica_counter}"
                st.replica_counter += 1
            Rep = ca.remote(Replica).options(
                name=self._replica_actor_name(st, rid),
                max_restarts=st.cfg.max_restarts,
                **st.cfg.actor_options(),
            )
            try:
                h = Rep.remote(
                    st.deployment_def,
                    st.init_args,
                    st.init_kwargs,
                    st.cfg.user_config,
                    rid,
                    deployment_name=f"{st.app}:{st.name}",
                )
                ca.get(h.check_health.remote(), timeout=60)
            except Exception as e:
                st.status = "UNHEALTHY"
                st.message = f"replica start failed: {e!r}"
                return
            if st.retired:
                # deploy/delete raced with this spawn: don't leak the replica
                try:
                    kill(h)
                except Exception:
                    pass
                return
            with self._lock:
                st.replicas[rid] = h
            changed = True
        while len(st.replicas) > st.target:
            with self._lock:
                rid = next(iter(st.replicas))
                h = st.replicas.pop(rid)
            try:
                ca.get(h.prepare_shutdown.remote(), timeout=st.cfg.graceful_shutdown_timeout_s)
            except Exception:
                pass
            try:
                kill(h)
            except Exception:
                pass
            changed = True
        if changed:
            self._bump_version(st)
        st.status = "HEALTHY" if len(st.replicas) == st.target else "UPDATING"
        if st.status == "HEALTHY":
            st.message = ""

    def _autoscale(self, st: _DeploymentState):
        cfg = st.cfg.autoscaling_config
        if cfg is None or not st.replicas:
            return
        lens = []
        for h in list(st.replicas.values()):
            try:
                lens.append(ca.get(h.get_queue_len.remote(), timeout=5))
            except Exception:
                pass
        if not lens:
            return
        avg = sum(lens) / len(lens)
        desired = max(
            cfg.min_replicas,
            min(
                cfg.max_replicas,
                -(-int(len(lens) * avg) // max(int(cfg.target_ongoing_requests), 1))
                if avg > 0
                else cfg.min_replicas,
            ),
        )
        now = time.monotonic()
        if desired > st.target and now - st._last_scale_t > cfg.upscale_delay_s:
            st.target = desired
            st._last_scale_t = now
        elif desired < st.target and now - st._last_scale_t > cfg.downscale_delay_s:
            st.target = max(desired, cfg.min_replicas)
            st._last_scale_t = now


def get_or_create_controller():
    """Get the cluster's controller actor, creating it if needed."""
    try:
        return get_actor(CONTROLLER_NAME)
    except Exception:
        pass
    Controller = ca.remote(ServeController).options(
        name=CONTROLLER_NAME, lifetime="detached", num_cpus=0.1, max_concurrency=16
    )
    try:
        h = Controller.remote()
        ca.get(h.ping.remote(), timeout=30)
        return h
    except Exception:
        # lost the creation race: someone else made it
        return get_actor(CONTROLLER_NAME)
