"""ServeController: the reconciliation control loop (analogue of
python/ray/serve/_private/controller.py ServeController +
deployment_state.py DeploymentStateManager).

A detached named actor. Holds desired state (applications -> deployments ->
target replica counts), reconciles actual replica actors toward it on a
background thread, runs autoscaling from replica queue-length metrics,
replaces dead replicas, and bumps a version counter per deployment that
routers poll (the long-poll analogue of serve/_private/long_poll.py).

Threading: all methods are sync and run on the actor's executor pool
(max_concurrency > 1); the reconcile loop is a dedicated thread. Blocking
`ca.get` is safe on these threads (the process's IO loop is separate); it
would deadlock on the loop itself, so nothing here is async.
"""

from __future__ import annotations

import threading
import time
import traceback
from typing import Any, Dict, List

from ..core import api as ca
from ..core.actor import get_actor, kill
from ..util import flightrec
from .config import DeploymentConfig, DeploymentStatus
from .replica import Replica

CONTROLLER_NAME = "SERVE_CONTROLLER"


class _DeploymentState:
    def __init__(self, app: str, name: str, deployment_def, init_args, init_kwargs, cfg: DeploymentConfig):
        self.app = app
        self.name = name
        self.deployment_def = deployment_def
        self.init_args = init_args
        self.init_kwargs = init_kwargs
        self.cfg = cfg
        self.target = (
            cfg.autoscaling_config.min_replicas
            if cfg.autoscaling_config
            else cfg.num_replicas
        )
        self.replicas: Dict[str, Any] = {}  # replica_id -> actor handle
        self.version = 0
        self.replica_counter = 0
        self.status = "UPDATING"
        self.message = ""
        self.payload_digest: str = ""
        # generation disambiguates replica actor names across redeploys;
        # retired tells a mid-flight reconcile pass to stop touching this state
        self.generation = 0
        self.retired = False
        self._last_scale_t = 0.0
        # drain plane: replicas on announced-exiting nodes.  STICKY — once a
        # replica is draining it only leaves the set by being retired/dying,
        # never by the drain window expiring (a node past its deadline is
        # about to be killed, not coming back).  Routers stop picking these;
        # the reconcile pass starts replacements first and retires each
        # draining replica once it has zero in-flight requests.
        self.draining_rids: set = set()
        self.draining_marked: Dict[str, float] = {}  # rid -> monotonic mark time
        self.replica_nodes: Dict[str, str] = {}  # replica_id -> node_id
        self.qlens: Dict[str, int] = {}  # replica_id -> last reported ongoing
        # autoscale observability: the last actual scale decision and the
        # last observation that informed one (ca status / /api/serve)
        self.last_scale: Optional[Dict[str, Any]] = None
        self.last_autoscale_obs: Optional[Dict[str, Any]] = None

    def key(self) -> str:
        return f"{self.app}/{self.name}"

    def active_rids(self) -> List[str]:
        return [rid for rid in self.replicas if rid not in self.draining_rids]


class ServeController:
    def __init__(self):
        self.apps: Dict[str, Dict[str, _DeploymentState]] = {}
        self.route_prefixes: Dict[str, str] = {}  # app -> route_prefix
        self.ingress: Dict[str, str] = {}  # app -> ingress deployment name
        self._lock = threading.RLock()
        self._stopped = False
        self._last_plane_pub = 0.0
        self._thread = threading.Thread(
            target=self._reconcile_loop, daemon=True, name="serve-reconcile"
        )
        self._thread.start()

    # ------------------------------------------------------------ deploy API
    def deploy_application(
        self,
        app_name: str,
        route_prefix: str,
        ingress: str,
        deployments: List[Dict[str, Any]],
    ) -> str:
        import pickle

        with self._lock:
            app = self.apps.setdefault(app_name, {})
            wanted = set()
            for spec in deployments:
                name = spec["name"]
                wanted.add(name)
                cfg: DeploymentConfig = pickle.loads(spec["config"])
                d_def, init_args, init_kwargs = pickle.loads(spec["payload"])
                old = app.get(name)
                st = _DeploymentState(app_name, name, d_def, init_args, init_kwargs, cfg)
                st.payload_digest = __import__("hashlib").sha256(spec["payload"]).hexdigest()
                if old is not None:
                    old.retired = True  # a mid-flight reconcile must stop
                    st.replica_counter = old.replica_counter
                    st.generation = old.generation + 1
                    st.version = old.version + 1
                    if st.payload_digest == getattr(old, "payload_digest", None):
                        # same code: keep live replicas, push config deltas
                        st.replicas = old.replicas
                        st.generation = old.generation
                        st.draining_rids = old.draining_rids
                        st.draining_marked = old.draining_marked
                        st.replica_nodes = old.replica_nodes
                        st.qlens = old.qlens
                        if cfg.user_config is not None and old.cfg.user_config != cfg.user_config:
                            for h in st.replicas.values():
                                try:
                                    h.reconfigure.remote(cfg.user_config)
                                except Exception:
                                    pass
                    else:
                        # code/init-args changed: old replicas must not keep
                        # serving stale code — replace them
                        self._teardown_deployment(old)
                app[name] = st
            for name in list(app):
                if name not in wanted:
                    app[name].retired = True
                    self._teardown_deployment(app[name])
                    del app[name]
            self.route_prefixes[app_name] = route_prefix
            self.ingress[app_name] = ingress
        return "ok"

    def wait_ready(self, app_name: str, timeout_s: float = 60.0) -> str:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                app = dict(self.apps.get(app_name, {}))
                statuses = {n: (st.status, st.message) for n, st in app.items()}
            if statuses and all(s == "HEALTHY" for s, _ in statuses.values()):
                return "ok"
            for n, (s, msg) in statuses.items():
                if s == "UNHEALTHY":
                    raise RuntimeError(f"deployment {app_name}/{n} unhealthy: {msg}")
            time.sleep(0.05)
        raise TimeoutError(f"app {app_name!r} not ready after {timeout_s}s")

    def delete_application(self, app_name: str) -> str:
        with self._lock:
            app = self.apps.pop(app_name, None)
            self.route_prefixes.pop(app_name, None)
            self.ingress.pop(app_name, None)
            if app:
                for st in app.values():
                    st.retired = True
        if app:
            for st in app.values():
                self._teardown_deployment(st)
        return "ok"

    def shutdown(self) -> str:
        with self._lock:
            apps, self.apps = self.apps, {}
            self._stopped = True
        for app in apps.values():
            for st in app.values():
                self._teardown_deployment(st)
        return "ok"

    def _teardown_deployment(self, st: _DeploymentState):
        for h in st.replicas.values():
            try:
                kill(h)
            except Exception:
                pass
        st.replicas.clear()
        st.draining_rids.clear()
        st.draining_marked.clear()
        st.qlens.clear()
        st.replica_nodes.clear()

    # ----------------------------------------------------------- router API
    def get_deployment_info(self, app: str, deployment: str) -> Dict[str, Any]:
        with self._lock:
            st = self._state(app, deployment)
            return {
                "version": st.version,
                "max_ongoing_requests": st.cfg.max_ongoing_requests,
                "replicas": [
                    {
                        "replica_id": rid,
                        "actor_name": self._replica_actor_name(st, rid),
                        # routers stop picking draining replicas (in-flight
                        # streams on them run to completion)
                        "draining": rid in st.draining_rids,
                        # last controller-observed ongoing count: the shared
                        # load signal behind power-of-two-choices (each
                        # router's local view only sees its own traffic)
                        "queue_len": int(st.qlens.get(rid, 0)),
                    }
                    for rid in st.replicas
                ],
            }

    def poll_deployment_info(
        self, app: str, deployment: str, known_version: int, timeout_s: float = 10.0
    ) -> Dict[str, Any]:
        """Long-poll: returns when version != known_version or timeout."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            with self._lock:
                st = self._state(app, deployment)
                if st.version != known_version:
                    break
            time.sleep(0.05)
        return self.get_deployment_info(app, deployment)

    def get_app_route(self, app: str) -> Dict[str, str]:
        with self._lock:
            return {
                "route_prefix": self.route_prefixes.get(app, "/"),
                "ingress": self.ingress.get(app, ""),
            }

    def list_routes(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            out: Dict[str, Dict[str, Any]] = {}
            for app, ing in self.ingress.items():
                info: Dict[str, Any] = {
                    "route_prefix": self.route_prefixes.get(app, "/"),
                    "ingress": ing,
                }
                st = self.apps.get(app, {}).get(ing)
                if st is not None:
                    # the proxy's admission gate rides the route table: the
                    # policy plus the live capacity its depth cap derives from
                    info["max_ongoing_requests"] = st.cfg.max_ongoing_requests
                    info["replicas"] = len(st.active_rids()) or len(st.replicas)
                    if st.cfg.admission is not None:
                        info["admission"] = st.cfg.admission.to_wire()
                out[app] = info
            return out

    def status(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {}
            for app_name, app in self.apps.items():
                out[app_name] = {}
                for name, st in app.items():
                    n_drain = len(st.draining_rids & set(st.replicas))
                    states = {"RUNNING": len(st.replicas) - n_drain}
                    if n_drain:
                        states["DRAINING"] = n_drain
                    out[app_name][name] = DeploymentStatus(
                        name=name,
                        status=st.status,
                        replica_states=states,
                        message=st.message,
                    ).__dict__
            return out

    def serve_plane_info(self) -> Dict[str, Any]:
        """Autoscale + drain observability: per-deployment target vs actual
        replicas, per-replica node/queue/draining state, and the last scale
        decision — the payload behind `ca status`, /api/serve, and
        util.state.serve_plane()."""
        with self._lock:
            out: Dict[str, Any] = {}
            for app_name, app in self.apps.items():
                out[app_name] = {}
                for name, st in app.items():
                    out[app_name][name] = {
                        "status": st.status,
                        "version": st.version,
                        "target_replicas": st.target,
                        "actual_replicas": len(st.replicas),
                        "draining_replicas": sorted(
                            st.draining_rids & set(st.replicas)
                        ),
                        "max_ongoing_requests": st.cfg.max_ongoing_requests,
                        "autoscaling": st.cfg.autoscaling_config is not None,
                        "admission": (
                            st.cfg.admission.to_wire()
                            if st.cfg.admission is not None else None
                        ),
                        "replicas": {
                            rid: {
                                "node_id": st.replica_nodes.get(rid),
                                "queue_len": int(st.qlens.get(rid, 0)),
                                "draining": rid in st.draining_rids,
                            }
                            for rid in st.replicas
                        },
                        "last_scale": st.last_scale,
                        "last_autoscale_obs": st.last_autoscale_obs,
                    }
            return out

    def ping(self) -> str:
        return "pong"

    def _state(self, app: str, deployment: str) -> _DeploymentState:
        try:
            return self.apps[app][deployment]
        except KeyError:
            raise KeyError(f"unknown deployment {app}/{deployment}")

    # ------------------------------------------------------------- reconcile
    def _replica_actor_name(self, st: _DeploymentState, rid: str) -> str:
        # generation-qualified: replicas of a retired deploy can never collide
        # with the names the replacement state will use
        return f"SERVE_REPLICA::{st.app}::{st.name}::g{st.generation}::{rid}"

    def _draining_node_ids(self) -> set:
        """Nodes inside an announced drain window.  The head pushes `drain`
        pubs to every client — including this controller's host process — so
        the read is a local dict lookup, zero RPCs."""
        try:
            from ..core.worker import global_worker

            return global_worker().draining_node_ids()
        except Exception:
            return set()

    def _publish_plane_digest(self):
        """Ship serve_plane_info to the head KV (~1/s): `ca status`, the
        dashboard's /api/serve, and util.state.serve_plane() read it without
        needing an actor round-trip to this controller."""
        import json as _json

        now = time.monotonic()
        if now - self._last_plane_pub < 1.0:
            return
        self._last_plane_pub = now
        try:
            from ..core.worker import global_worker

            global_worker().head_call(
                "kv_put", key="serve:plane",
                value=_json.dumps(self.serve_plane_info(), default=str).encode(),
            )
        except Exception:
            pass  # head briefly unreachable: next tick retries

    def _reconcile_loop(self):
        while not self._stopped:
            try:
                with self._lock:
                    states = [
                        st for app in self.apps.values() for st in app.values()
                    ]
                draining_nodes = self._draining_node_ids()
                for st in states:
                    self._mark_draining(st, draining_nodes)
                    self._reconcile_deployment(st)
                    self._autoscale(st)
                self._publish_plane_digest()
            except Exception:
                traceback.print_exc()
            time.sleep(0.1)

    def _mark_draining(self, st: _DeploymentState, draining_nodes: set):
        """Flag replicas hosted on announced-exiting nodes (sticky).  The
        version bump makes every router refresh and stop picking them —
        step one of the zero-drop drain story."""
        if not draining_nodes or st.retired:
            return
        newly = {
            rid
            for rid, nid in st.replica_nodes.items()
            if nid in draining_nodes and rid in st.replicas
        } - st.draining_rids
        if newly:
            now = time.monotonic()
            with self._lock:
                st.draining_rids |= newly
                for rid in newly:
                    st.draining_marked[rid] = now
            self._bump_version(st)
            if flightrec.REC is not None:
                flightrec.REC.record(
                    "serve", "serve_replica_draining", deployment=st.key(),
                    replicas=sorted(newly),
                    nodes=sorted({st.replica_nodes.get(r) for r in newly
                                  if st.replica_nodes.get(r)}),
                )

    def _bump_version(self, st: _DeploymentState):
        with self._lock:
            st.version += 1

    def _retire_replica(self, st: _DeploymentState, rid: str, h) -> None:
        try:
            ca.get(h.prepare_shutdown.remote(), timeout=st.cfg.graceful_shutdown_timeout_s)
        except Exception:
            pass
        try:
            kill(h)
        except Exception:
            pass

    def _reconcile_deployment(self, st: _DeploymentState):
        if st.retired:
            return
        # telemetry doubles as the health check: one RPC per replica per
        # pass yields alive/deadness, the ongoing-request count (router P2C
        # signal + drain retirement gate + autoscale input), and the hosting
        # node (drain detection)
        dead = []
        for rid, h in list(st.replicas.items()):
            try:
                t = ca.get(h.telemetry.remote(), timeout=30)
                with self._lock:
                    st.qlens[rid] = int(t.get("queue_len", 0))
                    if t.get("node_id"):
                        st.replica_nodes[rid] = t["node_id"]
            except Exception:
                dead.append(rid)
        for rid in dead:
            try:
                kill(st.replicas[rid])
            except Exception:
                pass
            with self._lock:
                st.replicas.pop(rid, None)
                st.draining_rids.discard(rid)
                st.draining_marked.pop(rid, None)
                st.qlens.pop(rid, None)
                st.replica_nodes.pop(rid, None)
        if dead:
            self._bump_version(st)
            if flightrec.REC is not None:
                flightrec.REC.record(
                    "serve", "serve_replica_dead", deployment=st.key(),
                    replicas=dead,
                )
        changed = False
        # replacements FIRST: spawn until the ACTIVE (non-draining) count
        # reaches target.  Draining replicas keep serving their in-flight
        # requests but no longer count toward capacity; new actors place on
        # survivors automatically (the head excludes draining nodes).
        while len(st.active_rids()) < st.target and not self._stopped and not st.retired:
            with self._lock:
                rid = f"r{st.replica_counter}"
                st.replica_counter += 1
            Rep = ca.remote(Replica).options(
                name=self._replica_actor_name(st, rid),
                max_restarts=st.cfg.max_restarts,
                **st.cfg.actor_options(),
            )
            try:
                h = Rep.remote(
                    st.deployment_def,
                    st.init_args,
                    st.init_kwargs,
                    st.cfg.user_config,
                    rid,
                    deployment_name=f"{st.app}:{st.name}",
                )
                t = ca.get(h.telemetry.remote(), timeout=60)
            except Exception as e:
                st.status = "UNHEALTHY"
                st.message = f"replica start failed: {e!r}"
                return
            if st.retired:
                # deploy/delete raced with this spawn: don't leak the replica
                try:
                    kill(h)
                except Exception:
                    pass
                return
            with self._lock:
                st.replicas[rid] = h
                st.qlens[rid] = 0
                if t.get("node_id"):
                    st.replica_nodes[rid] = t["node_id"]
            changed = True
            if flightrec.REC is not None:
                # replacement or migration target: pairs with the draining /
                # dead event that caused it in the incident timeline
                flightrec.REC.record(
                    "serve", "serve_replica_started", deployment=st.key(),
                    replica=rid, node=t.get("node_id"),
                )
        # normal downscale: retire surplus ACTIVE replicas (draining ones
        # are on their own retirement track below)
        while len(st.active_rids()) > st.target:
            with self._lock:
                rid = st.active_rids()[0]
                h = st.replicas.pop(rid)
                st.qlens.pop(rid, None)
                st.replica_nodes.pop(rid, None)
            self._retire_replica(st, rid, h)
            changed = True
            if flightrec.REC is not None:
                flightrec.REC.record(
                    "serve", "serve_replica_retired", deployment=st.key(),
                    replica=rid, reason="downscale",
                )
        # drain retirement: once replacements are up, retire each draining
        # replica when its last in-flight request (including SSE streams)
        # finishes.  The grace window matters: routers only refresh on-route
        # (~1s period), so a replica marked draining can still RECEIVE a
        # request for up to a refresh period — killing it at the first
        # qlen==0 sample would race that request.  2.5s > 2x refresh closes
        # the window; after it, every router has seen the draining flag.
        if st.draining_rids:
            now = time.monotonic()
            for rid in sorted(st.draining_rids & set(st.replicas)):
                if len(st.active_rids()) < st.target:
                    break  # replacements not ready: keep serving
                if now - st.draining_marked.get(rid, 0.0) < 2.5:
                    continue  # routers may still route here: too early
                if st.qlens.get(rid, 1) != 0:
                    continue  # in-flight work: let it run out
                with self._lock:
                    h = st.replicas.pop(rid)
                    st.draining_rids.discard(rid)
                    st.draining_marked.pop(rid, None)
                    st.qlens.pop(rid, None)
                    st.replica_nodes.pop(rid, None)
                self._retire_replica(st, rid, h)
                changed = True
                if flightrec.REC is not None:
                    # zero-drop migration complete: last in-flight request
                    # finished, replacements carried the traffic
                    flightrec.REC.record(
                        "serve", "serve_replica_retired", deployment=st.key(),
                        replica=rid, reason="drained",
                    )
        if changed:
            self._bump_version(st)
        st.status = (
            "HEALTHY" if len(st.active_rids()) == st.target else "UPDATING"
        )
        if st.status == "HEALTHY":
            st.message = ""

    def _autoscale(self, st: _DeploymentState):
        cfg = st.cfg.autoscaling_config
        if cfg is None or not st.replicas or st.retired:
            return
        # draining replicas are excluded: their load is migrating to the
        # actives, and counting them would double the apparent demand right
        # when capacity planning matters most
        lens = [
            st.qlens[rid] for rid in st.active_rids() if rid in st.qlens
        ]
        if not lens:
            return
        avg = sum(lens) / len(lens)
        desired = max(
            cfg.min_replicas,
            min(
                cfg.max_replicas,
                -(-int(len(lens) * avg) // max(int(cfg.target_ongoing_requests), 1))
                if avg > 0
                else cfg.min_replicas,
            ),
        )
        now = time.monotonic()
        st.last_autoscale_obs = {
            "ts": time.time(),
            "avg_ongoing": round(avg, 3),
            "active_replicas": len(lens),
            "desired": desired,
        }
        decided = None
        if desired > st.target and now - st._last_scale_t > cfg.upscale_delay_s:
            decided = ("up", st.target, desired)
            st.target = desired
            st._last_scale_t = now
        elif desired < st.target and now - st._last_scale_t > cfg.downscale_delay_s:
            decided = ("down", st.target, max(desired, cfg.min_replicas))
            st.target = max(desired, cfg.min_replicas)
            st._last_scale_t = now
        if decided is not None:
            st.last_scale = {
                "ts": time.time(),
                "direction": decided[0],
                "from": decided[1],
                "to": decided[2],
                "avg_ongoing": round(avg, 3),
            }
            if flightrec.REC is not None:
                flightrec.REC.record(
                    "serve", "serve_autoscale", deployment=st.key(),
                    direction=decided[0], from_replicas=decided[1],
                    to_replicas=decided[2], avg_ongoing=round(avg, 3),
                )


def get_or_create_controller():
    """Get the cluster's controller actor, creating it if needed."""
    from ..core.scheduling_strategies import NodeAffinitySchedulingStrategy

    try:
        return get_actor(CONTROLLER_NAME)
    except Exception:
        pass
    Controller = ca.remote(ServeController).options(
        name=CONTROLLER_NAME, lifetime="detached", num_cpus=0.1, max_concurrency=16,
        # system actors live with the control plane: the head node never
        # drains, so the controller doesn't restart mid-drain-orchestration
        # (soft: single-node clusters and full heads still place somewhere)
        scheduling_strategy=NodeAffinitySchedulingStrategy("n0", soft=True),
    )
    try:
        h = Controller.remote()
        ca.get(h.ping.remote(), timeout=30)
        return h
    except Exception:
        # lost the creation race: someone else made it
        return get_actor(CONTROLLER_NAME)
