"""Replica actor: hosts one copy of a deployment's user callable (analogue of
python/ray/serve/_private/replica.py Replica + UserCallableWrapper).
"""

from __future__ import annotations

import asyncio
import contextvars
import inspect
import time
from typing import Any, Dict, Optional

_request_context: contextvars.ContextVar = contextvars.ContextVar(
    "ca_serve_request_context", default=None
)


class RequestContext:
    def __init__(self, request_id: str = "", multiplexed_model_id: str = ""):
        self.request_id = request_id
        self.multiplexed_model_id = multiplexed_model_id


def get_request_context() -> RequestContext:
    ctx = _request_context.get()
    return ctx if ctx is not None else RequestContext()


_metrics_cache = {}


def _serve_metrics():
    """Per-request Prometheus series (reference serve metrics:
    ray_serve_deployment_request_counter / _processing_latency_ms — here
    ca_serve_requests_total / ca_serve_request_latency_seconds /
    ca_serve_request_errors_total, tagged by deployment).  Lazy: replicas
    that never serve a request register nothing."""
    if not _metrics_cache:
        from ..util import metrics as m

        _metrics_cache["requests"] = m.Counter(
            "ca_serve_requests_total", "serve requests handled",
            tag_keys=("deployment",),
        )
        _metrics_cache["errors"] = m.Counter(
            "ca_serve_request_errors_total", "serve requests errored",
            tag_keys=("deployment",),
        )
        _metrics_cache["latency"] = m.Histogram(
            "ca_serve_request_latency_seconds", "serve request latency",
            boundaries=[0.001, 0.005, 0.02, 0.05, 0.1, 0.25, 1.0, 5.0],
            tag_keys=("deployment",),
        )
    return _metrics_cache


class Replica:
    """One replica process. Methods are async so many requests interleave on
    the actor's event loop up to max_ongoing_requests."""

    def __init__(
        self,
        deployment_def,
        init_args: tuple,
        init_kwargs: Dict[str, Any],
        user_config: Optional[Dict[str, Any]],
        replica_id: str,
        handle_specs: Optional[Dict[str, Any]] = None,
        deployment_name: Optional[str] = None,
    ):
        # late-bind nested DeploymentHandles (model composition): bound
        # sub-deployments arrive as specs and materialize into handles here
        from .router import DeploymentHandle

        def resolve(v):
            if isinstance(v, dict) and v.get("__ca_serve_handle__"):
                return DeploymentHandle(v["app"], v["deployment"])
            if isinstance(v, list):
                return [resolve(x) for x in v]
            if isinstance(v, tuple):
                return tuple(resolve(x) for x in v)
            if isinstance(v, dict):
                return {k: resolve(x) for k, x in v.items()}
            return v

        init_args = tuple(resolve(a) for a in init_args)
        init_kwargs = {k: resolve(v) for k, v in init_kwargs.items()}
        self.replica_id = replica_id
        self._metric_tags = {"deployment": deployment_name or replica_id}
        self._is_function = not inspect.isclass(deployment_def)
        if self._is_function:
            self.instance = deployment_def
        else:
            self.instance = deployment_def(*init_args, **init_kwargs)
        self.num_ongoing = 0
        self.total_requests = 0
        if user_config is not None:
            self._apply_user_config(user_config)

    def _apply_user_config(self, cfg: Dict[str, Any]):
        fn = getattr(self.instance, "reconfigure", None)
        if fn is not None:
            fn(cfg)

    # ----------------------------------------------------------- control API
    def reconfigure(self, user_config: Dict[str, Any]):
        self._apply_user_config(user_config)
        return "ok"

    def check_health(self) -> str:
        fn = getattr(self.instance, "check_health", None)
        if fn is not None:
            fn()
        return "ok"

    def get_queue_len(self) -> int:
        return self.num_ongoing

    def telemetry(self) -> Dict[str, Any]:
        """One RPC for the controller's reconcile pass: liveness (raises if
        the user's check_health hook does), ongoing-request count (router
        P2C signal, drain retirement gate, autoscale input), and the hosting
        node (drain detection)."""
        fn = getattr(self.instance, "check_health", None)
        if fn is not None:
            fn()
        try:
            from ..core.worker import global_worker

            node_id = global_worker().node_id
        except Exception:
            node_id = None
        return {
            "queue_len": self.num_ongoing,
            "node_id": node_id,
            "total": self.total_requests,
        }

    def stats(self) -> Dict[str, Any]:
        return {
            "replica_id": self.replica_id,
            "num_ongoing": self.num_ongoing,
            "total": self.total_requests,
        }

    def prepare_shutdown(self) -> str:
        """Run user cleanup before the controller hard-kills the process —
        GC finalizers never fire on kill()."""
        fn = getattr(self.instance, "__del__", None)
        if fn is not None:
            try:
                fn()
            except Exception:
                pass
        return "ok"

    # ----------------------------------------------------------- request path
    async def handle_request(self, meta: Dict[str, Any], *args, **kwargs):
        self.num_ongoing += 1
        self.total_requests += 1
        mets = _serve_metrics()
        mets["requests"].inc(1, tags=self._metric_tags)
        t0 = time.perf_counter()
        token = _request_context.set(
            RequestContext(
                request_id=meta.get("request_id", ""),
                multiplexed_model_id=meta.get("multiplexed_model_id", ""),
            )
        )
        try:
            target = self.instance
            method_name = meta.get("method", "__call__")
            if self._is_function:
                fn = target
            else:
                fn = getattr(target, method_name)
            if inspect.iscoroutinefunction(fn):
                return await fn(*args, **kwargs)
            # sync user code must not block the replica's event loop
            loop = asyncio.get_running_loop()
            ctx = contextvars.copy_context()
            return await loop.run_in_executor(None, lambda: ctx.run(fn, *args, **kwargs))
        except Exception:
            # Exception only: client cancellation (CancelledError /
            # GeneratorExit are BaseException) is not a deployment error and
            # must not feed the errors series alerts watch
            mets["errors"].inc(1, tags=self._metric_tags)
            raise
        finally:
            mets["latency"].observe(
                time.perf_counter() - t0, tags=self._metric_tags
            )
            _request_context.reset(token)
            self.num_ongoing -= 1

    def handle_request_streaming(self, meta: Dict[str, Any], *args, **kwargs):
        """Generator twin of handle_request: iterates the user method's
        generator so items stream back as ObjectRefGenerator frames
        (reference replica.py streaming path)."""
        self.num_ongoing += 1
        self.total_requests += 1
        mets = _serve_metrics()
        mets["requests"].inc(1, tags=self._metric_tags)
        t0 = time.perf_counter()
        token = _request_context.set(
            RequestContext(
                request_id=meta.get("request_id", ""),
                multiplexed_model_id=meta.get("multiplexed_model_id", ""),
            )
        )
        try:
            target = self.instance
            fn = target if self._is_function else getattr(
                target, meta.get("method", "__call__")
            )
            out = fn(*args, **kwargs)
            if not hasattr(out, "__iter__") or isinstance(out, (str, bytes, dict)):
                yield out  # non-generator result: one-item stream
                return
            yield from out
        except Exception as e:
            # Exception only: client cancellation (CancelledError /
            # GeneratorExit are BaseException) is not a deployment error and
            # must not feed the errors series alerts watch.  TaskCancelledError
            # is the consumer abandoning the stream (proxy SSE disconnect) —
            # same story, different spelling.
            from ..core.errors import TaskCancelledError

            if not isinstance(e, TaskCancelledError):
                mets["errors"].inc(1, tags=self._metric_tags)
            raise
        finally:
            # latency covers the full stream (first byte to exhaustion)
            mets["latency"].observe(
                time.perf_counter() - t0, tags=self._metric_tags
            )
            _request_context.reset(token)
            self.num_ongoing -= 1
