"""cluster_anywhere_tpu.serve: scalable model serving on the actor runtime
(analogue of the reference's Ray Serve, python/ray/serve/).

    from cluster_anywhere_tpu import serve

    @serve.deployment(num_replicas=2)
    class Model:
        def __call__(self, x):
            return x * 2

    handle = serve.run(Model.bind())
    assert handle.remote(21).result() == 42
"""

from __future__ import annotations

import inspect
import pickle
from typing import Any, Callable, Dict, List, Optional, Union

from ..core import api as ca
from ..core.actor import get_actor, kill
from .batching import batch
from .config import AdmissionPolicy, AutoscalingConfig, DeploymentConfig, HTTPOptions
from .controller import CONTROLLER_NAME, ServeController, get_or_create_controller
from .multiplex import get_multiplexed_model_id, multiplexed
from .grpc_proxy import grpc_call, grpc_call_typed, grpc_healthz, grpc_list_applications
from .proxy import ProxyActor, Request
from .replica import get_request_context
from .router import DeploymentHandle, DeploymentResponseGenerator, DeploymentResponse

PROXY_NAME = "SERVE_PROXY"


class Application:
    """A bound deployment graph node (reference serve/_private/build_app.py)."""

    def __init__(self, deployment: "Deployment", args: tuple, kwargs: dict):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs


class Deployment:
    def __init__(self, func_or_class, name: str, config: DeploymentConfig):
        self.func_or_class = func_or_class
        self.name = name
        self.config = config

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    def options(self, **kw) -> "Deployment":
        import dataclasses

        name = kw.pop("name", self.name)
        # dict spellings accepted everywhere the dataclasses are (config
        # files route through here)
        if isinstance(kw.get("autoscaling_config"), dict):
            kw["autoscaling_config"] = AutoscalingConfig(**kw["autoscaling_config"])
        if isinstance(kw.get("admission"), dict):
            kw["admission"] = AdmissionPolicy(**kw["admission"])
        cfg_kw = {}
        for f in dataclasses.fields(DeploymentConfig):
            if f.name in kw:
                cfg_kw[f.name] = kw.pop(f.name)
        if "ray_actor_options" in kw:  # reference-compat spelling
            opts = kw.pop("ray_actor_options")
            cfg_kw.setdefault("num_cpus", opts.get("num_cpus", self.config.num_cpus))
        if kw:
            raise TypeError(f"unknown deployment options: {sorted(kw)}")
        cfg = dataclasses.replace(self.config, **cfg_kw)
        return Deployment(self.func_or_class, name, cfg)


def deployment(
    _func_or_class: Optional[Any] = None,
    *,
    name: Optional[str] = None,
    num_replicas: Union[int, str, None] = None,
    max_ongoing_requests: int = 8,
    user_config: Optional[Dict[str, Any]] = None,
    autoscaling_config: Optional[Union[AutoscalingConfig, Dict[str, Any]]] = None,
    admission: Optional[Union["AdmissionPolicy", Dict[str, Any]]] = None,
    num_cpus: float = 1.0,
    num_tpus: float = 0.0,
    resources: Optional[Dict[str, float]] = None,
    health_check_period_s: float = 2.0,
    graceful_shutdown_timeout_s: float = 5.0,
    max_restarts: int = 3,
):
    """@serve.deployment decorator (reference serve/api.py deployment)."""

    def deco(func_or_class):
        if isinstance(autoscaling_config, dict):
            asc = AutoscalingConfig(**autoscaling_config)
        else:
            asc = autoscaling_config
        adm = AdmissionPolicy(**admission) if isinstance(admission, dict) else admission
        n_replicas = num_replicas
        if n_replicas == "auto":
            n_replicas = None
        if n_replicas is None:
            n_replicas = asc.min_replicas if asc else 1
        cfg = DeploymentConfig(
            num_replicas=n_replicas,
            max_ongoing_requests=max_ongoing_requests,
            user_config=user_config,
            autoscaling_config=asc,
            admission=adm,
            num_cpus=num_cpus,
            num_tpus=num_tpus,
            resources=resources or {},
            health_check_period_s=health_check_period_s,
            graceful_shutdown_timeout_s=graceful_shutdown_timeout_s,
            max_restarts=max_restarts,
        )
        return Deployment(
            func_or_class,
            name or getattr(func_or_class, "__name__", "deployment"),
            cfg,
        )

    if _func_or_class is not None:
        return deco(_func_or_class)
    return deco


def _collect_deployments(app: Application, out: Dict[str, Application]):
    """DFS the bind graph; Applications nested anywhere in init args (also
    inside lists/tuples/dicts) become handles."""
    name = app.deployment.name
    if name in out and out[name] is not app:
        raise ValueError(f"duplicate deployment name {name!r} in application")
    out[name] = app

    def walk(v):
        if isinstance(v, Application):
            _collect_deployments(v, out)
        elif isinstance(v, (list, tuple)):
            for x in v:
                walk(x)
        elif isinstance(v, dict):
            for x in v.values():
                walk(x)

    for a in list(app.args) + list(app.kwargs.values()):
        walk(a)


def _resolve_arg(a, app_name: str):
    if isinstance(a, Application):
        return {"__ca_serve_handle__": True, "app": app_name, "deployment": a.deployment.name}
    if isinstance(a, list):
        return [_resolve_arg(x, app_name) for x in a]
    if isinstance(a, tuple):
        return tuple(_resolve_arg(x, app_name) for x in a)
    if isinstance(a, dict):
        return {k: _resolve_arg(v, app_name) for k, v in a.items()}
    return a


GRPC_PROXY_NAME = "SERVE_GRPC_PROXY"


def start(http_options: Optional[HTTPOptions] = None, grpc_port: Optional[int] = None, **kw) -> None:
    """Start the Serve system actors (controller + HTTP proxy; pass
    grpc_port to also start the gRPC ingress)."""
    get_or_create_controller()
    opts = http_options or HTTPOptions(**kw)
    try:
        get_actor(PROXY_NAME)
    except Exception:
        from ..core.scheduling_strategies import NodeAffinitySchedulingStrategy as _NA

        Proxy = ca.remote(ProxyActor).options(
            name=PROXY_NAME, lifetime="detached", num_cpus=0.1, max_concurrency=4,
            # the proxy owns live client sockets: pin it to the undrainable
            # head node so a worker-node drain can't restart it mid-stream
            scheduling_strategy=_NA("n0", soft=True),
        )
        h = Proxy.remote(opts.host, opts.port)
        ca.get(h.ready.remote(), timeout=30)
    if grpc_port is not None:
        start_grpc_proxy(port=grpc_port)


def start_grpc_proxy(host: str = "127.0.0.1", port: int = 0) -> str:
    """Start (or find) the gRPC ingress; returns its host:port target."""
    from .grpc_proxy import GrpcProxyActor

    try:
        h = get_actor(GRPC_PROXY_NAME)
    except Exception:
        Proxy = ca.remote(GrpcProxyActor).options(
            name=GRPC_PROXY_NAME, lifetime="detached", num_cpus=0.1, max_concurrency=4
        )
        h = Proxy.remote(host, port)
    return ca.get(h.ready.remote(), timeout=30)


def run(
    app: Application,
    *,
    name: str = "default",
    route_prefix: str = "/",
    _blocking: bool = True,
    wait_timeout_s: float = 60.0,
) -> DeploymentHandle:
    """Deploy an application and return a handle to its ingress
    (reference serve/api.py serve.run)."""
    if not isinstance(app, Application):
        raise TypeError("serve.run expects Deployment.bind(...)")
    ctrl = get_or_create_controller()
    graph: Dict[str, Application] = {}
    _collect_deployments(app, graph)
    specs: List[Dict[str, Any]] = []
    for dname, a in graph.items():
        args = tuple(_resolve_arg(x, name) for x in a.args)
        kwargs = {k: _resolve_arg(v, name) for k, v in a.kwargs.items()}
        specs.append(
            {
                "name": dname,
                "config": pickle.dumps(a.deployment.config),
                "payload": __import__("cloudpickle").dumps(
                    (a.deployment.func_or_class, args, kwargs)
                ),
            }
        )
    ingress = app.deployment.name
    ca.get(
        ctrl.deploy_application.remote(name, route_prefix, ingress, specs), timeout=60
    )
    if _blocking:
        ca.get(ctrl.wait_ready.remote(name, wait_timeout_s), timeout=wait_timeout_s + 10)
    return DeploymentHandle(name, ingress)


def get_app_handle(name: str = "default") -> DeploymentHandle:
    ctrl = get_or_create_controller()
    info = ca.get(ctrl.get_app_route.remote(name))
    if not info["ingress"]:
        raise KeyError(f"no application named {name!r}")
    return DeploymentHandle(name, info["ingress"])


def get_deployment_handle(deployment_name: str, app_name: str = "default") -> DeploymentHandle:
    return DeploymentHandle(app_name, deployment_name)


def status() -> Dict[str, Any]:
    ctrl = get_or_create_controller()
    return ca.get(ctrl.status.remote())

def delete(name: str):
    ctrl = get_or_create_controller()
    ca.get(ctrl.delete_application.remote(name))


def shutdown():
    try:
        ctrl = get_actor(CONTROLLER_NAME)
    except Exception:
        return
    try:
        ca.get(ctrl.shutdown.remote(), timeout=30)
    except Exception:
        pass
    for actor_name in (PROXY_NAME, CONTROLLER_NAME):
        try:
            kill(get_actor(actor_name))
        except Exception:
            pass


__all__ = [
    "deployment",
    "Deployment",
    "Application",
    "run",
    "run_config",
    "start",
    "start_grpc_proxy",
    "grpc_call",
    "grpc_call_typed",
    "grpc_list_applications",
    "grpc_healthz",
    "delete",
    "shutdown",
    "status",
    "get_app_handle",
    "get_deployment_handle",
    "DeploymentHandle",
    "DeploymentResponse",
    "DeploymentConfig",
    "AutoscalingConfig",
    "AdmissionPolicy",
    "HTTPOptions",
    "Request",
    "batch",
    "multiplexed",
    "get_multiplexed_model_id",
    "get_request_context",
]


def run_config(config, *, _blocking: bool = True) -> Dict[str, DeploymentHandle]:
    """Deploy applications from a config file/dict (reference `serve deploy`
    + serve/schema.py ServeDeploySchema, compact):

        applications:
          - name: app1
            route_prefix: /app1
            import_path: my.module:app      # a module-level Application
            deployments:                    # optional per-deployment overrides
              - name: Doubler
                num_replicas: 2

    Returns {app_name: ingress handle}.  import_path targets must be
    importable by replica processes (same host or shipped via runtime_env).
    """
    import importlib

    if isinstance(config, str):
        import yaml

        with open(config) as f:
            config = yaml.safe_load(f) or {}
    handles: Dict[str, DeploymentHandle] = {}
    for app_spec in config.get("applications") or []:
        name = app_spec.get("name", "default")
        module_name, _, attr = app_spec["import_path"].partition(":")
        app = getattr(importlib.import_module(module_name), attr)
        if isinstance(app, Deployment):
            app = app.bind()
        if not isinstance(app, Application):
            raise TypeError(
                f"{app_spec['import_path']} is not an Application/Deployment"
            )
        overrides = {
            d["name"]: {k: v for k, v in d.items() if k != "name"}
            for d in app_spec.get("deployments") or []
        }
        if overrides:
            app = _apply_overrides(app, overrides)
        handles[name] = run(
            app,
            name=name,
            route_prefix=app_spec.get("route_prefix", f"/{name}"),
            _blocking=_blocking,
        )
    return handles


def _apply_overrides(app: Application, overrides: Dict[str, Dict[str, Any]]) -> Application:
    """Rebuild the bind graph with per-deployment option overrides applied."""
    def rebuild(a: Application) -> Application:
        dep = a.deployment
        if dep.name in overrides:
            dep = dep.options(**overrides[dep.name])
        new_args = tuple(rebuild(x) if isinstance(x, Application) else x for x in a.args)
        new_kwargs = {
            k: rebuild(v) if isinstance(v, Application) else v
            for k, v in a.kwargs.items()
        }
        return Application(dep, new_args, new_kwargs)

    return rebuild(app)
