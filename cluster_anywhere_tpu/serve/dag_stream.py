"""Compiled-DAG stream plumbing for the serving plane.

The RPC streaming path moves every token through the worker's task-return
machinery: serialize -> stream_ack RPC -> driver inbox -> SSE writer.  On
the compiled path the replica pushes frames straight into a pre-opened
shared-memory channel and the proxy futex-waits on the header word -- no
per-token RPC at all.  Exactly one RPC remains per request: the handshake
(`dag_stream`) that submits the prompt and returns the channel spec.

Wire format rides on the shm channel frame (see channel/shm_channel.py):
each payload is one pickled event dict {"token_id": int, "text": str};
the stream terminates with the DAG_EOF sentinel string, or with one
{DAG_ERR: repr} dict if the engine died mid-decode.
"""

from typing import Optional

DAG_EOF = "__ca_dag_eof__"  # final frame: stream ended normally
DAG_ERR = "__ca_dag_err__"  # key of a terminal error frame: {DAG_ERR: repr}


class DagStreamReader:
    """Proxy-side endpoint of a replica's token channel.

    Iterates event dicts until the EOF/error frame.  Duck-types the two
    methods the SSE pump needs from a streaming ObjectRefGenerator --
    iteration and cancel() -- so the proxy's pump/abandonment machinery
    works unchanged on either path.
    """

    def __init__(self, spec: dict, timeout_s: float = 120.0):
        from ..channel.shm_channel import open_channel

        self._ch = open_channel(spec, 0)
        self._timeout = timeout_s

    def __iter__(self):
        try:
            while True:
                frame = self._ch.read(self._timeout)
                if frame == DAG_EOF:
                    return
                if isinstance(frame, dict) and DAG_ERR in frame:
                    raise RuntimeError(frame[DAG_ERR])
                yield frame
        finally:
            self.release()

    def cancel(self):
        """Abandonment: set the shared closed flag so the replica-side
        forwarder's next write raises ChannelClosedError and frees the
        decode slot (mirrors ObjectRefGenerator.cancel on the RPC path)."""
        try:
            self._ch.close()
        except Exception:
            pass

    def release(self):
        try:
            self._ch.release()
        except Exception:
            pass


def open_dag_stream(spec: dict, timeout_s: Optional[float] = None) -> DagStreamReader:
    return DagStreamReader(spec, 120.0 if timeout_s is None else timeout_s)
