"""DeploymentHandle + Router: the request path (analogue of
python/ray/serve/handle.py DeploymentHandle -> serve/_private/router.py
Router -> replica_scheduler/pow_2_scheduler.py PowerOfTwoChoicesReplicaScheduler).

The router keeps a local in-flight count per replica and picks the less-loaded
of two random replicas (power-of-two-choices with locally-observed queue
lengths), refreshing replica membership from the controller when its cached
version goes stale.
"""

from __future__ import annotations

import contextvars
import random
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

from ..core import api as ca
from ..core.actor import get_actor
from .controller import CONTROLLER_NAME

_REFRESH_PERIOD_S = 1.0


class DeploymentResponse:
    """Future-like result of handle.remote() (reference serve/handle.py
    DeploymentResponse). Wraps a future-of-ObjectRef: routing happens on the
    router's dispatch thread, so .remote() never blocks — critical inside
    async replica code, where blocking the event loop would deadlock the
    process's IO."""

    def __init__(self, ref_future):
        self._ref_future = ref_future

    def result(self, timeout_s: Optional[float] = None):
        deadline = None if timeout_s is None else time.monotonic() + timeout_s
        ref = self._ref_future.result(timeout_s)
        remain = None if deadline is None else max(0.0, deadline - time.monotonic())
        return ca.get(ref, timeout=remain)

    def _to_object_ref(self, timeout_s: Optional[float] = 30.0):
        return self._ref_future.result(timeout_s)

    def __await__(self):
        import asyncio

        async def _wait():
            loop = asyncio.get_running_loop()
            return await loop.run_in_executor(None, self.result)

        return _wait().__await__()


class DeploymentResponseGenerator:
    """Iterable result of handle.options(stream=True).remote() (reference
    serve/handle.py DeploymentResponseGenerator): yields the replica
    generator's items in production order with streaming backpressure."""

    def __init__(self, gen_future):
        self._gen_future = gen_future
        self._gen = None  # resolved ObjectRefGenerator (cancel target)

    def _resolve(self):
        if self._gen is None:
            self._gen = self._gen_future.result(30)  # ObjectRefGenerator
        return self._gen

    def __iter__(self):
        gen = self._resolve()
        try:
            for ref in gen:
                yield ca.get(ref, timeout=60)
        except GeneratorExit:
            # consumer close()d us mid-stream: stop the replica-side
            # generator too, or it decodes to completion for nobody
            self.cancel()
            raise

    def cancel(self):
        """Abandon the stream: interrupt the replica-side generator (it gets
        TaskCancelledError at its next yield) and release this consumer.
        Call when the downstream client is gone (proxy SSE disconnect).
        Runs off-loop (callers use an executor): still-queued routing is
        cancelled outright; in-flight routing gets a grace LONGER than
        _acquire_replica's 30 s backpressure deadline — under saturation
        (exactly when clients give up) the submit resolves late, and a
        shorter wait would swallow the cancel and let the replica decode
        the whole abandoned stream for nobody."""
        try:
            if self._gen is None and not self._gen_future.done():
                if self._gen_future.cancel():
                    return  # routing never started: nothing replica-side
            self._gen = self._gen_future.result(35)
            self._gen.cancel()
        except Exception:
            pass  # routing itself failed / replica dead: nothing to stop


_backpressure_hist = None


def _backpressure_metric():
    """ca_serve_backpressure_seconds: time route() spent waiting because
    every pickable replica was saturated — the visible form of what used to
    be an invisible CPU-burning spin-wait."""
    global _backpressure_hist
    if _backpressure_hist is None:
        from ..util import metrics as m

        _backpressure_hist = m.Histogram(
            "ca_serve_backpressure_seconds",
            "serve router wait for replica capacity",
            boundaries=[0.001, 0.005, 0.02, 0.05, 0.1, 0.25, 1.0, 5.0, 30.0],
            tag_keys=("deployment",),
        )
    return _backpressure_hist


class Router:
    def __init__(self, app: str, deployment: str):
        import concurrent.futures

        self.app = app
        self.deployment = deployment
        # all blocking work (controller RPCs, backpressure waits) happens on
        # this thread so handle.remote() stays non-blocking for callers
        self._dispatch = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="serve-router"
        )
        self._lock = threading.Lock()
        self._replicas: List[Dict[str, Any]] = []
        self._handles: Dict[str, Any] = {}  # replica_id -> actor handle
        self._inflight: Dict[str, int] = {}
        self._version = -1
        self._max_ongoing = 8
        self._last_refresh = 0.0
        self._watched: List = []  # [(replica_id, ref)]
        self._watch_cv = threading.Condition(self._lock)
        # saturation backpressure: route() waits HERE (bounded, no spin)
        # until the watch loop's completion decrements free capacity
        self._capacity_cv = threading.Condition(self._lock)
        self._watcher: Optional[threading.Thread] = None
        self._metric_tags = {"deployment": f"{app}/{deployment}"}

    def _controller(self):
        return get_actor(CONTROLLER_NAME)

    def _refresh(self, force: bool = False):
        now = time.monotonic()
        with self._lock:
            if not force and self._replicas and now - self._last_refresh < _REFRESH_PERIOD_S:
                return
            self._last_refresh = now
        info = ca.get(
            self._controller().get_deployment_info.remote(self.app, self.deployment)
        )
        with self._lock:
            stale = info["version"] == self._version and self._replicas
            self._version = info["version"]
            self._max_ongoing = info.get("max_ongoing_requests", 8)
            self._replicas = info["replicas"]
            if stale:
                # same membership, but the controller-reported queue_lens
                # (merged in _pick) are fresh — keep them
                self._capacity_cv.notify_all()
                return
            live = {r["replica_id"] for r in self._replicas}
            self._handles = {k: v for k, v in self._handles.items() if k in live}
            self._inflight = {
                k: self._inflight.get(k, 0) for k in live
            }
            self._capacity_cv.notify_all()

    def _handle_for(self, rid: str, actor_name: str):
        h = self._handles.get(rid)
        if h is None:
            h = get_actor(actor_name)
            self._handles[rid] = h
        return h

    def _load(self, rep: Dict[str, Any]) -> int:
        """Replica load estimate for power-of-two-choices: the max of this
        router's own in-flight count and the controller-reported ongoing
        count (which sees EVERY router's traffic plus the replica's own
        concurrency, ~1s stale).  max() rather than sum: the reported number
        already includes whatever of our in-flight work reached the replica."""
        return max(
            self._inflight.get(rep["replica_id"], 0),
            int(rep.get("queue_len", 0)),
        )

    def _pick_locked(self) -> Optional[Dict[str, Any]]:
        reps = [r for r in self._replicas if not r.get("draining")]
        if not reps:
            # every replica draining (replacements still starting): keep
            # serving on the draining ones — they're alive until the drain
            # deadline, and refusing would drop requests a drain promised
            # to preserve
            reps = list(self._replicas)
        if not reps:
            return None
        if len(reps) == 1:
            return reps[0]
        a, b = random.sample(reps, 2)
        return a if self._load(a) <= self._load(b) else b

    def _acquire_replica(self, meta: Dict[str, Any]) -> Dict[str, Any]:
        """Pick a replica with free capacity, waiting on the capacity
        condition when saturated (bounded waits, visible in the
        ca_serve_backpressure_seconds histogram) instead of spinning."""
        deadline = time.monotonic() + 30.0
        t_wait0 = None
        while True:
            self._refresh()
            with self._capacity_cv:
                pick = self._pick_locked()
                if (
                    pick is not None
                    and self._inflight.get(pick["replica_id"], 0) < self._max_ongoing
                ):
                    if t_wait0 is not None:
                        _backpressure_metric().observe(
                            time.monotonic() - t_wait0, tags=self._metric_tags
                        )
                    return pick
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise RuntimeError(
                        f"no available replica for {self.app}/{self.deployment}"
                    )
                if t_wait0 is None:
                    t_wait0 = time.monotonic()
                # bounded: completions notify; the cap also forces a
                # periodic membership refresh while saturated/empty
                self._capacity_cv.wait(timeout=min(0.25, remaining))
            if pick is None:
                self._refresh(force=True)

    def route(self, meta: Dict[str, Any], args, kwargs):
        """Blocking routing + submission; runs on the dispatch thread only.
        Returns the ObjectRef of the replica call."""
        pick = self._acquire_replica(meta)
        rid = pick["replica_id"]
        h = self._handle_for(rid, pick["actor_name"])
        with self._lock:
            self._inflight[rid] = self._inflight.get(rid, 0) + 1
        try:
            ref = h.handle_request.remote(meta, *args, **kwargs)
        except Exception:
            with self._lock:
                self._inflight[rid] -= 1
                self._capacity_cv.notify_all()
            raise
        self._watch_completion(rid, ref)
        return ref

    def route_streaming(self, meta: Dict[str, Any], args, kwargs):
        """Like route(), but invokes the replica's streaming twin and returns
        an ObjectRefGenerator.  Inflight is released at submit: stream
        lifetimes are unbounded (token generation), so queue-gating on them
        would starve the replica for regular traffic.  (The controller-side
        queue_len still counts streams — the replica's num_ongoing covers
        the stream's whole life — so P2C and drain retirement see them.)"""
        pick = self._acquire_replica(meta)
        h = self._handle_for(pick["replica_id"], pick["actor_name"])
        return h.handle_request_streaming.options(num_returns="streaming").remote(
            meta, *args, **kwargs
        )

    def _watch_completion(self, rid: str, ref):
        """One watcher thread per router drains completions in batches (a
        thread per request would be far too heavy for the request path)."""
        with self._watch_cv:
            self._watched.append((rid, ref))
            if self._watcher is None:
                self._watcher = threading.Thread(
                    target=self._watch_loop, daemon=True, name="serve-router-watch"
                )
                self._watcher.start()
            self._watch_cv.notify()

    def _watch_loop(self):
        while True:
            with self._watch_cv:
                while not self._watched:
                    self._watch_cv.wait()
                batch = list(self._watched)
            refs = [ref for _, ref in batch]
            ready, _ = ca.wait(refs, num_returns=len(refs), timeout=0.05)
            if not ready:
                continue
            done = set(id(r) for r in ready)
            with self._watch_cv:
                still = []
                for rid, ref in self._watched:
                    if id(ref) in done:
                        if rid in self._inflight:
                            self._inflight[rid] -= 1
                    else:
                        still.append((rid, ref))
                self._watched = still
                # capacity freed: wake saturated route() waiters
                self._capacity_cv.notify_all()


_router_cache: Dict[tuple, Router] = {}
_router_cache_lock = threading.Lock()


def _shared_router(app: str, deployment: str) -> Router:
    """One router (and dispatch thread) per deployment per process — handle
    objects are created freely (handle.method.remote()), routers are not."""
    key = (app, deployment)
    r = _router_cache.get(key)
    if r is None:
        with _router_cache_lock:
            r = _router_cache.get(key)
            if r is None:
                r = Router(app, deployment)
                _router_cache[key] = r
    return r


class DeploymentHandle:
    """Serializable handle to a deployment; each process lazily builds its own
    Router on first use."""

    def __init__(self, app: str, deployment: str, method: str = "__call__", multiplexed_model_id: str = ""):
        self.app = app
        self.deployment = deployment
        self._method = method
        self._multiplexed_model_id = multiplexed_model_id
        self._stream = False
        self._router: Optional[Router] = None

    # serialization: drop the router; the receiving process builds a new one
    def __getstate__(self):
        return {
            "app": self.app,
            "deployment": self.deployment,
            "_method": self._method,
            "_multiplexed_model_id": self._multiplexed_model_id,
            "_stream": self._stream,
        }

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._stream = state.get("_stream", False)
        self._router = None

    def options(
        self,
        *,
        method_name: Optional[str] = None,
        multiplexed_model_id: Optional[str] = None,
        stream: Optional[bool] = None,
    ) -> "DeploymentHandle":
        h = DeploymentHandle(
            self.app,
            self.deployment,
            method_name or self._method,
            multiplexed_model_id
            if multiplexed_model_id is not None
            else self._multiplexed_model_id,
        )
        h._stream = self._stream if stream is None else bool(stream)
        return h

    def __getattr__(self, name: str) -> "DeploymentHandle":
        if name.startswith("_") or name in ("app", "deployment"):
            raise AttributeError(name)
        h = DeploymentHandle(self.app, self.deployment, name, self._multiplexed_model_id)
        h._stream = self._stream  # h.options(stream=True).method.remote() keeps streaming
        return h

    def remote(self, *args, **kwargs):
        if self._router is None:
            self._router = _shared_router(self.app, self.deployment)
        meta = {
            "request_id": uuid.uuid4().hex,
            "method": self._method,
            "multiplexed_model_id": self._multiplexed_model_id,
        }
        # the dispatch thread starts with an empty context: carry the
        # caller's contextvars (ambient trace, log attribution) across so
        # the replica call joins the request's trace instead of losing it
        # at the thread hop
        ctx = contextvars.copy_context()
        if self._stream:
            fut = self._router._dispatch.submit(
                ctx.run, self._router.route_streaming, meta, args, kwargs
            )
            return DeploymentResponseGenerator(fut)
        fut = self._router._dispatch.submit(
            ctx.run, self._router.route, meta, args, kwargs
        )
        return DeploymentResponse(fut)

    def to_spec(self) -> Dict[str, str]:
        return {
            "__ca_serve_handle__": True,
            "app": self.app,
            "deployment": self.deployment,
        }
