"""Serve configuration schemas (analogue of python/ray/serve/config.py and
serve/schema.py — DeploymentConfig, AutoscalingConfig, HTTPOptions).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class AutoscalingConfig:
    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 1.0
    downscale_delay_s: float = 5.0
    metrics_interval_s: float = 0.5

    def __post_init__(self):
        if self.min_replicas < 0 or self.max_replicas < max(1, self.min_replicas):
            raise ValueError("need 0 <= min_replicas <= max_replicas, max >= 1")


@dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_ongoing_requests: int = 8
    user_config: Optional[Dict[str, Any]] = None
    autoscaling_config: Optional[AutoscalingConfig] = None
    health_check_period_s: float = 2.0
    graceful_shutdown_timeout_s: float = 5.0
    num_cpus: float = 1.0
    num_tpus: float = 0.0
    resources: Dict[str, float] = field(default_factory=dict)
    max_restarts: int = 3

    def actor_options(self) -> Dict[str, Any]:
        opts: Dict[str, Any] = {
            "num_cpus": self.num_cpus,
            "max_concurrency": max(2, self.max_ongoing_requests + 2),
        }
        if self.num_tpus:
            opts["num_tpus"] = self.num_tpus
        if self.resources:
            opts["resources"] = dict(self.resources)
        return opts


@dataclass
class HTTPOptions:
    host: str = "127.0.0.1"
    port: int = 8000


@dataclass
class ReplicaInfo:
    """What routers need to reach one replica."""

    replica_id: str
    actor_name: str
    max_ongoing_requests: int


@dataclass
class DeploymentStatus:
    name: str
    status: str  # UPDATING | HEALTHY | UNHEALTHY
    replica_states: Dict[str, int] = field(default_factory=dict)
    message: str = ""
