"""Serve configuration schemas (analogue of python/ray/serve/config.py and
serve/schema.py — DeploymentConfig, AutoscalingConfig, HTTPOptions).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class AutoscalingConfig:
    min_replicas: int = 1
    max_replicas: int = 4
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 1.0
    downscale_delay_s: float = 5.0
    metrics_interval_s: float = 0.5

    def __post_init__(self):
        if self.min_replicas < 0 or self.max_replicas < max(1, self.min_replicas):
            raise ValueError("need 0 <= min_replicas <= max_replicas, max >= 1")


@dataclass
class AdmissionPolicy:
    """Proxy-side admission control for one deployment (the reference gets
    this from external gateways; here the HTTP proxy is the gate).  Instead
    of queueing unboundedly past the saturation knee, the proxy SHEDS:

    - queue-depth gate: more than `max_queue_depth` requests in flight
      through this proxy (dispatched + streaming) -> 503.  None derives
      `queue_depth_factor * replicas * max_ongoing_requests` from the live
      deployment state, so the cap scales with the autoscaler.
    - token-budget gate (LLM deployments): the summed cost estimate of
      in-flight requests (prompt chars/4 + max_new_tokens, or
      `default_request_tokens` when the body carries neither) would exceed
      `max_tokens_in_flight` -> 429.  None disables the gate.

    Shed responses carry `Retry-After: retry_after_s` and count into
    ca_serve_shed_total{deployment,reason}."""

    max_queue_depth: Optional[int] = None
    queue_depth_factor: float = 2.0
    max_tokens_in_flight: Optional[int] = None
    default_request_tokens: int = 64
    retry_after_s: float = 1.0

    def depth_cap(self, replicas: int, max_ongoing: int) -> int:
        if self.max_queue_depth is not None:
            return max(1, int(self.max_queue_depth))
        return max(1, int(self.queue_depth_factor * max(1, replicas) * max_ongoing))

    def to_wire(self) -> Dict[str, Any]:
        return {
            "max_queue_depth": self.max_queue_depth,
            "queue_depth_factor": self.queue_depth_factor,
            "max_tokens_in_flight": self.max_tokens_in_flight,
            "default_request_tokens": self.default_request_tokens,
            "retry_after_s": self.retry_after_s,
        }


@dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_ongoing_requests: int = 8
    user_config: Optional[Dict[str, Any]] = None
    autoscaling_config: Optional[AutoscalingConfig] = None
    admission: Optional[AdmissionPolicy] = None
    health_check_period_s: float = 2.0
    graceful_shutdown_timeout_s: float = 5.0
    num_cpus: float = 1.0
    num_tpus: float = 0.0
    resources: Dict[str, float] = field(default_factory=dict)
    max_restarts: int = 3

    def actor_options(self) -> Dict[str, Any]:
        opts: Dict[str, Any] = {
            "num_cpus": self.num_cpus,
            "max_concurrency": max(2, self.max_ongoing_requests + 2),
            # the controller drains replicas app-aware (replacements first,
            # in-flight streams run out); the head must not restart-migrate
            # them mid-request on a node drain
            "drain_migration": False,
        }
        if self.num_tpus:
            opts["num_tpus"] = self.num_tpus
        if self.resources:
            opts["resources"] = dict(self.resources)
        return opts


@dataclass
class HTTPOptions:
    host: str = "127.0.0.1"
    port: int = 8000


@dataclass
class ReplicaInfo:
    """What routers need to reach one replica."""

    replica_id: str
    actor_name: str
    max_ongoing_requests: int


@dataclass
class DeploymentStatus:
    name: str
    status: str  # UPDATING | HEALTHY | UNHEALTHY
    replica_states: Dict[str, int] = field(default_factory=dict)
    message: str = ""
