"""RPC contract extraction: handler tables + call sites, from the AST.

The protocol is msgpack maps dispatched on a string method name, so the
"schema" lives in three code shapes:

  handlers   head: `_h_<method>` methods (dispatch is
             `getattr(self, "_h_" + m)`); worker/agent/driver-push: if/elif
             chains comparing `m` / `msg.get("m")` against string literals.
  reads      handlers read `msg["x"]` (required) or `msg.get("x")` /
             `"x" in msg` (optional).  A `msg["x"]` read under any
             conditional (if/try/loop/boolop) is demoted to optional: role-
             polymorphic handlers like `register` require different fields
             per branch, and only unconditional reads are a hard contract.
             A handler that hands the whole `msg` to a helper is resolved
             into same-module helpers; anything deeper marks its reads
             "opaque" (unread-field checks are skipped for that method
             rather than guessed).
  call sites `conn.call("method", field=...)` / `call_cb` / `notify` /
             `head_call` / `call_template` / `_notify_threadsafe` with a
             literal method name, plus message-shaped dict literals
             (`{"m": "pub", ...}`) fed to `write_frame` and the task-spec
             template builders.  `**kwargs` at a site makes its field set
             dynamic (method checks still apply; field checks are skipped).

Extraction is deliberately table-driven (SURFACES below): a new peer surface
is one line here, and the generated contract names every surface so drift is
visible in review.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
from typing import Dict, List, Optional, Set, Tuple

# envelope fields supplied by the transport, never by call-site kwargs
RESERVED_FIELDS = {"m", "i", "tr", "ok", "err"}

# Connection.call()/head_call() consume `timeout` client-side (wait_for);
# it is an RPC deadline, not a wire field
_CLIENT_ONLY_KWARGS = {"timeout"}

_CALL_NAMES = {
    "call": "request",
    "request": "request",
    "head_call": "request",
    "call_cb": "request",
    "call_template": "request",
    "notify": "notify",
    "_notify_threadsafe": "notify",
}

# bare-name wrappers around a blocking head call (first arg = method)
_WRAPPER_NAMES = {"_head"}

# (surface name, file, kind, spec) — kind "prefix": every `_h_<m>` def in the
# file; kind "chain": if/elif dispatch inside the named functions
SURFACES = (
    ("head", "cluster_anywhere_tpu/core/head.py", "prefix", "_h_"),
    ("worker", "cluster_anywhere_tpu/core/workerproc.py", "chain",
     ("_handle", "_fast_handle")),
    ("agent", "cluster_anywhere_tpu/core/nodeagent.py", "chain", ("_handle",)),
    ("driver_push", "cluster_anywhere_tpu/core/worker.py", "chain",
     ("_on_push", "_on_peer_push")),
    # the driver's own RPC listener (owner_locate/owner_refs/coll_push/…):
    # a nested `handle` closure inside Worker._start_p2p_server
    ("driver_p2p", "cluster_anywhere_tpu/core/worker.py", "chain", ("handle",)),
)


@dataclasses.dataclass
class HandlerInfo:
    surface: str
    method: str
    file: str
    line: int
    context: str
    required: Set[str] = dataclasses.field(default_factory=set)
    optional: Set[str] = dataclasses.field(default_factory=set)
    opaque: bool = False  # msg escaped: the read set is not closed


@dataclasses.dataclass
class CallSite:
    file: str
    line: int
    context: str
    method: str
    kind: str                       # "request" | "notify" | "spec"
    fields: Optional[Set[str]]      # None = dynamic (**kwargs / template)

    @property
    def loc(self) -> str:
        return f"{self.file}:{self.line}"


@dataclasses.dataclass
class Contract:
    handlers: List[HandlerInfo]
    call_sites: List[CallSite]

    def handlers_for(self, method: str) -> List[HandlerInfo]:
        return [h for h in self.handlers if h.method == method]

    def handler_methods(self) -> Set[str]:
        return {h.method for h in self.handlers}

    def called_methods(self) -> Set[str]:
        return {c.method for c in self.call_sites}

    def known_methods(self) -> Set[str]:
        return self.handler_methods() | self.called_methods()


# ------------------------------------------------------------ AST utilities

def _const_str(node) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _qualname_index(tree) -> Dict[ast.AST, str]:
    """def/class node -> dotted qualname."""
    out: Dict[ast.AST, str] = {}

    def walk(node, prefix):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                q = f"{prefix}.{child.name}" if prefix else child.name
                out[child] = q
                walk(child, q)
            else:
                walk(child, prefix)

    walk(tree, "")
    return out


class _ModuleIndex:
    """Same-module lookup for one-level msg-flow resolution: method name ->
    def node (per class), plus module-level functions."""

    def __init__(self, tree):
        self.module_funcs: Dict[str, ast.AST] = {}
        self.class_methods: Dict[str, Dict[str, ast.AST]] = {}
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.module_funcs[node.name] = node
            elif isinstance(node, ast.ClassDef):
                methods = {}
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        methods[sub.name] = sub
                self.class_methods[node.name] = methods

    def resolve(self, call: ast.Call, cls: Optional[str]):
        """The def node a call dispatches to, when it's statically a
        same-module function or a method on the same class; else None."""
        fn = call.func
        if isinstance(fn, ast.Name):
            return self.module_funcs.get(fn.id)
        if (
            isinstance(fn, ast.Attribute)
            and isinstance(fn.value, ast.Name)
            and fn.value.id == "self"
            and cls is not None
        ):
            return self.class_methods.get(cls, {}).get(fn.attr)
        return None


def _analyze_msg_use(
    stmts, msg_name: str, index: _ModuleIndex, cls: Optional[str],
    _visited: Optional[set] = None,
) -> Tuple[Set[str], Set[str], bool]:
    """(required, optional, opaque) for how `msg_name` is consumed in stmts.

    required: `msg["x"]` loads.  optional: `.get/.pop/.setdefault("x")`,
    `"x" in msg`.  opaque: the dict escaped somewhere we can't follow
    (stored, returned, `**msg`, non-literal key, passed out of module)."""
    required: Set[str] = set()
    optional: Set[str] = set()
    opaque = False
    _visited = _visited if _visited is not None else set()

    parents: Dict[ast.AST, ast.AST] = {}
    roots = list(stmts)
    for root in roots:
        for node in ast.walk(root):
            for child in ast.iter_child_nodes(node):
                parents[child] = node

    _COND = (
        ast.If, ast.IfExp, ast.Try, ast.ExceptHandler, ast.While, ast.For,
        ast.AsyncFor, ast.BoolOp, ast.ListComp, ast.SetComp, ast.DictComp,
        ast.GeneratorExp, ast.Assert,
        # a read inside a nested def/lambda runs only if the closure does
        ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
    )

    def conditional(node) -> bool:
        """True when `node` may not execute on every message (so a
        `msg["x"]` there is not a hard requirement on senders)."""
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, _COND):
                return True
            cur = parents.get(cur)
        return False

    def follow(call: ast.Call, name_node: ast.AST) -> bool:
        """Resolve msg flowing into a same-module helper; True if followed."""
        target = index.resolve(call, cls)
        if target is None or id(target) in _visited:
            return False
        # positional index / keyword name -> parameter name
        params = [a.arg for a in target.args.args]
        if params and params[0] == "self":
            params = params[1:]
        param = None
        args = call.args
        if name_node in args:
            pos = args.index(name_node)
            if pos < len(params):
                param = params[pos]
        else:
            for kw in call.keywords:
                if kw.value is name_node and kw.arg is not None:
                    param = kw.arg
        if param is None:
            return False
        _visited.add(id(target))
        r, o, op = _analyze_msg_use(target.body, param, index, cls, _visited)
        if conditional(call):
            # the helper only runs on some branch: its hard reads are
            # conditional from the sender's point of view
            optional.update(r)
        else:
            required.update(r)
        optional.update(o)
        return not op

    for root in roots:
        for node in ast.walk(root):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue  # closures over msg are rare; names inside still walk —
                # accepted: over-collection beats missing a read
            if not (isinstance(node, ast.Name) and node.id == msg_name):
                continue
            p = parents.get(node)
            if isinstance(p, ast.Subscript) and p.value is node:
                key = _const_str(p.slice)
                if key is None:
                    opaque = True
                elif isinstance(p.ctx, ast.Load):
                    (optional if conditional(node) else required).add(key)
                continue
            if isinstance(p, ast.Attribute) and p.value is node:
                gp = parents.get(p)
                if isinstance(gp, ast.Call) and gp.func is p:
                    if p.attr in ("get", "pop", "setdefault"):
                        key = _const_str(gp.args[0]) if gp.args else None
                        if key is None:
                            opaque = True
                        else:
                            optional.add(key)
                        continue
                opaque = True
                continue
            if (
                isinstance(p, ast.Compare)
                and node in p.comparators
                and all(isinstance(op, (ast.In, ast.NotIn)) for op in p.ops)
            ):
                key = _const_str(p.left)
                if key is not None:
                    optional.add(key)
                else:
                    opaque = True
                continue
            if isinstance(p, ast.Call) and (node in p.args):
                if not follow(p, node):
                    opaque = True
                continue
            if isinstance(p, ast.keyword) and p.value is node:
                gp = parents.get(p)
                if not (isinstance(gp, ast.Call) and follow(gp, node)):
                    opaque = True
                continue
            opaque = True
    return required, optional, opaque


# --------------------------------------------------------- handler surfaces

def _msg_param(fndef) -> str:
    names = [a.arg for a in fndef.args.args]
    if "msg" in names:
        return "msg"
    # _h_*(self, state, msg, reply, reply_err) convention
    return names[2] if len(names) > 2 else (names[-1] if names else "msg")


def _extract_prefix_surface(sf, surface: str, prefix: str) -> List[HandlerInfo]:
    index = _ModuleIndex(sf.tree)
    quals = _qualname_index(sf.tree)
    out = []
    for node, qual in quals.items():
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if not node.name.startswith(prefix):
            continue
        cls = qual.rsplit(".", 2)[0] if "." in qual else None
        req, opt, opaque = _analyze_msg_use(
            node.body, _msg_param(node), index, cls
        )
        out.append(HandlerInfo(
            surface=surface, method=node.name[len(prefix):], file=sf.relpath,
            line=node.lineno, context=qual,
            required=req - {"m"}, optional=opt - {"m"}, opaque=opaque,
        ))
    return out


def _dispatch_methods(test, dispatch_names: Set[str]) -> Tuple[List[str], bool]:
    """Match a chain branch test against the dispatch var.  Returns
    (methods, negated): `m == "x"` -> (["x"], False); `m in ("x","y")` ->
    (["x","y"], False); `msg.get("m") != "x"` -> (["x"], True)."""

    def is_dispatch(expr) -> bool:
        if isinstance(expr, ast.Name) and expr.id in dispatch_names:
            return True
        if isinstance(expr, ast.Subscript) and _const_str(expr.slice) == "m":
            return True
        if (
            isinstance(expr, ast.Call)
            and isinstance(expr.func, ast.Attribute)
            and expr.func.attr == "get"
            and expr.args
            and _const_str(expr.args[0]) == "m"
        ):
            return True
        return False

    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
        for sub in test.values:
            methods, neg = _dispatch_methods(sub, dispatch_names)
            if methods and not neg:
                return methods, False
        return [], False
    if not (isinstance(test, ast.Compare) and len(test.ops) == 1):
        return [], False
    if not is_dispatch(test.left):
        return [], False
    op, right = test.ops[0], test.comparators[0]
    if isinstance(op, (ast.Eq, ast.NotEq)):
        lit = _const_str(right)
        return ([lit] if lit is not None else []), isinstance(op, ast.NotEq)
    if isinstance(op, ast.In) and isinstance(right, (ast.Tuple, ast.List, ast.Set)):
        lits = [s for s in (_const_str(e) for e in right.elts) if s is not None]
        return lits, False
    return [], False


def _extract_chain_surface(sf, surface: str, fn_names) -> List[HandlerInfo]:
    index = _ModuleIndex(sf.tree)
    quals = _qualname_index(sf.tree)
    out = []
    for node, qual in quals.items():
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name not in fn_names:
            continue
        cls = qual.rsplit(".", 2)[0] if "." in qual else None
        # names assigned from msg["m"] / msg.get("m") act as the dispatch var
        dispatch_names: Set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and len(sub.targets) == 1 \
                    and isinstance(sub.targets[0], ast.Name):
                v = sub.value
                if isinstance(v, ast.Subscript) and _const_str(v.slice) == "m":
                    dispatch_names.add(sub.targets[0].id)
                elif (
                    isinstance(v, ast.Call)
                    and isinstance(v.func, ast.Attribute)
                    and v.func.attr == "get"
                    and v.args and _const_str(v.args[0]) == "m"
                ):
                    dispatch_names.add(sub.targets[0].id)

        def emit(methods, body, line):
            req, opt, opaque = _analyze_msg_use(body, _msg_param(node), index, cls)
            for m in methods:
                out.append(HandlerInfo(
                    surface=surface, method=m, file=sf.relpath, line=line,
                    context=qual, required=req - {"m"}, optional=opt - {"m"},
                    opaque=opaque,
                ))

        def walk_block(stmts):
            for i, stmt in enumerate(stmts):
                if isinstance(stmt, ast.If):
                    methods, negated = _dispatch_methods(stmt.test, dispatch_names)
                    if methods and negated and all(
                        isinstance(s, (ast.Return, ast.Raise, ast.Continue))
                        for s in stmt.body
                    ):
                        # `if m != "pub": return` — the rest of this block IS
                        # the "pub" handler
                        emit(methods, stmts[i + 1:], stmt.lineno)
                    elif methods and not negated:
                        emit(methods, stmt.body, stmt.lineno)
                        walk_block(stmt.orelse)  # elif chain continues
                        continue
                    walk_block(stmt.body)
                    walk_block(stmt.orelse)
                elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                    walk_block(stmt.body)
                elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                    walk_block(stmt.body)
                elif isinstance(stmt, ast.Try):
                    walk_block(stmt.body)
                    for h in stmt.handlers:
                        walk_block(h.body)
                    walk_block(stmt.finalbody)

        walk_block(node.body)
    return out


# -------------------------------------------------------------- call sites

def _extract_call_sites(sf) -> List[CallSite]:
    quals = _qualname_index(sf.tree)
    out: List[CallSite] = []

    def context_of(stack) -> str:
        for node in reversed(stack):
            q = quals.get(node)
            if q is not None:
                return q
        return "<module>"

    stack: List[ast.AST] = []

    def visit(node):
        stack.append(node)
        if isinstance(node, ast.Call):
            site = _call_site_from_call(sf, node, context_of(stack))
            out.extend(site)
        elif isinstance(node, ast.Dict):
            site = _call_site_from_dict(sf, node, context_of(stack))
            if site is not None:
                out.append(site)
        for child in ast.iter_child_nodes(node):
            visit(child)
        stack.pop()

    visit(sf.tree)
    return out


def _call_site_from_call(sf, node: ast.Call, context: str) -> List[CallSite]:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        if fn.attr not in _CALL_NAMES:
            return []
        # subprocess.call("cmd") is not an RPC
        if isinstance(fn.value, ast.Name) and fn.value.id in ("subprocess", "sp"):
            return []
        name = fn.attr
    elif isinstance(fn, ast.Name) and fn.id in _WRAPPER_NAMES:
        # module-level blocking-RPC wrappers (util/state._head)
        name = "call"
    else:
        return []
    if not node.args:
        return []  # cond.notify() and friends
    methods: List[str] = []
    first = node.args[0]
    lit = _const_str(first)
    if lit is not None:
        methods = [lit]
    elif isinstance(first, ast.IfExp):
        # "worker_blocked" if blocked else "worker_unblocked"
        lits = [_const_str(first.body), _const_str(first.orelse)]
        methods = [s for s in lits if s is not None]
    if not methods:
        return []  # dynamic method (generic forwarder): nothing to check
    kind = _CALL_NAMES[name]
    fields: Optional[Set[str]] = set()
    if name == "call_template":
        fields = None  # fields ride the pre-encoded template
    else:
        for kw in node.keywords:
            if kw.arg is None:
                fields = None  # **fields: open field set
                break
            fields.add(kw.arg)
        if fields is not None and name in ("call", "head_call", "request"):
            fields -= _CLIENT_ONLY_KWARGS
    return [
        CallSite(file=sf.relpath, line=node.lineno, context=context,
                 method=m, kind=kind, fields=fields)
        for m in methods
    ]


def _call_site_from_dict(sf, node: ast.Dict, context: str) -> Optional[CallSite]:
    """Message-shaped dict literal: {"m": "<method>", ...} — push frames fed
    to write_frame, the task-spec field dicts, drain/gone pub frames."""
    method = None
    fields: Optional[Set[str]] = set()
    for k, v in zip(node.keys, node.values):
        if k is None:
            fields = None  # **expansion
            continue
        key = _const_str(k)
        if key is None:
            fields = None
            continue
        if key == "m":
            method = _const_str(v)
        elif fields is not None:
            fields.add(key)
    if method is None:
        return None
    return CallSite(file=sf.relpath, line=node.lineno, context=context,
                    method=method, kind="spec", fields=fields)


# ------------------------------------------------------------- entry points

def extract_contract(files) -> Contract:
    by_path = {sf.relpath: sf for sf in files}
    handlers: List[HandlerInfo] = []
    for surface, path, kind, spec in SURFACES:
        sf = by_path.get(path)
        if sf is None or sf.tree is None:
            continue
        if kind == "prefix":
            handlers.extend(_extract_prefix_surface(sf, surface, spec))
        else:
            handlers.extend(_extract_chain_surface(sf, surface, spec))
    # the protocol layer itself consumes `batch` envelopes (iter_messages)
    handlers.append(HandlerInfo(
        surface="protocol", method="batch",
        file="cluster_anywhere_tpu/core/protocol.py", line=1,
        context="iter_messages", optional={"b"},
    ))
    call_sites: List[CallSite] = []
    for sf in files:
        if sf.tree is not None:
            call_sites.extend(_extract_call_sites(sf))
    # chain branches that handle multiple methods produce duplicate
    # HandlerInfo rows per method; merge them (union reads, OR opaque)
    merged: Dict[Tuple[str, str], HandlerInfo] = {}
    for h in handlers:
        key = (h.surface, h.method)
        cur = merged.get(key)
        if cur is None:
            merged[key] = h
        else:
            cur.required |= h.required
            cur.optional |= h.optional
            cur.opaque = cur.opaque or h.opaque
    return Contract(handlers=list(merged.values()), call_sites=call_sites)


def contract_to_json(contract: Contract) -> dict:
    surfaces: Dict[str, dict] = {}
    callers: Dict[str, List[str]] = {}
    for c in sorted(contract.call_sites, key=lambda c: (c.file, c.line)):
        callers.setdefault(c.method, []).append(c.loc)
    for h in sorted(contract.handlers, key=lambda h: (h.surface, h.method)):
        surf = surfaces.setdefault(h.surface, {"file": h.file, "methods": {}})
        surf["methods"][h.method] = {
            "line": h.line,
            "context": h.context,
            "required": sorted(h.required),
            "optional": sorted(h.optional),
            "opaque": h.opaque,
            "callers": callers.get(h.method, []),
        }
    return {
        "version": 1,
        "generated_by": "ca lint --contract",
        "surfaces": surfaces,
        "methods": sorted(contract.known_methods()),
    }


def render_markdown(contract: Contract) -> str:
    """The human table for ARCHITECTURE.md, one row per (surface, method)."""
    lines = [
        "| surface | method | required fields | optional fields | call sites |",
        "|---|---|---|---|---|",
    ]
    callers: Dict[str, int] = {}
    for c in contract.call_sites:
        callers[c.method] = callers.get(c.method, 0) + 1
    for h in sorted(contract.handlers, key=lambda h: (h.surface, h.method)):
        req = ", ".join(sorted(h.required)) or "—"
        opt = ", ".join(sorted(h.optional)) or "—"
        if h.opaque:
            opt += " …"
        lines.append(
            f"| {h.surface} | `{h.method}` | {req} | {opt} | {callers.get(h.method, 0)} |"
        )
    return "\n".join(lines)


def load_contract(root: Optional[str] = None) -> Optional[dict]:
    """The committed contract (docs/PROTOCOL_CONTRACT.json), for runtime
    consumers (the chaos-spec validator).  None when not checked out."""
    if root is None:
        from .engine import default_root

        root = default_root()
    path = os.environ.get("CA_CONTRACT_PATH") or os.path.join(
        root, "docs", "PROTOCOL_CONTRACT.json"
    )
    try:
        with open(path, "r", encoding="utf-8") as f:
            return json.load(f)
    except (OSError, ValueError):
        return None
