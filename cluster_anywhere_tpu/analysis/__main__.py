"""`python -m cluster_anywhere_tpu.analysis` == `ca lint`."""

import sys

from .lint import main

sys.exit(main())
