"""Pass 2: asyncio hazard analysis over every `async def` in the tree.

async-blocking-call   a synchronous blocking call on the event loop —
                      `time.sleep`, `subprocess.run/…`, socket dials,
                      unawaited `.wait()`/`.result()`/`.communicate()`,
                      `urlopen` — stalls every connection the loop serves
                      (the head is ONE loop; a 1 s sleep is a 1 s cluster
                      outage for control RPCs).
async-dropped-task    `create_task`/`ensure_future` whose Task object is
                      discarded at statement level: the loop holds only a
                      weak ref (the task can vanish mid-flight) and its
                      exception is silently parked until GC.  Use
                      util.aio.spawn_logged (names the task, pins it, logs
                      the exception) or keep a handle + done-callback.
async-await-race      read-modify-write of `self.*` state split across an
                      `await`: the value read before the yield is stale by
                      the time it's written back if any other task touched
                      the attribute.  Detected both across statements
                      (x = self.a … await … self.a = f(x)) and within one
                      (self.a = self.a + await f(), self.a += await f()).

Nested `def`s inside an async function are skipped: they execute wherever
they're called (usually an executor thread), not necessarily on the loop.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .engine import Finding, dotted_name as _dotted

RULES = {
    "async-blocking-call": (
        "a synchronous blocking call (time.sleep, subprocess, socket dials, "
        "unawaited .wait()/.result()) inside async def stalls every "
        "connection the event loop serves"
    ),
    "async-dropped-task": (
        "create_task/ensure_future whose Task is dropped at statement level "
        "can be GC'd mid-flight and parks its exception — use "
        "util.aio.spawn_logged or hold the Task"
    ),
    "async-await-race": (
        "read-modify-write of self.* state split across an await: another "
        "task can interleave between the read and the write"
    ),
}

# module.attr callables that block the loop outright
_BLOCKING_DOTTED = {
    ("time", "sleep"),
    ("subprocess", "run"),
    ("subprocess", "call"),
    ("subprocess", "check_call"),
    ("subprocess", "check_output"),
    ("subprocess", "getoutput"),
    ("subprocess", "getstatusoutput"),
    ("socket", "create_connection"),
    ("socket", "getaddrinfo"),
    ("socket", "gethostbyname"),
    ("urllib.request", "urlopen"),
}

# method names that block when the call is NOT awaited (sync socket/proc/
# future APIs share these names with awaitable asyncio duals)
_BLOCKING_METHODS_UNAWAITED = {
    "result", "wait", "communicate", "accept", "recv", "recvfrom", "sendall",
}

_SPAWN_NAMES = {"create_task", "ensure_future"}
# wrappers that pin the task and guard its exception; calling them bare is fine
_SAFE_SPAWN_WRAPPERS = {"spawn_bg", "spawn_logged"}


def _self_attr_reads(expr) -> Set[str]:
    """Attribute paths `self.x` loaded anywhere in expr (subscripts of
    self.d[...] count as reads of self.d)."""
    out: Set[str] = set()
    for node in ast.walk(expr):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and isinstance(node.ctx, ast.Load)
        ):
            out.add(node.attr)
    return out


def _write_target_attr(target) -> Optional[str]:
    """`self.x = …` / `self.x[k] = …` -> "x"."""
    if isinstance(target, ast.Subscript):
        target = target.value
    if (
        isinstance(target, ast.Attribute)
        and isinstance(target.value, ast.Name)
        and target.value.id == "self"
    ):
        return target.attr
    return None


def _awaits_in(node) -> bool:
    """True if node yields to the loop (await / async for / async with),
    skipping nested function bodies."""
    stack = [node]
    while stack:
        cur = stack.pop()
        for child in ast.iter_child_nodes(cur):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            if isinstance(child, (ast.Await, ast.AsyncFor, ast.AsyncWith)):
                return True
            stack.append(child)
    return isinstance(node, (ast.Await, ast.AsyncFor, ast.AsyncWith))


def check(files) -> List[Finding]:
    from .contract import _qualname_index

    findings: List[Finding] = []
    for sf in files:
        if sf.tree is None:
            continue
        quals = _qualname_index(sf.tree)
        for node, qual in quals.items():
            if isinstance(node, ast.AsyncFunctionDef):
                _check_async_fn(sf, node, qual, findings)
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # dropped fire-and-forget tasks are a hazard from sync code
                # too: create_task only works with a running loop, so any
                # caller is loop-adjacent
                _check_dropped_tasks(sf, node, qual, findings)
    return findings


def _iter_own_nodes(fn):
    """Every node in fn's body, excluding nested function/lambda bodies."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            # a nested def that is a DIRECT statement of the body lands on
            # the stack itself; its body is that function's own concern
            continue
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            stack.append(child)


def _check_async_fn(sf, fn, qual, findings: List[Finding]):
    awaited_calls = {
        id(n.value) for n in _iter_own_nodes(fn) if isinstance(n, ast.Await)
        if isinstance(n.value, ast.Call)
    }

    for node in _iter_own_nodes(fn):
        if not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func)
        if dotted is not None and tuple(dotted.rsplit(".", 1)) in _BLOCKING_DOTTED:
            findings.append(Finding(
                rule="async-blocking-call", file=sf.relpath, line=node.lineno,
                context=qual,
                message=f"blocking call {dotted}() inside async def {fn.name}",
                detail=dotted,
            ))
            continue
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _BLOCKING_METHODS_UNAWAITED
            and id(node) not in awaited_calls
        ):
            recv = _dotted(node.func.value) or "<expr>"
            findings.append(Finding(
                rule="async-blocking-call", file=sf.relpath, line=node.lineno,
                context=qual,
                message=(
                    f"unawaited .{node.func.attr}() on {recv} inside async "
                    f"def {fn.name} blocks the event loop if it is the sync API"
                ),
                detail=f"{recv}.{node.func.attr}",
            ))

    _check_await_races(sf, fn, qual, findings)


def _check_dropped_tasks(sf, fn, qual, findings: List[Finding]):
    """Statement-level Expr of create_task/ensure_future: the Task object is
    discarded, so it can be GC'd mid-flight and its exception vanishes."""
    for node in _iter_own_nodes(fn):
        if not (isinstance(node, ast.Expr) and isinstance(node.value, ast.Call)):
            continue
        call = node.value
        name = (
            call.func.attr if isinstance(call.func, ast.Attribute)
            else call.func.id if isinstance(call.func, ast.Name) else None
        )
        if name in _SPAWN_NAMES:
            findings.append(Finding(
                rule="async-dropped-task", file=sf.relpath, line=node.lineno,
                context=qual,
                message=(
                    f"{name}(...) result dropped: the loop keeps only a weak "
                    f"ref and the task's exception is lost — use "
                    f"util.aio.spawn_logged or hold the Task"
                ),
                detail=_first_arg_repr(call),
            ))


def _first_arg_repr(call: ast.Call) -> str:
    if call.args:
        try:
            return ast.unparse(call.args[0])[:80]
        except Exception:
            pass
    return "?"


def _check_await_races(sf, fn, qual, findings: List[Finding]):
    def scan_block(stmts, bindings: Dict[str, Tuple[Set[str], bool]]):
        """bindings: local var -> (self attrs its value was read from,
        awaited-since-binding)."""
        for stmt in stmts:
            stmt_awaits = _awaits_in(stmt)

            if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (
                    stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
                )
                value = stmt.value
                for target in targets:
                    attr = _write_target_attr(target)
                    if attr is None:
                        continue
                    stale_vars = set()
                    if value is not None:
                        for v in ast.walk(value):
                            if isinstance(v, ast.Name) and isinstance(v.ctx, ast.Load):
                                bound = bindings.get(v.id)
                                if bound and attr in bound[0] and (
                                    bound[1] or stmt_awaits
                                ):
                                    stale_vars.add(v.id)
                    direct_rmw = (
                        stmt_awaits and value is not None and (
                            isinstance(stmt, ast.AugAssign)
                            or attr in _self_attr_reads(value)
                        )
                    )
                    if stale_vars or direct_rmw:
                        how = (
                            f"via stale local {sorted(stale_vars)[0]!r}"
                            if stale_vars else "in the same statement"
                        )
                        findings.append(Finding(
                            rule="async-await-race", file=sf.relpath,
                            line=stmt.lineno, context=qual,
                            message=(
                                f"read-modify-write of self.{attr} crosses an "
                                f"await ({how}): another task can interleave "
                                f"between the read and the write"
                            ),
                            detail=f"self.{attr}",
                        ))
                # a plain rebind invalidates staleness tracking for the var;
                # record fresh bindings reading self attrs
                if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name) \
                        and stmt.value is not None:
                    reads = _self_attr_reads(stmt.value)
                    name = stmt.targets[0].id
                    if reads and not stmt_awaits:
                        bindings[name] = (reads, False)
                    else:
                        bindings.pop(name, None)
            elif isinstance(stmt, (ast.If,)):
                scan_block(stmt.body, dict(bindings))
                scan_block(stmt.orelse, dict(bindings))
            elif isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
                scan_block(stmt.body, dict(bindings))
                scan_block(stmt.orelse, dict(bindings))
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                scan_block(stmt.body, bindings)
            elif isinstance(stmt, ast.Try):
                scan_block(stmt.body, bindings)
                for h in stmt.handlers:
                    scan_block(h.body, dict(bindings))
                scan_block(stmt.finalbody, bindings)

            if stmt_awaits:
                for name, (attrs, _) in list(bindings.items()):
                    bindings[name] = (attrs, True)

    scan_block(fn.body, {})
