"""Lint engine: file collection, findings, pragma suppression, baselines.

The engine is rule-agnostic: rules (rpc_rules, async_rules) return Finding
lists; the engine suppresses pragma'd ones, diffs the rest against the
checked-in baseline, and renders reports.  Fingerprints deliberately exclude
line numbers so unrelated edits above a finding don't churn the baseline —
a finding is identified by (rule, file, context, detail).
"""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Tuple

# directories never scanned (relative path components)
_SKIP_DIRS = {"__pycache__", ".git", "tests", "build", "dist"}


def dotted_name(node) -> Optional[str]:
    """Flatten `a.b.c` Attribute chains to "a.b.c"; None for anything whose
    base isn't a plain Name.  Shared by every rule module."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None

# the marker may share a comment with prose ("# operator probe: ca-lint: …")
PRAGMA_RE = re.compile(r"#.*?ca-lint:\s*ignore(?:\[([a-z0-9_,\- ]+)\])?")


@dataclasses.dataclass
class Finding:
    rule: str      # e.g. "rpc-unknown-method"
    file: str      # repo-relative posix path
    line: int      # 1-based; display only, not part of the fingerprint
    context: str   # dotted qualname ("Head._h_register") or "surface:method"
    message: str   # human sentence
    detail: str = ""  # stable key material; defaults to message

    @property
    def fingerprint(self) -> str:
        key = "|".join((self.rule, self.file, self.context, self.detail or self.message))
        return hashlib.sha1(key.encode()).hexdigest()[:16]

    def to_json(self) -> dict:
        return {
            "fingerprint": self.fingerprint,
            "rule": self.rule,
            "file": self.file,
            "line": self.line,
            "context": self.context,
            "message": self.message,
        }

    def render(self) -> str:
        return f"{self.file}:{self.line}: [{self.rule}] {self.message}"


class SourceFile:
    """One parsed module: path, source lines, AST, and pragma map."""

    def __init__(self, root: str, relpath: str):
        self.relpath = relpath.replace(os.sep, "/")
        self.abspath = os.path.join(root, relpath)
        with open(self.abspath, "r", encoding="utf-8") as f:
            self.source = f.read()
        self.lines = self.source.splitlines()
        self.tree = ast.parse(self.source, filename=self.relpath)
        # line -> set of ignored rules (empty set = ignore every rule)
        self.pragmas: Dict[int, set] = {}
        for i, text in enumerate(self.lines, start=1):
            m = PRAGMA_RE.search(text)
            if m:
                rules = m.group(1)
                self.pragmas[i] = (
                    {r.strip() for r in rules.split(",") if r.strip()}
                    if rules else set()
                )

    def suppressed(self, finding: Finding) -> bool:
        """A pragma on the finding's line (or the line above it, for sites
        too long to carry a trailing comment) suppresses matching rules.
        Findings anchored at a decorated `def` climb the decorator stack so
        a pragma above `@decorator` lines still scopes to the def."""
        def hit(ln: int) -> bool:
            rules = self.pragmas.get(ln)
            return rules is not None and (not rules or finding.rule in rules)

        if hit(finding.line) or hit(finding.line - 1):
            return True
        ln = finding.line - 1
        while ln >= 1 and self.lines[ln - 1].lstrip().startswith("@"):
            ln -= 1
            if hit(ln):
                return True
        return False


def collect_files(root: str, subpaths: Optional[Iterable[str]] = None) -> List[SourceFile]:
    """Parse every .py under `subpaths` (default: the package + scripts +
    bench.py).  Tests are excluded: they exercise fake methods and sockets on
    purpose, and a handler only a test reaches is still dead code."""
    if subpaths is None:
        subpaths = ("cluster_anywhere_tpu", "scripts", "bench.py")
    def load(rel: str) -> SourceFile:
        try:
            return SourceFile(root, rel)
        except (SyntaxError, UnicodeDecodeError):
            # a file the analyzer can't parse is a finding, not a crash
            sf = object.__new__(SourceFile)
            sf.relpath = rel.replace(os.sep, "/")
            sf.abspath = os.path.join(root, rel)
            sf.source, sf.lines, sf.tree, sf.pragmas = "", [], None, {}
            return sf

    out: List[SourceFile] = []
    for sub in subpaths:
        top = os.path.join(root, sub)
        if os.path.isfile(top):
            out.append(load(sub))
            continue
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = [d for d in sorted(dirnames) if d not in _SKIP_DIRS]
            for name in sorted(filenames):
                if not name.endswith(".py"):
                    continue
                out.append(load(os.path.relpath(os.path.join(dirpath, name), root)))
    return out


# --------------------------------------------------------------- baselines

def load_baseline(path: str) -> List[dict]:
    if not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    return list(data.get("findings", []))


def save_baseline(path: str, findings: List[Finding]) -> None:
    entries = sorted(
        (f.to_json() for f in findings),
        key=lambda e: (e["rule"], e["file"], e["context"], e["fingerprint"]),
    )
    for e in entries:
        e.pop("line", None)  # line drift must not churn the baseline
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "findings": entries}, f, indent=1, sort_keys=True)
        f.write("\n")


def diff_baseline(
    findings: List[Finding], baseline: List[dict]
) -> Tuple[List[Finding], List[dict]]:
    """Returns (new_findings, stale_entries).  Stale = baseline entries whose
    finding no longer exists: the code was fixed or removed, so the entry must
    be dropped (`ca lint --update-baseline`) — the baseline only shrinks."""
    current = {f.fingerprint for f in findings}
    known = {e["fingerprint"] for e in baseline}
    new = [f for f in findings if f.fingerprint not in known]
    stale = [e for e in baseline if e["fingerprint"] not in current]
    return new, stale


# ------------------------------------------------------------------ driver

def default_root() -> str:
    """The repo root: the directory holding the cluster_anywhere_tpu package
    this module was imported from (works from any cwd), else cwd."""
    here = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    if os.path.isdir(os.path.join(here, "cluster_anywhere_tpu")):
        return here
    return os.getcwd()


def baseline_path(root: str) -> str:
    return os.path.join(root, "cluster_anywhere_tpu", "analysis", "baseline.json")


# the single pass registry: name -> rule module (each exports check() over
# the file list — "rpc" over the extracted contract — plus a RULES dict).
# ALL_PASSES, all_rules(), and run_lint() all derive from this one table.
_PASS_MODULES = {
    "rpc": "rpc_rules",
    "async": "async_rules",
    "res": "resource_rules",
    "await": "await_rules",
    "cancel": "cancel_rules",
}
ALL_PASSES = tuple(_PASS_MODULES)


def _pass_module(name: str):
    import importlib

    return importlib.import_module(f".{_PASS_MODULES[name]}", __package__)


def all_rules() -> Dict[str, Dict[str, str]]:
    """pass name -> {rule name -> one-line description}, for `ca lint
    --rules` and the generated ARCHITECTURE table."""
    return {name: dict(_pass_module(name).RULES) for name in ALL_PASSES}


def run_lint(
    root: Optional[str] = None,
    passes: Iterable[str] = ALL_PASSES,
    baseline_file: Optional[str] = None,
) -> dict:
    """Run the analyzer over the repo.  Returns a report dict:

    {"findings": [Finding...]   (unsuppressed, both baselined and new),
     "new": [Finding...], "stale": [baseline entries...],
     "suppressed": int, "contract": Contract, "ok": bool}
    """
    from . import contract

    passes = tuple(passes)
    unknown = sorted(set(passes) - set(_PASS_MODULES))
    if unknown:
        # a typo'd pass name must not silently run zero checks and pass CI
        raise ValueError(f"unknown lint pass(es) {unknown}; valid: {ALL_PASSES}")

    root = root or default_root()
    files = collect_files(root)
    findings: List[Finding] = []
    for sf in files:
        if sf.tree is None:
            findings.append(Finding(
                rule="parse-error", file=sf.relpath, line=1, context=sf.relpath,
                message=f"{sf.relpath} does not parse; the analyzer cannot see it",
            ))

    extracted = contract.extract_contract(files)
    for name in ALL_PASSES:
        if name in passes:
            mod = _pass_module(name)
            findings.extend(mod.check(extracted if name == "rpc" else files))

    by_file = {sf.relpath: sf for sf in files}
    kept: List[Finding] = []
    suppressed = 0
    for f in findings:
        sf = by_file.get(f.file)
        if sf is not None and sf.suppressed(f):
            suppressed += 1
        else:
            kept.append(f)
    kept.sort(key=lambda f: (f.file, f.line, f.rule))

    baseline = load_baseline(baseline_file or baseline_path(root))
    new, stale = diff_baseline(kept, baseline)
    return {
        "root": root,
        "findings": kept,
        "new": new,
        "stale": stale,
        "suppressed": suppressed,
        "contract": extracted,
        "ok": not new and not stale,
    }
