"""Pass 3: resource-lifetime leak detection (CFG + dataflow).

res-leak-on-raise    an execution path exists from an acquire to the
                     function's *exceptional* exit with no release: the slice
                     / fd / socket / lock leaks exactly when something goes
                     wrong — the path chaos tests (and preempted VMs) take.
res-leak-on-return   a path from an acquire to a normal return with no
                     release and no escape (the resource wasn't returned,
                     stored, or handed to anyone who could release it).
                     Re-acquiring or rebinding a variable that may still
                     hold a live resource reports here too: the previous
                     resource becomes unreachable at the overwrite (the
                     loop-carried-acquire shape).
res-double-release   a release reaches a variable that may already be
                     released on some path — only for pairs whose release is
                     NOT idempotent (lock.release raises RuntimeError,
                     double os.close can close a stranger's recycled fd).

The analysis is intraprocedural, per function: a forward may-analysis over
analysis/cfg.py graphs tracking, per variable, the set of (state, pair,
acquire-line) facts.  Escape analysis keeps the false-positive rate down —
a resource that is returned, yielded, stored into an attribute/container,
passed to an unknown callee, or captured by a nested function stops being
this function's responsibility and is dropped from tracking.  `with` /
`async with` managed acquires are never tracked (release is structural).
Branch narrowing understands `if fd:` / `if conn is None:` guards so the
guarded-release idiom doesn't fire.

WHAT COUNTS as an acquire/release is declared in REGISTRY below; a new
resource class is a one-line Pair(...) addition.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Tuple

from .cfg import build_cfg, header_exprs
from .dataflow import Analysis, solve
from .engine import Finding, dotted_name as _dotted

RULES = {
    "res-leak-on-raise": (
        "a path from an acquire (fd/file/socket/lock/arena slice) to the "
        "function's exceptional exit has no release — leaks exactly when "
        "something goes wrong"
    ),
    "res-leak-on-return": (
        "a path from an acquire to a normal return (or a rebind/re-acquire, "
        "incl. loop-carried) drops the resource without releasing it"
    ),
    "res-double-release": (
        "a non-idempotent release (lock.release, os.close, free_slice) may "
        "run twice on the same resource along some path"
    ),
}


@dataclasses.dataclass(frozen=True)
class Pair:
    """One acquire/release discipline.  Adding a resource class is one entry."""

    name: str
    # value-producing acquires, matched on the exact dotted callee
    # ("os.open", bare "open"); the bound name becomes the tracked resource
    acquire_calls: frozenset = frozenset()
    # value-producing acquires matched on the METHOD name regardless of
    # receiver (arena.alloc -> slice offset).  `self.<method>(...)` never
    # matches: calling your own method is policy, not acquisition from a
    # resource-manager object
    acquire_methods: frozenset = frozenset()
    # statement-style acquires on an existing object: `lock.acquire()` as a
    # bare Expr marks the RECEIVER acquired
    receiver_acquire: frozenset = frozenset()
    # when the acquire returns a tuple, which element is the resource
    # (asyncio.open_connection -> (reader, writer)[1]; mkstemp -> fd[0])
    tuple_index: Optional[int] = None
    # releases: method on the resource (conn.close), function taking it as
    # first arg (os.close(fd)), or method on anything taking it as first arg
    # (arena.free_slice(off, sz))
    release_methods: frozenset = frozenset()
    release_funcs: frozenset = frozenset()
    release_arg_methods: frozenset = frozenset()
    # dotted callees that USE the resource as an argument without taking
    # ownership (os.read(fd, n) must not count as an escape)
    neutral_funcs: frozenset = frozenset()
    double_release_is_error: bool = False


_FD_NEUTRAL = frozenset({
    "os.read", "os.write", "os.pread", "os.pwrite", "os.lseek", "os.ftruncate",
    "os.fsync", "os.fstat", "os.fchmod", "os.fdatasync", "os.sendfile",
    "os.get_blocking", "os.set_blocking",
})

REGISTRY: Tuple[Pair, ...] = (
    Pair(
        name="file",
        acquire_calls=frozenset({"open", "io.open", "os.fdopen", "gzip.open"}),
        release_methods=frozenset({"close"}),
    ),
    Pair(
        name="fd",
        acquire_calls=frozenset({"os.open", "os.dup", "os.memfd_create"}),
        release_funcs=frozenset({"os.close"}),
        neutral_funcs=_FD_NEUTRAL,
        double_release_is_error=True,
    ),
    Pair(
        name="tmpfile-fd",
        acquire_calls=frozenset({"tempfile.mkstemp", "mkstemp"}),
        tuple_index=0,
        release_funcs=frozenset({"os.close"}),
        neutral_funcs=_FD_NEUTRAL,
        double_release_is_error=True,
    ),
    Pair(
        name="connection",
        acquire_calls=frozenset({
            "connect_addr", "connect_unix", "protocol.connect_addr",
            "protocol.connect_unix", "dial", "aio.dial",
        }),
        release_methods=frozenset({"close"}),
    ),
    Pair(
        name="stream",
        acquire_calls=frozenset({
            "asyncio.open_connection", "asyncio.open_unix_connection",
            "open_connection", "open_unix_connection",
        }),
        tuple_index=1,
        release_methods=frozenset({"close"}),
    ),
    Pair(
        name="lock",
        receiver_acquire=frozenset({"acquire"}),
        release_methods=frozenset({"release"}),
        double_release_is_error=True,
    ),
    Pair(
        name="arena-slice",
        acquire_methods=frozenset({"alloc"}),
        release_arg_methods=frozenset({"free_slice"}),
        double_release_is_error=True,
    ),
    # shm channels (channel/shm_channel.py): the backing /dev/shm segment is
    # freed by release(), not close() — close() only raises the shared
    # shutdown flag; a channel that is closed but never released leaks its
    # mmap and (writer-side) the on-disk segment until session sweep
    Pair(
        name="shm-channel",
        acquire_calls=frozenset({
            "ShmChannel", "BufferedShmChannel",
            "ShmChannel.open", "BufferedShmChannel.open",
            "open_channel", "shm_channel.open_channel",
        }),
        release_methods=frozenset({"release"}),
    ),
    # spill files ride the fd pair at creation (os.open O_EXCL) and the
    # unlink below for the on-disk name
    Pair(
        name="spill-path",
        acquire_calls=frozenset({"mktemp", "tempfile.mktemp"}),
        release_funcs=frozenset({"os.unlink", "os.remove"}),
        double_release_is_error=True,
    ),
)

_ALL_ACQUIRE_TOKENS = frozenset(
    tok
    for pair in REGISTRY
    for entry in (pair.acquire_calls | pair.acquire_methods | pair.receiver_acquire)
    for tok in (entry.rsplit(".", 1)[-1],)
)

_PAIRS_BY_NAME = {p.name: p for p in REGISTRY}

# fact tuples: ("acq" | "rel", pair-name, source line)
ACQ, REL = "acq", "rel"


def _acquire_binding(stmt) -> Optional[Tuple[str, Pair, int]]:
    """`x = open(...)` / `r, w = await asyncio.open_connection(...)` ->
    (bound name, pair, line)."""
    if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1:
        target, value = stmt.targets[0], stmt.value
    elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
        target, value = stmt.target, stmt.value
    else:
        return None
    if isinstance(value, ast.Await):
        value = value.value
    if not isinstance(value, ast.Call):
        return None
    dotted = _dotted(value.func)
    for pair in REGISTRY:
        hit = (dotted is not None and dotted in pair.acquire_calls) or (
            isinstance(value.func, ast.Attribute)
            and value.func.attr in pair.acquire_methods
            and not (
                isinstance(value.func.value, ast.Name)
                and value.func.value.id == "self"
            )
        )
        if not hit:
            continue
        if pair.tuple_index is None:
            if isinstance(target, ast.Name):
                return (target.id, pair, value.lineno)
        elif isinstance(target, ast.Tuple) and len(target.elts) > pair.tuple_index:
            elt = target.elts[pair.tuple_index]
            if isinstance(elt, ast.Name):
                return (elt.id, pair, value.lineno)
        return None
    return None


def _build_parents(exprs) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for root in exprs:
        for node in ast.walk(root):
            for child in ast.iter_child_nodes(node):
                parents[child] = node
    return parents


def _under_lambda(node, parents) -> bool:
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.Lambda):
            return True
        cur = parents.get(cur)
    return False


# pass-through containers between a value and the context that consumes it
_WRAPPERS = (ast.Tuple, ast.List, ast.Set, ast.Starred, ast.IfExp, ast.NamedExpr)


def _classify(node, parents):
    """How one Load occurrence of a tracked variable is used.

    Returns one of:
      ("method", attr, call)  receiver of a method call
      ("arg", call)           positional/keyword argument of a call
      ("escape",)             returned / yielded / raised / stored / aliased
      ("neutral",)            test, comparison, arithmetic, subscript index
    """
    p = parents.get(node)
    if isinstance(p, ast.Attribute) and p.value is node:
        gp = parents.get(p)
        if isinstance(gp, ast.Call) and gp.func is p:
            return ("method", p.attr, gp)
        return ("neutral",)
    if isinstance(p, ast.Call) and node in p.args:
        return ("arg", p)
    if isinstance(p, ast.keyword):
        return ("arg", parents.get(p))
    n, q = node, p
    while isinstance(q, _WRAPPERS):
        n, q = q, parents.get(q)
    if isinstance(q, (ast.Return, ast.Yield, ast.YieldFrom, ast.Raise)):
        return ("escape",)
    if isinstance(q, ast.Assign) and n is q.value:
        return ("escape",)
    if isinstance(q, (ast.AnnAssign, ast.AugAssign)) and n is getattr(q, "value", None):
        return ("escape",)
    if isinstance(q, ast.Await):
        return ("neutral",)
    if isinstance(q, ast.Call):  # wrapped (starred/tuple) into a call
        return ("arg", q)
    if isinstance(q, ast.Dict):
        return ("escape",)
    return ("neutral",)


def _narrow_test(test) -> Optional[Tuple[str, str]]:
    """`if fd:` / `if conn is None:` style guards -> (key, arm-to-drop-on).
    Returns (dotted key, "false"|"true"): the arm on which the variable is
    known falsy/None, so acquire facts can be dropped there."""
    node = test
    drop_on = "false"
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.Not):
        node, drop_on = node.operand, "true"
    if isinstance(node, ast.Compare) and len(node.ops) == 1 and (
        isinstance(node.comparators[0], ast.Constant)
        and node.comparators[0].value is None
    ):
        key = _dotted(node.left)
        if key is None:
            return None
        if isinstance(node.ops[0], (ast.Is, ast.Eq)):
            return (key, "true" if drop_on == "false" else "false")
        if isinstance(node.ops[0], (ast.IsNot, ast.NotEq)):
            return (key, drop_on)
        return None
    key = _dotted(node)
    if key is not None:
        return (key, drop_on)
    return None


class _ResourceAnalysis(Analysis):
    """Per-variable acquire/release facts; transfer doubles as the event
    reporter when `report` is set (post-fixpoint pass)."""

    def __init__(self):
        self.report = None  # callable(rule, line, key, pair, message) | None

    # ------------------------------------------------------------- transfer
    def transfer(self, block, state):
        s = block.stmt
        if s is None:
            return {"normal": state, "exc": state}
        if isinstance(s, ast.ExceptHandler):
            out = dict(state)
            if s.name:
                out.pop(s.name, None)
            return {"normal": out, "exc": out}

        out = dict(state)
        acquired_this_stmt = False

        exprs = header_exprs(s)
        parents = _build_parents(exprs)

        # nested function/class bodies: anything they capture escapes
        if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            for node in ast.walk(s):
                if isinstance(node, ast.Name) and node.id in out:
                    out.pop(node.id, None)

        acq = _acquire_binding(s)
        acq_value_call = None
        if acq is not None:
            value = s.value.value if isinstance(s.value, ast.Await) else s.value
            acq_value_call = value

        # classify every use of a tracked key in the header expressions
        releases: List[Tuple[str, Pair, int]] = []
        for root in exprs:
            for node in ast.walk(root):
                if isinstance(node, ast.Lambda):
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Name) and sub.id in out:
                            out.pop(sub.id, None)
            for node in ast.walk(root):
                key = None
                if isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
                    getattr(node, "ctx", None), ast.Load
                ):
                    key = _dotted(node)
                if key is None or key not in out:
                    continue
                if _under_lambda(node, parents):
                    out.pop(key, None)
                    continue
                use = _classify(node, parents)
                pair = self._pair_of(out.get(key, state.get(key)))
                if use[0] == "method":
                    _kind, attr, call = use
                    if pair is not None and attr in pair.release_methods:
                        releases.append((key, pair, node.lineno))
                    # other method calls on the resource are neutral reads
                elif use[0] == "arg":
                    call = use[1]
                    callee = _dotted(call.func) if call is not None else None
                    if pair is not None and callee in pair.release_funcs \
                            and call.args and _dotted(call.args[0]) == key:
                        releases.append((key, pair, node.lineno))
                    elif pair is not None and isinstance(
                        getattr(call, "func", None), ast.Attribute
                    ) and call.func.attr in pair.release_arg_methods \
                            and call.args and _dotted(call.args[0]) == key:
                        releases.append((key, pair, node.lineno))
                    elif pair is not None and callee in pair.neutral_funcs:
                        pass
                    else:
                        out.pop(key, None)  # unknown callee takes the resource
                elif use[0] == "escape":
                    out.pop(key, None)

        # statement-style lock acquire: `x.acquire()` as a bare Expr
        if isinstance(s, ast.Expr) and isinstance(s.value, ast.Call):
            call = s.value
            if isinstance(call.func, ast.Attribute):
                key = _dotted(call.func.value)
                for pair in REGISTRY:
                    if call.func.attr in pair.receiver_acquire and key is not None:
                        self._note_overwrite(out, key, s.lineno)
                        out[key] = frozenset({(ACQ, pair.name, s.lineno)})
                        acquired_this_stmt = True

        # apply releases (after use-classification so `conn.close()` isn't
        # first treated as an escape)
        for key, pair, line in releases:
            facts = out.get(key, frozenset())
            if pair.double_release_is_error and any(f[0] == REL for f in facts):
                self._emit(
                    "res-double-release", line, key, pair,
                    f"{pair.name} {key!r} may already be released on some "
                    f"path reaching this release",
                )
            out[key] = frozenset({(REL, pair.name, line)})

        # value-producing acquire binds last (its call args were evaluated
        # against the PRE state above)
        if acq is not None:
            key, pair, line = acq
            self._note_overwrite(out, key, line)
            out[key] = frozenset({(ACQ, pair.name, line)})
            acquired_this_stmt = True
        else:
            self._apply_rebinds(s, out)

        self._apply_structural(s, out)

        # a statement whose only calls are non-awaited methods on the tracked
        # resource itself (`conn.set_push_handler(cb)`) is not a realistic
        # raise point between acquire and release: treating it as one would
        # flag every configure-then-store idiom
        calls = [
            n for root in exprs for n in ast.walk(root)
            if isinstance(n, ast.Call)
        ]
        has_yield_point = isinstance(s, (ast.Raise, ast.Assert)) or any(
            isinstance(n, (ast.Await, ast.Yield, ast.YieldFrom))
            for root in exprs for n in ast.walk(root)
        )
        benign_exc = bool(calls) and not has_yield_point and all(
            isinstance(c.func, ast.Attribute)
            and _dotted(c.func.value) in state
            for c in calls
        )

        exc_state = None if benign_exc else (state if acquired_this_stmt else out)
        result = {"normal": out, "exc": exc_state}
        if isinstance(s, (ast.If, ast.While)):
            narrowed = _narrow_test(s.test)
            if narrowed is not None:
                key, arm = narrowed
                if key in out:
                    dropped = dict(out)
                    dropped.pop(key, None)
                    result[arm] = dropped
        return result

    # ------------------------------------------------------------- helpers
    @staticmethod
    def _pair_of(facts) -> Optional[Pair]:
        if not facts:
            return None
        for _state, pairname, _line in facts:
            return _PAIRS_BY_NAME.get(pairname)
        return None

    def _emit(self, rule, line, key, pair, message):
        if self.report is not None:
            self.report(rule, line, key, pair, message)

    def _note_overwrite(self, out, key, line):
        facts = out.get(key)
        if not facts:
            return
        for state, pairname, acq_line in facts:
            if state == ACQ:
                pair = _PAIRS_BY_NAME[pairname]
                self._emit(
                    "res-leak-on-return", line, key, pair,
                    f"{pair.name} {key!r} acquired at line {acq_line} is "
                    f"rebound here while possibly still held — the previous "
                    f"resource leaks (loop-carried acquires hit this)",
                )
                break

    def _apply_rebinds(self, s, out):
        """A plain rebind of a tracked name drops tracking (and reports if a
        live resource is overwritten); `del x` drops tracking silently."""
        targets = []
        if isinstance(s, ast.Assign):
            targets = s.targets
        elif isinstance(s, (ast.AnnAssign, ast.AugAssign)):
            targets = [s.target]
        elif isinstance(s, ast.Delete):
            for t in s.targets:
                key = _dotted(t)
                if key is not None:
                    out.pop(key, None)
            return
        for t in targets:
            for node in ast.walk(t):
                key = None
                if isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
                    getattr(node, "ctx", None), ast.Store
                ):
                    key = _dotted(node)
                if key is not None and key in out:
                    # rebinding to None after a release is the common idiom;
                    # rebinding while acquired loses the resource
                    if not (
                        isinstance(s, ast.Assign)
                        and isinstance(s.value, ast.Constant)
                        and s.value.value is None
                    ):
                        self._note_overwrite(out, key, s.lineno)
                    out.pop(key, None)

    @staticmethod
    def _apply_structural(s, out):
        """`with acquire() as x:` manages x's release structurally; a `for`
        target is rebound every iteration; both end tracking."""
        if isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                key = _dotted(item.context_expr)
                if key is not None:
                    out.pop(key, None)  # `with lock:` — managed
                if item.optional_vars is not None:
                    for node in ast.walk(item.optional_vars):
                        k = _dotted(node)
                        if k is not None:
                            out.pop(k, None)
        elif isinstance(s, (ast.For, ast.AsyncFor)):
            for node in ast.walk(s.target):
                k = _dotted(node)
                if k is not None:
                    out.pop(k, None)


def _fn_mentions_acquire(fn) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = (
                node.func.attr if isinstance(node.func, ast.Attribute)
                else node.func.id if isinstance(node.func, ast.Name) else None
            )
            if name in _ALL_ACQUIRE_TOKENS:
                return True
    return False


def check(files) -> List[Finding]:
    from .contract import _qualname_index

    findings: List[Finding] = []
    for sf in files:
        if sf.tree is None:
            continue
        for node, qual in _qualname_index(sf.tree).items():
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not _fn_mentions_acquire(node):
                continue
            _check_fn(sf, node, qual, findings)
    return findings


def _check_fn(sf, fn, qual, findings: List[Finding]) -> None:
    cfg = build_cfg(fn)
    analysis = _ResourceAnalysis()
    states = solve(cfg, analysis)

    seen = set()

    def emit(rule, line, key, pair, message):
        f = Finding(
            rule=rule, file=sf.relpath, line=line, context=qual,
            message=f"{message} (in {fn.name})",
            detail=f"{pair.name}:{key}:{rule}",
        )
        if f.fingerprint not in seen:
            seen.add(f.fingerprint)
            findings.append(f)

    # exit-state leaks
    for exit_block, rule, how in (
        (cfg.exit, "res-leak-on-return", "a normal return path"),
        (cfg.raise_exit, "res-leak-on-raise", "an exception path"),
    ):
        for key, facts in (states.get(exit_block.id) or {}).items():
            for state, pairname, line in sorted(facts):
                if state != ACQ:
                    continue
                pair = _PAIRS_BY_NAME[pairname]
                emit(
                    rule, line, key, pair,
                    f"{pair.name} {key!r} acquired here can reach {how} "
                    f"without a release",
                )

    # event findings (double release, overwrite) against fixpoint in-states
    analysis.report = emit
    for block in cfg.blocks:
        st = states.get(block.id)
        if st is not None and block.stmt is not None:
            analysis.transfer(block, st)
    analysis.report = None
