"""Worklist dataflow solver over analysis/cfg.py graphs.

Forward may-analysis framework: a client provides an initial state, a join,
and a per-block transfer; the solver iterates to fixpoint and returns every
block's IN state.  States are per-variable maps to *fact sets* — joining is
pointwise union, so the lattice has finite height (facts are drawn from the
finitely many acquire/release sites in one function) and termination is
structural, not fuel-based.

The transfer returns per-edge-kind out-states:

    transfer(block, in_state) -> {"normal": state, "exc": state | None, ...}

Edges of kind "exc" receive the "exc" entry; every other kind ("true",
"false", "back", "endfinally", "normal") receives its own entry if present,
else "normal".  A None state marks the edge infeasible for this client
(e.g. "this statement cannot actually raise"), and nothing propagates.
Returning per-kind states is what lets clients be flow-precise where it
matters: the acquire statement's own exc edge carries the PRE state (the
acquire failed, nothing was held), a branch on `if fd:` can drop facts on
the false arm, and an `endfinally` edge carries the normal out-state of a
completed finally body.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, Optional

from .cfg import CFG, Block

__all__ = ["Analysis", "solve", "State", "join_states"]

# var name (possibly dotted, e.g. "self._lock") -> frozenset of fact tuples
State = Dict[str, frozenset]


def join_states(a: State, b: State) -> State:
    """Pointwise union join (may-analysis)."""
    if not a:
        return dict(b)
    if not b:
        return dict(a)
    out = dict(a)
    for k, facts in b.items():
        cur = out.get(k)
        out[k] = facts if cur is None else (cur | facts)
    return out


class Analysis:
    """Client interface; subclass and override transfer()."""

    def initial(self) -> State:
        return {}

    def join(self, a: State, b: State) -> State:
        return join_states(a, b)

    def transfer(self, block: Block, state: State) -> Dict[str, Optional[State]]:
        return {"normal": state, "exc": state}


def solve(cfg: CFG, analysis: Analysis) -> Dict[int, State]:
    """Run to fixpoint; returns block id -> IN state.  Blocks never reached
    (dead code, infeasible handlers) have no entry."""
    in_states: Dict[int, State] = {cfg.entry.id: analysis.initial()}
    work = deque([cfg.entry])
    queued = {cfg.entry.id}

    while work:
        block = work.popleft()
        queued.discard(block.id)
        outs = analysis.transfer(block, in_states[block.id])
        normal = outs.get("normal")
        for succ, kind in block.succs:
            out = outs.get(kind, normal)
            if out is None:
                continue
            cur = in_states.get(succ.id)
            if cur is None:
                merged = dict(out)
            else:
                merged = analysis.join(cur, out)
                if merged == cur:
                    continue
            in_states[succ.id] = merged
            if succ.id not in queued:
                queued.add(succ.id)
                work.append(succ)
    return in_states
