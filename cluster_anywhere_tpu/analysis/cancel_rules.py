"""Pass 5: cancellation hygiene inside `async def`.

async-swallowed-cancel  a `try` whose body awaits, whose except chain
                        reaches a generic handler (`except Exception`, bare
                        `except`, `except BaseException`, or an explicit
                        CancelledError catch) that neither re-raises nor is
                        preceded by a CancelledError handler that does.  The
                        drain plane shuts nodes down by cancelling their
                        loops; a generic handler inside such a loop turns
                        "stop now" into "log and keep going" (bare/
                        BaseException catches today, `except Exception` the
                        moment someone widens it or the code runs on an old
                        asyncio).  Fix idiom:

                            except asyncio.CancelledError:
                                raise
                            except Exception:
                                ...

finally-await           an `await` inside a `finally:` while the task is
                        being cancelled raises CancelledError *immediately*,
                        masking the in-flight exception and abandoning the
                        rest of the cleanup.  Route cleanup awaits through
                        util.aio.finally_await (shields the cleanup, logs
                        instead of masking) or make the cleanup synchronous.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .engine import Finding, dotted_name as _dotted

RULES = {
    "async-swallowed-cancel": (
        "a generic except around an await swallows (or will swallow) task "
        "cancellation — re-raise CancelledError before the generic handler"
    ),
    "finally-await": (
        "an await inside finally: raises immediately under cancellation, "
        "masking the in-flight exception and skipping the rest of the "
        "cleanup — use util.aio.finally_await"
    ),
}

# awaited callees that are safe inside a finally (they guard themselves)
_SAFE_FINALLY_CALLS = {"finally_await", "aio.finally_await"}


def _own_nodes(stmts):
    """Every node under `stmts`, not descending into nested functions (an
    await in a nested async def is that function's concern)."""
    stack = list(stmts)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue  # a nested def seeded directly: its body is its own
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                continue
            stack.append(child)


def _has_await(stmts) -> bool:
    return any(
        isinstance(n, (ast.Await, ast.AsyncFor, ast.AsyncWith))
        for n in _own_nodes(stmts)
    )


def _handler_names(handler: ast.ExceptHandler):
    if handler.type is None:
        yield "<bare>"
        return
    def walk(node):
        if isinstance(node, ast.Tuple):
            for elt in node.elts:
                yield from walk(elt)
        else:
            d = _dotted(node)
            if d is not None:
                yield d.rsplit(".", 1)[-1]
    yield from walk(handler.type)


def _reraises(handler: ast.ExceptHandler) -> bool:
    """A bare `raise` anywhere in the handler body counts: the common shapes
    re-raise unconditionally or behind an isinstance check."""
    return any(
        isinstance(n, ast.Raise) and n.exc is None
        for n in _own_nodes(handler.body)
    )


def check(files) -> List[Finding]:
    from .contract import _qualname_index

    findings: List[Finding] = []
    for sf in files:
        if sf.tree is None:
            continue
        for node, qual in _qualname_index(sf.tree).items():
            if isinstance(node, ast.AsyncFunctionDef):
                _check_fn(sf, node, qual, findings)
    return findings


def _check_fn(sf, fn, qual, findings: List[Finding]) -> None:
    tries = sorted(
        (n for n in _own_nodes(fn.body) if isinstance(n, ast.Try)),
        key=lambda n: (n.lineno, n.col_offset),
    )
    # the ordinal keeps fingerprints distinct for same-shaped try blocks in
    # one function without baking line numbers into them
    for ordinal, node in enumerate(tries):
        if node.handlers and _has_await(node.body):
            _check_handlers(sf, node, qual, ordinal, findings)
        if node.finalbody:
            _check_finally(sf, node, qual, ordinal, findings)


def _check_handlers(sf, try_node, qual, ordinal, findings: List[Finding]) -> None:
    for handler in try_node.handlers:
        names = set(_handler_names(handler))
        catches_cancel = bool(names & {"<bare>", "BaseException", "CancelledError"})
        generic = bool(names & {"<bare>", "BaseException", "Exception"})
        if not (catches_cancel or generic):
            continue  # narrow handler: cancellation flows past it
        if _reraises(handler):
            if catches_cancel:
                return  # cancellation is re-raised here; done
            # an `except Exception: ...; raise` cannot catch cancellation:
            # keep scanning — a later broader handler may still swallow it
            continue
        what = (
            "catches CancelledError and does not re-raise it"
            if catches_cancel else
            "is not preceded by a CancelledError re-raise"
        )
        findings.append(Finding(
            rule="async-swallowed-cancel", file=sf.relpath,
            line=handler.lineno, context=qual,
            message=(
                f"except {'/'.join(sorted(names))} around an await {what}: "
                f"task cancellation (drain-plane shutdown) can be swallowed "
                f"— add `except asyncio.CancelledError: raise` first"
            ),
            detail=f"try{ordinal}:{'/'.join(sorted(names))}",
        ))
        return  # one finding per try statement


def _check_finally(sf, try_node, qual, ordinal, findings: List[Finding]) -> None:
    # fingerprint by the await's ordinal AMONG AWAITS (not among all nodes):
    # unrelated edits to the finally body must not churn fingerprints
    awaits = sorted(
        (n for n in _own_nodes(try_node.finalbody) if isinstance(n, ast.Await)),
        key=lambda n: (n.lineno, n.col_offset),
    )
    for idx, node in enumerate(awaits):
        call = node.value
        if isinstance(call, ast.Call):
            callee = _dotted(call.func)
            if callee in _SAFE_FINALLY_CALLS or (
                isinstance(call.func, ast.Attribute)
                and call.func.attr == "finally_await"
            ):
                continue
        findings.append(Finding(
            rule="finally-await", file=sf.relpath, line=node.lineno,
            context=qual,
            message=(
                "await inside finally: under cancellation this raises "
                "immediately, masking the in-flight exception and skipping "
                "the rest of the cleanup — use util.aio.finally_await"
            ),
            detail=f"try{ordinal}:finally-await:{idx}",
        ))
