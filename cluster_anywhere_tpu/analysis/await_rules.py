"""Pass 4: unbounded network awaits.

async-unbounded-io   an `await` on a network dial / stream read / drain that
                     no timeout dominates.  On preemptible VMs a peer can
                     vanish mid-handshake (or mid-write with a full TCP
                     window) and an unbounded await parks the coroutine
                     forever — the drain plane can't finish a node that's
                     waiting on a dead socket.

What counts as network IO:
  dials   asyncio.open_connection / open_unix_connection, the repo's own
          protocol.connect_addr / connect_unix, loop.create_connection /
          sock_connect
  reads   .readline() / .readexactly() / .readuntil() on a stream reader
  drains  .drain() on a stream writer

What counts as a dominating timeout:
  - the call sits inside `asyncio.wait_for(...)`'s arguments
  - an enclosing `async with asyncio.timeout(...)` / `timeout_at(...)` block
  - the call itself carries a `timeout=` keyword (timeout-aware helpers)
  - the call IS a registered timeout-carrying helper: `util.aio.dial` /
    `aio.read_frame` / `aio.drain` bound it internally

Deliberately-unbounded sites (a server's persistent-connection read loop
idles legitimately) carry a justified `# ca-lint: ignore[async-unbounded-io]`
pragma at the await.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from .engine import Finding, dotted_name as _dotted

RULES = {
    "async-unbounded-io": (
        "an await on a network dial/read/drain with no dominating timeout "
        "(asyncio.wait_for, asyncio.timeout block, timeout= kwarg, or a "
        "util.aio bounded helper) can hang forever on a dead peer"
    ),
}

# dial-class callees, matched on the exact dotted name
_DIAL_CALLS = {
    "asyncio.open_connection", "asyncio.open_unix_connection",
    "open_connection", "open_unix_connection",
    "connect_addr", "connect_unix",
    "protocol.connect_addr", "protocol.connect_unix",
}
# dial/read/drain-class method names, matched on the attribute regardless of
# receiver (stream readers/writers are passed around under many names)
_IO_METHODS = {
    "readline", "readexactly", "readuntil",
    "drain",
    "create_connection", "sock_connect",
}
# helpers that bound their IO internally (util/aio.py): awaiting them bare
# is the FIX for this rule, not a finding
_BOUNDED_HELPERS = {"dial", "aio.dial", "aio.read_frame", "aio.drain"}

_WAIT_WRAPPERS = {"wait_for", "asyncio.wait_for"}
_TIMEOUT_CTX = {"timeout", "timeout_at"}  # asyncio.timeout(...) blocks


def _flags(call: ast.Call) -> Optional[str]:
    """The short name of the IO class this call belongs to, or None."""
    dotted = _dotted(call.func)
    if dotted in _DIAL_CALLS:
        return dotted
    if isinstance(call.func, ast.Attribute) and call.func.attr in _IO_METHODS:
        recv = _dotted(call.func.value) or "<expr>"
        return f"{recv}.{call.func.attr}"
    return None


def _is_bounded_call(call: ast.Call) -> bool:
    dotted = _dotted(call.func)
    if dotted in _BOUNDED_HELPERS:
        return True
    if isinstance(call.func, ast.Attribute) and call.func.attr in _BOUNDED_HELPERS:
        return True
    return any(kw.arg == "timeout" for kw in call.keywords)


def check(files) -> List[Finding]:
    from .contract import _qualname_index

    findings: List[Finding] = []
    for sf in files:
        if sf.tree is None:
            continue
        for node, qual in _qualname_index(sf.tree).items():
            if isinstance(node, ast.AsyncFunctionDef):
                _check_fn(sf, node, qual, findings)
    return findings


def _check_fn(sf, fn, qual, findings: List[Finding]) -> None:
    def visit(node, bounded: bool):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return  # nested scopes are visited as their own functions
        if isinstance(node, ast.AsyncWith):
            for item in node.items:
                ce = item.context_expr
                if isinstance(ce, ast.Call):
                    callee = _dotted(ce.func) or ""
                    if callee.rsplit(".", 1)[-1] in _TIMEOUT_CTX:
                        bounded = True
        if isinstance(node, ast.Call):
            callee = _dotted(node.func) or (
                node.func.attr if isinstance(node.func, ast.Attribute) else ""
            )
            if callee in _WAIT_WRAPPERS or (
                callee.rsplit(".", 1)[-1] == "wait_for"
            ):
                for child in ast.iter_child_nodes(node):
                    visit(child, True)
                return
        if isinstance(node, ast.Await) and isinstance(node.value, ast.Call):
            call = node.value
            what = _flags(call)
            if what is not None and not bounded and not _is_bounded_call(call):
                findings.append(Finding(
                    rule="async-unbounded-io", file=sf.relpath,
                    line=node.lineno, context=qual,
                    message=(
                        f"await {what}(...) has no dominating timeout: a "
                        f"dead peer parks this coroutine forever — wrap in "
                        f"asyncio.wait_for or use the util.aio bounded helper"
                    ),
                    detail=what,
                ))
        for child in ast.iter_child_nodes(node):
            visit(child, bounded)

    for stmt in fn.body:
        visit(stmt, False)
