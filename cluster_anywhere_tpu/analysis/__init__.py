"""Static analysis for the cluster: `ca lint`.

The wire protocol is schema-by-convention — handlers dispatch on a string
method name (`head._handle` does `getattr(self, "_h_" + m)`), call sites name
methods as string literals, and handlers read `msg["field"]` — so nothing in
the type system catches a typo'd method, a field nobody sends, or a handler no
caller reaches.  The reference gets all of that for free from protobuf
(`src/ray/protobuf/*.proto`); we get it from this package instead: a stdlib
`ast` analyzer with five passes.

Pass 1 (contract.py + rpc_rules.py) extracts every RPC handler table and every
call site into a machine-readable contract (docs/PROTOCOL_CONTRACT.json) and
cross-checks them: unknown methods, dead handlers, required-but-unsent fields,
sent-but-unread fields.

Pass 2 (async_rules.py) audits the event-loop code: blocking calls inside
`async def`, fire-and-forget `create_task`/`ensure_future` whose failures
would vanish, and read-modify-write of shared state split across an `await`.

Passes 3-5 are *path* analyses over an intraprocedural CFG + worklist
dataflow framework (cfg.py, dataflow.py):

Pass 3 (resource_rules.py) tracks acquire/release disciplines (fds, files,
connections, locks, arena slices — declared in a one-line-per-pair REGISTRY)
and reports paths that leak on raise/return, loop-carried re-acquires, and
non-idempotent double releases.

Pass 4 (await_rules.py) flags network dials/reads/drains no timeout
dominates (async-unbounded-io) — the fix surface is util/aio.py's dial()/
read_frame()/drain() bounded helpers.

Pass 5 (cancel_rules.py) enforces cancellation hygiene: generic excepts that
swallow CancelledError around awaits, and awaits inside finally: blocks that
mask the in-flight exception (fix: util.aio.finally_await).

Findings flow through a checked-in baseline (analysis/baseline.json): accepted
pre-existing findings don't fail CI, new findings do, and baseline entries
whose code no longer exists fail too — the baseline only shrinks.  Intentional
dynamics are annotated in source with `# ca-lint: ignore[rule]` pragmas, which
beat baseline entries (visible at the site, not in a side file).

No dependencies beyond the standard library: the analyzer must run anywhere
the repo checks out, including CI images without the runtime deps.
"""

from .engine import Finding, run_lint  # noqa: F401
