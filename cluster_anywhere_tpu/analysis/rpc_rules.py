"""Pass 1 checks: cross-check the extracted contract.

rpc-unknown-method   a call site names a method no peer handles — a typo'd
                     string would otherwise surface only as a runtime
                     reply_err (or, for a notify, as nothing at all).
rpc-dead-handler     a handler no call site ever reaches: dead code, or the
                     caller was refactored away and nobody noticed.
rpc-missing-field    a literal call site omits a field every handler for the
                     method reads via `msg["x"]` — a guaranteed KeyError (or
                     reply_err) when that site fires.
rpc-unread-field     a literal call site sends a field no handler for the
                     method ever reads (and every handler's read set is
                     closed): wire bytes for nothing, usually a renamed or
                     half-removed field.

Required fields are intersected across surfaces handling the same method (a
site targets one peer; we don't resolve which), read fields are unioned, and
any opaque handler disables unread-field checks for its method.
"""

from __future__ import annotations

from typing import Dict, List, Set

from .contract import RESERVED_FIELDS, Contract
from .engine import Finding

RULES = {
    "rpc-unknown-method": (
        "a call site names an RPC method no peer surface handles — a typo'd "
        "string surfaces only as a runtime reply_err, or as nothing at all"
    ),
    "rpc-dead-handler": (
        "a handler no call site anywhere reaches: dead code, or the caller "
        "was refactored away unnoticed"
    ),
    "rpc-missing-field": (
        "a literal call site omits a field every handler for the method "
        "reads unconditionally — a guaranteed KeyError when it fires"
    ),
    "rpc-unread-field": (
        "a literal call site sends a field no handler for the method reads "
        "— wire bytes for nothing, usually a renamed or half-removed field"
    ),
    "parse-error": (
        "a file under analysis does not parse, so no pass can see it"
    ),
}


def check(contract: Contract) -> List[Finding]:
    findings: List[Finding] = []
    handler_methods = contract.handler_methods()
    called_methods = contract.called_methods()

    by_method: Dict[str, list] = {}
    for h in contract.handlers:
        by_method.setdefault(h.method, []).append(h)

    seen: Set[str] = set()  # fingerprint dedup (same site shape repeated)

    def emit(f: Finding):
        if f.fingerprint not in seen:
            seen.add(f.fingerprint)
            findings.append(f)

    for site in contract.call_sites:
        if site.method not in handler_methods:
            emit(Finding(
                rule="rpc-unknown-method", file=site.file, line=site.line,
                context=site.context,
                message=(
                    f"{site.kind} names RPC method {site.method!r} but no "
                    f"peer surface handles it"
                ),
                detail=site.method,
            ))
            continue
        handlers = by_method[site.method]
        if site.fields is None:
            continue  # dynamic field set: method check only
        required = None
        for h in handlers:
            required = h.required if required is None else (required & h.required)
        for field in sorted((required or set()) - site.fields - RESERVED_FIELDS):
            emit(Finding(
                rule="rpc-missing-field", file=site.file, line=site.line,
                context=site.context,
                message=(
                    f"{site.kind} of {site.method!r} never sends {field!r}, "
                    f"which every handler reads as msg[{field!r}]"
                ),
                detail=f"{site.method}.{field}",
            ))
        if any(h.opaque for h in handlers):
            continue
        read: Set[str] = set()
        for h in handlers:
            read |= h.required | h.optional
        for field in sorted(site.fields - read - RESERVED_FIELDS):
            emit(Finding(
                rule="rpc-unread-field", file=site.file, line=site.line,
                context=site.context,
                message=(
                    f"{site.kind} of {site.method!r} sends {field!r} but no "
                    f"handler for the method reads it"
                ),
                detail=f"{site.method}.{field}",
            ))

    for h in contract.handlers:
        if h.surface == "protocol":
            continue
        if h.method not in called_methods:
            emit(Finding(
                rule="rpc-dead-handler", file=h.file, line=h.line,
                context=h.context,
                message=(
                    f"{h.surface} handler for {h.method!r} has no call site "
                    f"anywhere in the repo (dead code?)"
                ),
                detail=f"{h.surface}:{h.method}",
            ))
    return findings
