"""Intraprocedural control-flow graphs over stdlib AST.

PR 8's passes were statement-local: they could see *a* blocking call or *a*
dropped task, but not a *path* property — "this file descriptor is opened on
line 10 and there exists an execution path to the function's exceptional exit
on which nobody closed it".  Path properties need a CFG; this module builds
one per function, and analysis/dataflow.py runs worklist fixpoints over it.

Shape
-----
One statement per block (lint-scale functions are small; merging basic blocks
buys nothing here).  Compound statements contribute a *header* block holding
only the expressions the statement itself evaluates (an `if` test, a `for`
iterable, a `with` item list) — their bodies become separate blocks wired
with edges.  Three distinguished virtual blocks:

  entry       no statement; predecessor of the first real block
  exit        every normal return path ends here
  raise_exit  every path on which an unhandled exception leaves the function

Edges carry a kind:

  normal      sequential flow
  true/false  the two arms of a branch test (dataflow clients may narrow:
              `if fd:` implies fd is live on the true arm only)
  back        a loop back-edge (body bottom -> loop header)
  exc         exceptional flow out of a statement that can raise, into the
              innermost handler dispatch / finally copy / raise_exit.  A
              dataflow transfer provides a *separate* state for exc edges
              (e.g. the acquire statement itself raising means the resource
              was never acquired).
  endfinally  the re-raise continuation at the bottom of an exception-path
              `finally` copy: flow continues to the outer exception target,
              but with the block's NORMAL out-state (the finally body ran to
              completion; the in-flight exception is what propagates).

try/except/finally
------------------
Exceptions from the protected body flow to a virtual `except.dispatch` block
with an `exc` edge to every handler entry, plus a no-match `exc` edge onward
(suppressed when a catch-all handler — bare, Exception, BaseException — is
present).  `finally` bodies are *inlined by duplication*, the standard lint
trick: one copy on the normal path, one on the exception path (ending in an
`endfinally` edge to the outer exception target), and one fresh copy per
abrupt exit (`return`/`break`/`continue`) threaded before the jump resolves.
Duplication introduces no infeasible-path trouble for may-analyses and keeps
the solver oblivious to finally semantics.

`with` statements contribute their item expressions as a header block; the
managed release on block exit is a *client* concern (the resource pass simply
never tracks context-managed acquires).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

__all__ = ["Block", "CFG", "build_cfg", "header_exprs", "may_raise"]

# edge kinds a dataflow transfer receives its exceptional out-state on
EXC_KINDS = ("exc",)


class Block:
    __slots__ = ("id", "stmt", "label", "succs", "preds")

    def __init__(self, bid: int, label: str = "", stmt: Optional[ast.AST] = None):
        self.id = bid
        self.stmt = stmt          # None for virtual blocks (entry/exit/joins)
        self.label = label
        self.succs: List[Tuple["Block", str]] = []
        self.preds: List[Tuple["Block", str]] = []

    def add_succ(self, other: "Block", kind: str = "normal") -> None:
        self.succs.append((other, kind))
        other.preds.append((self, kind))

    def __repr__(self):  # pragma: no cover - debugging aid
        line = getattr(self.stmt, "lineno", "-")
        return f"<Block {self.id} {self.label or type(self.stmt).__name__ if self.stmt else self.label}:{line}>"


class CFG:
    def __init__(self, fn: ast.AST):
        self.fn = fn
        self.blocks: List[Block] = []
        self.entry = self.new_block("entry")
        self.exit = self.new_block("exit")
        self.raise_exit = self.new_block("raise")

    def new_block(self, label: str = "", stmt: Optional[ast.AST] = None) -> Block:
        b = Block(len(self.blocks), label, stmt)
        self.blocks.append(b)
        return b

    def stmt_blocks(self) -> List[Block]:
        """Real (non-virtual) blocks, in creation (~source) order."""
        return [b for b in self.blocks if b.stmt is not None]


def header_exprs(stmt: ast.AST) -> List[ast.AST]:
    """The expressions a compound statement's header block evaluates itself
    (bodies are separate blocks).  Simple statements evaluate themselves."""
    if isinstance(stmt, (ast.If, ast.While)):
        return [stmt.test]
    if isinstance(stmt, (ast.For, ast.AsyncFor)):
        return [stmt.iter, stmt.target]
    if isinstance(stmt, (ast.With, ast.AsyncWith)):
        out: List[ast.AST] = []
        for item in stmt.items:
            out.append(item.context_expr)
            if item.optional_vars is not None:
                out.append(item.optional_vars)
        return out
    if isinstance(stmt, ast.Try):
        return []
    if isinstance(stmt, ast.ExceptHandler):
        return [stmt.type] if stmt.type is not None else []
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
        # the def statement evaluates decorators and defaults; the body is a
        # separate scope (clients handle captures themselves)
        return list(stmt.decorator_list) + [
            d for d in (stmt.args.defaults + stmt.args.kw_defaults) if d is not None
        ]
    if isinstance(stmt, ast.ClassDef):
        return list(stmt.decorator_list) + list(stmt.bases)
    return [stmt]


def may_raise(stmt: ast.AST) -> bool:
    """Conservative can-this-statement-raise, at lint granularity: calls,
    awaits, explicit raises, asserts, and iteration can; plain data plumbing
    (name binds, attribute reads, arithmetic) is treated as safe — treating
    *everything* as raising would put an exc edge after every acquire and
    drown the leak rules in noise."""
    if isinstance(stmt, (ast.Raise, ast.Assert)):
        return True
    if isinstance(stmt, (ast.For, ast.AsyncFor, ast.With, ast.AsyncWith)):
        return True  # iteration / __enter__ can raise
    for expr in header_exprs(stmt):
        for node in ast.walk(expr):
            if isinstance(node, (ast.Call, ast.Await, ast.Yield, ast.YieldFrom)):
                return True
    return False


def _is_catch_all(handler: ast.ExceptHandler) -> bool:
    if handler.type is None:
        return True

    def names(node):
        if isinstance(node, ast.Tuple):
            for elt in node.elts:
                yield from names(elt)
        elif isinstance(node, ast.Name):
            yield node.id
        elif isinstance(node, ast.Attribute):
            yield node.attr

    return any(n in ("Exception", "BaseException") for n in names(handler.type))


class _Ctx:
    """Build-time context: where exceptions go, which finally bodies an
    abrupt exit must thread through, and the innermost loop's targets."""

    __slots__ = ("exc", "finallies", "loop")

    def __init__(self, exc, finallies=(), loop=None):
        self.exc = exc                # Block receiving exc edges
        self.finallies = finallies    # tuple of (finalbody, ctx-at-try)
        self.loop = loop              # (continue_target, break_edges, fin_depth)

    def replace(self, **kw) -> "_Ctx":
        out = _Ctx(self.exc, self.finallies, self.loop)
        for k, v in kw.items():
            setattr(out, k, v)
        return out


# frontier: list of (Block, kind) edges waiting to be attached to whatever
# block comes next; an empty frontier means the point is unreachable
Frontier = List[Tuple[Block, str]]


class _Builder:
    def __init__(self, cfg: CFG):
        self.cfg = cfg

    def attach(self, frontier: Frontier, block: Block) -> None:
        for src, kind in frontier:
            src.add_succ(block, kind)

    def block_for(self, stmt, frontier: Frontier, label: str = "") -> Block:
        blk = self.cfg.new_block(label, stmt)
        self.attach(frontier, blk)
        return blk

    def seq(self, stmts, frontier: Frontier, ctx: _Ctx) -> Frontier:
        for s in stmts:
            if not frontier:
                break  # unreachable (code after return/raise): not modeled
            frontier = self.stmt(s, frontier, ctx)
        return frontier

    def unwind_finallies(self, frontier: Frontier, ctx: _Ctx, upto: int) -> Frontier:
        """Inline a fresh copy of every finally body between the abrupt exit
        and `upto` entries deep, innermost first."""
        for fb, fctx in reversed(ctx.finallies[upto:]):
            frontier = self.seq(fb, frontier, fctx)
        return frontier

    def stmt(self, s, frontier: Frontier, ctx: _Ctx) -> Frontier:
        if isinstance(s, ast.Return):
            blk = self.block_for(s, frontier, "return")
            if may_raise(s):
                blk.add_succ(ctx.exc, "exc")
            out = self.unwind_finallies([(blk, "normal")], ctx, 0)
            self.attach(out, self.cfg.exit)
            return []
        if isinstance(s, ast.Raise):
            blk = self.block_for(s, frontier, "raise")
            blk.add_succ(ctx.exc, "exc")
            return []
        if isinstance(s, (ast.Break, ast.Continue)):
            blk = self.block_for(s, frontier)
            if ctx.loop is None:
                return []  # malformed source; nothing sensible to wire
            cont, brk, depth = ctx.loop
            out = self.unwind_finallies([(blk, "normal")], ctx, depth)
            if isinstance(s, ast.Break):
                brk.extend(out)
            else:
                self.attach(out, cont)
            return []
        if isinstance(s, ast.If):
            return self._if(s, frontier, ctx)
        if isinstance(s, ast.While):
            return self._loop(s, frontier, ctx, header_may_raise=may_raise(s))
        if isinstance(s, (ast.For, ast.AsyncFor)):
            return self._loop(s, frontier, ctx, header_may_raise=True)
        if isinstance(s, (ast.With, ast.AsyncWith)):
            blk = self.block_for(s, frontier, "with")
            blk.add_succ(ctx.exc, "exc")
            return self.seq(s.body, [(blk, "normal")], ctx)
        if isinstance(s, ast.Try):
            return self._try(s, frontier, ctx)
        # simple statement (including nested def/class, whose body is a
        # separate scope the clients inspect for captures)
        blk = self.block_for(s, frontier)
        if may_raise(s):
            blk.add_succ(ctx.exc, "exc")
        return [(blk, "normal")]

    def _if(self, s: ast.If, frontier: Frontier, ctx: _Ctx) -> Frontier:
        blk = self.block_for(s, frontier, "if")
        if may_raise(s):
            blk.add_succ(ctx.exc, "exc")
        body_f = self.seq(s.body, [(blk, "true")], ctx)
        if s.orelse:
            else_f = self.seq(s.orelse, [(blk, "false")], ctx)
        else:
            else_f = [(blk, "false")]
        return body_f + else_f

    def _loop(self, s, frontier: Frontier, ctx: _Ctx, header_may_raise: bool) -> Frontier:
        head = self.block_for(s, frontier, "loop")
        if header_may_raise:
            head.add_succ(ctx.exc, "exc")
        break_edges: Frontier = []
        body_ctx = ctx.replace(loop=(head, break_edges, len(ctx.finallies)))
        body_f = self.seq(s.body, [(head, "true")], body_ctx)
        for src, kind in body_f:
            # keep branch-arm kinds on the back edge so dataflow narrowing
            # (`if off is not None: return` -> the false arm loops) survives
            src.add_succ(head, "back" if kind == "normal" else kind)
        const_true = (
            isinstance(s, ast.While)
            and isinstance(s.test, ast.Constant)
            and bool(s.test.value)
        )
        if const_true:
            out: Frontier = []  # `while True:` only leaves via break/raise
        elif s.orelse:
            out = self.seq(s.orelse, [(head, "false")], ctx)
        else:
            out = [(head, "false")]
        return out + break_edges

    def _try(self, s: ast.Try, frontier: Frontier, ctx: _Ctx) -> Frontier:
        # exception-path finally copy: runs, then re-raises outward with its
        # normal out-state (endfinally edge)
        if s.finalbody:
            fent = self.cfg.new_block("finally.exc")
            ftail = self.seq(s.finalbody, [(fent, "normal")], ctx)
            for src, _kind in ftail:
                src.add_succ(ctx.exc, "endfinally")
            exc_base: Block = fent
            inner_finallies = ctx.finallies + ((s.finalbody, ctx),)
        else:
            exc_base = ctx.exc
            inner_finallies = ctx.finallies

        if s.handlers:
            dispatch = self.cfg.new_block("except.dispatch")
            body_exc: Block = dispatch
        else:
            body_exc = exc_base

        body_ctx = ctx.replace(exc=body_exc, finallies=inner_finallies)
        body_f = self.seq(s.body, frontier, body_ctx)

        # the else clause runs after normal body completion and is NOT
        # protected by the handlers
        after_ctx = ctx.replace(exc=exc_base, finallies=inner_finallies)
        if s.orelse:
            body_f = self.seq(s.orelse, body_f, after_ctx)

        handler_f: Frontier = []
        if s.handlers:
            catch_all = False
            for h in s.handlers:
                hblk = self.cfg.new_block("except", stmt=h)
                dispatch.add_succ(hblk, "exc")
                handler_f += self.seq(h.body, [(hblk, "normal")], after_ctx)
                catch_all = catch_all or _is_catch_all(h)
            if not catch_all:
                dispatch.add_succ(exc_base, "exc")

        normal_f = body_f + handler_f
        if s.finalbody and normal_f:
            normal_f = self.seq(s.finalbody, normal_f, ctx)
        return normal_f


def build_cfg(fn) -> CFG:
    """Build the CFG for one ast.FunctionDef / ast.AsyncFunctionDef."""
    cfg = CFG(fn)
    builder = _Builder(cfg)
    ctx = _Ctx(exc=cfg.raise_exit)
    tail = builder.seq(fn.body, [(cfg.entry, "normal")], ctx)
    builder.attach(tail, cfg.exit)  # falling off the end returns None
    return cfg
