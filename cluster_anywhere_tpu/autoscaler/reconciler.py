"""Declarative autoscaler (analogue of the reference's autoscaler v2 —
python/ray/autoscaler/v2/autoscaler.py Autoscaler +
instance_manager/reconciler.py Reconciler + scheduler.py bin-packing).

Loop: read the head's autoscaler state (pending demand shapes + utilization)
-> bin-pack unmet demand onto node types -> launch; terminate nodes idle
beyond the timeout. `step()` is a single reconcile pass (tests drive it
directly); `Autoscaler.start()` runs it on a background thread.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional

from ..core.worker import global_worker
from .provider import NodeInfo, NodeProvider, NodeType


@dataclass
class AutoscalerConfig:
    node_types: List[NodeType] = None
    idle_timeout_s: float = 30.0
    interval_s: float = 1.0
    max_total_nodes: int = 8

    def __post_init__(self):
        if self.node_types is None:
            self.node_types = [NodeType("cpu2", {"CPU": 2.0})]


def _fits(avail: Dict[str, float], shape: Dict[str, float]) -> bool:
    return all(avail.get(k, 0.0) + 1e-9 >= v for k, v in shape.items())


def _take(avail: Dict[str, float], shape: Dict[str, float]):
    for k, v in shape.items():
        avail[k] = avail.get(k, 0.0) - v


class Reconciler:
    def __init__(self, provider: NodeProvider, config: AutoscalerConfig, state_fn=None):
        self.provider = provider
        self.config = config
        # state_fn() -> the head's autoscaler_state dict; injectable so tests
        # drive step() through synthetic cluster states without a live head
        self._state_fn = state_fn or (lambda: global_worker().head_call("autoscaler_state"))
        self._idle_since: Optional[float] = None
        self.requested_min: Dict[str, float] = {}

    def request_resources(self, shape: Dict[str, float]):
        """SDK hint (reference autoscaler/sdk/request_resources): keep at
        least this much capacity regardless of observed demand."""
        self.requested_min = dict(shape)

    def step(self) -> Dict[str, int]:
        """One reconcile pass. Returns {'launched': n, 'terminated': m}."""
        state = self._state_fn()
        launched = self._scale_up(state)
        terminated = self._scale_down(state) if not launched else 0
        return {"launched": launched, "terminated": terminated}

    # ------------------------------------------------------------- scale up
    def _scale_up(self, state) -> int:
        demands = [dict(d) for d in state["pending_demands"]]
        # demand that the current free capacity cannot serve
        free = dict(state["available"])
        unmet = []
        for d in demands:
            if _fits(free, d):
                _take(free, d)
            else:
                unmet.append(d)
        # bin-pack unmet demand onto new nodes, smallest node type first
        current = self.provider.non_terminated_nodes()
        count_by_type = {}
        for n in current:
            count_by_type[n.node_type] = count_by_type.get(n.node_type, 0) + 1
        to_launch: List[NodeType] = []
        packing: List[Dict[str, float]] = []

        def can_launch(nt: NodeType) -> bool:
            used = count_by_type.get(nt.name, 0) + sum(
                1 for t in to_launch if t.name == nt.name
            )
            return (
                used < nt.max_nodes
                and len(current) + len(to_launch) < self.config.max_total_nodes
            )

        for d in unmet:
            placed = False
            for cap in packing:  # try already-planned nodes
                if _fits(cap, d):
                    _take(cap, d)
                    placed = True
                    break
            if placed:
                continue
            for nt in sorted(self.config.node_types, key=lambda t: sum(t.resources.values())):
                if not can_launch(nt):
                    continue
                if _fits(dict(nt.resources), d):
                    cap = dict(nt.resources)
                    _take(cap, d)
                    packing.append(cap)
                    to_launch.append(nt)
                    placed = True
                    break
            # unplaceable demand (too big for any node type): skip
        if self.requested_min:
            # the requested minimum is an AGGREGATE capacity floor, not a
            # single-node shape: launch nodes until free + planned covers it
            floor_free = dict(state["available"])
            for nt in to_launch:
                self._give(floor_free, nt.resources)
            guard = 0
            while not _fits(floor_free, self.requested_min) and guard < 64:
                guard += 1
                deficit = {
                    k: v - floor_free.get(k, 0.0)
                    for k, v in self.requested_min.items()
                    if v - floor_free.get(k, 0.0) > 1e-9
                }
                chosen = None
                for nt in sorted(
                    self.config.node_types, key=lambda t: sum(t.resources.values())
                ):
                    if can_launch(nt) and any(nt.resources.get(k, 0.0) > 0 for k in deficit):
                        chosen = nt
                        break
                if chosen is None:
                    break  # caps reached or no type contributes
                to_launch.append(chosen)
                self._give(floor_free, chosen.resources)
        for nt in to_launch:
            self.provider.create_node(nt)
        return len(to_launch)

    @staticmethod
    def _give(avail: Dict[str, float], shape: Dict[str, float]):
        for k, v in shape.items():
            avail[k] = avail.get(k, 0.0) + v

    # ----------------------------------------------------------- scale down
    def _scale_down(self, state) -> int:
        nodes = self.provider.non_terminated_nodes()
        if not nodes:
            self._idle_since = None
            return 0
        busy = state["pending_demands"] or self._capacity_in_use(state)
        if busy:
            self._idle_since = None
            return 0
        if self._idle_since is None:
            self._idle_since = time.monotonic()
            return 0
        if time.monotonic() - self._idle_since < self.config.idle_timeout_s:
            return 0
        # terminate provider nodes while staying above any requested minimum
        terminated = 0
        for node in sorted(nodes, key=lambda n: n.created_at):
            remaining_total = dict(state["total"])
            for k, v in node.resources.items():
                remaining_total[k] = remaining_total.get(k, 0.0) - v
            if self.requested_min and not _fits(remaining_total, self.requested_min):
                continue
            self.provider.terminate_node(node)
            state["total"] = remaining_total
            terminated += 1
        if terminated:
            self._idle_since = None
        return terminated

    def _capacity_in_use(self, state) -> bool:
        """Provider-node capacity is in use when cluster-wide used resources
        exceed what the base (non-provider) capacity could absorb."""
        base_total = dict(state["total"])
        for n in self.provider.non_terminated_nodes():
            for k, v in n.resources.items():
                base_total[k] = base_total.get(k, 0.0) - v
        for k, total in state["total"].items():
            used = total - state["available"].get(k, 0.0)
            if used - 1e-9 > base_total.get(k, 0.0):
                return True
        return False


class Autoscaler:
    """Background reconcile loop (monitor.py analogue)."""

    def __init__(self, provider: NodeProvider, config: Optional[AutoscalerConfig] = None):
        self.config = config or AutoscalerConfig()
        self.reconciler = Reconciler(provider, self.config)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop, daemon=True, name="ca-autoscaler")
        self._thread.start()

    def _loop(self):
        while not self._stop.is_set():
            try:
                self.reconciler.step()
            except Exception:
                pass
            self._stop.wait(self.config.interval_s)

    def request_resources(self, shape: Dict[str, float]):
        self.reconciler.request_resources(shape)

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
