"""Node providers (analogue of the reference's
python/ray/autoscaler/node_provider.py NodeProvider + the fake_multi_node
local provider used in its tests).

A "node" contributes a fixed resource shape to the cluster. The
LocalNodeProvider launches real worker processes that register with the head
(the in-process analogue of launching a VM) and credits their capacity via
the head's update_resources RPC.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class NodeType:
    name: str
    resources: Dict[str, float]
    max_nodes: int = 4


@dataclass
class NodeInfo:
    node_id: str
    node_type: str
    state: str = "running"  # launching | running | terminated
    created_at: float = field(default_factory=time.monotonic)
    resources: Dict[str, float] = field(default_factory=dict)
    handle: Any = None  # provider-private


class NodeProvider:
    def create_node(self, node_type: NodeType) -> NodeInfo:
        raise NotImplementedError

    def terminate_node(self, node: NodeInfo) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[NodeInfo]:
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """Launches worker processes against the connected cluster. Each "node"
    is `workers_per_node` pool worker processes plus a capacity credit."""

    def __init__(self, workers_per_node: Optional[int] = None):
        from ..core.worker import global_worker

        self.w = global_worker()
        self.nodes: Dict[str, NodeInfo] = {}
        self.workers_per_node = workers_per_node

    def _spawn_worker(self, node_id: str, index: int) -> subprocess.Popen:
        w = self.w
        wid = f"ext-{node_id}-{index}"
        addr = os.path.join(w.session_dir, f"{wid}.sock")
        env = dict(os.environ)
        env["CA_SESSION_DIR"] = w.session_dir
        env["CA_HEAD_SOCK"] = w.head_sock
        env["CA_WORKER_ID"] = wid
        env["CA_WORKER_SOCK"] = addr
        env["CA_CONFIG_JSON"] = w.config.to_json()
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        logf = open(os.path.join(w.session_dir, f"{wid}.log"), "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "cluster_anywhere_tpu.core.workerproc"],
            env=env,
            stdout=logf,
            stderr=subprocess.STDOUT,
            start_new_session=True,
        )
        logf.close()
        return proc

    def create_node(self, node_type: NodeType) -> NodeInfo:
        node_id = uuid.uuid4().hex[:8]
        n_workers = self.workers_per_node or max(1, int(node_type.resources.get("CPU", 1)))
        procs = [self._spawn_worker(node_id, i) for i in range(n_workers)]
        self.w.head_call("update_resources", delta=dict(node_type.resources))
        info = NodeInfo(
            node_id=node_id,
            node_type=node_type.name,
            resources=dict(node_type.resources),
            handle=procs,
        )
        self.nodes[node_id] = info
        return info

    def terminate_node(self, node: NodeInfo) -> None:
        import signal

        if node.state == "terminated":
            return
        node.state = "terminated"
        for p in node.handle or []:
            try:
                os.kill(p.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
        # debit the capacity this node contributed
        if node.resources:
            delta = {k: -v for k, v in node.resources.items()}
            self.w.head_call("update_resources", delta=delta)
        self.nodes.pop(node.node_id, None)

    def non_terminated_nodes(self) -> List[NodeInfo]:
        return [n for n in self.nodes.values() if n.state != "terminated"]


class AgentNodeProvider(NodeProvider):
    """Launches REAL node-agent processes against the connected cluster —
    each autoscaled "node" is a full raylet-analogue with its own worker
    pool, shm namespace, and TCP link to the head (the in-process analogue
    of a cloud provider booting a VM; reference fake_multi_node provider).

    Scheduling spillover, per-node stores, and node-death semantics all
    behave exactly as for cluster_utils.Cluster nodes, so autoscaled
    capacity is indistinguishable from statically added nodes."""

    def __init__(self):
        import json

        from ..core.worker import global_worker

        self.w = global_worker()
        self.session_dir = self.w.session_dir
        self.head_tcp = open(os.path.join(self.session_dir, "head.addr")).read().strip()
        if not self.head_tcp:
            raise RuntimeError("head has no TCP endpoint; cannot add agent nodes")
        self.nodes: Dict[str, NodeInfo] = {}
        self._json = json

    def create_node(self, node_type: NodeType) -> NodeInfo:
        node_id = f"as-{uuid.uuid4().hex[:8]}"
        shape = dict(node_type.resources)
        shape.setdefault("memory", float(self.w.config.object_store_memory))
        env = dict(os.environ)
        env["CA_SESSION_DIR"] = self.session_dir
        env["CA_HEAD_ADDR"] = self.head_tcp
        env["CA_NODE_ID"] = node_id
        env["CA_NODE_RESOURCES"] = self._json.dumps(shape)
        env["CA_CONFIG_JSON"] = self.w.config.to_json()
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        node_dir = os.path.join(self.session_dir, "nodes", node_id)
        os.makedirs(node_dir, exist_ok=True)
        logf = open(os.path.join(node_dir, "agent.log"), "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "cluster_anywhere_tpu.core.nodeagent"],
            env=env,
            stdout=logf,
            stderr=subprocess.STDOUT,
            start_new_session=True,
        )
        logf.close()
        ready = os.path.join(node_dir, "agent.ready")
        deadline = time.monotonic() + 30
        while not os.path.exists(ready):
            if proc.poll() is not None or time.monotonic() > deadline:
                raise RuntimeError(f"agent node {node_id} failed to start")
            time.sleep(0.02)
        info = NodeInfo(
            node_id=node_id,
            node_type=node_type.name,
            resources=shape,
            handle=proc,
        )
        self.nodes[node_id] = info
        return info

    def terminate_node(self, node: NodeInfo) -> None:
        import signal

        if node.state == "terminated":
            return
        node.state = "terminated"
        proc = node.handle
        if proc is not None:
            try:
                os.kill(proc.pid, signal.SIGTERM)
                proc.wait(timeout=10)
            except (ProcessLookupError, subprocess.TimeoutExpired):
                try:
                    os.kill(proc.pid, signal.SIGKILL)
                except ProcessLookupError:
                    pass
        self.nodes.pop(node.node_id, None)

    def non_terminated_nodes(self) -> List[NodeInfo]:
        for n in list(self.nodes.values()):
            proc = n.handle
            if proc is not None and proc.poll() is not None:
                n.state = "terminated"  # crashed out from under us
                self.nodes.pop(n.node_id, None)
        return [n for n in self.nodes.values() if n.state != "terminated"]
