"""Node providers (analogue of the reference's
python/ray/autoscaler/node_provider.py NodeProvider + the fake_multi_node
local provider used in its tests).

A "node" contributes a fixed resource shape to the cluster. The
LocalNodeProvider launches real worker processes that register with the head
(the in-process analogue of launching a VM) and credits their capacity via
the head's update_resources RPC.
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class NodeType:
    name: str
    resources: Dict[str, float]
    max_nodes: int = 4
    labels: Optional[Dict[str, str]] = None  # scheduling labels for launched nodes


@dataclass
class NodeInfo:
    node_id: str
    node_type: str
    state: str = "running"  # launching | running | terminated
    created_at: float = field(default_factory=time.monotonic)
    resources: Dict[str, float] = field(default_factory=dict)
    handle: Any = None  # provider-private


class NodeProvider:
    def create_node(self, node_type: NodeType) -> NodeInfo:
        raise NotImplementedError

    def terminate_node(self, node: NodeInfo) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[NodeInfo]:
        raise NotImplementedError


def _drain_at_head(w, node_id: str, reason: str = "idle") -> bool:
    """Drain-then-kill, step one: ask the head to drain `node_id` (recall
    lease blocks, evacuate actors and sole-copy objects, let running tasks
    finish) and wait until the node reaches `drained`/`dead` — so provider
    termination never strands in-flight work.  Returns True once the node is
    out of the cluster; False when it never was a head node (LocalNodeProvider
    capacity credits), the head is unreachable, or the window expired (the
    caller falls back to the hard kill — exactly the old behavior)."""
    try:
        r = w.head_call("drain_node", node_id=node_id, reason=reason, timeout=5)
    except Exception:
        return False
    if r.get("state") in ("drained", "dead"):
        return True
    deadline = time.monotonic() + float(w.config.drain_deadline_s) + 10.0
    errors = 0
    while time.monotonic() < deadline:
        try:
            for n in w.head_call("nodes", timeout=5)["nodes"]:
                if n["node_id"] == node_id:
                    if n.get("state") in ("drained", "dead"):
                        return True
                    break
            else:
                return True  # gone from the table entirely
            errors = 0
        except Exception:
            # one dropped/slow poll must not abort a healthy mid-flight
            # drain into a hard kill; only a head that stays unreachable
            # ends the wait early
            errors += 1
            if errors >= 10:
                return False
        time.sleep(0.1)
    return False


class LocalNodeProvider(NodeProvider):
    """Launches worker processes against the connected cluster. Each "node"
    is `workers_per_node` pool worker processes plus a capacity credit."""

    def __init__(self, workers_per_node: Optional[int] = None):
        from ..core.worker import global_worker

        self.w = global_worker()
        self.nodes: Dict[str, NodeInfo] = {}
        self.workers_per_node = workers_per_node

    def _spawn_worker(self, node_id: str, index: int) -> subprocess.Popen:
        w = self.w
        wid = f"ext-{node_id}-{index}"
        addr = os.path.join(w.session_dir, f"{wid}.sock")
        env = dict(os.environ)
        env["CA_SESSION_DIR"] = w.session_dir
        env["CA_HEAD_SOCK"] = w.head_sock
        env["CA_WORKER_ID"] = wid
        env["CA_WORKER_SOCK"] = addr
        env["CA_CONFIG_JSON"] = w.config.to_json()
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        logf = open(os.path.join(w.session_dir, f"{wid}.log"), "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "cluster_anywhere_tpu.core.workerproc"],
            env=env,
            stdout=logf,
            stderr=subprocess.STDOUT,
            start_new_session=True,
        )
        logf.close()
        return proc

    def create_node(self, node_type: NodeType) -> NodeInfo:
        node_id = uuid.uuid4().hex[:8]
        n_workers = self.workers_per_node or max(1, int(node_type.resources.get("CPU", 1)))
        procs = [self._spawn_worker(node_id, i) for i in range(n_workers)]
        self.w.head_call("update_resources", delta=dict(node_type.resources))
        info = NodeInfo(
            node_id=node_id,
            node_type=node_type.name,
            resources=dict(node_type.resources),
            handle=procs,
        )
        self.nodes[node_id] = info
        return info

    def terminate_node(self, node: NodeInfo) -> None:
        import signal

        if node.state == "terminated":
            return
        node.state = "terminated"
        # drain-then-kill: this provider's "node" is ext-worker processes on
        # the head node (no head node record to drain), so the evacuation is
        # local — debit the capacity first so nothing NEW is granted on these
        # workers, then give in-flight leases until the drain deadline to
        # finish before the kill
        if node.resources:
            delta = {k: -v for k, v in node.resources.items()}
            self.w.head_call("update_resources", delta=delta)
        prefix = f"ext-{node.node_id}-"
        deadline = time.monotonic() + float(self.w.config.drain_deadline_s)
        killed: set = set()
        while time.monotonic() < deadline:
            try:
                mine = [
                    w
                    for w in self.w.head_call("list_workers")["workers"]
                    if w["worker_id"].startswith(prefix) and w["state"] != "dead"
                ]
            except Exception:
                break  # head gone: nothing to wait for
            busy = [w for w in mine if w["state"] in ("leased", "actor", "delegated")]
            # kill IDLE workers now: each one gone is one fewer slot a new
            # lease could land on mid-wait (and then die a budgeted death —
            # these workers never get a drain pub, n0 is not draining)
            for w in mine:
                if w not in busy and w["pid"] and w["pid"] not in killed:
                    killed.add(w["pid"])
                    try:
                        os.kill(w["pid"], signal.SIGKILL)
                    except ProcessLookupError:
                        pass
            if not busy:
                break
            time.sleep(0.1)
        for p in node.handle or []:
            try:
                os.kill(p.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
        self.nodes.pop(node.node_id, None)

    def non_terminated_nodes(self) -> List[NodeInfo]:
        return [n for n in self.nodes.values() if n.state != "terminated"]


class CommandRunnerNodeProvider(NodeProvider):
    """Launches nodes by executing user-supplied COMMANDS — the seam a real
    cloud deployment plugs into (reference autoscaler/_private/
    command_runner.py SSHCommandRunner role).  The provider knows nothing
    about transport: `launch_cmd` is typically
    ``ssh {host} 'ca join --head {head_addr} --node-id {node_id}
    --resources {resources_json}'`` with ``quote_levels=2`` (the JSON
    traverses the local AND remote shell) against a pool of machines, but
    any shell command that ends with the node registering at the head
    works (tests use a local `ca join` with the default quote_levels=1).

    Template variables: {host} {node_id} {head_addr} {resources_json}
    {labels_json}.  Liveness is judged by the HEAD's node table, not the
    runner process (an ssh session dying does not mean the node died);
    terminate falls back to killing the runner when no terminate_cmd is
    given (fine for local/ssh-with-tty runners)."""

    def __init__(
        self,
        hosts: List[str],
        launch_cmd: str,
        terminate_cmd: Optional[str] = None,
        wait_s: float = 60.0,
        quote_levels: int = 1,
    ):
        """quote_levels: how many shells the JSON template values traverse —
        1 for a local command, 2 for `ssh host '...'` (the remote shell
        word-splits again, so values need one more quoting layer)."""
        from ..core.worker import global_worker

        self.w = global_worker()
        self.session_dir = self.w.session_dir
        self.head_tcp = open(os.path.join(self.session_dir, "head.addr")).read().strip()
        if not self.head_tcp:
            raise RuntimeError("head has no TCP endpoint; cannot launch remote nodes")
        self.hosts = list(hosts)
        self.launch_cmd = launch_cmd
        self.terminate_cmd = terminate_cmd
        self.wait_s = wait_s
        self.quote_levels = max(1, int(quote_levels))
        self._host_of: Dict[str, str] = {}  # node_id -> host
        self.nodes: Dict[str, NodeInfo] = {}

    def _alive_at_head(self, node_id: str) -> bool:
        for n in self.w.head_call("nodes")["nodes"]:
            if n["node_id"] == node_id:
                return n["alive"]
        return False

    def _fmt(self, template: str, host: str, node_id: str, shape, labels) -> str:
        import json
        import shlex

        def q(s: str) -> str:
            for _ in range(self.quote_levels):
                s = shlex.quote(s)
            return s

        return template.format(
            host=host,
            node_id=node_id,
            head_addr=self.head_tcp,
            resources_json=q(json.dumps(shape)),
            labels_json=q(json.dumps(labels or {})),
        )

    def create_node(self, node_type: NodeType) -> NodeInfo:
        used = set(self._host_of.values())
        free = [h for h in self.hosts if h not in used]
        if not free:
            raise RuntimeError("no free hosts in the provider pool")
        host = free[0]
        node_id = f"cr-{uuid.uuid4().hex[:8]}"
        shape = dict(node_type.resources)
        shape.setdefault("memory", float(self.w.config.object_store_memory))
        if node_type.labels and "{labels_json}" not in self.launch_cmd:
            # fail loud: silently launching without the labels would strand
            # every NodeLabelSchedulingStrategy targeting this node type
            raise ValueError(
                f"node type {node_type.name!r} has labels but launch_cmd has no "
                "{labels_json} placeholder to carry them"
            )
        cmd = self._fmt(self.launch_cmd, host, node_id, shape, node_type.labels)
        logf = open(os.path.join(self.session_dir, f"runner-{node_id}.log"), "ab")
        proc = subprocess.Popen(
            cmd, shell=True, stdout=logf, stderr=subprocess.STDOUT,
            start_new_session=True,
        )
        logf.close()
        deadline = time.monotonic() + self.wait_s
        while not self._alive_at_head(node_id):
            if proc.poll() is not None and not self._alive_at_head(node_id):
                raise RuntimeError(
                    f"launch command exited rc={proc.returncode} before node "
                    f"{node_id} registered (see runner-{node_id}.log)"
                )
            if time.monotonic() > deadline:
                # kill the launcher before giving up: a node registering
                # AFTER the raise would be untracked live capacity on a host
                # the provider still considers free (double-booking)
                self._kill_runner(proc)
                raise RuntimeError(f"node {node_id} did not register within {self.wait_s}s")
            time.sleep(0.1)
        self._host_of[node_id] = host
        info = NodeInfo(
            node_id=node_id, node_type=node_type.name, resources=shape, handle=proc
        )
        self.nodes[node_id] = info
        return info

    @staticmethod
    def _kill_runner(proc) -> None:
        import signal

        if proc is None or proc.poll() is not None:
            return
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
            proc.wait(timeout=10)
        except (ProcessLookupError, subprocess.TimeoutExpired, PermissionError):
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass

    def terminate_node(self, node: NodeInfo) -> None:
        if node.state == "terminated":
            return
        node.state = "terminated"
        host = self._host_of.pop(node.node_id, "")
        # command-runner nodes are real agent nodes (ca join): evacuate via
        # the head before running the terminate command / killing the runner
        _drain_at_head(self.w, node.node_id, reason="idle")
        if self.terminate_cmd:
            try:
                subprocess.run(
                    self._fmt(self.terminate_cmd, host, node.node_id, node.resources, None),
                    shell=True,
                    timeout=30,
                )
            except (subprocess.TimeoutExpired, OSError):
                pass  # dead host: the runner kill below is the fallback
        self._kill_runner(node.handle)
        self.nodes.pop(node.node_id, None)

    def non_terminated_nodes(self) -> List[NodeInfo]:
        alive = {
            n["node_id"]: n["alive"] for n in self.w.head_call("nodes")["nodes"]
        }
        for n in list(self.nodes.values()):
            if not alive.get(n.node_id, False):
                # head declared it dead (crash, network cut): kill the
                # runner BEFORE freeing the host slot, or a lingering agent
                # would share the host with the reconciler's relaunch
                self._kill_runner(n.handle)
                n.state = "terminated"
                self._host_of.pop(n.node_id, None)
                self.nodes.pop(n.node_id, None)
        return [n for n in self.nodes.values() if n.state != "terminated"]


class AgentNodeProvider(NodeProvider):
    """Launches REAL node-agent processes against the connected cluster —
    each autoscaled "node" is a full raylet-analogue with its own worker
    pool, shm namespace, and TCP link to the head (the in-process analogue
    of a cloud provider booting a VM; reference fake_multi_node provider).

    Scheduling spillover, per-node stores, and node-death semantics all
    behave exactly as for cluster_utils.Cluster nodes, so autoscaled
    capacity is indistinguishable from statically added nodes."""

    def __init__(self):
        import json

        from ..core.worker import global_worker

        self.w = global_worker()
        self.session_dir = self.w.session_dir
        self.head_tcp = open(os.path.join(self.session_dir, "head.addr")).read().strip()
        if not self.head_tcp:
            raise RuntimeError("head has no TCP endpoint; cannot add agent nodes")
        self.nodes: Dict[str, NodeInfo] = {}
        self._json = json

    def create_node(self, node_type: NodeType) -> NodeInfo:
        node_id = f"as-{uuid.uuid4().hex[:8]}"
        shape = dict(node_type.resources)
        shape.setdefault("memory", float(self.w.config.object_store_memory))
        env = dict(os.environ)
        env["CA_SESSION_DIR"] = self.session_dir
        env["CA_HEAD_ADDR"] = self.head_tcp
        env["CA_NODE_ID"] = node_id
        env["CA_NODE_RESOURCES"] = self._json.dumps(shape)
        if node_type.labels:
            env["CA_NODE_LABELS"] = self._json.dumps(node_type.labels)
        env["CA_CONFIG_JSON"] = self.w.config.to_json()
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        node_dir = os.path.join(self.session_dir, "nodes", node_id)
        os.makedirs(node_dir, exist_ok=True)
        logf = open(os.path.join(node_dir, "agent.log"), "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "cluster_anywhere_tpu.core.nodeagent"],
            env=env,
            stdout=logf,
            stderr=subprocess.STDOUT,
            start_new_session=True,
        )
        logf.close()
        ready = os.path.join(node_dir, "agent.ready")
        deadline = time.monotonic() + 30
        while not os.path.exists(ready):
            if proc.poll() is not None or time.monotonic() > deadline:
                raise RuntimeError(f"agent node {node_id} failed to start")
            time.sleep(0.02)
        info = NodeInfo(
            node_id=node_id,
            node_type=node_type.name,
            resources=shape,
            handle=proc,
        )
        self.nodes[node_id] = info
        return info

    def terminate_node(self, node: NodeInfo) -> None:
        import signal

        if node.state == "terminated":
            return
        node.state = "terminated"
        proc = node.handle
        # drain-then-kill: evacuate through the head first (autoscaler
        # downscale must never strand in-flight tasks, actors, or sole-copy
        # objects).  On drain completion the head's node_shutdown notify makes
        # the agent exit on its own; the signals below are the fallback for
        # an unreachable head or a hung agent.
        drained = _drain_at_head(self.w, node.node_id, reason="idle")
        if proc is not None:
            try:
                proc.wait(timeout=10 if drained else 0.1)
            except subprocess.TimeoutExpired:
                try:
                    os.kill(proc.pid, signal.SIGTERM)
                    proc.wait(timeout=10)
                except (ProcessLookupError, subprocess.TimeoutExpired):
                    try:
                        os.kill(proc.pid, signal.SIGKILL)
                    except ProcessLookupError:
                        pass
        self.nodes.pop(node.node_id, None)

    def non_terminated_nodes(self) -> List[NodeInfo]:
        for n in list(self.nodes.values()):
            proc = n.handle
            if proc is not None and proc.poll() is not None:
                n.state = "terminated"  # crashed out from under us
                self.nodes.pop(n.node_id, None)
        return [n for n in self.nodes.values() if n.state != "terminated"]
