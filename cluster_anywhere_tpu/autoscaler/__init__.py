"""cluster_anywhere_tpu.autoscaler: declarative cluster autoscaling
(analogue of the reference's autoscaler v2, python/ray/autoscaler/v2/).

    from cluster_anywhere_tpu import autoscaler
    prov = autoscaler.LocalNodeProvider()
    asc = autoscaler.Autoscaler(prov, autoscaler.AutoscalerConfig(
        node_types=[autoscaler.NodeType("cpu2", {"CPU": 2.0})],
        idle_timeout_s=30,
    ))
    asc.start()
"""

from .provider import (
    AgentNodeProvider,
    CommandRunnerNodeProvider,
    LocalNodeProvider,
    NodeInfo,
    NodeProvider,
    NodeType,
)
from .reconciler import Autoscaler, AutoscalerConfig, Reconciler

__all__ = [
    "NodeProvider",
    "LocalNodeProvider",
    "AgentNodeProvider",
    "CommandRunnerNodeProvider",
    "NodeType",
    "NodeInfo",
    "Autoscaler",
    "AutoscalerConfig",
    "Reconciler",
]
