"""Flagship decoder-only transformer (LLaMA-style), TPU-first.

Design points (vs. the reference, which delegates all modeling to torch):
- pure-pytree params + functional forward: jit/grad/vmap compose freely
- layers stacked on a leading axis and iterated with `lax.scan` — one block
  gets compiled once regardless of depth (compile-time O(1) in layers)
- every parallelism axis is native: DP/FSDP/TP via GSPMD param/activation
  shardings (parallel.sharding), PP via the shard_map pipeline schedule
  (parallel.pipeline), SP via ring attention or Ulysses (parallel.ring_attention,
  parallel.ulysses) under a partial-manual shard_map over {'pp','sp'}
- bfloat16 activations, fp32 params/optimizer, RoPE, GQA, SwiGLU, RMSNorm

The model is the `entry()` / `dryrun_multichip()` flagship in
__graft_entry__.py and the subject of bench.py's training benchmark.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from ..ops.attention import attention as dense_attention
from ..parallel.pipeline import pipeline_apply
from ..parallel.ring_attention import ring_attention
from ..parallel.ulysses import ulysses_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 8
    d_head: int = 64
    d_ff: int = 1408
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16  # activation/compute dtype
    param_dtype: Any = jnp.float32
    attn_impl: str = "auto"  # dense | ring | ulysses | auto
    # flash kernel tile overrides (None = the kernel's measured defaults);
    # exposed so the bench can sweep tiles per shape without forking the model
    flash_block_q: Any = None
    flash_block_k: Any = None
    pp: int = 1
    sp: int = 1
    num_microbatches: int = 1
    remat: bool = False
    # unroll the layer scan: XLA overlaps each layer's weight streaming with
    # the previous layer's compute across iteration boundaries (a rolled
    # while-loop can't), worth ~12% a step on v5e; compile time grows with
    # depth, so deep stacks can turn it off
    unroll_layers: bool = True
    # mixture-of-experts FFN (parallel/moe.py switch-style top-1): every
    # layer's dense FFN becomes n_experts experts sharded over the 'ep' mesh
    # axis, tokens routed via all_to_all.  0 = dense.
    n_experts: int = 0
    ep: int = 1
    capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01

    @property
    def layers_per_stage(self) -> int:
        if self.n_layers % self.pp != 0:
            raise ValueError(f"n_layers {self.n_layers} not divisible by pp {self.pp}")
        return self.n_layers // self.pp

    def resolved_attn(self) -> str:
        if self.attn_impl != "auto":
            return self.attn_impl
        return "ring" if self.sp > 1 else "dense"


# ---------------------------------------------------------------------------
# init
# ---------------------------------------------------------------------------


def _init_block(key, cfg: TransformerConfig):
    e, h, kv, d, f = cfg.d_model, cfg.n_heads, cfg.n_kv_heads, cfg.d_head, cfg.d_ff
    ks = jax.random.split(key, 7)
    s = lambda fan_in: fan_in ** -0.5
    pd = cfg.param_dtype
    out = {
        "ln1": jnp.ones((e,), pd),
        "wq": jax.random.normal(ks[0], (e, h * d), pd) * s(e),
        "wk": jax.random.normal(ks[1], (e, kv * d), pd) * s(e),
        "wv": jax.random.normal(ks[2], (e, kv * d), pd) * s(e),
        "wo": jax.random.normal(ks[3], (h * d, e), pd) * s(h * d),
        "ln2": jnp.ones((e,), pd),
    }
    if cfg.n_experts:
        from ..parallel.moe import init_moe_params

        out.update(init_moe_params(ks[4], e, f, cfg.n_experts, pd))
    else:
        out.update(
            {
                "w_gate": jax.random.normal(ks[4], (e, f), pd) * s(e),
                "w_up": jax.random.normal(ks[5], (e, f), pd) * s(e),
                "w_down": jax.random.normal(ks[6], (f, e), pd) * s(f),
            }
        )
    return out


def init_params(key, cfg: TransformerConfig) -> Dict[str, Any]:
    k_embed, k_blocks, k_head = jax.random.split(key, 3)
    block_keys = jax.random.split(k_blocks, cfg.n_layers)
    blocks = jax.vmap(lambda k: _init_block(k, cfg))(block_keys)
    if cfg.pp > 1:
        # restack [L, ...] -> [pp, L/pp, ...] for stage sharding
        blocks = jax.tree_util.tree_map(
            lambda x: x.reshape(cfg.pp, cfg.layers_per_stage, *x.shape[1:]), blocks
        )
    return {
        "embed": jax.random.normal(k_embed, (cfg.vocab_size, cfg.d_model), cfg.param_dtype)
        * 0.02,
        "blocks": blocks,
        "ln_f": jnp.ones((cfg.d_model,), cfg.param_dtype),
        "lm_head": jax.random.normal(k_head, (cfg.d_model, cfg.vocab_size), cfg.param_dtype)
        * cfg.d_model ** -0.5,
    }


def param_specs(cfg: TransformerConfig) -> Dict[str, Any]:
    """PartitionSpecs: tp shards head/ff/vocab dims, fsdp shards the other
    matmul dim, pp shards the stage axis of stacked blocks."""
    lead = ("pp", None) if cfg.pp > 1 else (None,)

    def blk(*spec):
        return P(*lead, *spec)

    blocks: Dict[str, Any] = {
        "ln1": blk(None),
        "wq": blk("fsdp", "tp"),
        "wk": blk("fsdp", "tp"),
        "wv": blk("fsdp", "tp"),
        "wo": blk("tp", "fsdp"),
        "ln2": blk(None),
    }
    if cfg.n_experts:
        blocks.update(
            {
                # experts sharded over 'ep'; each expert's matmuls tp-sharded
                "router": blk(None, None),
                "w_in": blk("ep", "fsdp", "tp"),
                "w_out": blk("ep", "tp", "fsdp"),
            }
        )
    else:
        blocks.update(
            {
                "w_gate": blk("fsdp", "tp"),
                "w_up": blk("fsdp", "tp"),
                "w_down": blk("tp", "fsdp"),
            }
        )
    return {
        "embed": P("fsdp", "tp"),
        "blocks": blocks,
        "ln_f": P(None),
        "lm_head": P("fsdp", "tp"),
    }


def shard_params(params, cfg: TransformerConfig, mesh):
    """Annotate params with their mesh shardings.  Skipped on a single-device
    mesh: NamedSharding-constrained inputs put XLA through the SPMD
    partitioner's layout constraints, which measured 10x slower per train
    step on one TPU chip (167 ms -> 1627 ms at the bench config) for zero
    benefit."""
    if mesh is None or mesh.size <= 1:
        return params
    specs = param_specs(cfg)
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs,
        is_leaf=lambda x: isinstance(x, P),
    )


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _rms_norm(x, w, eps=1e-6):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * lax.rsqrt(var + eps).astype(x.dtype)) * w.astype(x.dtype)


def _rope(q, k, positions, cfg: TransformerConfig):
    """Rotary embeddings; q,k: [B, T, H, D]. positions: [T] global positions,
    or [B, T] per-row positions (left-padded prompts shift each row's real
    tokens to start at position 0)."""
    d = cfg.d_head
    freqs = cfg.rope_theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [..., T, D/2]
    if angles.ndim == 2:
        angles = angles[None]  # broadcast over batch
    cos = jnp.cos(angles)[:, :, None, :]  # [B|1, T, 1, D/2]
    sin = jnp.sin(angles)[:, :, None, :]

    def rot(x):
        x1, x2 = x[..., 0::2], x[..., 1::2]
        xr1 = x1 * cos - x2 * sin
        xr2 = x2 * cos + x1 * sin
        return jnp.stack([xr1, xr2], axis=-1).reshape(x.shape)

    return rot(q.astype(jnp.float32)).astype(q.dtype), rot(k.astype(jnp.float32)).astype(
        k.dtype
    )


def _attention(q, k, v, cfg: TransformerConfig, sp_manual: bool):
    impl = cfg.resolved_attn()
    if impl == "ring" and sp_manual:
        return ring_attention(q, k, v, axis_name="sp", causal=True)
    if impl == "ulysses" and sp_manual:
        return ulysses_attention(q, k, v, axis_name="sp", causal=True)
    if impl == "jnp":  # force the XLA-fused dense path (perf A/B)
        from ..ops.attention import reference_attention

        return reference_attention(q, k, v, causal=True)
    if impl == "flash":  # force the Pallas kernel (perf A/B)
        from ..ops.attention import flash_attention

        return flash_attention(
            q, k, v, causal=True,
            block_q=cfg.flash_block_q, block_k=cfg.flash_block_k,
        )
    # auto dense path: the dispatcher picks per shape/platform
    return dense_attention(q, k, v, causal=True)


def _block_forward(bp, x, cfg: TransformerConfig, sp_manual: bool):
    """One transformer block. x: [B, T_local, E]."""
    b, t, e = x.shape
    h, kv, d = cfg.n_heads, cfg.n_kv_heads, cfg.d_head
    dt = x.dtype

    y = _rms_norm(x, bp["ln1"])
    q = (y @ bp["wq"].astype(dt)).reshape(b, t, h, d)
    k = (y @ bp["wk"].astype(dt)).reshape(b, t, kv, d)
    v = (y @ bp["wv"].astype(dt)).reshape(b, t, kv, d)

    if sp_manual and cfg.sp > 1:
        offset = lax.axis_index("sp") * t
    else:
        offset = 0
    positions = offset + jnp.arange(t)
    q, k = _rope(q, k, positions, cfg)

    if kv != h:  # GQA: repeat kv heads
        rep = h // kv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)

    attn = _attention(q, k, v, cfg, sp_manual).reshape(b, t, h * d)
    x = x + attn @ bp["wo"].astype(dt)

    y = _rms_norm(x, bp["ln2"])
    if cfg.n_experts:
        # MoE FFN: tokens flatten, route to experts over 'ep', come back
        # (only traced under shard_map manual over 'ep' — see forward())
        from ..parallel.moe import moe_ffn

        r = moe_ffn(
            y.reshape(b * t, e),
            bp["router"].astype(dt),
            bp["w_in"].astype(dt),
            bp["w_out"].astype(dt),
            axis_name="ep",
            capacity_factor=cfg.capacity_factor,
        )
        x = x + r.out.reshape(b, t, e)
        return x, r.aux_loss.astype(jnp.float32)
    gated = jax.nn.silu(y @ bp["w_gate"].astype(dt)) * (y @ bp["w_up"].astype(dt))
    x = x + gated @ bp["w_down"].astype(dt)
    return x, jnp.zeros((), jnp.float32)


def _stage_forward(stage_blocks, x, cfg: TransformerConfig, sp_manual: bool):
    """Scan over this stage's layers. stage_blocks leaves: [L_stage, ...].
    Returns (x, aux) — aux is the summed MoE load-balance loss (0 dense)."""
    block = functools.partial(_block_forward, cfg=cfg, sp_manual=sp_manual)
    if cfg.remat:
        block = jax.checkpoint(block)

    def body(carry, bp):
        x, aux = carry
        x, a = block(bp, x)
        return (x, aux + a), None

    (x, aux), _ = lax.scan(
        body,
        (x, jnp.zeros((), jnp.float32)),
        stage_blocks,
        unroll=True if cfg.unroll_layers else 1,
    )
    return x, aux


def forward(params, ids, cfg: TransformerConfig, mesh=None, return_aux: bool = False):
    """ids: [B, T] int32 -> logits [B, T, V] (with the MoE load-balance aux
    loss when return_aux; 0 for dense configs)."""
    x = params["embed"].astype(cfg.dtype)[ids]  # [B, T, E]
    manual_axes = set()
    if cfg.pp > 1:
        manual_axes.add("pp")
    if cfg.sp > 1 and cfg.resolved_attn() in ("ring", "ulysses"):
        manual_axes.add("sp")
    if cfg.n_experts:
        manual_axes.add("ep")

    if manual_axes:
        if mesh is None:
            raise ValueError("mesh required for pp/sp/ep execution")
        if cfg.n_experts:
            mesh_ep = mesh.shape["ep"]
            if cfg.ep > 1 and cfg.ep != mesh_ep:
                raise ValueError(
                    f"cfg.ep={cfg.ep} disagrees with the mesh's ep axis ({mesh_ep})"
                )
            if cfg.n_experts % mesh_ep != 0:
                raise ValueError(
                    f"n_experts={cfg.n_experts} not divisible by the mesh's "
                    f"ep axis ({mesh_ep})"
                )
        x, aux = _apply_blocks_manual(
            params["blocks"], x, cfg, mesh, frozenset(manual_axes)
        )
    else:
        x, aux = _stage_forward(params["blocks"], x, cfg, sp_manual=False)

    x = _rms_norm(x, params["ln_f"])
    logits = x @ params["lm_head"].astype(cfg.dtype)
    return (logits, aux) if return_aux else logits


def _apply_blocks_manual(blocks, x, cfg: TransformerConfig, mesh, manual_axes):
    """Run the block stack under shard_map, manual over {'pp','sp','ep'}
    (subset), GSPMD-auto over dp/fsdp/tp.  With 'ep' manual, the batch dim
    shards over experts' owner devices (tokens all_to_all inside moe_ffn)."""
    sp_manual = "sp" in manual_axes
    pp_manual = "pp" in manual_axes
    ep_manual = "ep" in manual_axes

    def inner(blocks_local, x_local):
        if pp_manual:
            my_blocks = jax.tree_util.tree_map(lambda p: p[0], blocks_local)
            stage = functools.partial(
                _stage_forward, cfg=cfg, sp_manual=sp_manual
            )
            if cfg.n_experts:
                # MoE through the pipeline: each stage's MoE layers
                # all_to_all over 'ep' inside their pipeline step; the
                # load-balance aux threads through the schedule (bubble
                # steps masked) and comes back psum'd over stages
                x_out, aux = pipeline_apply(
                    stage,
                    my_blocks,
                    x_local,
                    axis_name="pp",
                    num_microbatches=cfg.num_microbatches,
                    with_aux=True,
                )
            else:
                x_out = pipeline_apply(
                    lambda bp, a: stage(bp, a)[0],
                    my_blocks,
                    x_local,
                    axis_name="pp",
                    num_microbatches=cfg.num_microbatches,
                )
                aux = jnp.zeros((), jnp.float32)
        else:
            x_out, aux = _stage_forward(
                blocks_local, x_local, cfg=cfg, sp_manual=sp_manual
            )
        # the P() out-spec claims aux is replicated across EVERY manual axis;
        # each shard computed it over its own tokens, so reduce over all
        # (pp already reduced inside pipeline_apply)
        if ep_manual:
            aux = lax.pmean(aux, "ep")
        if sp_manual:
            aux = lax.pmean(aux, "sp")
        return x_out, aux

    def leaf_spec(path, _leaf):
        # expert tensors carry their 'ep' shard inside the manual region;
        # the leading stacked-layer axis (and pp stage axis) comes first
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        lead = ("pp",) if pp_manual else ()
        if ep_manual and name in ("w_in", "w_out"):
            return P(*lead, None, "ep")  # [.., L, n_experts, ...]
        return P(*lead) if lead else P()

    block_specs = jax.tree_util.tree_map_with_path(leaf_spec, blocks)
    batch_axis = "ep" if ep_manual else None
    x_spec = P(batch_axis, "sp" if sp_manual else None, None)
    aux_spec = P()
    from ..parallel.compat import shard_map as _shard_map

    out, aux = _shard_map(
        inner,
        mesh=mesh,
        in_specs=(block_specs, x_spec),
        out_specs=(x_spec, aux_spec),
        axis_names=frozenset(manual_axes),
        check_vma=False,
    )(blocks, x)
    return out, aux


# ---------------------------------------------------------------------------
# loss / train step
# ---------------------------------------------------------------------------


def cross_entropy_loss(logits, targets, mask=None):
    logits = logits.astype(jnp.float32)
    logz = jax.scipy.special.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = logz - gold
    if mask is not None:
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(nll)


def make_loss_fn(cfg: TransformerConfig, mesh=None):
    def loss_fn(params, batch):
        ids = batch["ids"]  # [B, T+1]
        logits, aux = forward(params, ids[:, :-1], cfg, mesh, return_aux=True)
        loss = cross_entropy_loss(logits, ids[:, 1:])
        if cfg.n_experts:
            loss = loss + cfg.moe_aux_weight * aux
        return loss

    return loss_fn


def make_train_step(cfg: TransformerConfig, mesh, optimizer=None, learning_rate=3e-4):
    """Returns (train_step, init_state). train_step is jittable:
    (params, opt_state, batch) -> (params, opt_state, loss)."""
    import optax

    if optimizer is None:
        optimizer = optax.adamw(learning_rate, weight_decay=0.01)
    loss_fn = make_loss_fn(cfg, mesh)

    def train_step(params, opt_state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    def init_state(key):
        params = init_params(key, cfg)
        params = shard_params(params, cfg, mesh)
        opt_state = optimizer.init(params)  # inherits param shardings
        return params, opt_state

    return train_step, init_state


def make_batch_sharding(cfg: TransformerConfig, mesh):
    """Input batch sharding: batch over (dp, fsdp), sequence over sp."""
    return NamedSharding(mesh, P(("dp", "fsdp"), "sp" if cfg.sp > 1 else None))
