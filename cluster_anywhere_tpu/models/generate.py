"""Autoregressive generation with a KV cache for the flagship transformer
(the decode path the reference delegates to vLLM; here TPU-native:
static-shape cache + `lax.scan` decode loop so the whole generate compiles
into one XLA program).

Cache layout: one stacked pytree over layers —
    k, v: [L, B, T_max, H_kv, D]
Decode steps write slot `pos` with `lax.dynamic_update_slice` and attend over
the full T_max with a position mask (static shapes; no recompilation per
step).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .transformer import TransformerConfig, _rms_norm, _rope


def _project_qkv(bp, y, cfg: TransformerConfig):
    b, t, _ = y.shape
    h, kv, d = cfg.n_heads, cfg.n_kv_heads, cfg.d_head
    dt = y.dtype
    q = (y @ bp["wq"].astype(dt)).reshape(b, t, h, d)
    k = (y @ bp["wk"].astype(dt)).reshape(b, t, kv, d)
    v = (y @ bp["wv"].astype(dt)).reshape(b, t, kv, d)
    return q, k, v


def _gqa_repeat(x, cfg: TransformerConfig):
    if cfg.n_kv_heads != cfg.n_heads:
        x = jnp.repeat(x, cfg.n_heads // cfg.n_kv_heads, axis=2)
    return x


def _mlp(bp, x, cfg):
    dt = x.dtype
    y = _rms_norm(x, bp["ln2"])
    if cfg.n_experts:
        return x + _moe_infer(bp, y, cfg)
    gated = jax.nn.silu(y @ bp["w_gate"].astype(dt)) * (y @ bp["w_up"].astype(dt))
    return x + gated @ bp["w_down"].astype(dt)


_MOE_CHUNK = 64  # prefill tokens per all-experts pass (bounds [B,c,X,F])


def _moe_infer(bp, y, cfg: TransformerConfig):
    """MoE inference FFN delta: compute every expert and mask by the top-1
    route.  Single-host decode has no 'ep' axis to all_to_all over; the
    all-experts einsum stays MXU-shaped and drops nothing (capacity is a
    train-time constraint).  The FLOP cost is n_experts x the routed path —
    fine for the modest expert counts this serves; prefill is CHUNKED over
    the prompt so the [B, chunk, X, F] intermediate stays bounded instead
    of materializing [B, T, X, F] for long prompts.  (A capacity-dispatch
    prefill like parallel/moe.py would cut the FLOPs too; do that if MoE
    serving ever needs big expert counts.)"""
    dt = y.dtype

    def dense_pass(y_c):  # [B, c, E] -> [B, c, E]
        logits = (y_c @ bp["router"].astype(dt)).astype(jnp.float32)
        probs = jax.nn.softmax(logits, axis=-1)
        idx = jnp.argmax(probs, axis=-1)
        gate = jnp.take_along_axis(probs, idx[..., None], axis=-1)[..., 0]
        h = jax.nn.silu(jnp.einsum("bte,xef->btxf", y_c, bp["w_in"].astype(dt)))
        out_x = jnp.einsum("btxf,xfe->btxe", h, bp["w_out"].astype(dt))
        pick = jax.nn.one_hot(idx, out_x.shape[2], dtype=dt) * gate[..., None].astype(dt)
        return jnp.einsum("btxe,btx->bte", out_x, pick)

    b, t, e = y.shape
    if t <= _MOE_CHUNK:
        return dense_pass(y)
    pad = (-t) % _MOE_CHUNK
    yp = jnp.pad(y, ((0, 0), (0, pad), (0, 0)))
    nc = (t + pad) // _MOE_CHUNK
    chunks = yp.reshape(b, nc, _MOE_CHUNK, e).transpose(1, 0, 2, 3)
    out = lax.map(dense_pass, chunks)  # [nc, B, c, E]
    out = out.transpose(1, 0, 2, 3).reshape(b, t + pad, e)
    return out[:, :t]


def _masked_attention(q, k_cache, v_cache, valid_len, cfg: TransformerConfig, pad=None):
    """q: [B, Tq, H, D]; caches: [B, T_max, H, D]; cache slots >= valid_len are
    masked out, as are slots < pad[b] (left-padding of the prompt; pad is a
    per-row [B] count of pad tokens, None = no padding). For decode Tq == 1."""
    scale = cfg.d_head ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), k_cache.astype(jnp.float32))
    logits = logits * scale
    t_max = k_cache.shape[1]
    slots = jnp.arange(t_max)[None, None, None, :]
    mask = slots < valid_len
    if pad is not None:
        mask = mask & (slots >= pad[:, None, None, None])
    logits = jnp.where(mask, logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v_cache)


def init_cache(cfg: TransformerConfig, batch: int, t_max: int):
    shape = (cfg.n_layers, batch, t_max, cfg.n_kv_heads, cfg.d_head)
    return {
        "k": jnp.zeros(shape, cfg.dtype),
        "v": jnp.zeros(shape, cfg.dtype),
    }


def _block_decode(bp, x, layer_cache, pos, cfg: TransformerConfig, pad=None):
    """One block, one token. x: [B, 1, E]; layer_cache: (k,v) [B,Tmax,KV,D].
    pad: [B] left-pad counts — the RoPE position of the token written at cache
    slot `pos` is `pos - pad[b]` so each row's positions count real tokens."""
    k_cache, v_cache = layer_cache
    y = _rms_norm(x, bp["ln1"])
    q, k, v = _project_qkv(bp, y, cfg)
    if pad is None:
        positions = jnp.array([0]) + pos  # [1]
    else:
        positions = (pos - pad)[:, None]  # [B, 1]
    q, k = _rope(q, k, positions, cfg)
    k_cache = lax.dynamic_update_slice(k_cache, k, (0, pos, 0, 0))
    v_cache = lax.dynamic_update_slice(v_cache, v, (0, pos, 0, 0))
    attn = _masked_attention(
        q, _gqa_repeat(k_cache, cfg), _gqa_repeat(v_cache, cfg), pos + 1, cfg, pad
    )
    b = x.shape[0]
    x = x + attn.reshape(b, 1, -1) @ bp["wo"].astype(x.dtype)
    return _mlp(bp, x, cfg), (k_cache, v_cache)


def _block_decode_rowpos(bp, x, layer_cache, pos, cfg: TransformerConfig, pads):
    """One block, one token, PER-ROW cache positions (continuous batching:
    every slot decodes at its own depth).  x: [B, 1, E]; pos/pads: [B];
    layer_cache: (k, v) [B, Tmax, KV, D].  Row b writes its k/v at slot
    pos[b], takes RoPE position pos[b] - pads[b], and attends to cache
    slots [pads[b], pos[b]]."""
    k_cache, v_cache = layer_cache
    y = _rms_norm(x, bp["ln1"])
    q, k, v = _project_qkv(bp, y, cfg)
    positions = (pos - pads)[:, None]  # [B, 1]
    q, k = _rope(q, k, positions, cfg)
    b = x.shape[0]
    rows = jnp.arange(b)
    k_cache = k_cache.at[rows, pos].set(k[:, 0])
    v_cache = v_cache.at[rows, pos].set(v[:, 0])
    attn = _masked_attention(
        q,
        _gqa_repeat(k_cache, cfg),
        _gqa_repeat(v_cache, cfg),
        (pos + 1)[:, None, None, None],  # per-row valid length
        cfg,
        pads,
    )
    x = x + attn.reshape(b, 1, -1) @ bp["wo"].astype(x.dtype)
    return _mlp(bp, x, cfg), (k_cache, v_cache)


def _prefill_block(bp, x, pad, cfg: TransformerConfig, t_max: int):
    """One block over the whole prompt; returns padded caches [B,Tmax,KV,D].
    pad: [B] per-row left-pad counts or None. Real tokens sit at columns
    [pad[b], T); they get RoPE positions starting at 0 and never attend to
    pad-token keys (ADVICE r1: unmasked pads skewed generation)."""
    b, t, _ = x.shape
    y = _rms_norm(x, bp["ln1"])
    q, k, v = _project_qkv(bp, y, cfg)
    if pad is None:
        positions = jnp.arange(t)
    else:
        positions = jnp.maximum(jnp.arange(t)[None, :] - pad[:, None], 0)  # [B,T]
    q, k = _rope(q, k, positions, cfg)
    k_cache = jnp.zeros((b, t_max, cfg.n_kv_heads, cfg.d_head), x.dtype)
    v_cache = jnp.zeros_like(k_cache)
    k_cache = lax.dynamic_update_slice(k_cache, k, (0, 0, 0, 0))
    v_cache = lax.dynamic_update_slice(v_cache, v, (0, 0, 0, 0))
    # causal attention within the prompt (q already has full heads; only
    # k/v need the GQA repeat).  Dispatches to the pad-masked Pallas flash
    # kernel on TPU when the prompt tiles (ops/attention.py), so long-prompt
    # prefill never materializes the [T, T] score matrix.
    from ..ops.attention import attention as _attn

    kr = _gqa_repeat(k, cfg)
    vr = _gqa_repeat(v, cfg)
    attn = _attn(q, kr, vr, causal=True, pad=pad).reshape(b, t, -1).astype(x.dtype)
    x = x + attn @ bp["wo"].astype(x.dtype)
    return _mlp(bp, x, cfg), (k_cache, v_cache)


def prefill(params, ids, cfg: TransformerConfig, t_max: int, pad=None):
    """ids: [B, T_prompt] -> (last-token logits [B, V], cache).
    pad: optional [B] left-pad counts (see _prefill_block)."""
    x = params["embed"].astype(cfg.dtype)[ids]

    def body(x, bp):
        x, (kc, vc) = _prefill_block(bp, x, pad, cfg, t_max)
        return x, (kc, vc)

    blocks = params["blocks"]
    x, (k_all, v_all) = lax.scan(body, x, blocks)
    x = _rms_norm(x, params["ln_f"])
    logits = x[:, -1] @ params["lm_head"].astype(cfg.dtype)
    return logits.astype(jnp.float32), {"k": k_all, "v": v_all}


def decode_one(params, cache, token, pos, cfg: TransformerConfig, pad=None):
    """token: [B] -> (logits [B, V], updated cache)."""
    x = params["embed"].astype(cfg.dtype)[token][:, None, :]  # [B,1,E]

    def body(x, inputs):
        bp, kc, vc = inputs
        x, (kc, vc) = _block_decode(bp, x, (kc, vc), pos, cfg, pad)
        return x, (kc, vc)

    x, (k_all, v_all) = lax.scan(body, x, (params["blocks"], cache["k"], cache["v"]))
    x = _rms_norm(x, params["ln_f"])
    logits = (x[:, 0] @ params["lm_head"].astype(cfg.dtype)).astype(jnp.float32)
    return logits, {"k": k_all, "v": v_all}


def _nucleus_mask(scaled, top_p):
    """Mask logits outside the smallest probability-mass prefix >= top_p
    (nucleus sampling).  top_p is TRACED; <= 0 or >= 1 disables.  The
    highest-probability token is always kept (its exclusive cumsum is 0)."""
    probs = jax.nn.softmax(scaled, axis=-1)
    sorted_p = -jnp.sort(-probs, axis=-1)
    cum_excl = jnp.cumsum(sorted_p, axis=-1) - sorted_p
    included = cum_excl < top_p
    thresh = jnp.min(
        jnp.where(included, sorted_p, jnp.inf), axis=-1, keepdims=True
    )
    apply = (top_p > 0.0) & (top_p < 1.0)
    return jnp.where(apply & (probs < thresh), -1e30, scaled)


def _sample(logits, rng, temperature, top_k: int, top_p=1.0):
    """temperature/top_p are traced (no recompile per request value); top_k
    stays static (lax.top_k needs a static k). temperature <= 0 means
    greedy; top_p in (0, 1) applies nucleus truncation."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    t = jnp.maximum(temperature, 1e-6)
    scaled = logits / t
    if top_k > 0:
        top = lax.top_k(scaled, top_k)[0][..., -1:]
        scaled = jnp.where(scaled < top, -1e30, scaled)
    # statically skip a guaranteed no-op mask (python-float defaults): the
    # nucleus pass costs a full-vocab softmax+sort per step.  Traced top_p
    # (streaming/continuous paths) always runs it — the mask itself gates
    # on (0, 1) membership.
    if not (isinstance(top_p, (int, float)) and not (0.0 < float(top_p) < 1.0)):
        scaled = _nucleus_mask(scaled, top_p)
    sampled = jax.random.categorical(rng, scaled).astype(jnp.int32)
    return jnp.where(temperature <= 0.0, greedy, sampled)


@functools.partial(jax.jit, static_argnames=("cfg", "max_new_tokens", "top_k", "top_p"))
def generate(
    params,
    prompt_ids,
    rng,
    *,
    cfg: TransformerConfig,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    prompt_lens: Optional[jax.Array] = None,
) -> jax.Array:
    """prompt_ids: [B, T_prompt] int32 -> generated ids [B, max_new_tokens].
    One compiled program: prefill + a lax.scan of decode steps.
    prompt_lens: optional [B] int32 count of real (rightmost) tokens per row
    when prompts are left-padded to a fixed T_prompt; pads are masked out of
    attention and RoPE positions count real tokens only."""
    b, t_prompt = prompt_ids.shape
    t_max = t_prompt + max_new_tokens
    pad = None if prompt_lens is None else (t_prompt - prompt_lens).astype(jnp.int32)
    logits, cache = prefill(params, prompt_ids, cfg, t_max, pad)
    rngs = jax.random.split(rng, max_new_tokens)
    first = _sample(logits, rngs[0], temperature, top_k, top_p)

    def step(carry, rng_i):
        token, cache, pos = carry
        logits, cache = decode_one(params, cache, token, pos, cfg, pad)
        nxt = _sample(logits, rng_i, temperature, top_k, top_p)
        return (nxt, cache, pos + 1), nxt

    (_, _, _), tokens = lax.scan(
        step, (first, cache, jnp.int32(t_prompt)), rngs[1:]
    )
    # tokens: the N-1 follow-on samples; prepend the prefill sample
    out = jnp.concatenate([first[None], tokens], axis=0)
    return out.T  # [B, N]


@functools.lru_cache(maxsize=8)
def _stream_fns(cfg: TransformerConfig, t_prompt: int, t_max: int, top_k: int):
    """Jitted prefill+sample and single-decode-step closures for streaming
    decoding (compiled once per shape/config/top_k; temperature and top_p
    are TRACED operands, so per-request values never recompile)."""

    def _prefill(params, ids, pad, rng, temperature, top_p):
        logits, cache = prefill(params, ids, cfg, t_max, pad)
        return _sample(logits, rng, temperature, top_k, top_p), cache

    def _step(params, cache, token, pos, pad, rng, temperature, top_p):
        logits, cache = decode_one(params, cache, token, pos, cfg, pad)
        return _sample(logits, rng, temperature, top_k, top_p), cache

    return jax.jit(_prefill), jax.jit(_step)


def stream_generate(
    params,
    prompt_ids,
    rng,
    *,
    cfg: TransformerConfig,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
    prompt_lens: Optional[jax.Array] = None,
):
    """Python generator yielding one [B] int32 token array per decode step.

    The interactive/streaming counterpart of generate(): a host loop over a
    jitted single decode step, so each token is observable as soon as it is
    sampled (wired to num_returns="streaming" actor methods by the LLM
    layer).  generate()'s scanned loop remains the throughput path."""
    import numpy as np

    b, t_prompt = prompt_ids.shape
    t_max = t_prompt + max_new_tokens
    pad = None if prompt_lens is None else (t_prompt - prompt_lens).astype(jnp.int32)
    pre, step = _stream_fns(cfg, t_prompt, t_max, int(top_k))
    temp_op = jnp.float32(temperature)
    top_p_op = jnp.float32(top_p)
    rngs = jax.random.split(rng, max_new_tokens)
    token, cache = pre(params, prompt_ids, pad, rngs[0], temp_op, top_p_op)
    yield np.asarray(token)
    pos = t_prompt
    for i in range(1, max_new_tokens):
        token, cache = step(params, cache, token, jnp.int32(pos), pad, rngs[i], temp_op, top_p_op)
        pos += 1
        yield np.asarray(token)
