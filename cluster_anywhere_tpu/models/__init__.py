from .transformer import (
    TransformerConfig,
    cross_entropy_loss,
    forward,
    init_params,
    make_loss_fn,
    make_train_step,
    param_specs,
    shard_params,
)

__all__ = [
    "TransformerConfig",
    "init_params",
    "forward",
    "param_specs",
    "shard_params",
    "cross_entropy_loss",
    "make_loss_fn",
    "make_train_step",
]
