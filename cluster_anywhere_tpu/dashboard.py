"""HTTP dashboard served by the head process (compact analogue of the
reference's dashboard/head.py + state aggregator modules: cluster status over
HTTP for humans and tools).

Endpoints:
  GET /               single-page HTML UI (auto-refreshing)
  GET /api/summary    nodes/resources/stats in one call
  GET /api/nodes      node table
  GET /api/actors     actor table
  GET /api/workers    worker table
  GET /api/objects    object directory sample
  GET /api/tasks      recent task events
  GET /api/pgs        placement groups
  GET /api/serve      serving plane (replica targets, drain, last autoscale)
  GET /api/flightrec  flight-recorder journal (trace/plane/node/event filters)
  GET /metrics        Prometheus text (user + runtime metrics)

Zero extra process: the head owns every table locally, so requests are
answered without RPC.  The listen address is written to
<session>/dashboard.addr.
"""

from __future__ import annotations

import asyncio
import json
import os
import subprocess
import threading
import time
import uuid
from typing import Any

_PAGE = """<!doctype html>
<html><head><title>cluster_anywhere_tpu dashboard</title>
<style>
body { font-family: ui-monospace, monospace; margin: 24px; background: #101418; color: #d8dee6; }
h1 { font-size: 18px; } h2 { font-size: 14px; margin: 18px 0 6px; color: #8ab4f8; }
table { border-collapse: collapse; width: 100%; font-size: 12px; }
th, td { text-align: left; padding: 3px 10px; border-bottom: 1px solid #2a3038; }
th { color: #9aa5b1; font-weight: 600; }
.ok { color: #7ee787; } .bad { color: #ff7b72; } .warn { color: #e3b341; }
#res { font-size: 13px; margin: 8px 0; }
#tl { position: relative; background: #161b22; border: 1px solid #2a3038; margin-top: 4px; }
.lane-label { position: absolute; left: 4px; font-size: 10px; color: #9aa5b1; }
.bar { position: absolute; height: 12px; border-radius: 2px; min-width: 2px; }
.bar.FINISHED { background: #2ea04366; border: 1px solid #7ee787; }
.bar.FAILED { background: #da363366; border: 1px solid #ff7b72; }
.bar.PENDING { background: #6e768166; border: 1px solid #9aa5b1; }
.bar.SCHED { background: #e3b34144; border: 1px solid #e3b341; }
#tlaxis { font-size: 10px; color: #9aa5b1; }
</style></head><body>
<h1>cluster_anywhere_tpu</h1>
<div id="res"></div>
<h2>Metrics <span id="tsmeta" style="color:#9aa5b1;font-weight:400"></span></h2>
<div id="sparks" style="display:flex;flex-wrap:wrap;gap:14px"></div>
<h2>Nodes</h2><table id="nodes"></table>
<h2>Actors</h2><table id="actors"></table>
<h2>Workers</h2><table id="workers"></table>
<h2>Jobs</h2><table id="jobs"></table>
<h2>Placement groups</h2><table id="pgs"></table>
<h2>Task timeline <span id="tlaxis"></span></h2><div id="tl"></div>
<h2>Recent tasks</h2><table id="tasks"></table>
<h2>Flight recorder <span id="frstats" style="color:#9aa5b1;font-weight:400"></span></h2>
<table id="flightrec"></table>
<h2>Logs <select id="logsel"><option value="">(choose a process)</option></select>
<span id="logstats"></span></h2>
<pre id="logview" style="background:#161b22;border:1px solid #2a3038;padding:8px;
max-height:300px;overflow:auto;font-size:11px;white-space:pre-wrap"></pre>
<script>
function row(cells, tag) {
  return "<tr>" + cells.map(c => "<" + (tag||"td") + ">" + c + "</" + (tag||"td") + ">").join("") + "</tr>";
}
function esc(s) {
  // attribute-safe: esc() output lands inside title="..." too
  return String(s == null ? "" : s).replace(/&/g, "&amp;").replace(/</g, "&lt;")
    .replace(/"/g, "&quot;");
}
function timeline(events) {
  // chrome-trace-style lanes: one per worker, bars = task spans.  With
  // tracing enabled the ring also carries lifecycle phase events
  // (SUBMITTED/QUEUED/SCHEDULED/RUNNING without start/end); those prepend
  // grey (pending at the submitter) and yellow (scheduled -> running)
  // segments before each green/red execute bar.
  const el = document.getElementById("tl");
  const byTask = {};
  events.forEach(e => {
    if (!e.task_id) return;
    (byTask[e.task_id] = byTask[e.task_id] || []).push(e);
  });
  const segs = [];
  let nSpans = 0;
  for (const evs of Object.values(byTask)) {
    const term = evs.find(e => e.end && e.start);
    if (!term) continue;
    nSpans++;
    const ph = {};
    evs.forEach(e => { if (!e.end && e.ts) ph[e.state] = e.ts; });
    const title = term.name + " (" + term.type + ")";
    const runStart = ph.RUNNING || term.start;
    if (ph.SUBMITTED && ph.SUBMITTED < runStart) {
      const schedAt = ph.SCHEDULED || runStart;
      segs.push({w: term.worker_id, s: ph.SUBMITTED, e: schedAt,
                 cls: "PENDING", title: title + " pending"});
      if (schedAt < runStart)
        segs.push({w: term.worker_id, s: schedAt, e: runStart,
                   cls: "SCHED", title: title + " scheduled"});
    }
    segs.push({w: term.worker_id, s: term.start, e: term.end, cls: term.state,
               title: title + " " + ((term.end - term.start) * 1000).toFixed(1) + " ms"});
  }
  if (!segs.length) { el.style.height = "20px"; el.innerHTML = ""; return; }
  const t0 = Math.min(...segs.map(t => t.s));
  const t1 = Math.max(...segs.map(t => t.e));
  const span = Math.max(t1 - t0, 1e-6);
  const lanes = [...new Set(segs.map(t => t.w))];
  const W = el.clientWidth || 900, LH = 16, PAD = 70;
  el.style.height = (lanes.length * LH + 4) + "px";
  let html = "";
  lanes.forEach((w, i) => {
    html += '<div class="lane-label" style="top:' + (i * LH + 2) + 'px">' + esc(w) + "</div>";
  });
  segs.forEach(t => {
    const lane = lanes.indexOf(t.w);
    const x = PAD + (t.s - t0) / span * (W - PAD - 8);
    const w = Math.max((t.e - t.s) / span * (W - PAD - 8), 2);
    html += '<div class="bar ' + esc(t.cls) + '" style="left:' + x + "px;top:" +
      (lane * LH + 2) + "px;width:" + w + 'px" title="' + esc(t.title) + '"></div>';
  });
  el.innerHTML = html;
  document.getElementById("tlaxis").textContent =
    "window " + (span).toFixed(2) + "s, " + nSpans + " spans";
}
async function refresh() {
  const s = await (await fetch("/api/summary")).json();
  document.getElementById("res").innerHTML =
    "CPU " + (s.total.CPU - (s.available.CPU||0)).toFixed(1) + "/" + (s.total.CPU||0) +
    " &nbsp; nodes " + s.stats.n_nodes + " &nbsp; workers " + s.stats.n_workers +
    " &nbsp; actors " + s.stats.n_actors + " &nbsp; objects " + s.stats.n_objects +
    " &nbsp; pending leases " + s.stats.pending_leases;
  const nodes = await (await fetch("/api/nodes")).json();
  document.getElementById("nodes").innerHTML = row(["node", "state", "head", "CPU avail/total", "workers", "leases used/delegated", "labels"], "th") +
    nodes.map(n => row([n.node_id,
      n.state == "alive" ? "<span class=ok>alive</span>" :
      n.state == "draining" ? "<span class=warn>draining " + esc((n.drain||{}).reason||"") +
        " " + ((n.drain||{}).deadline_in_s||0).toFixed(0) + "s</span>" :
      "<span class=bad>" + esc((n.state||"dead").toUpperCase()) + "</span>",
      n.is_head_node ? "*" : "", (n.available.CPU||0) + "/" + (n.resources.CPU||0), n.n_workers,
      esc(Object.entries(n.lease_blocks||{})
        .map(([p, b]) => p + " " + b.used + "/" + b.size).join(" ") || "-"),
      esc(Object.entries(n.labels||{}).filter(([k]) => k != "ca.io/node-id")
        .map(([k, v]) => k.replace("ca.io/", "") + "=" + v).join(" "))])).join("");
  const actors = await (await fetch("/api/actors")).json();
  document.getElementById("actors").innerHTML = row(["actor", "name", "state", "node", "restarts"], "th") +
    actors.slice(0, 50).map(a => row([a.actor_id.slice(0, 12), esc(a.name), a.state, a.node_id||"", a.incarnation])).join("");
  const workers = await (await fetch("/api/workers")).json();
  document.getElementById("workers").innerHTML = row(["worker", "pid", "state", "node"], "th") +
    workers.slice(0, 80).map(w => row([w.worker_id, w.pid, w.state, w.node_id])).join("");
  const jobs = await (await fetch("/api/jobs")).json();
  const jcls = {RUNNING: "warn", SUCCEEDED: "ok", FAILED: "bad", STOPPED: "bad"};
  document.getElementById("jobs").innerHTML = row(["job", "status", "entrypoint", "runtime s"], "th") +
    jobs.slice(0, 30).map(j => row([esc(j.submission_id),
      '<span class="' + (jcls[j.status]||"") + '">' + esc(j.status) + "</span>",
      esc((j.entrypoint||"").slice(0, 80)),
      (j.runtime_s == null ? "" : j.runtime_s.toFixed(1))])).join("");
  const pgs = await (await fetch("/api/pgs")).json();
  document.getElementById("pgs").innerHTML = row(["pg", "strategy", "state", "bundle nodes"], "th") +
    pgs.slice(0, 30).map(p => row([p.pg_id.slice(0, 12), p.strategy, p.state,
      esc((p.bundle_nodes||[]).join(" "))])).join("");
  const tasks = await (await fetch("/api/tasks?limit=600")).json();
  timeline(tasks);
  const done = tasks.filter(t => t.task_id && t.end && t.start && t.state != "SPAN");
  document.getElementById("tasks").innerHTML = row(["name", "type", "state", "worker", "ms"], "th") +
    done.slice(-30).reverse().map(t => row([esc(t.name), t.type, t.state, t.worker_id,
      ((t.end - t.start) * 1000).toFixed(1)])).join("");
}
async function refreshLogs() {
  const sel = document.getElementById("logsel");
  const ids = await (await fetch("/api/logs")).json();
  const cur = sel.value;
  sel.innerHTML = '<option value="">(choose a process)</option>' +
    ids.map(i => '<option' + (i === cur ? " selected" : "") + '>' + esc(i) + "</option>").join("");
  const lp = await (await fetch("/api/logplane")).json();
  document.getElementById("logstats").textContent =
    " lines " + (lp.ca_log_lines_total||0) + " shipped " + (lp.log_lines_shipped||0) +
    " dropped " + ((lp.ca_log_dropped_total||0) + (lp.log_lines_dropped||0));
  if (!sel.value) return;
  const r = await (await fetch("/api/logs?id=" + encodeURIComponent(sel.value) + "&tail=100")).json();
  document.getElementById("logview").textContent = r.data != null ? r.data : (r.error || "");
}
function spark(label, pts, unit) {
  // inline SVG sparkline over the tier-0 window (newest right)
  const W = 180, H = 36;
  let path = "", cur = "";
  if (pts.length > 1) {
    const vs = pts.map(p => p[1]);
    const vmax = Math.max(...vs, 1e-9), t0 = pts[0][0],
          span = Math.max(pts[pts.length-1][0] - t0, 1e-9);
    path = pts.map((p, i) =>
      (i ? "L" : "M") + ((p[0]-t0)/span*W).toFixed(1) + "," +
      (H - 2 - (p[1]/vmax)*(H-6)).toFixed(1)).join(" ");
    cur = vs[vs.length-1] >= 100 ? vs[vs.length-1].toFixed(0)
        : vs[vs.length-1].toPrecision(3);
  }
  return '<div style="background:#161b22;border:1px solid #2a3038;padding:6px 8px">' +
    '<div style="font-size:11px;color:#9aa5b1">' + esc(label) + "</div>" +
    '<svg width="' + W + '" height="' + H + '"><path d="' + path +
    '" fill="none" stroke="#8ab4f8" stroke-width="1.5"/></svg>' +
    '<div style="font-size:12px" class="ok">' + cur + " " + unit + "</div></div>";
}
async function refreshSparks() {
  // one sparkline per plane: core scheduling + the post-PR-7 planes
  // (dag / serve / train / transfer) + the flight recorder itself
  const names = [
    ["head_tasks_pushed", "tasks/s", 1],
    ["head_objects_created", "obj/s", 1],
    ["head_rpc_messages_recv", "msg/s", 1],
    ["ca_head_loop_lag_seconds", "ms lag", 0],
    ["head_nodes_draining", "draining", 0],
    ["ca_owner_owner_gc", "owner gc/s", 1],
    ["ca_dag_executions", "dag ticks/s", 1, "dag_executions"],
    ["ca_serve_request_latency_seconds_count", "req/s", 1, "serve_requests"],
    ["ca_serve_shed_total", "shed/s", 1, "serve_shed"],
    ["ca_train_preempt_restarts_total", "preempt/s", 1, "train_preempts"],
    ["ca_transfer_pulls", "pulls/s", 1, "transfer_pulls"],
    ["ca_flightrec_recorded", "ev/s", 1, "flightrec_events"],
  ];
  const r = await (await fetch("/api/timeseries?rate=1&names=" +
    names.map(n => n[0]).join(","))).json();
  if (r.meta && r.meta.disabled) return;
  let html = "";
  names.forEach(([n, unit, isRate, label]) => {
    const tagged = r.series[n];
    if (!tagged) return;
    let pts = Object.values(tagged)[0].points;
    if (n === "ca_head_loop_lag_seconds") pts = pts.map(p => [p[0], p[1]*1000]);
    if (pts.length > 1)
      html += spark(label || n.replace(/^head_|^ca_head_/, ""), pts, unit);
  });
  document.getElementById("sparks").innerHTML = html;
  document.getElementById("tsmeta").textContent =
    (r.meta.n_series||0) + " series, " +
    ((r.meta.memory_bytes||0)/1024).toFixed(0) + " KiB retained";
}
async function refreshFlight() {
  const r = await (await fetch("/api/flightrec?limit=25")).json();
  document.getElementById("frstats").textContent = r.enabled
    ? " " + (r.total||0) + " events retained"
    : " (disabled: flightrec_plane=false)";
  const evs = (r.events||[]).slice().reverse();
  document.getElementById("flightrec").innerHTML =
    row(["time", "node/proc", "event", "detail", "trace"], "th") +
    evs.map(e => {
      const extra = Object.entries(e)
        .filter(([k]) => !["ts","seq","plane","event","node","proc","trace"].includes(k))
        .map(([k, v]) => k + "=" + (typeof v === "object" ? JSON.stringify(v) : v))
        .join(" ");
      return row([new Date(e.ts * 1000).toLocaleTimeString(),
        esc((e.node||"") + (e.proc ? "/" + e.proc : "")),
        esc(e.plane + ":" + e.event), esc(extra.slice(0, 120)),
        esc(e.trace ? e.trace.tid : "")]);
    }).join("");
}
document.getElementById("logsel").addEventListener("change", refreshLogs);
refresh(); setInterval(refresh, 2000);
refreshLogs(); setInterval(refreshLogs, 3000);
refreshSparks(); setInterval(refreshSparks, 5000);
refreshFlight(); setInterval(refreshFlight, 4000);
</script></body></html>"""


class Dashboard:
    def __init__(self, head):
        self.head = head
        self._server = None
        self.addr = None
        self._loop = None
        self._rest_jobs = {}  # submission_id -> Popen (REST-submitted)

    async def start(self, host: str = "127.0.0.1", port: int = 0) -> str:
        self._loop = asyncio.get_running_loop()
        self._server = await asyncio.start_server(self._on_client, host, port)
        h, p = self._server.sockets[0].getsockname()[:2]
        self.addr = f"http://{h}:{p}"
        with open(os.path.join(self.head.session_dir, "dashboard.addr"), "w") as f:
            f.write(self.addr)
        return self.addr

    async def stop(self):
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    # ---------------------------------------------------------------- http
    async def _on_client(self, reader, writer):
        try:
            req = await asyncio.wait_for(reader.readline(), 10)
            parts = req.decode("latin1").split()
            if len(parts) < 2 or parts[0] not in ("GET", "POST"):
                await self._respond(writer, 405, "text/plain", b"GET/POST only")
                return
            method, path = parts[0], parts[1]
            clen = 0
            while True:  # drain headers, keep content-length
                line = await asyncio.wait_for(reader.readline(), 10)
                if line in (b"\r\n", b"\n", b""):
                    break
                k, _, v = line.decode("latin1").partition(":")
                if k.strip().lower() == "content-length":
                    try:
                        clen = min(int(v.strip()), 1 << 20)
                    except ValueError:
                        clen = 0
            body = (
                await asyncio.wait_for(reader.readexactly(clen), 10)
                if clen else b""
            )
            if method == "POST":
                status, ctype, resp = self._route_post(path, body)
            elif path.split("?", 1)[0] == "/api/logs":
                # async route: cross-node reads proxy through the owning
                # node's agent (head._log_fetch_data awaits the agent RPC)
                status, ctype, resp = await self._route_logs(path)
            else:
                status, ctype, resp = self._route(path)
            await self._respond(writer, status, ctype, resp)
        except asyncio.CancelledError:
            raise  # dashboard shutdown: the finally still closes the socket
        except Exception:
            pass
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _respond(self, writer, status: int, ctype: str, body: bytes):
        reason = {200: "OK", 404: "Not Found", 405: "Method Not Allowed"}.get(status, "OK")
        writer.write(
            f"HTTP/1.1 {status} {reason}\r\nContent-Type: {ctype}\r\n"
            f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n".encode()
        )
        writer.write(body)
        from .util.aio import drain

        await drain(writer, timeout=10)

    def _route(self, path: str):
        if "?" in path:
            path, _, query = path.partition("?")
        else:
            query = ""
        params = dict(p.partition("=")[::2] for p in query.split("&") if p)
        h = self.head
        if path == "/":
            return 200, "text/html", _PAGE.encode()
        if path == "/api/summary":
            return self._json(
                {
                    "total": h._agg_total(),
                    "available": h._agg_avail(),
                    "stats": dict(
                        h.stats,
                        pending_leases=len(h.pending_leases),
                        n_workers=sum(1 for w in h.workers.values() if w.state != "dead"),
                        n_actors=len(h.actors),
                        n_objects=len(h.objects),
                        n_nodes=len(h._alive_nodes()),
                    ),
                    # HA plane: role/epoch/replication state so the summary
                    # answers "can this cluster lose its head right now?"
                    "ha": h._ha_status_dict(),
                }
            )
        if path == "/api/nodes":
            return self._json(
                [
                    {
                        "node_id": n.node_id,
                        "alive": n.up,  # draining: up but unschedulable
                        "state": n.state,
                        "drain": (
                            {
                                "reason": n.drain_reason,
                                "deadline_in_s": round(
                                    max(
                                        0.0,
                                        n.drain_deadline - time.monotonic(),
                                    ),
                                    3,
                                ),
                            }
                            if n.state == "draining"
                            else None
                        ),
                        "is_head_node": n.is_local,
                        "resources": n.total,
                        "available": n.avail,
                        "load": n.load,
                        "labels": n.labels,
                        # delegated vs used lease-block capacity per pool:
                        # an exhausted block is diagnosable at a glance
                        "lease_blocks": h._node_lease_blocks(n),
                        "n_workers": sum(
                            1
                            for w in h.workers.values()
                            if w.node_id == n.node_id and w.state != "dead"
                        ),
                    }
                    for n in h.nodes.values()
                ]
            )
        if path == "/api/actors":
            return self._json([h._actor_info(a) for a in h.actors.values()])
        if path == "/api/workers":
            return self._json(
                [
                    {
                        "worker_id": w.worker_id, "pid": w.pid, "state": w.state,
                        "node_id": w.node_id, "actor_id": w.actor_id,
                    }
                    for w in h.workers.values()
                ]
            )
        if path == "/api/objects":
            limit = int(params.get("limit", 200))
            out = []
            for rec in list(h.objects.values())[:limit]:
                holders, ledger = h.digest_holders(rec)
                out.append(
                    {
                        "object_id": rec.oid.hex(), "size": rec.size,
                        "node_id": rec.node_id, "holders": holders,
                        "owner_ledger": ledger,
                        "spilled": rec.spill_path is not None,
                    }
                )
            return self._json(out)
        if path == "/api/jobs":
            # runtime computed with the SERVER clock (start_time is ours; a
            # skewed browser clock would show negative runtimes otherwise)
            now = time.time()
            out = []
            for v in self._job_kv().values():
                j = json.loads(v)
                if j.get("start_time"):
                    j["runtime_s"] = (j.get("end_time") or now) - j["start_time"]
                out.append(j)
            return self._json(out)
        if path.startswith("/api/jobs/"):
            sid = path[len("/api/jobs/"):]
            raw = self._job_kv().get(sid)
            if raw is None:
                return 404, "application/json", b'{"error": "unknown job"}'
            return self._json(json.loads(raw))
        if path == "/api/tasks":
            limit = int(params.get("limit", 100))
            return self._json(list(h.task_events)[-limit:])
        if path == "/api/pgs":
            return self._json(
                [
                    {
                        "pg_id": p.pg_id, "strategy": p.strategy, "state": p.state,
                        "bundle_nodes": [b.node_id for b in p.bundles],
                    }
                    for p in h.pgs.values()
                ]
            )
        if path == "/api/serve":
            # serving plane: the controller's ~1s digest (target/actual
            # replicas, per-replica node/queue/draining, last autoscale
            # decision) rides the head KV, so this works even while the
            # controller actor is busy reconciling
            raw = h.kv.get("", {}).get("serve:plane")
            plane = {}
            if raw:
                try:
                    plane = json.loads(raw)
                except Exception:
                    plane = {}
            return self._json({"deployments": plane})
        if path == "/api/timeseries":
            # metrics-plane history: the head's retention store (ring
            # buffers, two tiers), counter→rate derivable server-side
            ts = h.timeseries
            if ts is None:
                return self._json({"series": {}, "meta": {"disabled": True}})
            names = params.get("names")
            return self._json(
                {
                    "series": ts.query(
                        names=names.split(",") if names else None,
                        prefix=params.get("prefix") or None,
                        tier=int(params.get("tier", 0)),
                        rate=params.get("rate") in ("1", "true"),
                    ),
                    "meta": ts.meta(),
                }
            )
        if path == "/api/logplane":
            # log-plane counter snapshot: capture-side aggregates from the
            # metrics table + this head's ship/drop stats
            out = {
                "log_lines_shipped": h.stats.get("log_lines_shipped", 0),
                "log_lines_dropped": h.stats.get("log_lines_dropped", 0),
                **h._log_counter_totals(),
            }
            return self._json(out)
        if path == "/api/flightrec":
            # flight-recorder journal: cluster-merged decision events with
            # the same filters as the `flightrec` head RPC / `ca events`
            return self._json(
                h._flightrec_query(
                    trace=params.get("trace") or None,
                    plane=params.get("plane") or None,
                    node=params.get("node") or None,
                    event=params.get("event") or None,
                    since=float(params["since"]) if params.get("since") else None,
                    limit=int(params.get("limit", 200)),
                )
            )
        if path == "/metrics":
            from .util.metrics import render_prometheus

            try:
                text = render_prometheus(h.metrics)
            except Exception:
                text = ""
            return 200, "text/plain; version=0.0.4", text.encode()
        return 404, "text/plain", b"not found"

    # ------------------------------------------------------------- log view
    async def _route_logs(self, path: str):
        """GET /api/logs            -> available log ids
        GET /api/logs?id=X&tail=N[&off=M] -> that process's log text (any
        node; reads proxy through the owning agent)."""
        query = path.partition("?")[2]
        params = dict(p.partition("=")[::2] for p in query.split("&") if p)
        h = self.head
        ident = params.get("id")
        if not ident:
            # dead workers stay listed: a crashed worker's log is exactly
            # the one worth reading (readable as long as its node is up)
            ids = (
                ["head"]
                + sorted(w.worker_id for w in h.workers.values())
                + sorted(
                    n.node_id
                    for n in h.nodes.values()
                    if not n.is_local and n.state == "alive"
                )
            )
            return self._json(ids)
        try:
            out = await h._log_fetch_data(
                ident,
                tail=int(params.get("tail", 200)),
                off=int(params["off"]) if params.get("off") else None,
                structured=params.get("structured") in ("1", "true"),
            )
        except (FileNotFoundError, RuntimeError, ValueError) as e:
            return 404, "application/json", json.dumps({"error": str(e)}).encode()
        return self._json(
            {"id": ident, "node_id": out["node_id"], "off": out["off"],
             "data": out["data"]}
        )

    # --------------------------------------------------------- job REST API
    # Reference parity: dashboard/modules/job REST surface (JobSubmissionClient
    # speaks HTTP to the dashboard).  The head spawns and tracks the job's
    # driver subprocess itself — same contract as jobs.JobSupervisor, same KV
    # namespace, so `ca jobs` and the SDK see REST-submitted jobs too.

    def _job_kv(self):
        return self.head.kv.setdefault("__jobs__", {})

    def _route_post(self, path: str, body: bytes):
        if path == "/api/jobs":
            try:
                spec = json.loads(body or b"{}")
                entrypoint = spec["entrypoint"]
            except (ValueError, KeyError):
                return 400, "application/json", b'{"error": "entrypoint required"}'
            sid = spec.get("submission_id") or f"cajob_{uuid.uuid4().hex[:10]}"
            info = {
                "submission_id": sid,
                "status": "RUNNING",
                "entrypoint": entrypoint,
                "start_time": time.time(),
                "end_time": None,
                "return_code": None,
                "message": "submitted via REST",
            }
            env = dict(os.environ)
            env.update(spec.get("env_vars") or {})
            env["CA_ADDRESS"] = self.head.session_dir
            env["CA_JOB_SUBMISSION_ID"] = sid
            log_path = os.path.join(self.head.session_dir, f"job-{sid}.log")
            logf = open(log_path, "ab")
            try:
                proc = subprocess.Popen(
                    entrypoint,
                    shell=True,
                    env=env,
                    cwd=spec.get("cwd"),
                    stdout=logf,
                    stderr=subprocess.STDOUT,
                    start_new_session=True,
                )
            except OSError as e:
                return 500, "application/json", json.dumps({"error": repr(e)}).encode()
            finally:
                logf.close()
            self._rest_jobs[sid] = proc
            self._job_kv()[sid] = json.dumps(info).encode()
            threading.Thread(
                target=self._watch_job, args=(sid, proc, dict(info)), daemon=True
            ).start()
            return self._json({"submission_id": sid})
        if path.startswith("/api/jobs/") and path.endswith("/stop"):
            sid = path[len("/api/jobs/") : -len("/stop")]
            proc = self._rest_jobs.get(sid)
            if proc is None:
                return 404, "application/json", b'{"error": "unknown job"}'
            if proc.poll() is None:
                import signal as _signal

                raw = self._job_kv().get(sid)
                if raw:
                    info = json.loads(raw)
                    info["status"] = "STOPPED"
                    self._job_kv()[sid] = json.dumps(info).encode()
                try:
                    os.killpg(os.getpgid(proc.pid), _signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    pass
            return self._json({"submission_id": sid, "status": "STOPPED"})
        return 404, "text/plain", b"not found"

    def _watch_job(self, sid: str, proc, info: dict):
        rc = proc.wait()

        def _update():
            raw = self._job_kv().get(sid)
            final = json.loads(raw) if raw else dict(info)
            if final.get("status") == "RUNNING":
                final["status"] = "SUCCEEDED" if rc == 0 else "FAILED"
            final["return_code"] = rc
            final["end_time"] = time.time()
            self._job_kv()[sid] = json.dumps(final).encode()

        # marshal onto the head loop: the kv dict is also walked by the
        # snapshot persister there
        if self._loop is not None:
            self._loop.call_soon_threadsafe(_update)

    @staticmethod
    def _json(obj: Any):
        return 200, "application/json", json.dumps(obj, default=str).encode()
