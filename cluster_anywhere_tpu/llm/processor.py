"""Batch LLM inference pipeline (analogue of the reference's
python/ray/llm/_internal/batch/processor/ + stages/: chat template ->
tokenize -> inference -> detokenize, composed as Data map stages).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import numpy as np


class ByteTokenizer:
    """Offline byte-level tokenizer (ids: 0=pad, 1=bos, 2=eos, byte b -> b+3).
    Stands in for HF tokenizers in air-gapped environments; any object with
    encode/decode can be plugged into ProcessorConfig.tokenizer."""

    vocab_size = 259
    pad_id, bos_id, eos_id = 0, 1, 2

    def encode(self, text: str, add_bos: bool = True) -> List[int]:
        ids = [b + 3 for b in text.encode("utf-8")]
        return ([self.bos_id] + ids) if add_bos else ids

    def decode(self, ids) -> str:
        out = bytearray()
        for i in ids:
            i = int(i)
            if i >= 3:
                out.append(i - 3)
        return out.decode("utf-8", "replace")


@dataclass
class ModelSpec:
    """Which flagship-transformer weights to run. Presets init random weights
    deterministically (seed) — checkpoint loading goes through `params_path`
    (an orbax/np.savez dir produced by train)."""

    preset: str = "tiny"  # tiny | small | custom
    params_path: Optional[str] = None
    seed: int = 0
    config_overrides: Dict[str, Any] = field(default_factory=dict)

    def transformer_config(self, vocab_size: int):
        from ..models.transformer import TransformerConfig

        presets = {
            "tiny": dict(d_model=64, n_layers=2, n_heads=4, n_kv_heads=4, d_head=16, d_ff=128),
            "small": dict(d_model=256, n_layers=4, n_heads=8, n_kv_heads=8, d_head=32, d_ff=512),
        }
        base = presets.get(self.preset, presets["tiny"])
        base.update(self.config_overrides)
        return TransformerConfig(vocab_size=vocab_size, **base)


@dataclass
class ProcessorConfig:
    model: ModelSpec = field(default_factory=ModelSpec)
    tokenizer: Any = None  # defaults to ByteTokenizer
    batch_size: int = 8
    concurrency: int = 1
    max_prompt_len: int = 64
    max_new_tokens: int = 16
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    apply_chat_template: bool = False
    system_prompt: str = ""
    # prefix/KV-cache reuse in ContinuousLLMServer: requests sharing a
    # system-prompt prefix skip its prefill (0 entries disables)
    prefix_cache_entries: int = 8
    prefix_block: int = 16


class _InferenceWorker:
    """Actor-pool UDF: holds compiled model + params for its lifetime
    (reference: stages run in vLLM engine actors)."""

    def __init__(self, cfg: ProcessorConfig):
        import jax

        self.cfg = cfg
        self.tok = cfg.tokenizer or ByteTokenizer()
        self.tcfg = cfg.model.transformer_config(self.tok.vocab_size)
        from ..models.transformer import init_params

        if cfg.model.params_path:
            from . import _params_io

            self.params = _params_io.load_params(cfg.model.params_path)
        else:
            self.params = init_params(jax.random.key(cfg.model.seed), self.tcfg)
        self._step = 0

    def __call__(
        self,
        batch: Dict[str, np.ndarray],
        max_new_tokens: Optional[int] = None,
        temperature: Optional[float] = None,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
    ) -> Dict[str, np.ndarray]:
        import jax
        import jax.numpy as jnp

        from ..models.generate import generate

        cfg = self.cfg
        max_new_tokens = cfg.max_new_tokens if max_new_tokens is None else max_new_tokens
        temperature = cfg.temperature if temperature is None else temperature
        top_k = cfg.top_k if top_k is None else top_k
        top_p = getattr(cfg, "top_p", 1.0) if top_p is None else top_p
        prompts = [str(p) for p in batch["prompt"].tolist()]
        encoded = [self.tok.encode(p)[: cfg.max_prompt_len] for p in prompts]
        # left-pad to the FIXED max_prompt_len so every batch hits the same
        # compiled program (per-batch max length would recompile per shape)
        max_len = cfg.max_prompt_len
        ids = np.full((len(encoded), max_len), self.tok.pad_id, np.int32)
        lens = np.empty(len(encoded), np.int32)
        for i, e in enumerate(encoded):
            ids[i, max_len - len(e):] = e
            lens[i] = len(e)
        self._step += 1
        out = generate(
            self.params,
            jnp.asarray(ids),
            jax.random.key(cfg.model.seed * 1000003 + self._step),
            cfg=self.tcfg,
            max_new_tokens=max_new_tokens,
            temperature=temperature,
            top_k=top_k,
            top_p=top_p,
            prompt_lens=jnp.asarray(lens),
        )
        out = np.asarray(out)
        texts = [self.tok.decode(row) for row in out]
        result = dict(batch)
        result["generated_tokens"] = out
        result["generated_text"] = np.asarray(texts, dtype=object)
        return result

    def stream(
        self,
        prompt: str,
        max_new_tokens: Optional[int] = None,
        temperature: Optional[float] = None,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
    ):
        """Token-by-token decoding of one prompt; a generator meant to run as
        a num_returns="streaming" actor call, so clients receive tokens as
        they are sampled (the streaming-decode path of the reference's serve
        LLM engines)."""
        import jax
        import jax.numpy as jnp

        from ..models.generate import stream_generate

        cfg = self.cfg
        max_new_tokens = cfg.max_new_tokens if max_new_tokens is None else max_new_tokens
        encoded = self.tok.encode(prompt)[: cfg.max_prompt_len]
        ids = np.full((1, cfg.max_prompt_len), self.tok.pad_id, np.int32)
        ids[0, cfg.max_prompt_len - len(encoded):] = encoded
        self._step += 1
        for tok in stream_generate(
            self.params,
            jnp.asarray(ids),
            jax.random.key(cfg.model.seed * 1000003 + self._step),
            cfg=self.tcfg,
            max_new_tokens=max_new_tokens,
            temperature=cfg.temperature if temperature is None else temperature,
            top_k=cfg.top_k if top_k is None else top_k,
            top_p=getattr(cfg, "top_p", 1.0) if top_p is None else top_p,
            prompt_lens=jnp.asarray([len(encoded)], np.int32),
        ):
            tid = int(tok[0])
            yield {"token_id": tid, "text": self.tok.decode([tid])}


class Processor:
    """Callable dataset -> dataset pipeline."""

    def __init__(
        self,
        config: ProcessorConfig,
        preprocess: Optional[Callable[[dict], dict]] = None,
        postprocess: Optional[Callable[[dict], dict]] = None,
    ):
        self.config = config
        self.preprocess = preprocess
        self.postprocess = postprocess

    def __call__(self, dataset):
        cfg = self.config
        ds = dataset
        if self.preprocess is not None:
            ds = ds.map(self.preprocess)
        if cfg.apply_chat_template:
            system = cfg.system_prompt

            def template(row):
                prompt = row.get("prompt", "") if isinstance(row, dict) else str(row)
                msgs = row.get("messages") if isinstance(row, dict) else None
                if msgs:
                    text = "".join(
                        f"<|{m['role']}|>{m['content']}" for m in msgs
                    ) + "<|assistant|>"
                else:
                    text = (f"<|system|>{system}" if system else "") + f"<|user|>{prompt}<|assistant|>"
                out = dict(row)
                out["prompt"] = text
                return out

            ds = ds.map(template)
        ds = ds.map_batches(
            _InferenceWorker,
            fn_constructor_args=(cfg,),
            batch_size=cfg.batch_size,
            concurrency=cfg.concurrency,
            batch_format="numpy",
        )
        if self.postprocess is not None:
            ds = ds.map(self.postprocess)
        return ds


def build_llm_processor(
    config: ProcessorConfig,
    preprocess: Optional[Callable[[dict], dict]] = None,
    postprocess: Optional[Callable[[dict], dict]] = None,
) -> Processor:
    return Processor(config, preprocess, postprocess)
