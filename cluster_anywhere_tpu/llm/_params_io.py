"""Flat npz save/load for model param pytrees (checkpoint interchange between
train and the llm inference stages)."""

from __future__ import annotations

import os
from typing import Any, Dict

import numpy as np


def _flatten(tree: Any, prefix: str = "") -> Dict[str, Any]:
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        # list pytrees (e.g. MLP layer stacks) flatten under numeric keys and
        # are rebuilt as lists by load_params
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}{i}/"))
    else:
        out[prefix.rstrip("/")] = np.asarray(tree)
    return out


def save_params(params: Any, path: str):
    os.makedirs(path, exist_ok=True)
    np.savez(os.path.join(path, "params.npz"), **_flatten(params))


def load_params(path: str) -> Dict[str, Any]:
    import jax.numpy as jnp

    f = np.load(os.path.join(path, "params.npz"))
    tree: Dict[str, Any] = {}
    for key in f.files:
        parts = key.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = jnp.asarray(f[key])
    return _relist(tree)


def _relist(node):
    """Rebuild list pytrees: an all-digit-keyed dict came from a list and must
    round-trip as one (ordered numerically, not lexically)."""
    if isinstance(node, dict):
        if node and all(k.isdigit() for k in node):
            return [_relist(node[str(i)]) for i in range(len(node))]
        return {k: _relist(v) for k, v in node.items()}
    return node
