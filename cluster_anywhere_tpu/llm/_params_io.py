"""Flat npz save/load for model param pytrees (checkpoint interchange between
train and the llm inference stages)."""

from __future__ import annotations

import os
from typing import Any, Dict

import numpy as np


def _flatten(tree: Any, prefix: str = "") -> Dict[str, Any]:
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
    else:
        out[prefix.rstrip("/")] = np.asarray(tree)
    return out


def save_params(params: Any, path: str):
    os.makedirs(path, exist_ok=True)
    np.savez(os.path.join(path, "params.npz"), **_flatten(params))


def load_params(path: str) -> Dict[str, Any]:
    import jax.numpy as jnp

    f = np.load(os.path.join(path, "params.npz"))
    tree: Dict[str, Any] = {}
    for key in f.files:
        parts = key.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = jnp.asarray(f[key])
    return tree
