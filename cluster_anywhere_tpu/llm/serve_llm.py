"""serve.llm: online LLM serving deployment (analogue of the reference's
python/ray/serve/llm.py build_openai_app — compact: one deployment class with
request batching over the compiled generate path).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from .processor import ByteTokenizer, ModelSpec, ProcessorConfig, _InferenceWorker


def _parse_body(request) -> Dict[str, Any]:
    """Accept a serve HTTP Request, a dict, or a bare prompt string — the
    one body parser every LLM deployment method shares."""
    from ..serve import Request

    if isinstance(request, Request):
        return request.json() if request.method == "POST" else dict(request.query_params)
    return request if isinstance(request, dict) else {"prompt": str(request)}


class LLMServer:
    """Serve deployment hosting one model; understands dict and HTTP requests:
       {"prompt": "...", "max_new_tokens": 16} -> {"generated_text": "..."}"""

    def __init__(self, config: ProcessorConfig):
        import numpy as np

        self.config = config
        self.worker = _InferenceWorker(config)
        self.np = np

    def reconfigure(self, cfg: Dict[str, Any]):
        if "max_new_tokens" in cfg:
            self.config.max_new_tokens = int(cfg["max_new_tokens"])
        if "temperature" in cfg:
            self.config.temperature = float(cfg["temperature"])

    def __call__(self, request) -> Dict[str, Any]:
        body = _parse_body(request)
        prompt = body.get("prompt", "")
        batch = {"prompt": self.np.asarray([prompt], dtype=object)}
        overrides = {}
        if "max_new_tokens" in body:
            overrides["max_new_tokens"] = int(body["max_new_tokens"])
        if "temperature" in body:
            overrides["temperature"] = float(body["temperature"])
        if "top_k" in body:
            overrides["top_k"] = int(body["top_k"])
        if "top_p" in body:
            overrides["top_p"] = float(body["top_p"])
        out = self.worker(batch, **overrides)
        return {
            "prompt": prompt,
            "generated_text": str(out["generated_text"][0]),
            "num_generated_tokens": int(len(out["generated_tokens"][0])),
        }

    def stream(self, request):
        """Token-streaming twin of __call__: yields one
        {"token_id", "text"} dict per sampled token.  Reaches HTTP clients
        as SSE via the proxy's text/event-stream path (serve streaming
        handles end-to-end: replica generator -> streaming actor frames ->
        one SSE event per token)."""
        body = _parse_body(request)
        kwargs = {}
        if "max_new_tokens" in body:
            kwargs["max_new_tokens"] = int(body["max_new_tokens"])
        if "temperature" in body:
            kwargs["temperature"] = float(body["temperature"])
        if "top_k" in body:
            kwargs["top_k"] = int(body["top_k"])
        if "top_p" in body:
            kwargs["top_p"] = float(body["top_p"])
        yield from self.worker.stream(body.get("prompt", ""), **kwargs)


def build_llm_deployment(
    config: Optional[ProcessorConfig] = None,
    *,
    num_replicas: int = 1,
    num_tpus: float = 0.0,
    name: str = "LLMServer",
):
    """Returns a bound serve Application for `serve.run`."""
    from .. import serve

    config = config or ProcessorConfig()
    dep = serve.deployment(
        LLMServer,
        name=name,
        num_replicas=num_replicas,
        num_tpus=num_tpus,
        max_ongoing_requests=4,
    )
    return dep.bind(config)


class ContinuousLLMServer:
    """LLM deployment with ITERATION-LEVEL scheduling (the vLLM-engine role
    of the reference's serve.llm): concurrent requests share the decode loop
    through one ContinuousBatcher — a request admits the moment a slot
    frees, instead of waiting for the current static batch to drain.

    One background pump thread drives decode steps; caller threads (the
    replica runs methods concurrently up to max_ongoing_requests) submit and
    wait on per-request events, or consume a token queue when streaming."""

    def __init__(self, config: ProcessorConfig, slots: int = 8):
        import queue
        import threading

        import jax

        from ..models.transformer import init_params
        from .continuous import ContinuousBatcher

        self.config = config
        self.tok = config.tokenizer or ByteTokenizer()
        tcfg = config.model.transformer_config(self.tok.vocab_size)
        if config.model.params_path:
            from . import _params_io

            params = _params_io.load_params(config.model.params_path)
        else:
            params = init_params(jax.random.key(config.model.seed), tcfg)
        t_max = config.max_prompt_len + config.max_new_tokens
        self.cb = ContinuousBatcher(
            params, tcfg, slots=slots, t_max=t_max,
            prefill_buckets=(config.max_prompt_len,), top_k=config.top_k,
            prefix_cache_entries=getattr(config, "prefix_cache_entries", 8),
            prefix_block=getattr(config, "prefix_block", 16),
        )
        self._metrics_synced: dict = {}
        self._lock = threading.Lock()  # batcher is single-threaded inside
        self._queues: dict = {}  # request_id -> queue of token ids (+ None EOF)
        self._reqs: dict = {}  # request_id -> Request (done detection)
        self._queue_cls = queue.Queue
        self._stop = False
        self._engine_error: Optional[BaseException] = None
        self._pump = threading.Thread(target=self._pump_loop, daemon=True)
        self._pump.start()

    def check_health(self):
        """Serve controller hook: a dead pump means every request on this
        replica would hang to queue timeout — report it so the controller
        replaces the replica instead."""
        if self._engine_error is not None:
            raise RuntimeError(f"LLM engine pump died: {self._engine_error!r}")

    def close(self):
        """Stop the pump thread (dropping a replica without close() would
        leave it spinning and pinning params + the KV cache forever)."""
        self._stop = True
        if self._pump.is_alive():
            self._pump.join(timeout=5)

    def __del__(self):  # best-effort; serve teardown also kills the process
        try:
            self.close()
        except Exception:
            pass

    _llm_metrics: dict = {}  # class-level: one registry entry per process

    def _sync_engine_metrics(self):
        """Ship the batcher's counters (prefix-cache hits/misses/tokens
        reused, decode steps) as ca_serve_* cluster metrics — the series
        behind the envelope's "hits skip prefill" claim."""
        if not self._llm_metrics:
            from ..util import metrics as m

            for key, name, desc in (
                ("prefix_hits", "ca_serve_prefix_hits_total",
                 "LLM admits that reused cached prefix KV rows"),
                ("prefix_misses", "ca_serve_prefix_misses_total",
                 "LLM admits that prefilled (and cached) their prefix"),
                ("prefix_tokens_reused", "ca_serve_prefix_tokens_reused_total",
                 "prompt tokens whose prefill was skipped via the prefix cache"),
                ("decode_steps", "ca_serve_decode_steps_total",
                 "continuous-batcher decode iterations"),
            ):
                self._llm_metrics[key] = m.Counter(name, desc)
        for key, counter in self._llm_metrics.items():
            cur = self.cb.stats.get(key, 0)
            delta = cur - self._metrics_synced.get(key, 0)
            if delta:
                counter.inc(delta)
                self._metrics_synced[key] = cur

    def _pump_loop(self):
        import time as _time

        last_sync = 0.0
        while not self._stop:
            now = _time.monotonic()
            if now - last_sync > 1.0:
                last_sync = now
                try:
                    self._sync_engine_metrics()
                except Exception:
                    pass  # metrics must never kill the decode pump
            try:
                with self._lock:
                    work = self.cb.has_work
                    out = self.cb.step() if work else {}
                    delivered = []
                    for rid, toks in out.items():
                        q = self._queues.get(rid)
                        req = self._reqs.get(rid)
                        if q is not None:
                            for t in toks:
                                q.put(t)
                            if req is not None and req.done:
                                q.put(None)
                                delivered.append(rid)
                    for rid in delivered:
                        self._reqs.pop(rid, None)
            except BaseException as e:
                # engine failure (device OOM, shape bug): without this the
                # pump dies silently and every request blocks to the queue
                # timeout.  Fail fast: error every in-flight queue, mark the
                # replica unhealthy, stop pumping.
                with self._lock:
                    self._engine_error = e
                    for q in self._queues.values():
                        q.put(e)
                    self._queues.clear()
                    self._reqs.clear()
                return
            if not work:
                _time.sleep(0.005)

    def _submit(self, body) -> tuple:
        prompt = body.get("prompt", "")
        ids = self.tok.encode(prompt)[: self.config.max_prompt_len]
        mnt = int(body.get("max_new_tokens", self.config.max_new_tokens))
        temp = float(body.get("temperature", self.config.temperature))
        top_k = body.get("top_k")
        top_p = float(body.get("top_p", 1.0))
        q = self._queue_cls()
        with self._lock:
            if self._engine_error is not None:
                raise RuntimeError(
                    f"LLM engine pump died: {self._engine_error!r}"
                ) from self._engine_error
            # queue registered under the same lock as submit: the pump's
            # next step (admit + decode) finds it before any token flows
            req = self.cb.submit(
                ids, max_new_tokens=mnt, temperature=temp,
                top_k=None if top_k is None else int(top_k),
                top_p=top_p,
            )
            self._queues[req.request_id] = q
            self._reqs[req.request_id] = req
        return prompt, req, q

    def _forget(self, req):
        with self._lock:
            self._queues.pop(req.request_id, None)
            self._reqs.pop(req.request_id, None)
            if not req.done:
                # consumer abandoned mid-decode (SSE client disconnect):
                # free the slot NOW instead of decoding tokens nobody reads
                self.cb.cancel(req.request_id)

    def __call__(self, request) -> Dict[str, Any]:
        prompt, req, q = self._submit(_parse_body(request))
        toks = []
        try:
            while True:
                t = q.get(timeout=120)
                if t is None:
                    break
                if isinstance(t, BaseException):
                    raise RuntimeError(f"LLM engine pump died: {t!r}") from t
                toks.append(t)
        finally:
            self._forget(req)
        import numpy as np

        return {
            "prompt": prompt,
            "generated_text": self.tok.decode(np.asarray(toks, np.int32)),
            "num_generated_tokens": len(toks),
        }

    def stream(self, request):
        """Per-token streaming while other requests decode in the same loop."""
        import numpy as np

        prompt, req, q = self._submit(_parse_body(request))
        try:
            while True:
                t = q.get(timeout=120)
                if t is None:
                    return
                if isinstance(t, BaseException):
                    raise RuntimeError(f"LLM engine pump died: {t!r}") from t
                yield {
                    "token_id": int(t),
                    "text": self.tok.decode(np.asarray([t], np.int32)),
                }
        finally:
            self._forget(req)

    def dag_stream(self, request) -> dict:
        """Compiled-DAG streaming: decode-step -> detokenize -> stream-out
        without a per-token RPC.  Submits the prompt, pre-opens a shm
        channel, and returns its spec; a forwarder thread pushes
        {"token_id","text"} frames into the channel and the proxy-side
        DagStreamReader futex-waits on them.  The only RPC left on the hot
        path is this handshake."""
        import threading

        import numpy as np

        from ..channel.shm_channel import BufferedShmChannel, ChannelClosedError
        from ..core.config import get_config
        from ..serve.dag_stream import DAG_EOF, DAG_ERR

        cfg = get_config()
        prompt, req, q = self._submit(_parse_body(request))
        ch = BufferedShmChannel(
            num_readers=1, num_buffers=max(2, cfg.serve_dag_stream_buffers)
        )
        spec = ch.spec()

        def forward():
            try:
                while True:
                    t = q.get(timeout=120)
                    if t is None:
                        ch.write(DAG_EOF, timeout=30)
                        # drain barrier: release() unlinks the segment, so
                        # wait until the proxy acked the EOF frame first
                        ch.wait_consumed(30.0)
                        return
                    if isinstance(t, BaseException):
                        ch.write(
                            {DAG_ERR: f"LLM engine pump died: {t!r}"}, timeout=30
                        )
                        ch.wait_consumed(30.0)
                        return
                    # 120s matches the RPC path's queue timeout: a consumer
                    # stalled longer than that loses the stream either way
                    ch.write(
                        {
                            "token_id": int(t),
                            "text": self.tok.decode(np.asarray([t], np.int32)),
                        },
                        timeout=120,
                    )
            except (ChannelClosedError, TimeoutError):
                pass  # proxy abandoned the stream; free the decode slot below
            except Exception:
                pass
            finally:
                self._forget(req)
                ch.release()

        threading.Thread(
            target=forward, daemon=True, name="ca-dag-stream"
        ).start()
        return spec


class StreamingLLMIngress(ContinuousLLMServer):
    """ContinuousLLMServer whose __call__ STREAMS when the HTTP client asks
    for SSE (Accept: text/event-stream) and answers one JSON body otherwise
    — the proxy's SSE path invokes the ingress's __call__, so token
    streaming over plain `curl -H 'Accept: text/event-stream'` needs the
    branch here."""

    def __call__(self, request):
        from ..serve import Request

        if isinstance(request, Request) and "text/event-stream" in request.headers.get(
            "accept", ""
        ):
            return self.stream(request)  # generator -> one SSE event per token
        return ContinuousLLMServer.__call__(self, request)


def build_continuous_llm_deployment(
    config: Optional[ProcessorConfig] = None,
    *,
    slots: int = 8,
    num_replicas: int = 1,
    num_tpus: float = 0.0,
    name: str = "ContinuousLLMServer",
    admission=None,
    autoscaling_config=None,
    sse_ingress: bool = False,
):
    """Continuous-batching twin of build_llm_deployment: up to `slots`
    requests share every decode iteration on each replica.  `admission`
    (AdmissionPolicy/dict) arms the proxy's load-shedding gate;
    `sse_ingress=True` serves token-streaming SSE from __call__."""
    from .. import serve

    config = config or ProcessorConfig()
    dep = serve.deployment(
        StreamingLLMIngress if sse_ingress else ContinuousLLMServer,
        name=name,
        num_replicas=num_replicas,
        num_tpus=num_tpus,
        max_ongoing_requests=slots,  # callers block in __call__; pump is a thread
        admission=admission,
        autoscaling_config=autoscaling_config,
    )
    return dep.bind(config, slots)
