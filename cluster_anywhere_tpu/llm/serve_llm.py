"""serve.llm: online LLM serving deployment (analogue of the reference's
python/ray/serve/llm.py build_openai_app — compact: one deployment class with
request batching over the compiled generate path).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from .processor import ByteTokenizer, ModelSpec, ProcessorConfig, _InferenceWorker


class LLMServer:
    """Serve deployment hosting one model; understands dict and HTTP requests:
       {"prompt": "...", "max_new_tokens": 16} -> {"generated_text": "..."}"""

    def __init__(self, config: ProcessorConfig):
        import numpy as np

        self.config = config
        self.worker = _InferenceWorker(config)
        self.np = np

    def reconfigure(self, cfg: Dict[str, Any]):
        if "max_new_tokens" in cfg:
            self.config.max_new_tokens = int(cfg["max_new_tokens"])
        if "temperature" in cfg:
            self.config.temperature = float(cfg["temperature"])

    def __call__(self, request) -> Dict[str, Any]:
        from ..serve import Request

        if isinstance(request, Request):
            body = request.json() if request.method == "POST" else dict(request.query_params)
        else:
            body = request if isinstance(request, dict) else {"prompt": str(request)}
        prompt = body.get("prompt", "")
        batch = {"prompt": self.np.asarray([prompt], dtype=object)}
        overrides = {}
        if "max_new_tokens" in body:
            overrides["max_new_tokens"] = int(body["max_new_tokens"])
        if "temperature" in body:
            overrides["temperature"] = float(body["temperature"])
        if "top_k" in body:
            overrides["top_k"] = int(body["top_k"])
        out = self.worker(batch, **overrides)
        return {
            "prompt": prompt,
            "generated_text": str(out["generated_text"][0]),
            "num_generated_tokens": int(len(out["generated_tokens"][0])),
        }

    def stream(self, request):
        """Token-streaming twin of __call__: yields one
        {"token_id", "text"} dict per sampled token.  Reaches HTTP clients
        as SSE via the proxy's text/event-stream path (serve streaming
        handles end-to-end: replica generator -> streaming actor frames ->
        one SSE event per token)."""
        from ..serve import Request

        if isinstance(request, Request):
            body = request.json() if request.method == "POST" else dict(request.query_params)
        else:
            body = request if isinstance(request, dict) else {"prompt": str(request)}
        kwargs = {}
        if "max_new_tokens" in body:
            kwargs["max_new_tokens"] = int(body["max_new_tokens"])
        if "temperature" in body:
            kwargs["temperature"] = float(body["temperature"])
        if "top_k" in body:
            kwargs["top_k"] = int(body["top_k"])
        yield from self.worker.stream(body.get("prompt", ""), **kwargs)


def build_llm_deployment(
    config: Optional[ProcessorConfig] = None,
    *,
    num_replicas: int = 1,
    num_tpus: float = 0.0,
    name: str = "LLMServer",
):
    """Returns a bound serve Application for `serve.run`."""
    from .. import serve

    config = config or ProcessorConfig()
    dep = serve.deployment(
        LLMServer,
        name=name,
        num_replicas=num_replicas,
        num_tpus=num_tpus,
        max_ongoing_requests=4,
    )
    return dep.bind(config)
