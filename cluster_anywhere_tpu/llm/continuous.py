"""Continuous batching for LLM decoding (the iteration-level scheduler the
reference gets from its vLLM-backed `serve.llm` deployments —
python/ray/llm's engine does exactly this; redesigned here for the XLA
compilation model instead of paged CUDA kernels).

The scheduler owns a fixed pool of decode SLOTS over one shared KV cache
[L, S, T_max, KV, D].  Each slot runs one request; requests at different
depths decode together in ONE jitted step whose shapes never change — slot
count and cache length are static, per-row positions are traced — so
admitting or finishing requests never recompiles anything:

- admit: a queued request prefills (batch-1 program, prompt padded to a
  bucket length to bound compile count) and its cache rows scatter into its
  slot between decode steps.
- decode: every live slot advances one token per step.  Per-row cache
  positions/pads drive RoPE and masking; finished or empty slots still
  compute (their lanes are garbage) but write only to their own frozen
  cache rows, which the next admit fully overwrites.
- finish: a slot frees the moment its request hits max_new_tokens or eos;
  the next step() can admit into it immediately — no head-of-line batching
  barrier, which is the whole point vs static generate() batching.

Reference anchors: models/generate.py (single-position decode this
generalizes), serve_llm.py (the deployment that drives it).
"""

from __future__ import annotations

import functools
import itertools
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..models.generate import (
    _block_decode_rowpos,
    _nucleus_mask,
    _rms_norm,
    _sample,
    decode_one,
    prefill,
)
from ..models.transformer import TransformerConfig


@dataclass
class Request:
    request_id: int
    prompt_ids: np.ndarray
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    eos_id: Optional[int] = None
    # filled as the request runs
    out_tokens: List[int] = field(default_factory=list)
    slot: int = -1
    done: bool = False


def _sample_rowwise(logits, rngs, temps, top_ks, top_ps):
    """Per-row sampling with TRACED temperature, top-k, and top-p (requests
    in one decode batch carry their own knobs; a static top_k would force
    one value per compiled program).  top_k <= 0 means no truncation;
    top_p outside (0, 1) means no nucleus mask; temp <= 0 means greedy."""
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    t = jnp.maximum(temps, 1e-6)[:, None]
    scaled = logits / t
    v = logits.shape[-1]
    # traced top-k: k-th largest per row via a descending sort
    sorted_desc = -jnp.sort(-scaled, axis=-1)
    kth_idx = jnp.clip(top_ks - 1, 0, v - 1)[:, None]
    kth = jnp.take_along_axis(sorted_desc, kth_idx, axis=-1)
    scaled = jnp.where((top_ks[:, None] > 0) & (scaled < kth), -1e30, scaled)
    # per-row nucleus mask: [S,1] top_p broadcasts through the shared helper
    scaled = _nucleus_mask(scaled, top_ps[:, None])
    sampled = jax.vmap(lambda rng, row: jax.random.categorical(rng, row))(
        rngs, scaled
    ).astype(jnp.int32)
    return jnp.where(temps <= 0.0, greedy, sampled)


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnums=(1,))
def _decode_step_rowpos(params, cache, tokens, pos, pads, temps, top_ks, top_ps, rngs, *, cfg):
    """One token for every slot with PER-ROW cache positions.
    tokens/pos/pads/temps/top_ks: [S]; rngs: [S] keys.  Returns
    (next_tokens [S], cache).  The cache is donated: decode rewrites it in
    place instead of copying [L,S,Tmax,KV,D] x2 per token."""
    x = params["embed"].astype(cfg.dtype)[tokens][:, None, :]  # [S,1,E]

    def body(x, inputs):
        bp, kc, vc = inputs
        x, (kc, vc) = _block_decode_rowpos(bp, x, (kc, vc), pos, cfg, pads)
        return x, (kc, vc)

    x, (k_all, v_all) = lax.scan(body, x, (params["blocks"], cache["k"], cache["v"]))
    x = _rms_norm(x, params["ln_f"])
    logits = (x[:, 0] @ params["lm_head"].astype(cfg.dtype)).astype(jnp.float32)
    nxt = _sample_rowwise(logits, rngs, temps, top_ks, top_ps)
    return nxt, {"k": k_all, "v": v_all}


@functools.partial(jax.jit, donate_argnums=(0,))
def _install_slot(cache, slot_k, slot_v, slot):
    """Scatter one request's prefilled rows into its slot (on device)."""
    return {
        "k": cache["k"].at[:, slot].set(slot_k),
        "v": cache["v"].at[:, slot].set(slot_v),
    }


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnums=(1,))
def _suffix_step(params, rows, token, pos, pad, *, cfg):
    """One teacher-forced token over a SINGLE request's cache rows
    ([L, 1, t_max, KV, D], donated — updated in place) during chunked admit:
    feeds a known prompt token at cache slot `pos`, returns the next-token
    logits [1, V] and the updated rows.  The prefix-cache admit path runs
    the un-cached tail of the prompt through this instead of prefill, so a
    warm hit and a cold miss compute the suffix IDENTICALLY (bit-equal
    outputs is the cache's correctness contract)."""
    return decode_one(params, rows, token, pos, cfg, pad)


class PrefixCache:
    """Bounded LRU of prefilled prompt-prefix KV rows, keyed by the prefix
    token content (+ bucket shape).  A hit hands the admit path device-ready
    rows — the shared system prompt's prefill is skipped entirely and only
    the request's unique tail is computed."""

    def __init__(self, entries: int):
        from collections import OrderedDict

        self.entries = entries
        self._d: "OrderedDict[str, dict]" = OrderedDict()
        self.evictions = 0

    @staticmethod
    def key(prefix_ids: np.ndarray, bucket: int) -> str:
        import hashlib

        h = hashlib.sha1(np.ascontiguousarray(prefix_ids, np.int32).tobytes())
        return f"{h.hexdigest()}:{len(prefix_ids)}:{bucket}"

    def get(self, key: str):
        e = self._d.get(key)
        if e is not None:
            self._d.move_to_end(key)
        return e

    def put(self, key: str, rows: dict, pad: int) -> None:
        self._d[key] = {"rows": rows, "pad": pad}
        while len(self._d) > self.entries:
            self._d.popitem(last=False)
            self.evictions += 1

    def __len__(self) -> int:
        return len(self._d)

    def memory_bytes(self) -> int:
        return sum(
            int(a.size) * a.dtype.itemsize
            for e in self._d.values()
            for a in e["rows"].values()
        )


class ContinuousBatcher:
    """Iteration-level scheduler over a fixed slot pool (see module doc).

    Drive it with submit() + step() (one decode iteration), or pump() until
    a request finishes.  step() returns per-request newly produced tokens,
    enabling token streaming per request while others keep decoding."""

    def __init__(
        self,
        params,
        cfg: TransformerConfig,
        *,
        slots: int = 8,
        t_max: int = 512,
        prefill_buckets: (tuple) = (64, 128, 256),
        top_k: int = 0,
        prefix_cache_entries: int = 0,
        prefix_block: int = 16,
    ):
        self.params = params
        self.cfg = cfg
        self.slots = slots
        self.t_max = t_max
        self.top_k = top_k
        self.prefill_buckets = tuple(sorted(prefill_buckets))
        # prefix/KV reuse (0 entries = off, the pre-cache admit path
        # verbatim).  When on, admit splits the prompt at the largest
        # prefix_block multiple: the prefix prefills once and its KV rows
        # are cached; the suffix is teacher-forced through _suffix_step on
        # BOTH hit and miss so outputs are bit-identical either way.
        self.prefix_cache = (
            PrefixCache(prefix_cache_entries) if prefix_cache_entries > 0 else None
        )
        self.prefix_block = max(1, int(prefix_block))
        # split granularity: prefix prefill compiles one XLA program per
        # DISTINCT split length (the configured buckets rarely leave decode
        # room for bucket + suffix + max_new, so the exact-split fallback is
        # the common case).  Quantizing splits to max(block, longest
        # bucket/8) bounds the program count at ~8 for any prompt length —
        # a recompile stalls the shared pump thread, so an unbounded shape
        # family would freeze live streams on long-tail traffic.
        longest = self.prefill_buckets[-1] if self.prefill_buckets else t_max
        q = max(self.prefix_block, longest // 8)
        self._split_quantum = -(-q // self.prefix_block) * self.prefix_block
        self.cache = {
            "k": jnp.zeros(
                (cfg.n_layers, slots, t_max, cfg.n_kv_heads, cfg.d_head), cfg.dtype
            ),
            "v": jnp.zeros(
                (cfg.n_layers, slots, t_max, cfg.n_kv_heads, cfg.d_head), cfg.dtype
            ),
        }
        self._tokens = np.zeros(slots, np.int32)
        self._pos = np.zeros(slots, np.int32)  # cache slot of the NEXT write
        self._pads = np.zeros(slots, np.int32)
        self._temps = np.zeros(slots, np.float32)
        self._topks = np.zeros(slots, np.int32)
        self._topps = np.ones(slots, np.float32)
        self._by_slot: List[Optional[Request]] = [None] * slots
        self.queue: deque[Request] = deque()
        # bounded: pump() drains it; step()-driven servers track their own
        # Requests (an unbounded list would grow for the replica's lifetime)
        self._completed: deque[Request] = deque(maxlen=4096)
        self._ids = itertools.count(1)
        self._rng = jax.random.key(0)
        self.stats = {
            "admitted": 0, "finished": 0, "decode_steps": 0, "cancelled": 0,
            "prefix_hits": 0, "prefix_misses": 0, "prefix_tokens_reused": 0,
        }

    # ------------------------------------------------------------- interface
    def submit(
        self,
        prompt_ids,
        *,
        max_new_tokens: int = 32,
        temperature: float = 0.0,
        top_k: Optional[int] = None,
        top_p: float = 1.0,
        eos_id: Optional[int] = None,
    ) -> Request:
        prompt = np.asarray(prompt_ids, np.int32).reshape(-1)
        if len(prompt) + max_new_tokens > self.t_max:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new_tokens}) "
                f"exceeds the cache length {self.t_max}"
            )
        req = Request(
            next(self._ids), prompt, int(max_new_tokens), float(temperature),
            self.top_k if top_k is None else int(top_k), float(top_p), eos_id,
        )
        self.queue.append(req)
        return req

    def cancel(self, request_id: int) -> bool:
        """Abort one request: drop it from the queue, or free its slot so
        the next admit reuses it immediately (abandoned-stream path — the
        consumer is gone, decoding its remaining tokens is pure waste).
        Returns False when the request already finished (no-op)."""
        for i, r in enumerate(self.queue):
            if r.request_id == request_id:
                del self.queue[i]
                r.done = True
                self.stats["cancelled"] += 1
                return True
        for s, r in enumerate(self._by_slot):
            if r is not None and r.request_id == request_id:
                r.done = True
                self._by_slot[s] = None  # lane decodes garbage; rows frozen
                self.stats["cancelled"] += 1
                return True
        return False

    @property
    def has_work(self) -> bool:
        return bool(self.queue) or any(r is not None for r in self._by_slot)

    def step(self) -> Dict[int, List[int]]:
        """Admit into free slots, then decode one token on every live slot.
        Returns {request_id: [new tokens this step]} — including the
        prefill-sampled first token of requests admitted this step, so
        streaming consumers see every token exactly once."""
        out: Dict[int, List[int]] = {}
        self._admit(out)
        live = [s for s, r in enumerate(self._by_slot) if r is not None]
        if not live:
            return out
        self._rng, *keys = jax.random.split(self._rng, self.slots + 1)
        nxt, self.cache = _decode_step_rowpos(
            self.params,
            self.cache,
            jnp.asarray(self._tokens),
            jnp.asarray(self._pos),
            jnp.asarray(self._pads),
            jnp.asarray(self._temps),
            jnp.asarray(self._topks),
            jnp.asarray(self._topps),
            jnp.stack(keys),
            cfg=self.cfg,
        )
        nxt = np.asarray(nxt)
        self.stats["decode_steps"] += 1
        for s in live:
            req = self._by_slot[s]
            tok = int(nxt[s])
            req.out_tokens.append(tok)
            out.setdefault(req.request_id, []).append(tok)
            self._tokens[s] = tok
            self._pos[s] += 1
            if len(req.out_tokens) >= req.max_new_tokens or (
                req.eos_id is not None and tok == req.eos_id
            ):
                self._finish(s, req)
        return out

    def pump(self) -> List[Request]:
        """Run until every submitted request finishes; returns them in
        completion order (test/batch convenience — servers call step())."""
        before = list(self._completed)
        while self.has_work:
            self.step()
        seen = {id(r) for r in before}
        return [r for r in self._completed if id(r) not in seen]

    # ------------------------------------------------------------- internals
    def _finish(self, slot: int, req: Request) -> None:
        req.done = True
        self._by_slot[slot] = None  # slot frees for the next admit
        self._completed.append(req)
        self.stats["finished"] += 1

    def _bucket(self, n: int, max_new: int) -> int:
        """Smallest bucket holding the prompt AND leaving room to decode;
        falls back to the exact prompt length (one extra compile) when every
        bucket would overflow the cache."""
        for b in self.prefill_buckets:
            if n <= b and b + max_new <= self.t_max:
                return b
        return n

    def _prefix_split(self, prompt: np.ndarray) -> int:
        """Cacheable prefix length: the largest _split_quantum multiple that
        still leaves >= 1 suffix token (the last prompt token must be
        teacher-forced through _suffix_step to produce first-token logits).
        0 = no usable prefix (prompt too short)."""
        split = ((len(prompt) - 1) // self._split_quantum) * self._split_quantum
        return split if split >= self.prefix_block else 0

    def _admit_full_prefill(self, req: Request):
        """Cold admit: prefill the whole prompt (one bucketed batch-1
        program).  Returns (first-token logits [1,V], slot rows, pad,
        next_pos)."""
        prompt = req.prompt_ids
        bucket = self._bucket(len(prompt), req.max_new_tokens)
        padded = np.zeros(bucket, np.int32)
        pad = bucket - len(prompt)
        padded[pad:] = prompt  # LEFT pad: generate.py's prefill contract
        logits, rowcache = prefill(
            self.params,
            jnp.asarray(padded[None]),
            self.cfg,
            self.t_max,
            pad=jnp.asarray([pad], np.int32),
        )
        rows = {"k": rowcache["k"][:, 0], "v": rowcache["v"][:, 0]}
        return logits, rows, pad, bucket

    def _admit_prefix_cached(self, req: Request, split: int):
        """Chunked admit via the prefix cache: the block-aligned prefix
        comes from the cache (or prefills once, populating it); the suffix
        teacher-forces through _suffix_step token by token.  Hit and miss
        run the SAME suffix computation on the same prefix rows, so the
        produced tokens are bit-identical either way — a hit just skips the
        prefix prefill (the TTFT win on shared-system-prompt traffic)."""
        prompt = req.prompt_ids
        suffix = prompt[split:]
        # bucket must leave room for the stepped suffix AND decode
        bucket = self._bucket(split, req.max_new_tokens + len(suffix))
        key = PrefixCache.key(prompt[:split], bucket)
        entry = self.prefix_cache.get(key)
        if entry is None:
            padded = np.zeros(bucket, np.int32)
            pad = bucket - split
            padded[pad:] = prompt[:split]
            _, rowcache = prefill(
                self.params,
                jnp.asarray(padded[None]),
                self.cfg,
                self.t_max,
                pad=jnp.asarray([pad], np.int32),
            )
            rows = {"k": rowcache["k"][:, 0:1], "v": rowcache["v"][:, 0:1]}
            # store a snapshot BEFORE stepping: _suffix_step donates its rows
            self.prefix_cache.put(
                key, {k: jnp.copy(v) for k, v in rows.items()}, pad
            )
            self.stats["prefix_misses"] += 1
        else:
            pad = entry["pad"]
            rows = {k: jnp.copy(v) for k, v in entry["rows"].items()}
            self.stats["prefix_hits"] += 1
            self.stats["prefix_tokens_reused"] += split
        pad_arr = jnp.asarray([pad], np.int32)
        logits = None
        for i, tok in enumerate(suffix):
            logits, rows = _suffix_step(
                self.params, rows,
                jnp.asarray([int(tok)], np.int32),
                jnp.asarray(bucket + i, np.int32),
                pad_arr, cfg=self.cfg,
            )
        return logits, {"k": rows["k"][:, 0], "v": rows["v"][:, 0]}, pad, bucket + len(suffix)

    def _admit(self, out: Optional[Dict[int, List[int]]] = None) -> None:
        while self.queue and None in self._by_slot:
            req = self.queue.popleft()
            slot = self._by_slot.index(None)
            split = (
                self._prefix_split(req.prompt_ids)
                if self.prefix_cache is not None
                else 0
            )
            if split:
                logits, rows, pad, next_pos = self._admit_prefix_cached(req, split)
            else:
                logits, rows, pad, next_pos = self._admit_full_prefill(req)
            self.cache = _install_slot(self.cache, rows["k"], rows["v"], slot)
            self._rng, k = jax.random.split(self._rng)
            first = int(
                np.asarray(
                    _sample(
                        logits, k, jnp.float32(req.temperature), req.top_k,
                        jnp.float32(req.top_p),
                    )
                )[0]
            )
            req.out_tokens.append(first)
            if out is not None:
                out.setdefault(req.request_id, []).append(first)
            req.slot = slot
            self._by_slot[slot] = req
            self._tokens[slot] = first
            self._pos[slot] = next_pos  # next write lands after the prompt
            self._pads[slot] = pad
            self._temps[slot] = req.temperature
            self._topks[slot] = req.top_k
            self._topps[slot] = req.top_p
            self.stats["admitted"] += 1
            if len(req.out_tokens) >= req.max_new_tokens or (
                req.eos_id is not None and first == req.eos_id
            ):
                self._finish(slot, req)
