"""cluster_anywhere_tpu.llm: batch LLM inference on the Data library
(analogue of the reference's Ray LLM, python/ray/llm/ — Processor + stages),
TPU-native: the inference stage runs the flagship transformer's compiled
KV-cache generate (models/generate.py) inside actor-pool workers.

    from cluster_anywhere_tpu import llm
    cfg = llm.ProcessorConfig(model=llm.ModelSpec(preset="tiny"), batch_size=8)
    processor = llm.build_llm_processor(
        cfg, preprocess=lambda row: {"prompt": row["text"]}
    )
    out_ds = processor(cad.from_items([{"text": "hello"}]))
"""

from .processor import (
    ByteTokenizer,
    ModelSpec,
    Processor,
    ProcessorConfig,
    build_llm_processor,
)
from .continuous import ContinuousBatcher, Request
from .serve_llm import (
    ContinuousLLMServer,
    LLMServer,
    build_continuous_llm_deployment,
    build_llm_deployment,
)

__all__ = [
    "ProcessorConfig",
    "ModelSpec",
    "Processor",
    "ByteTokenizer",
    "build_llm_processor",
    "LLMServer",
    "build_llm_deployment",
    "ContinuousBatcher",
    "Request",
    "ContinuousLLMServer",
    "build_continuous_llm_deployment",
]
