"""Offline RL: rollout persistence + learning from logged data.

Reference parity: ``rllib/offline/json_writer.py`` / ``json_reader.py``
(SampleBatch JSONL persistence) and ``rllib/algorithms/bc`` (behavior
cloning, the canonical offline baseline).  Batches are stored as npz shards
(dense numeric arrays — the natural jax-side format) with a JSONL manifest
for streaming reads.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, Iterator, List, Optional

import numpy as np


class RolloutWriter:
    """Append rollout batches as npz shards under `path` with a manifest
    (json_writer.py analogue)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.manifest = os.path.join(path, "manifest.jsonl")
        self._n = 0

    def write(self, batch: Dict[str, np.ndarray]) -> str:
        name = f"shard_{int(time.time()*1000)}_{self._n:06d}.npz"
        self._n += 1
        fpath = os.path.join(self.path, name)
        # write via file object so numpy can't append another .npz suffix;
        # the .tmp name keeps a crashed partial write out of any *.npz glob
        with open(fpath + ".tmp", "wb") as f:
            np.savez_compressed(f, **batch)
        os.rename(fpath + ".tmp", fpath)
        rows = int(len(next(iter(batch.values()))))
        with open(self.manifest, "a") as f:
            f.write(json.dumps({"file": name, "rows": rows, "keys": sorted(batch)}) + "\n")
        return fpath


class RolloutReader:
    """Stream shards back (json_reader.py analogue); `sample` draws a
    uniform minibatch across all shards for offline updates."""

    def __init__(self, path: str, seed: int = 0):
        self.path = path
        self.shards: List[str] = []
        manifest = os.path.join(path, "manifest.jsonl")
        if os.path.exists(manifest):
            with open(manifest) as f:
                for line in f:
                    rec = json.loads(line)
                    self.shards.append(os.path.join(path, rec["file"]))
        else:
            self.shards = sorted(
                os.path.join(path, n) for n in os.listdir(path) if n.endswith(".npz")
            )
        if not self.shards:
            raise FileNotFoundError(f"no rollout shards under {path}")
        self.rng = np.random.default_rng(seed)
        self._cache: Optional[Dict[str, np.ndarray]] = None

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        for s in self.shards:
            with np.load(s) as z:
                yield {k: z[k] for k in z.files}

    def _all(self) -> Dict[str, np.ndarray]:
        if self._cache is None:
            parts: Dict[str, list] = {}
            for batch in self:
                for k, v in batch.items():
                    parts.setdefault(k, []).append(v)
            self._cache = {k: np.concatenate(v) for k, v in parts.items()}
        return self._cache

    @property
    def num_rows(self) -> int:
        return int(len(next(iter(self._all().values()))))

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        data = self._all()
        idx = self.rng.integers(0, self.num_rows, size=batch_size)
        return {k: v[idx] for k, v in data.items()}

    def add_derived_column(self, name: str, per_shard_fn) -> None:
        """Attach a computed column aligned with the stored rows.

        per_shard_fn(shard_dict) -> 1-D array of len(shard rows); shards are
        visited in this reader's iteration order, so the concatenation
        matches `_all()`'s row order by construction — callers never need to
        reason about (or reach into) the cache layout.  Used by MARWIL to
        inject per-episode discounted returns.

        A column already present in the data (e.g. returns logged at
        collection time with a different scheme) is honored, not
        overwritten."""
        if name in self._all():
            return
        parts = [np.asarray(per_shard_fn(shard)) for shard in self]
        data = dict(self._all())
        col = np.concatenate(parts)
        if len(col) != self.num_rows:
            raise ValueError(
                f"derived column {name!r} has {len(col)} rows, store has "
                f"{self.num_rows}"
            )
        data[name] = col
        self._cache = data


class BCLearner:
    """Behavior cloning: maximize log-likelihood of the logged actions
    (rllib/algorithms/bc; one jitted cross-entropy update)."""

    def __init__(self, module, *, lr: float = 1e-3, seed: int = 0):
        import jax
        import jax.numpy as jnp
        import optax

        self.module = module
        self.opt = optax.adam(lr)
        self.params = module.init(jax.random.key(seed))
        self.opt_state = self.opt.init(self.params)

        def loss_fn(params, batch):
            logits = module.logits(params, batch["obs"])
            logp = jax.nn.log_softmax(logits)
            nll = -jnp.take_along_axis(logp, batch["actions"][:, None], axis=-1)[:, 0]
            return jnp.mean(nll)

        def update_step(params, opt_state, batch):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        self._update = jax.jit(update_step)

    def get_weights(self):
        return self.params

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax.numpy as jnp

        jb = {
            "obs": jnp.asarray(batch["obs"]),
            "actions": jnp.asarray(batch["actions"]),
        }
        self.params, self.opt_state, loss = self._update(self.params, self.opt_state, jb)
        return {"bc_loss": float(loss)}


def record_rollouts(algo, path: str, num_iterations: int = 1) -> str:
    """Sample from a built Algorithm's env runners and persist the flat
    transitions — the 'generate offline data from a policy' workflow the
    reference documents for BC."""
    from ..core import api as _ca

    writer = RolloutWriter(path)
    for _ in range(num_iterations):
        rollouts = _ca.get(
            [r.sample.remote(algo.config.rollout_length) for r in algo.runners]
        )
        for ro in rollouts:
            ro.pop("metrics", None)
            T, N = ro["rewards"].shape
            acts = ro["actions"]
            if acts.ndim == 3:  # continuous [T, N, A]: keep vectors + dtype
                acts = acts.reshape(T * N, -1).astype(np.float32)
            else:
                acts = acts.reshape(-1).astype(np.int32)
            writer.write({
                "obs": ro["obs"].reshape(T * N, -1).astype(np.float32),
                "actions": acts,
                "rewards": ro["rewards"].reshape(-1).astype(np.float32),
                "dones": ro["dones"].reshape(-1).astype(np.float32),
            })
    return path


def train_bc(
    path: str,
    obs_dim: int,
    num_actions: int,
    *,
    hidden=(64, 64),
    lr: float = 1e-3,
    batch_size: int = 256,
    num_updates: int = 500,
    seed: int = 0,
):
    """Offline BC training loop over logged rollouts; returns the learner."""
    from .module import DiscretePolicyModule

    reader = RolloutReader(path, seed=seed)
    learner = BCLearner(
        DiscretePolicyModule(obs_dim, num_actions, hidden), lr=lr, seed=seed
    )
    stats = {}
    for _ in range(num_updates):
        stats = learner.update(reader.sample(batch_size))
    learner.last_stats = stats
    return learner


class CQLLearner:
    """Discrete conservative Q-learning (reference rllib/algorithms/cql):
    double-DQN TD loss plus the CQL regularizer — logsumexp over all
    actions minus the logged action's Q — which penalizes out-of-dataset
    actions so purely offline data can't inflate unseen-action values.
    One jitted update."""

    def __init__(self, module, *, lr: float = 1e-3, gamma: float = 0.99,
                 cql_alpha: float = 1.0, target_update_freq: int = 100,
                 seed: int = 0):
        import jax
        import jax.numpy as jnp
        import optax

        self.module = module
        self.target_update_freq = target_update_freq
        self.opt = optax.adam(lr)
        self.params = module.init(jax.random.key(seed))
        self.target_params = jax.tree_util.tree_map(lambda x: x, self.params)
        self.opt_state = self.opt.init(self.params)
        self.updates_done = 0

        def loss_fn(params, target_params, batch):
            q = module.q_values(params, batch["obs"])
            q_taken = jnp.take_along_axis(q, batch["actions"][:, None], -1)[:, 0]
            q_next_online = module.q_values(params, batch["next_obs"])
            best = jnp.argmax(q_next_online, axis=-1)
            q_next_target = module.q_values(target_params, batch["next_obs"])
            q_next = jnp.take_along_axis(q_next_target, best[:, None], -1)[:, 0]
            target = batch["rewards"] + gamma * (1.0 - batch["dones"]) * q_next
            td = jnp.mean((q_taken - jax.lax.stop_gradient(target)) ** 2)
            # conservative penalty: push down the soft-max over ALL actions,
            # push up the action the dataset actually took
            cql = jnp.mean(jax.scipy.special.logsumexp(q, axis=-1) - q_taken)
            return td + cql_alpha * cql, (td, cql)

        def update_step(params, target_params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, target_params, batch
            )
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, aux

        self._update = jax.jit(update_step)
        self._tree_copy = jax.tree_util.tree_map

    def get_weights(self):
        return self.params

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax.numpy as jnp

        jb = {
            "obs": jnp.asarray(batch["obs"], jnp.float32),
            "actions": jnp.asarray(batch["actions"], jnp.int32),
            "rewards": jnp.asarray(batch["rewards"], jnp.float32),
            "dones": jnp.asarray(batch["dones"], jnp.float32),
            "next_obs": jnp.asarray(batch["next_obs"], jnp.float32),
        }
        self.params, self.opt_state, loss, (td, cql) = self._update(
            self.params, self.target_params, self.opt_state, jb
        )
        self.updates_done += 1
        if self.updates_done % self.target_update_freq == 0:
            self.target_params = self._tree_copy(lambda x: x, self.params)
        return {"loss": float(loss), "td_loss": float(td), "cql_penalty": float(cql)}


def train_cql(
    path: str,
    obs_dim: int,
    num_actions: int,
    *,
    hidden=(64, 64),
    lr: float = 1e-3,
    gamma: float = 0.99,
    cql_alpha: float = 1.0,
    batch_size: int = 256,
    num_updates: int = 1000,
    seed: int = 0,
):
    """Offline CQL over logged transitions (shards must carry obs/actions/
    rewards/dones/next_obs; record_rollouts writes obs/actions/rewards/dones
    — next_obs is derived by shifting within each shard)."""
    from .module import QModule

    reader = RolloutReader(path, seed=seed)
    data = reader._all()
    if "next_obs" not in data:
        nxt = np.concatenate([data["obs"][1:], data["obs"][-1:]], axis=0)
        data = dict(data, next_obs=nxt)
        reader._cache = data
    learner = CQLLearner(
        QModule(obs_dim, num_actions, hidden),
        lr=lr, gamma=gamma, cql_alpha=cql_alpha, seed=seed,
    )
    stats = {}
    for _ in range(num_updates):
        stats = learner.update(reader.sample(batch_size))
    learner.last_stats = stats
    return learner
