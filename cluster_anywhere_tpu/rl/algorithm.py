"""Algorithm + AlgorithmConfig: the training driver (analogue of the
reference's rllib/algorithms/algorithm.py — EnvRunnerGroup sampling in
parallel actors, a jax Learner updating, weights broadcast each iteration).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional

import numpy as np

from ..core import api as ca
from ..core.actor import kill
from .env import make_env
from .env_runner import EnvRunner
from .learner import DQNLearner, IMPALALearner, PPOLearner, compute_gae
from .module import DiscretePolicyModule, QModule


class AlgorithmConfig:
    def __init__(self, algo: str = "PPO"):
        self.algo = algo
        self.env: Any = "CartPole-v1"
        self.num_env_runners = 2
        self.num_envs_per_runner = 4
        self.rollout_length = 64
        self.gamma = 0.99
        self.lam = 0.95
        self.lr = 3e-4
        self.hidden = (64, 64)
        self.seed = 0
        # ppo
        self.clip = 0.2
        self.epochs = 4
        self.minibatches = 4
        self.entropy_coeff = 0.01
        # dqn
        self.buffer_capacity = 50_000
        self.train_batch_size = 64
        self.target_update_freq = 100
        self.epsilon_decay = 0.99
        self.min_epsilon = 0.05
        self.updates_per_iteration = 32
        # prioritized replay (DQN): proportional PER with IS correction
        self.replay = "uniform"  # uniform | prioritized
        self.per_alpha = 0.6
        self.per_beta = 0.4
        self.per_beta_anneal_steps = 100_000
        # recurrent policy (PPO): GRU core instead of the plain MLP
        self.use_lstm = False
        self.lstm_hidden = 64
        # connector pipelines (rllib/connectors/): factories returning a
        # ConnectorPipeline (or list of connectors); obs transforms run
        # before every policy forward, action transforms before env.step
        self.env_to_module_connector = None
        self.module_to_env_connector = None
        # compiled actor->learner experience edge (flagship: PPO): rollouts
        # arrive over shm channels from a compiled DAG instead of per-
        # iteration RPCs; weights broadcast through the DAG input channel
        self.compiled_dag = False
        # sac
        self.tau = 0.005
        self.target_entropy = None  # default: -action_dim
        # td3
        self.policy_delay = 2
        self.target_noise = 0.2
        self.noise_clip = 0.5
        self.exploration_noise = 0.1

    def environment(self, env) -> "AlgorithmConfig":
        self.env = env
        return self

    def env_runners(self, num_env_runners: int, num_envs_per_runner: int = 4) -> "AlgorithmConfig":
        self.num_env_runners = num_env_runners
        self.num_envs_per_runner = num_envs_per_runner
        return self

    def training(self, **kw) -> "AlgorithmConfig":
        for k, v in kw.items():
            if not hasattr(self, k):
                raise ValueError(f"unknown training option {k!r}")
            setattr(self, k, v)
        return self

    def build(self) -> "Algorithm":
        return Algorithm(self)


class Algorithm:
    def __init__(self, config: AlgorithmConfig):
        self.config = config
        probe = make_env(config.env)
        obs_dim, num_actions = probe.observation_dim, probe.num_actions
        if config.algo == "SAC":
            kind = "gaussian"
        elif config.algo == "TD3":
            kind = "deterministic"
        elif config.algo == "PPO" and config.use_lstm:
            kind = "recurrent"
        elif config.algo in ("PPO", "IMPALA", "APPO"):
            kind = "policy"
        else:
            kind = "q"
        module_spec = {
            "kind": kind,
            "obs_dim": obs_dim,
            "num_actions": num_actions,
            "hidden": config.hidden,
            "lstm_hidden": config.lstm_hidden,
        }
        if kind in ("gaussian", "deterministic"):
            module_spec["action_dim"] = probe.action_dim
            module_spec["action_scale"] = getattr(probe, "action_scale", 1.0)
        if kind == "deterministic":
            module_spec["explore_noise"] = config.exploration_noise
        if kind == "recurrent":
            from .learner import RecurrentPPOLearner
            from .module import RecurrentPolicyModule

            self.module = RecurrentPolicyModule(
                obs_dim, num_actions, config.lstm_hidden
            )
            self.learner = RecurrentPPOLearner(
                self.module,
                lr=config.lr,
                clip=config.clip,
                entropy_coeff=config.entropy_coeff,
                epochs=config.epochs,
                seed=config.seed,
            )
        elif config.algo == "PPO":
            self.module = DiscretePolicyModule(obs_dim, num_actions, config.hidden)
            self.learner = PPOLearner(
                self.module,
                lr=config.lr,
                clip=config.clip,
                entropy_coeff=config.entropy_coeff,
                epochs=config.epochs,
                minibatches=config.minibatches,
                seed=config.seed,
            )
        elif config.algo in ("IMPALA", "APPO"):
            self.module = DiscretePolicyModule(obs_dim, num_actions, config.hidden)
            self.learner = IMPALALearner(
                self.module,
                lr=config.lr,
                gamma=config.gamma,
                entropy_coeff=config.entropy_coeff,
                surrogate_clip=config.clip if config.algo == "APPO" else None,
                seed=config.seed,
            )
            self._pending: Dict[Any, int] = {}  # in-flight sample ref -> runner idx
        elif config.algo == "DQN":
            from .buffer import PrioritizedReplayBuffer, ReplayBuffer

            self.module = QModule(obs_dim, num_actions, config.hidden)
            self.learner = DQNLearner(
                self.module,
                lr=config.lr,
                gamma=config.gamma,
                target_update_freq=config.target_update_freq,
                seed=config.seed,
            )
            if config.replay == "prioritized":
                self.buffer = PrioritizedReplayBuffer(
                    config.buffer_capacity, obs_dim, config.seed,
                    alpha=config.per_alpha, beta=config.per_beta,
                    beta_anneal_steps=config.per_beta_anneal_steps,
                )
            else:
                self.buffer = ReplayBuffer(config.buffer_capacity, obs_dim, config.seed)
            self.epsilon = 1.0
        elif config.algo == "SAC":
            from .buffer import ReplayBuffer
            from .learner import SACLearner
            from .module import SquashedGaussianModule, TwinQModule

            self.module = SquashedGaussianModule(
                obs_dim, probe.action_dim,
                getattr(probe, "action_scale", 1.0), config.hidden,
            )
            self.learner = SACLearner(
                self.module,
                TwinQModule(obs_dim, probe.action_dim, config.hidden),
                lr=config.lr,
                gamma=config.gamma,
                tau=config.tau,
                target_entropy=config.target_entropy,
                seed=config.seed,
            )
            self.buffer = ReplayBuffer(
                config.buffer_capacity, obs_dim, config.seed,
                action_dim=probe.action_dim,
            )
        elif config.algo == "TD3":
            from .buffer import ReplayBuffer
            from .learner import TD3Learner
            from .module import DeterministicPolicyModule, TwinQModule

            self.module = DeterministicPolicyModule(
                obs_dim, probe.action_dim,
                getattr(probe, "action_scale", 1.0), config.hidden,
            )
            self.learner = TD3Learner(
                self.module,
                TwinQModule(obs_dim, probe.action_dim, config.hidden),
                lr=config.lr,
                gamma=config.gamma,
                tau=config.tau,
                policy_delay=config.policy_delay,
                target_noise=config.target_noise,
                noise_clip=config.noise_clip,
                seed=config.seed,
            )
            self.buffer = ReplayBuffer(
                config.buffer_capacity, obs_dim, config.seed,
                action_dim=probe.action_dim,
            )
        else:
            raise ValueError(f"unknown algo {config.algo!r}")
        # resolve string env names to their creator callable here: the
        # registry is per-process, so runner actors must receive something
        # self-contained (cloudpickle ships locally-defined env classes)
        from .env import _ENV_REGISTRY

        env_spec = config.env
        if isinstance(env_spec, str):
            if env_spec not in _ENV_REGISTRY:
                raise KeyError(f"unknown env {env_spec!r}; register_env() it first")
            env_spec = _ENV_REGISTRY[env_spec]
        Runner = ca.remote(EnvRunner)
        self.runners = [
            Runner.remote(
                env_spec,
                module_spec,
                num_envs=config.num_envs_per_runner,
                seed=config.seed + 100 * i,
                explore="sample" if kind in ("policy", "recurrent") else "epsilon",
                env_to_module=config.env_to_module_connector,
                module_to_env=config.module_to_env_connector,
            )
            for i in range(config.num_env_runners)
        ]
        self._broadcast()
        self.iteration = 0
        self._dag = None  # compiled experience edge, built on first use

    def _broadcast(self):
        eps = getattr(self, "epsilon", None)
        ca.get([r.set_weights.remote(self.learner.get_weights(), eps) for r in self.runners])

    def _train_impala(self) -> Dict[str, Any]:
        """One IMPALA iteration: consume one rollout per runner AS THEY
        ARRIVE (actor-learner decoupling — runners keep sampling with lagged
        weights; V-trace corrects), update after each, resubmit immediately
        with fresh weights.  Reference rllib/algorithms/impala/ async mode."""
        cfg = self.config
        t0 = time.monotonic()
        if not self._pending:
            self._pending = {
                r.sample.remote(cfg.rollout_length): i
                for i, r in enumerate(self.runners)
            }
        stats: Dict[str, Any] = {}
        episodes, ep_returns = 0, []
        for _ in range(len(self.runners)):
            ready, _ = ca.wait(list(self._pending), num_returns=1, timeout=120)
            if not ready:
                raise TimeoutError(
                    "IMPALA: no env-runner produced a rollout within 120s "
                    f"({len(self._pending)} in flight)"
                )
            ref = ready[0]
            idx = self._pending.pop(ref)
            ro = ca.get(ref)
            m = ro.pop("metrics")
            episodes += m.get("episodes", 0)
            if "episode_return_mean" in m:
                ep_returns.append(m["episode_return_mean"])
            stats = self.learner.update(ro)
            runner = self.runners[idx]
            runner.set_weights.remote(self.learner.get_weights(), None)
            self._pending[runner.sample.remote(cfg.rollout_length)] = idx
        self.iteration += 1
        out = dict(stats)
        out.update(
            {
                "training_iteration": self.iteration,
                "episodes_this_iter": episodes,
                "env_steps_this_iter": cfg.rollout_length
                * cfg.num_envs_per_runner
                * cfg.num_env_runners,
                "time_this_iter_s": time.monotonic() - t0,
            }
        )
        if ep_returns:
            out["episode_return_mean"] = float(np.mean(ep_returns))
        return out

    def _dag_rollouts(self):
        """Compiled actor->learner experience edge: one shm write broadcasts
        the weights to every runner (the DAG input channel has num_readers=N,
        so the payload crosses process boundaries once, not N times), each
        runner's fused sync_sample ships its rollout back over a tensor-
        transport channel (per-shard buffer borrows, no pickle of array
        bytes) — versus 2N RPCs per iteration on the default path.

        On DeadActorError (a runner died mid-iteration) the DAG recompiles
        against the restarted actors once; a second death in the same
        iteration propagates."""
        from ..core.errors import DeadActorError
        from ..dag import InputNode, MultiOutputNode

        cfg = self.config
        if self._dag is None:
            with InputNode() as inp:
                leaves = [
                    r.sync_sample.bind(inp[0], inp[1]).with_tensor_transport()
                    for r in self.runners
                ]
            self._dag = MultiOutputNode(leaves).experimental_compile(
                max_inflight_executions=2
            )
        try:
            rollouts = self._dag.execute(
                self.learner.get_weights(), cfg.rollout_length
            ).get()
        except DeadActorError:
            self._dag.recompile()
            rollouts = self._dag.execute(
                self.learner.get_weights(), cfg.rollout_length
            ).get()
        # tensor transport lands array leaves on device; compute_gae mutates
        # numpy in place, so bring the rollout arrays back to host here
        return [
            {k: v if k == "metrics" else np.asarray(v) for k, v in ro.items()}
            for ro in rollouts
        ]

    def train(self) -> Dict[str, Any]:
        cfg = self.config
        if cfg.algo in ("IMPALA", "APPO"):
            return self._train_impala()
        t0 = time.monotonic()
        use_dag = cfg.compiled_dag and cfg.algo == "PPO" and not cfg.use_lstm
        if use_dag:
            rollouts = self._dag_rollouts()
        else:
            rollouts = ca.get(
                [r.sample.remote(cfg.rollout_length) for r in self.runners]
            )
        metrics: Dict[str, Any] = {}
        episodes, ep_returns = 0, []
        for ro in rollouts:
            m = ro.pop("metrics")
            episodes += m.get("episodes", 0)
            if "episode_return_mean" in m:
                ep_returns.append(m["episode_return_mean"])
        if cfg.algo == "PPO" and cfg.use_lstm:
            # sequence-shaped batch: runners concatenate along the env axis,
            # each sequence unrolled from its recorded initial hidden state
            batches = []
            for ro in rollouts:
                a, r = compute_gae(ro, cfg.gamma, cfg.lam)
                T, N = ro["rewards"].shape
                batches.append(
                    {
                        "obs": ro["obs"],
                        "actions": ro["actions"],
                        "logp_old": ro["logp"],
                        "dones": ro["dones"].astype(np.float32),
                        "advantages": a.reshape(T, N),
                        "returns": r.reshape(T, N),
                        "state0": ro["state0"],
                    }
                )
            batch = {
                k: np.concatenate(
                    [b[k] for b in batches], axis=0 if k == "state0" else 1
                )
                for k in batches[0]
            }
            stats = self.learner.update(batch)
        elif cfg.algo == "PPO":
            advs, rets, batches = [], [], []
            for ro in rollouts:
                a, r = compute_gae(ro, cfg.gamma, cfg.lam)
                obs = ro["obs"].reshape(-1, ro["obs"].shape[-1])
                batches.append(
                    {
                        "obs": obs,
                        "actions": ro["actions"].reshape(-1),
                        "logp_old": ro["logp"].reshape(-1),
                        "advantages": a,
                        "returns": r,
                    }
                )
            batch = {
                k: np.concatenate([b[k] for b in batches]) for k in batches[0]
            }
            stats = self.learner.update(batch)
        else:
            for ro in rollouts:
                T, N = ro["rewards"].shape
                obs = ro["obs"]
                next_obs = np.concatenate([obs[1:], ro["next_obs"][None]], axis=0)
                acts = ro["actions"]
                # continuous actions are [T, N, A]; discrete are [T, N]
                acts = acts.reshape(T * N, -1) if acts.ndim == 3 else acts.reshape(-1)
                self.buffer.add_batch(
                    obs.reshape(T * N, -1),
                    acts,
                    ro["rewards"].reshape(-1),
                    ro["dones"].reshape(-1).astype(np.float32),
                    next_obs.reshape(T * N, -1),
                )
            stats = {}
            if len(self.buffer) >= cfg.train_batch_size:
                for _ in range(cfg.updates_per_iteration):
                    stats = self.learner.update(self.buffer.sample(cfg.train_batch_size))
                    td_abs = stats.pop("td_abs", None)
                    indices = stats.pop("indices", None)
                    if td_abs is not None and hasattr(self.buffer, "update_priorities"):
                        self.buffer.update_priorities(indices, td_abs)
            if cfg.algo == "DQN":
                self.epsilon = max(cfg.min_epsilon, self.epsilon * cfg.epsilon_decay)
        if not use_dag:
            # dag path: fresh weights ride the NEXT execute()'s input write,
            # so a post-update RPC broadcast would be pure overhead
            self._broadcast()
        self.iteration += 1
        metrics.update(stats)
        metrics.update(
            {
                "training_iteration": self.iteration,
                "episodes_this_iter": episodes,
                "env_steps_this_iter": cfg.rollout_length
                * cfg.num_envs_per_runner
                * cfg.num_env_runners,
                "time_this_iter_s": time.monotonic() - t0,
            }
        )
        if ep_returns:
            metrics["episode_return_mean"] = float(np.mean(ep_returns))
        return metrics

    def evaluate(self, num_episodes: int = 5) -> float:
        return ca.get(self.runners[0].evaluate.remote(num_episodes))

    # ------------------------------------------------------------ checkpoint
    def save(self, path: str) -> str:
        from ..llm import _params_io

        _params_io.save_params({"weights": self.learner.get_weights()}, path)
        # connector stats (e.g. obs normalizer) are part of the policy: a
        # restored policy without them sees differently-scaled observations.
        # Side file (pickle): the state nests lists/None, which the flat
        # npz params format doesn't model
        cs = ca.get(self.runners[0].connector_state.remote())
        if cs is not None:
            import pickle

            with open(os.path.join(path, "connectors.pkl"), "wb") as f:
                pickle.dump(cs, f)
        return path

    def load(self, path: str):
        from ..llm import _params_io

        self.learner.params = _params_io.load_params(path)["weights"]
        cpath = os.path.join(path, "connectors.pkl")
        if os.path.exists(cpath):
            import pickle

            with open(cpath, "rb") as f:
                cs = pickle.load(f)
            ca.get(
                [
                    r.set_weights.remote(self.learner.get_weights(), None, cs)
                    for r in self.runners
                ]
            )
        self._broadcast()

    def stop(self):
        if self._dag is not None:
            try:
                self._dag.teardown()
            except Exception:
                pass
            self._dag = None
        for r in self.runners:
            try:
                kill(r)
            except Exception:
                pass
