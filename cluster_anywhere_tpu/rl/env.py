"""Environment API + built-in envs (analogue of the reference's
rllib/env/ — gymnasium-style step/reset; CartPole implemented in numpy so
tests run without external deps, vectorized for batched sampling).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np


class Env:
    """Single environment: gymnasium-style API."""

    observation_dim: int
    num_actions: int

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: int) -> Tuple[np.ndarray, float, bool, Dict[str, Any]]:
        raise NotImplementedError


class CartPole(Env):
    """Classic control CartPole-v1 dynamics (Barto, Sutton, Anderson)."""

    observation_dim = 4
    num_actions = 2
    max_steps = 500

    def __init__(self):
        self.gravity = 9.8
        self.masscart, self.masspole = 1.0, 0.1
        self.total_mass = self.masscart + self.masspole
        self.length = 0.5
        self.polemass_length = self.masspole * self.length
        self.force_mag = 10.0
        self.tau = 0.02
        self.theta_threshold = 12 * 2 * np.pi / 360
        self.x_threshold = 2.4
        self.rng = np.random.default_rng()
        self.state = np.zeros(4)
        self.steps = 0

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self.rng = np.random.default_rng(seed)
        self.state = self.rng.uniform(-0.05, 0.05, size=4)
        self.steps = 0
        return self.state.astype(np.float32)

    def step(self, action: int):
        x, x_dot, theta, theta_dot = self.state
        force = self.force_mag if action == 1 else -self.force_mag
        costheta, sintheta = np.cos(theta), np.sin(theta)
        temp = (force + self.polemass_length * theta_dot**2 * sintheta) / self.total_mass
        thetaacc = (self.gravity * sintheta - costheta * temp) / (
            self.length * (4.0 / 3.0 - self.masspole * costheta**2 / self.total_mass)
        )
        xacc = temp - self.polemass_length * thetaacc * costheta / self.total_mass
        x = x + self.tau * x_dot
        x_dot = x_dot + self.tau * xacc
        theta = theta + self.tau * theta_dot
        theta_dot = theta_dot + self.tau * thetaacc
        self.state = np.array([x, x_dot, theta, theta_dot])
        self.steps += 1
        terminated = bool(
            abs(x) > self.x_threshold
            or abs(theta) > self.theta_threshold
            or self.steps >= self.max_steps
        )
        return self.state.astype(np.float32), 1.0, terminated, {}


class Pendulum(Env):
    """Classic control Pendulum-v1 dynamics: continuous torque in
    [-max_torque, max_torque] swings the pole upright.  The continuous-action
    counterpart of CartPole for SAC coverage (rllib/algorithms/sac trains on
    exactly this family)."""

    observation_dim = 3
    num_actions = 0  # continuous
    continuous = True
    action_dim = 1
    action_scale = 2.0  # torque bound
    max_steps = 200

    def __init__(self):
        self.g, self.m, self.length = 10.0, 1.0, 1.0
        self.dt = 0.05
        self.max_speed = 8.0
        self.rng = np.random.default_rng()
        self.th = 0.0
        self.thdot = 0.0
        self.steps = 0

    def _obs(self) -> np.ndarray:
        return np.array(
            [np.cos(self.th), np.sin(self.th), self.thdot], np.float32
        )

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self.rng = np.random.default_rng(seed)
        self.th = self.rng.uniform(-np.pi, np.pi)
        self.thdot = self.rng.uniform(-1.0, 1.0)
        self.steps = 0
        return self._obs()

    def step(self, action):
        u = float(np.clip(np.asarray(action).reshape(-1)[0], -self.action_scale, self.action_scale))
        th_norm = ((self.th + np.pi) % (2 * np.pi)) - np.pi
        cost = th_norm**2 + 0.1 * self.thdot**2 + 0.001 * u**2
        self.thdot = self.thdot + (
            3 * self.g / (2 * self.length) * np.sin(self.th)
            + 3.0 / (self.m * self.length**2) * u
        ) * self.dt
        self.thdot = float(np.clip(self.thdot, -self.max_speed, self.max_speed))
        self.th = self.th + self.thdot * self.dt
        self.steps += 1
        return self._obs(), -float(cost), self.steps >= self.max_steps, {}


class MemoryChain(Env):
    """Memory-requiring diagnostic env (the T-maze family rllib uses to
    exercise use_lstm): a binary cue is visible ONLY on the first step; after
    `corridor` blank steps the agent must emit the cue as its action.
    Reward +1 for recalling correctly at the final step, -1 otherwise, 0 in
    the corridor.  A memoryless policy cannot beat 0 expected return; a
    recurrent one reaches ~+1."""

    observation_dim = 3  # [cue_is_0, cue_is_1, at_query_step]
    num_actions = 2

    def __init__(self, corridor: int = 4):
        self.corridor = corridor
        self.rng = np.random.default_rng()
        self.cue = 0
        self.t = 0

    def _obs(self) -> np.ndarray:
        o = np.zeros(3, np.float32)
        if self.t == 0:
            o[self.cue] = 1.0
        if self.t == self.corridor:
            o[2] = 1.0
        return o

    def reset(self, seed: Optional[int] = None) -> np.ndarray:
        if seed is not None:
            self.rng = np.random.default_rng(seed)
        self.cue = int(self.rng.integers(0, 2))
        self.t = 0
        return self._obs()

    def step(self, action: int):
        if self.t >= self.corridor:
            r = 1.0 if int(action) == self.cue else -1.0
            return self._obs(), r, True, {}
        self.t += 1
        return self._obs(), 0.0, False, {}


_ENV_REGISTRY: Dict[str, Callable[[], Env]] = {
    "CartPole-v1": CartPole,
    "Pendulum-v1": Pendulum,
    "MemoryChain-v0": MemoryChain,
}


def register_env(name: str, creator: Callable[[], Env]):
    _ENV_REGISTRY[name] = creator


def make_env(name_or_creator) -> Env:
    if callable(name_or_creator):
        return name_or_creator()
    if name_or_creator in _ENV_REGISTRY:
        return _ENV_REGISTRY[name_or_creator]()
    raise KeyError(f"unknown env {name_or_creator!r}; register_env() it first")


class VectorEnv:
    """N independent env copies with auto-reset (reference: vectorized
    sampling inside SingleAgentEnvRunner)."""

    def __init__(self, name_or_creator, num_envs: int, seed: int = 0):
        self.envs = [make_env(name_or_creator) for _ in range(num_envs)]
        self.obs = np.stack([e.reset(seed + i) for i, e in enumerate(self.envs)])
        self.episode_returns = np.zeros(num_envs)
        self.completed_returns: list = []
        self.continuous = bool(getattr(self.envs[0], "continuous", False))

    @property
    def num_envs(self) -> int:
        return len(self.envs)

    def step(self, actions: np.ndarray):
        obs, rewards, dones = [], [], []
        for i, (e, a) in enumerate(zip(self.envs, actions)):
            o, r, d, _ = e.step(a if self.continuous else int(a))
            self.episode_returns[i] += r
            if d:
                self.completed_returns.append(self.episode_returns[i])
                self.episode_returns[i] = 0.0
                o = e.reset()
            obs.append(o)
            rewards.append(r)
            dones.append(d)
        self.obs = np.stack(obs)
        return self.obs, np.asarray(rewards, np.float32), np.asarray(dones)

    def drain_metrics(self) -> Dict[str, float]:
        rets = self.completed_returns
        self.completed_returns = []
        if not rets:
            return {"episodes": 0}
        return {
            "episodes": len(rets),
            "episode_return_mean": float(np.mean(rets)),
            "episode_return_max": float(np.max(rets)),
        }
