"""DreamerV3 (compact): model-based RL via a recurrent state-space world
model and an actor-critic trained purely in imagination.

Reference parity: ``rllib/algorithms/dreamerv3`` (the reference's port of
Hafner et al., 2023).  This is an independent jax implementation of the
algorithm's core, sized for vector-observation control tasks:

- **RSSM**: deterministic GRU path + categorical stochastic latents
  (groups x classes, straight-through gradients, 1% unimix), prior from
  h_t, posterior from [h_t, enc(o_t)].
- **Heads**: decoder (symlog MSE), reward (twohot over symlog bins),
  continue (Bernoulli).
- **World-model loss**: prediction terms + KL balancing (dyn/rep scales
  with free bits).
- **Imagination actor-critic**: H-step latent rollouts from posterior
  starts; lambda-returns; critic twohot regression with an EMA target
  network; actor REINFORCE with returns normalized by an EMA of the
  5th-95th percentile range (the V3 robustness trick) + entropy bonus.

Everything jits end-to-end: the world-model update, the imagination
update, and the per-step act() are three compiled functions with static
shapes (scan over sequence/horizon).
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Optional

import numpy as np

# ------------------------------------------------------------------ symlog


def symlog(x):
    import jax.numpy as jnp

    return jnp.sign(x) * jnp.log1p(jnp.abs(x))


def symexp(x):
    import jax.numpy as jnp

    return jnp.sign(x) * (jnp.exp(jnp.abs(x)) - 1.0)


def twohot(x, bins):
    """Two-hot encode scalar x over a 1-D bin grid (piecewise-linear)."""
    import jax.numpy as jnp

    import jax

    x = jnp.clip(x, bins[0], bins[-1])
    idx = jnp.sum((bins[None, :] <= x[..., None]).astype(jnp.int32), axis=-1) - 1
    idx = jnp.clip(idx, 0, len(bins) - 2)
    lo, hi = bins[idx], bins[idx + 1]
    w_hi = (x - lo) / jnp.maximum(hi - lo, 1e-8)
    onehot_lo = jax.nn.one_hot(idx, len(bins))
    onehot_hi = jax.nn.one_hot(idx + 1, len(bins))
    return onehot_lo * (1 - w_hi)[..., None] + onehot_hi * w_hi[..., None]


class DreamerConfig(NamedTuple):
    obs_dim: int = 4
    num_actions: int = 2
    hidden: int = 128
    deter: int = 128
    groups: int = 8
    classes: int = 8
    num_bins: int = 41
    horizon: int = 10
    seq_len: int = 16
    batch_size: int = 16
    wm_lr: float = 3e-4
    ac_lr: float = 1e-4
    gamma: float = 0.985
    lam: float = 0.95
    entropy: float = 3e-3
    kl_dyn: float = 0.5
    kl_rep: float = 0.1
    free_bits: float = 1.0
    unimix: float = 0.01
    critic_ema: float = 0.02
    retnorm_decay: float = 0.99


def _mlp_params(key, sizes):
    import jax
    import jax.numpy as jnp

    params = []
    for i, (m, n) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, k = jax.random.split(key)
        params.append(
            {
                "w": jax.random.normal(k, (m, n), jnp.float32) * (2.0 / m) ** 0.5,
                "b": jnp.zeros((n,), jnp.float32),
            }
        )
    return params


def _mlp(params, x, act_last=False):
    import jax

    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1 or act_last:
            x = jax.nn.silu(x)
    return x


def _gru_params(key, in_dim, hidden):
    import jax
    import jax.numpy as jnp

    k1, k2 = jax.random.split(key)
    s = (2.0 / (in_dim + hidden)) ** 0.5
    return {
        "wi": jax.random.normal(k1, (in_dim, 3 * hidden), jnp.float32) * s,
        "wh": jax.random.normal(k2, (hidden, 3 * hidden), jnp.float32) * s,
        "b": jnp.zeros((3 * hidden,), jnp.float32),
    }


def _gru(p, h, x):
    import jax
    import jax.numpy as jnp

    gates = x @ p["wi"] + h @ p["wh"] + p["b"]
    r, z, n = jnp.split(gates, 3, axis=-1)
    r, z = jax.nn.sigmoid(r), jax.nn.sigmoid(z)
    n = jnp.tanh(r * n)
    return (1 - z) * n + z * h


class DreamerV3Learner:
    """World model + imagination actor-critic with jitted updates."""

    def __init__(self, cfg: DreamerConfig, seed: int = 0):
        import jax
        import jax.numpy as jnp
        import optax

        self.cfg = cfg
        self.jax, self.jnp = jax, jnp
        stoch = cfg.groups * cfg.classes
        feat = cfg.deter + stoch
        key = jax.random.key(seed)
        ks = jax.random.split(key, 12)
        self.params = {
            "enc": _mlp_params(ks[0], [cfg.obs_dim, cfg.hidden, cfg.hidden]),
            "gru": _gru_params(ks[1], stoch + cfg.num_actions, cfg.deter),
            "prior": _mlp_params(ks[2], [cfg.deter, cfg.hidden, stoch]),
            "post": _mlp_params(ks[3], [cfg.deter + cfg.hidden, cfg.hidden, stoch]),
            "dec": _mlp_params(ks[4], [feat, cfg.hidden, cfg.obs_dim]),
            "rew": _mlp_params(ks[5], [feat, cfg.hidden, cfg.num_bins]),
            "cont": _mlp_params(ks[6], [feat, cfg.hidden, 1]),
        }
        self.ac_params = {
            "actor": _mlp_params(ks[7], [feat, cfg.hidden, cfg.num_actions]),
            "critic": _mlp_params(ks[8], [feat, cfg.hidden, cfg.num_bins]),
        }
        self.target_critic = jax.tree.map(lambda x: x, self.ac_params["critic"])
        self.bins = jnp.linspace(-10.0, 10.0, cfg.num_bins)  # symlog space
        self.wm_opt = optax.chain(
            optax.clip_by_global_norm(100.0), optax.adam(cfg.wm_lr)
        )
        self.ac_opt = optax.chain(
            optax.clip_by_global_norm(100.0), optax.adam(cfg.ac_lr)
        )
        self.wm_state = self.wm_opt.init(self.params)
        self.ac_state = self.ac_opt.init(self.ac_params)
        self.ret_range = jnp.asarray(1.0)  # EMA of return 5-95 percentile
        self._build()

    # ------------------------------------------------------------ primitives

    def _sample_latent(self, key, logits):
        """Straight-through categorical sample per group with unimix."""
        jax, jnp = self.jax, self.jnp
        cfg = self.cfg
        logits = logits.reshape(logits.shape[:-1] + (cfg.groups, cfg.classes))
        probs = jax.nn.softmax(logits, -1)
        probs = (1 - cfg.unimix) * probs + cfg.unimix / cfg.classes
        logp = jnp.log(probs)
        idx = jax.random.categorical(key, logp)
        onehot = jax.nn.one_hot(idx, cfg.classes)
        sample = onehot + probs - jax.lax.stop_gradient(probs)  # straight-through
        return sample.reshape(sample.shape[:-2] + (-1,)), logp

    def _head_scalar(self, logits):
        """Expected value of a twohot head, decoded from symlog space."""
        jax, jnp = self.jax, self.jnp
        probs = jax.nn.softmax(logits, -1)
        return symexp(jnp.sum(probs * self.bins, -1))

    def _twohot_nll(self, logits, target_scalar):
        jax, jnp = self.jax, self.jnp
        target = twohot(symlog(target_scalar), self.bins)
        logp = jax.nn.log_softmax(logits, -1)
        return -jnp.sum(target * logp, -1)

    # ---------------------------------------------------------------- build

    def _build(self):
        jax, jnp = self.jax, self.jnp
        cfg = self.cfg
        stoch = cfg.groups * cfg.classes

        def obs_step(params, key, h, z_prev, a_prev_onehot, obs):
            """One posterior RSSM step."""
            h = _gru(params["gru"], h, jnp.concatenate([z_prev, a_prev_onehot], -1))
            e = _mlp(params["enc"], obs, act_last=True)
            post_logits = _mlp(params["post"], jnp.concatenate([h, e], -1))
            prior_logits = _mlp(params["prior"], h)
            z, _ = self._sample_latent(key, post_logits)
            return h, z, post_logits, prior_logits

        def wm_loss(params, key, batch):
            """batch: obs [B,L,O], actions [B,L] int, rewards [B,L],
            cont [B,L] (1 - done)."""
            B, L = batch["actions"].shape
            a_onehot = jax.nn.one_hot(batch["actions"], cfg.num_actions)
            keys = jax.random.split(key, L)

            is_first = batch["is_first"]

            def step(carry, t):
                h, z = carry
                # episode boundary inside the segment: reset the recurrent
                # state and the previous action (stream replay — segments
                # span episodes, the canonical Dreamer data pipeline)
                keep = (1.0 - is_first[:, t])[:, None]
                h = h * keep
                z = z * keep
                a_prev = jnp.where(
                    t == 0, jnp.zeros_like(a_onehot[:, 0]), a_onehot[:, t - 1]
                ) * keep
                h, z, post_l, prior_l = obs_step(
                    params, keys[t], h, z, a_prev, batch["obs"][:, t]
                )
                return (h, z), (h, z, post_l, prior_l)

            h0 = jnp.zeros((B, cfg.deter))
            z0 = jnp.zeros((B, stoch))
            (_, _), (hs, zs, post_l, prior_l) = jax.lax.scan(
                step, (h0, z0), jnp.arange(L)
            )
            # scan stacks time-major: [L, B, ...] -> [B, L, ...]
            hs, zs = hs.transpose(1, 0, 2), zs.transpose(1, 0, 2)
            post_l = post_l.transpose(1, 0, 2)
            prior_l = prior_l.transpose(1, 0, 2)
            feat = jnp.concatenate([hs, zs], -1)

            recon = _mlp(params["dec"], feat)
            loss_obs = jnp.mean(jnp.sum((recon - symlog(batch["obs"])) ** 2, -1))
            loss_rew = jnp.mean(
                self._twohot_nll(_mlp(params["rew"], feat), batch["rewards"])
            )
            cont_logit = _mlp(params["cont"], feat)[..., 0]
            loss_cont = jnp.mean(
                optax_sigmoid_ce(cont_logit, batch["cont"])
            )

            def cat_kl(lp, lq):
                """KL(p || q) per group, summed over groups; unimix'd."""
                shape = lp.shape[:-1] + (cfg.groups, cfg.classes)
                p = jax.nn.softmax(lp.reshape(shape), -1)
                p = (1 - cfg.unimix) * p + cfg.unimix / cfg.classes
                q = jax.nn.softmax(lq.reshape(shape), -1)
                q = (1 - cfg.unimix) * q + cfg.unimix / cfg.classes
                return jnp.sum(p * (jnp.log(p) - jnp.log(q)), (-2, -1))

            sg = jax.lax.stop_gradient
            kl_dyn = jnp.maximum(
                jnp.mean(cat_kl(sg(post_l), prior_l)), cfg.free_bits
            )
            kl_rep = jnp.maximum(
                jnp.mean(cat_kl(post_l, sg(prior_l))), cfg.free_bits
            )
            loss = (
                loss_obs
                + loss_rew
                + loss_cont
                + cfg.kl_dyn * kl_dyn
                + cfg.kl_rep * kl_rep
            )
            aux = {
                "wm_loss": loss,
                "obs_loss": loss_obs,
                "rew_loss": loss_rew,
                "kl_dyn": kl_dyn,
                "feat": feat,
            }
            return loss, aux

        import optax

        def optax_sigmoid_ce(logits, labels):
            return optax.sigmoid_binary_cross_entropy(logits, labels)

        def wm_update(params, opt_state, key, batch):
            (loss, aux), grads = jax.value_and_grad(wm_loss, has_aux=True)(
                params, key, batch
            )
            updates, opt_state = self.wm_opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, aux

        def imagine(wm_params, ac_params, key, feat0):
            """Roll the actor H steps through the PRIOR dynamics."""
            N = feat0.shape[0]
            h0 = feat0[:, : cfg.deter]
            z0 = feat0[:, cfg.deter :]
            keys = jax.random.split(key, cfg.horizon)

            def step(carry, k):
                h, z = carry
                ka, kz = jax.random.split(k)
                logits = _mlp(ac_params["actor"], jnp.concatenate([h, z], -1))
                a = jax.random.categorical(ka, logits)
                a_onehot = jax.nn.one_hot(a, cfg.num_actions)
                h = _gru(wm_params["gru"], h, jnp.concatenate([z, a_onehot], -1))
                prior_logits = _mlp(wm_params["prior"], h)
                z, _ = self._sample_latent(kz, prior_logits)
                return (h, z), (jnp.concatenate([h, z], -1), a)

            (_, _), (feats, acts) = jax.lax.scan(step, (h0, z0), keys)
            return feats, acts  # [H, N, F], [H, N]

        def ac_loss(ac_params, wm_params, target_critic, ret_range, key, feat0):
            sg = jax.lax.stop_gradient
            feats_post, acts = imagine(wm_params, ac_params, key, feat0)
            # state indexing: feat0 = s_0 (where a_0 is chosen);
            # feats_post[t] = s_{t+1} (reached by a_t).  Rewards/continues
            # belong to the arrived-at states s_1..s_H; action log-probs and
            # advantages to the pre-action states s_0..s_{H-1}.
            feats_pre = sg(
                jnp.concatenate([feat0[None], feats_post[:-1]], 0)
            )  # [H, N, F] = s_0..s_{H-1}
            feats_post = sg(feats_post)
            rew = self._head_scalar(_mlp(wm_params["rew"], feats_post))
            cont = jax.nn.sigmoid(_mlp(wm_params["cont"], feats_post)[..., 0])
            disc = cfg.gamma * cont
            v_post = self._head_scalar(_mlp(target_critic, feats_post))

            # lambda-returns at s_0..s_{H-1}:
            #   R_t = r_{t+1} + gamma c_{t+1} ((1-lam) V(s_{t+1}) + lam R_{t+1})
            def ret_step(nxt, t):
                r = rew[t] + disc[t] * ((1 - cfg.lam) * v_post[t] + cfg.lam * nxt)
                return r, r

            _, rets = jax.lax.scan(
                ret_step, v_post[-1], jnp.arange(cfg.horizon - 1, -1, -1)
            )
            rets = rets[::-1]  # [H, N]: rets[t] = R at s_t

            # percentile return scale (EMA outside)
            lo = jnp.percentile(rets, 5)
            hi = jnp.percentile(rets, 95)
            new_range = jnp.maximum(hi - lo, 1.0)

            critic_logits = _mlp(ac_params["critic"], feats_pre)
            critic_loss = jnp.mean(self._twohot_nll(critic_logits, sg(rets)))

            actor_logits = _mlp(ac_params["actor"], feats_pre)
            logp = jax.nn.log_softmax(actor_logits, -1)
            act_logp = jnp.take_along_axis(logp, acts[..., None], -1)[..., 0]
            v_pre = self._head_scalar(critic_logits)
            adv = sg((rets - v_pre) / jnp.maximum(ret_range, 1.0))
            entropy = -jnp.sum(jnp.exp(logp) * logp, -1)
            # weight[t] = probability the imagined trajectory is still alive
            # AT s_t (products of continues up to s_t); s_0 is alive
            weight = sg(jnp.cumprod(
                jnp.concatenate([jnp.ones_like(disc[:1]), disc[:-1]], 0), 0
            ))
            actor_loss = -jnp.mean(
                weight * (act_logp * adv + cfg.entropy * entropy)
            )
            total = actor_loss + critic_loss
            return total, (new_range, jnp.mean(rets), jnp.mean(entropy))

        self._wm_update = jax.jit(wm_update)

        def _ac_impl(wm_params, ac_params, opt_state, target_critic, ret_range,
                     key, feat0):
            (loss, (new_range, ret_mean, ent)), grads = jax.value_and_grad(
                ac_loss, has_aux=True
            )(ac_params, wm_params, target_critic, ret_range, key, feat0)
            import optax as _optax

            updates, opt_state = self.ac_opt.update(grads, opt_state, ac_params)
            ac_params = _optax.apply_updates(ac_params, updates)
            target_critic = jax.tree.map(
                lambda t, o: (1 - cfg.critic_ema) * t + cfg.critic_ema * o,
                target_critic,
                ac_params["critic"],
            )
            ret_range = (
                cfg.retnorm_decay * ret_range + (1 - cfg.retnorm_decay) * new_range
            )
            return ac_params, opt_state, target_critic, ret_range, (loss, ret_mean, ent)

        self._ac_update = jax.jit(_ac_impl)

        def act_fn(wm_params, ac_params, key, h, z, a_prev_onehot, obs, greedy):
            k_latent, k_act = jax.random.split(key)
            h, z, _, _ = obs_step(wm_params, k_latent, h, z, a_prev_onehot, obs)
            logits = _mlp(ac_params["actor"], jnp.concatenate([h, z], -1))
            a_sample = jax.random.categorical(k_act, logits)
            a_greedy = jnp.argmax(logits, -1)
            a = jnp.where(greedy, a_greedy, a_sample)
            return h, z, a

        self._act = jax.jit(act_fn)

    # ------------------------------------------------------------------- api

    def init_state(self, batch: int = 1):
        jnp = self.jnp
        cfg = self.cfg
        return (
            jnp.zeros((batch, cfg.deter)),
            jnp.zeros((batch, cfg.groups * cfg.classes)),
            jnp.zeros((batch, cfg.num_actions)),
        )

    def act(self, key, state, obs, greedy=False):
        h, z, a_prev = state
        jnp = self.jnp
        obs = jnp.asarray(obs, jnp.float32)[None]
        h, z, a = self._act(
            self.params, self.ac_params, key, h, z, a_prev, obs, greedy
        )
        a_onehot = self.jax.nn.one_hot(a, self.cfg.num_actions)
        return (h, z, a_onehot), int(a[0])

    def update(self, key, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        jax, jnp = self.jax, self.jnp
        k1, k2 = jax.random.split(key)
        jb = {
            "obs": jnp.asarray(batch["obs"], jnp.float32),
            "actions": jnp.asarray(batch["actions"], jnp.int32),
            "rewards": jnp.asarray(batch["rewards"], jnp.float32),
            "cont": jnp.asarray(batch["cont"], jnp.float32),
            "is_first": jnp.asarray(batch["is_first"], jnp.float32),
        }
        self.params, self.wm_state, aux = self._wm_update(
            self.params, self.wm_state, k1, jb
        )
        feat = aux.pop("feat").reshape(-1, self.cfg.deter + self.cfg.groups * self.cfg.classes)
        (
            self.ac_params,
            self.ac_state,
            self.target_critic,
            self.ret_range,
            (ac_l, ret_mean, ent),
        ) = self._ac_update(
            self.params, self.ac_params, self.ac_state, self.target_critic,
            self.ret_range, k2, feat,
        )
        out = {k: float(v) for k, v in aux.items()}
        out.update(ac_loss=float(ac_l), ret_mean=float(ret_mean), entropy=float(ent))
        return out


class _SeqReplay:
    """Stream replay: episodes concatenate into one step stream with
    is_first flags; any L-window is sampleable (segments span episode
    boundaries, which wm_loss handles by resetting the RSSM state).  A
    per-episode sampler silently excludes episodes shorter than L — a
    degrading policy then stops contributing data at all, making collapse
    an absorbing state (observed)."""

    def __init__(self, seq_len: int, capacity: int = 200_000, seed: int = 0):
        self.seq_len = seq_len
        self.capacity = capacity
        self.rng = np.random.default_rng(seed)
        self.obs: list = []
        self.actions: list = []
        self.rewards: list = []
        self.cont: list = []
        self.is_first: list = []

    def add_episode(self, obs, actions, rewards, dones):
        n = len(actions)
        self.obs.extend(np.asarray(o, np.float32) for o in obs)
        self.actions.extend(int(a) for a in actions)
        self.rewards.extend(float(r) for r in rewards)
        self.cont.extend(1.0 - float(d) for d in dones)
        self.is_first.extend([1.0] + [0.0] * (n - 1))
        if len(self.actions) > self.capacity:
            cut = len(self.actions) - self.capacity
            for lst in (self.obs, self.actions, self.rewards, self.cont,
                        self.is_first):
                del lst[:cut]
            if self.is_first:
                self.is_first[0] = 1.0  # truncated head starts a segment

    @property
    def num_steps(self):
        return len(self.actions)

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        L = self.seq_len
        if len(self.actions) < L:
            raise ValueError("replay shorter than one segment")
        out = {"obs": [], "actions": [], "rewards": [], "cont": [],
               "is_first": []}
        for _ in range(batch_size):
            start = int(self.rng.integers(0, len(self.actions) - L + 1))
            sl = slice(start, start + L)
            out["obs"].append(np.stack(self.obs[sl]))
            out["actions"].append(np.asarray(self.actions[sl], np.int32))
            out["rewards"].append(np.asarray(self.rewards[sl], np.float32))
            out["cont"].append(np.asarray(self.cont[sl], np.float32))
            first = np.asarray(self.is_first[sl], np.float32)
            first[0] = 1.0  # a window head is always a fresh RSSM start
            out["is_first"].append(first)
        return {k: np.stack(v) for k, v in out.items()}


def train_dreamer(
    env_maker,
    *,
    cfg: Optional[DreamerConfig] = None,
    episodes: int = 60,
    updates_per_episode: int = 20,
    seed: int = 0,
    warmup_episodes: int = 5,
    explore_eps: float = 0.1,
) -> DreamerV3Learner:
    """Online DreamerV3 loop: collect an episode with the current policy,
    then train the world model + imagination actor-critic from replay.

    explore_eps: epsilon-random actions during collection.  Imagination
    training is only as good as the model, and the model only knows the
    data — without a floor of exploration an early actor collapse makes the
    replay single-action and the collapse self-reinforcing."""
    import jax

    env = env_maker()
    if cfg is None:
        cfg = DreamerConfig(
            obs_dim=env.observation_dim, num_actions=env.num_actions
        )
    learner = DreamerV3Learner(cfg, seed=seed)
    replay = _SeqReplay(cfg.seq_len, seed=seed)
    key = jax.random.key(seed + 1)
    rng = np.random.default_rng(seed)
    returns = []
    for ep in range(episodes):
        obs = env.reset(seed=int(rng.integers(2**31)))
        state = learner.init_state(1)
        ep_obs, ep_act, ep_rew = [], [], []
        done = False
        while not done:
            key, k = jax.random.split(key)
            if ep < warmup_episodes:
                a = int(rng.integers(env.num_actions))
                # still advance the RSSM state so a_prev stays consistent
                state, _ = learner.act(k, state, obs)
                h, z, _ = state
                state = (h, z, learner.jax.nn.one_hot(
                    learner.jnp.asarray([a]), learner.cfg.num_actions))
            else:
                state, a = learner.act(k, state, obs)
                if rng.random() < explore_eps:
                    a = int(rng.integers(env.num_actions))
                    h, z, _ = state
                    state = (h, z, learner.jax.nn.one_hot(
                        learner.jnp.asarray([a]), learner.cfg.num_actions))
            nxt, r, done, _ = env.step(a)
            ep_obs.append(obs); ep_act.append(a); ep_rew.append(r)
            obs = nxt
        # canonical DreamerV3 row layout: one row per OBSERVED state incl.
        # the terminal one; reward is the reward received ON ARRIVAL at that
        # state (so the reward head's target depends only on (o_t, a_{t-1}),
        # both of which feat_t encodes), and cont marks the state itself
        # non-terminal.  The terminal row's action is a dummy — the next row
        # is a new episode whose is_first resets a_prev anyway.
        rows_obs = ep_obs + [obs]
        rows_act = ep_act + [0]
        rows_rew = [0.0] + list(ep_rew)
        rows_cont_inv = [0.0] * len(ep_obs) + [1.0]  # "done" per row
        replay.add_episode(rows_obs, rows_act, rows_rew, rows_cont_inv)
        returns.append(sum(ep_rew))
        if replay.num_steps >= cfg.batch_size * cfg.seq_len:
            for _ in range(updates_per_episode):
                key, k = jax.random.split(key)
                learner.last_stats = learner.update(
                    k, replay.sample(cfg.batch_size)
                )
    learner.episode_returns = returns
    return learner


def evaluate_dreamer(learner: DreamerV3Learner, env_maker, episodes: int = 3,
                     seed: int = 123) -> float:
    import jax

    env = env_maker()
    key = jax.random.key(seed)
    total = 0.0
    for ep in range(episodes):
        obs = env.reset(seed=seed + ep)
        state = learner.init_state(1)
        done = False
        while not done:
            key, k = jax.random.split(key)
            state, a = learner.act(k, state, obs, greedy=True)
            obs, r, done, _ = env.step(a)
            total += r
    return total / episodes
