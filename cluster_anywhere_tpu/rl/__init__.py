"""cluster_anywhere_tpu.rl: reinforcement learning on the actor runtime
(compact analogue of the reference's RLlib, rllib/ — Algorithm/
AlgorithmConfig, EnvRunner actors, jax Learners; PPO/recurrent-PPO, DQN+PER,
IMPALA/APPO, SAC, TD3, connectors, multi-agent, offline BC/CQL).

    from cluster_anywhere_tpu import rl
    algo = rl.AlgorithmConfig("PPO").environment("CartPole-v1").env_runners(2).build()
    for _ in range(20):
        result = algo.train()
"""

from .algorithm import Algorithm, AlgorithmConfig
from .connectors import (
    ClipObs,
    Connector,
    ConnectorPipeline,
    Lambda,
    RescaleActions,
    RunningObsNormalizer,
)
from .buffer import PrioritizedReplayBuffer, ReplayBuffer
from .env import CartPole, Env, MemoryChain, Pendulum, VectorEnv, make_env, register_env
from .env_runner import EnvRunner
from .learner import (
    DQNLearner,
    IMPALALearner,
    PPOLearner,
    RecurrentPPOLearner,
    TD3Learner,
    compute_gae,
)
from .module import (
    DeterministicPolicyModule,
    DiscretePolicyModule,
    QModule,
    RecurrentPolicyModule,
)
from .dreamer import (
    DreamerConfig,
    DreamerV3Learner,
    evaluate_dreamer,
    train_dreamer,
)
from .marwil import MARWILLearner, compute_returns, train_marwil
from .offline import (
    BCLearner,
    CQLLearner,
    RolloutReader,
    RolloutWriter,
    record_rollouts,
    train_bc,
    train_cql,
)
from .multi_agent import (
    CoordinationGame,
    MultiAgentEnv,
    MultiAgentPPO,
    RockPaperScissors,
)

__all__ = [
    "Algorithm",
    "AlgorithmConfig",
    "Connector",
    "ConnectorPipeline",
    "Lambda",
    "ClipObs",
    "RunningObsNormalizer",
    "RescaleActions",
    "Env",
    "CartPole",
    "Pendulum",
    "MultiAgentEnv",
    "MultiAgentPPO",
    "CoordinationGame",
    "RockPaperScissors",
    "BCLearner",
    "DreamerConfig",
    "DreamerV3Learner",
    "train_dreamer",
    "evaluate_dreamer",
    "MARWILLearner",
    "train_marwil",
    "compute_returns",
    "CQLLearner",
    "train_cql",
    "RolloutReader",
    "RolloutWriter",
    "record_rollouts",
    "train_bc",
    "VectorEnv",
    "make_env",
    "register_env",
    "EnvRunner",
    "PPOLearner",
    "DQNLearner",
    "IMPALALearner",
    "RecurrentPPOLearner",
    "TD3Learner",
    "compute_gae",
    "ReplayBuffer",
    "PrioritizedReplayBuffer",
    "DiscretePolicyModule",
    "QModule",
    "RecurrentPolicyModule",
    "DeterministicPolicyModule",
    "MemoryChain",
]
