"""Connector pipelines (analogue of the reference's rllib/connectors/):
composable transforms between the environment and the module, so
preprocessing is configuration, not code baked into each algorithm.

Two pipelines, mirroring rllib's env-to-module and module-to-env split:

- env->module: observation transforms applied before the policy forward
  pass, in both sampling and evaluation (and, because the runner stores the
  TRANSFORMED observations in its rollouts, training consumes exactly what
  the policy saw — no train/serve skew).
- module->env: action transforms applied to the sampled action before
  env.step (e.g. squashing/rescaling into the env's action box).

Connectors are plain callables on numpy batches; stateful ones (e.g.
RunningObsNormalizer) carry their state and are checkpointed with the
runner's weights payload so restored policies keep their normalization.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np


class Connector:
    """One transform step.  Subclass or wrap a callable via Lambda."""

    def __call__(self, batch: np.ndarray) -> np.ndarray:
        raise NotImplementedError

    # stateful connectors override these to ride the weight broadcast
    def get_state(self) -> Optional[dict]:
        return None

    def set_state(self, state: Optional[dict]) -> None:
        pass


class Lambda(Connector):
    def __init__(self, fn: Callable[[np.ndarray], np.ndarray]):
        self.fn = fn

    def __call__(self, batch):
        return self.fn(batch)


class ClipObs(Connector):
    """Clip observations into [-bound, bound] (rllib's clip_rewards/obs
    filters family)."""

    def __init__(self, bound: float = 10.0):
        self.bound = float(bound)

    def __call__(self, batch):
        return np.clip(batch, -self.bound, self.bound)


class RunningObsNormalizer(Connector):
    """Online mean/variance observation filter (rllib MeanStdFilter):
    normalizes with running statistics updated on every sampling batch.
    update=False freezes it (evaluation-time behavior)."""

    def __init__(self, eps: float = 1e-8):
        self.count = 0.0
        self.mean: Optional[np.ndarray] = None
        self.m2: Optional[np.ndarray] = None
        self.eps = eps
        self.update = True

    def __call__(self, batch):
        b = np.asarray(batch, np.float64)
        if self.mean is None:
            self.mean = np.zeros(b.shape[-1])
            self.m2 = np.ones(b.shape[-1])
        if self.update:
            flat = b.reshape(-1, b.shape[-1])
            for row in flat:  # Welford; rollout batches are small
                self.count += 1
                d = row - self.mean
                self.mean += d / self.count
                self.m2 += d * (row - self.mean)
        var = self.m2 / max(self.count, 1.0)
        return ((b - self.mean) / np.sqrt(var + self.eps)).astype(np.float32)

    def get_state(self):
        if self.mean is None:
            return {"count": 0.0}
        return {"count": self.count, "mean": self.mean.copy(), "m2": self.m2.copy()}

    def set_state(self, state):
        if not state or state.get("count", 0.0) == 0.0:
            return
        self.count = state["count"]
        self.mean = np.asarray(state["mean"], np.float64).copy()
        self.m2 = np.asarray(state["m2"], np.float64).copy()


class RescaleActions(Connector):
    """module->env: map tanh-range [-1, 1] actions into [low, high]."""

    def __init__(self, low: float, high: float):
        self.low, self.high = float(low), float(high)

    def __call__(self, batch):
        return self.low + (np.asarray(batch) + 1.0) * 0.5 * (self.high - self.low)


class ConnectorPipeline(Connector):
    """Ordered composition; state is the list of per-connector states."""

    def __init__(self, connectors: Sequence[Connector] = ()):
        self.connectors: List[Connector] = [
            c if isinstance(c, Connector) else Lambda(c) for c in connectors
        ]

    def __call__(self, batch):
        for c in self.connectors:
            batch = c(batch)
        return batch

    def append(self, c: Connector) -> "ConnectorPipeline":
        self.connectors.append(c if isinstance(c, Connector) else Lambda(c))
        return self

    def get_state(self):
        states = [c.get_state() for c in self.connectors]
        return {"steps": states} if any(s is not None for s in states) else None

    def set_state(self, state):
        if not state:
            return
        for c, s in zip(self.connectors, state.get("steps", [])):
            c.set_state(s)

    def set_update(self, update: bool) -> None:
        for c in self.connectors:
            if hasattr(c, "update"):
                c.update = update
