"""Learners: jitted parameter updates (analogue of the reference's
rllib/core/learner/learner.py + learner_group.py — the update itself is one
compiled XLA program instead of a torch loop).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class PPOLearner:
    """Clipped-surrogate PPO with GAE (reference rllib/algorithms/ppo/)."""

    def __init__(
        self,
        module,
        *,
        lr: float = 3e-4,
        clip: float = 0.2,
        vf_coeff: float = 0.5,
        entropy_coeff: float = 0.01,
        epochs: int = 4,
        minibatches: int = 4,
        seed: int = 0,
    ):
        import optax

        self.module = module
        self.clip = clip
        self.vf_coeff = vf_coeff
        self.entropy_coeff = entropy_coeff
        self.epochs = epochs
        self.minibatches = minibatches
        self.opt = optax.adam(lr)
        self.params = module.init(jax.random.key(seed))
        self.opt_state = self.opt.init(self.params)
        self.rng = np.random.default_rng(seed)

        def loss_fn(params, batch):
            logits = module.logits(params, batch["obs"])
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][:, None], axis=-1
            )[:, 0]
            ratio = jnp.exp(logp - batch["logp_old"])
            adv = batch["advantages"]
            unclipped = ratio * adv
            clipped = jnp.clip(ratio, 1 - self.clip, 1 + self.clip) * adv
            pi_loss = -jnp.mean(jnp.minimum(unclipped, clipped))
            values = module.value(params, batch["obs"])
            vf_loss = jnp.mean((values - batch["returns"]) ** 2)
            entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
            total = pi_loss + self.vf_coeff * vf_loss - self.entropy_coeff * entropy
            return total, {"pi_loss": pi_loss, "vf_loss": vf_loss, "entropy": entropy}

        def update_step(params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params, batch)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            import optax as _optax

            params = _optax.apply_updates(params, updates)
            return params, opt_state, loss, aux

        self._update = jax.jit(update_step)

    def get_weights(self):
        return self.params

    def set_weights(self, params):
        self.params = params
        return "ok"

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        """batch: flat arrays obs/actions/logp_old/advantages/returns."""
        n = len(batch["obs"])
        stats: Dict[str, float] = {}
        for _ in range(self.epochs):
            order = self.rng.permutation(n)
            for mb in np.array_split(order, self.minibatches):
                sub = {k: jnp.asarray(v[mb]) for k, v in batch.items()}
                self.params, self.opt_state, loss, aux = self._update(
                    self.params, self.opt_state, sub
                )
        stats["loss"] = float(loss)
        for k, v in aux.items():
            stats[k] = float(v)
        return stats


class RecurrentPPOLearner:
    """PPO over sequences with a recurrent (GRU) module (reference: rllib
    use_lstm=True through rllib/core/rl_module/ + PPO).  The loss unrolls
    the whole [T, N] rollout from each sequence's stored initial state,
    resetting hidden state at episode boundaries — no shuffled flat
    minibatches (that would sever the temporal chain); epochs re-unroll the
    same sequences, which is valid because logp_old/state0 were recorded at
    sample time."""

    def __init__(
        self,
        module,
        *,
        lr: float = 3e-4,
        clip: float = 0.2,
        vf_coeff: float = 0.5,
        entropy_coeff: float = 0.01,
        epochs: int = 4,
        seed: int = 0,
    ):
        import optax

        self.module = module
        self.clip = clip
        self.vf_coeff = vf_coeff
        self.entropy_coeff = entropy_coeff
        self.epochs = epochs
        self.opt = optax.adam(lr)
        self.params = module.init(jax.random.key(seed))
        self.opt_state = self.opt.init(self.params)

        def loss_fn(params, batch):
            # dones shifted: done at t resets the state entering t+1; the
            # state entering t=0 is state0 (recorded by the runner)
            prev_dones = jnp.concatenate(
                [jnp.zeros_like(batch["dones"][:1]), batch["dones"][:-1]], axis=0
            )
            logits, values, _ = module.unroll(
                params, batch["obs"], batch["state0"], prev_dones
            )
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(
                logp_all, batch["actions"][..., None], axis=-1
            )[..., 0]
            ratio = jnp.exp(logp - batch["logp_old"])
            adv = batch["advantages"]
            unclipped = ratio * adv
            clipped = jnp.clip(ratio, 1 - self.clip, 1 + self.clip) * adv
            pi_loss = -jnp.mean(jnp.minimum(unclipped, clipped))
            vf_loss = jnp.mean((values - batch["returns"]) ** 2)
            entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
            total = pi_loss + self.vf_coeff * vf_loss - self.entropy_coeff * entropy
            return total, {"pi_loss": pi_loss, "vf_loss": vf_loss, "entropy": entropy}

        def update_step(params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params, batch)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            import optax as _optax

            params = _optax.apply_updates(params, updates)
            return params, opt_state, loss, aux

        self._update = jax.jit(update_step)

    def get_weights(self):
        return self.params

    def set_weights(self, params):
        self.params = params
        return "ok"

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        """batch: sequence-shaped obs [T,N,D], actions/logp_old/advantages/
        returns/dones [T,N], state0 [N,H]."""
        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        for _ in range(self.epochs):
            self.params, self.opt_state, loss, aux = self._update(
                self.params, self.opt_state, jb
            )
        stats = {"loss": float(loss)}
        for k, v in aux.items():
            stats[k] = float(v)
        return stats


class DQNLearner:
    """Double-DQN update with a periodically synced target net
    (reference rllib/algorithms/dqn/)."""

    def __init__(
        self,
        module,
        *,
        lr: float = 1e-3,
        gamma: float = 0.99,
        target_update_freq: int = 100,
        seed: int = 0,
    ):
        import optax

        self.module = module
        self.gamma = gamma
        self.target_update_freq = target_update_freq
        self.opt = optax.adam(lr)
        self.params = module.init(jax.random.key(seed))
        self.target_params = jax.tree_util.tree_map(lambda x: x, self.params)
        self.opt_state = self.opt.init(self.params)
        self.updates_done = 0

        def td_errors(params, target_params, batch):
            q = module.q_values(params, batch["obs"])
            q_taken = jnp.take_along_axis(q, batch["actions"][:, None], -1)[:, 0]
            # double dqn: online net picks the argmax, target net evaluates it
            q_next_online = module.q_values(params, batch["next_obs"])
            best = jnp.argmax(q_next_online, axis=-1)
            q_next_target = module.q_values(target_params, batch["next_obs"])
            q_next = jnp.take_along_axis(q_next_target, best[:, None], -1)[:, 0]
            target = batch["rewards"] + self.gamma * (1.0 - batch["dones"]) * q_next
            return q_taken - jax.lax.stop_gradient(target)

        def loss_fn(params, target_params, batch, weights):
            td = td_errors(params, target_params, batch)
            return jnp.mean(weights * td**2), jnp.abs(td)

        def update_step(params, target_params, opt_state, batch, weights):
            (loss, td_abs), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                params, target_params, batch, weights
            )
            updates, opt_state = self.opt.update(grads, opt_state, params)
            import optax as _optax

            params = _optax.apply_updates(params, updates)
            return params, opt_state, loss, td_abs

        self._update = jax.jit(update_step)

    def get_weights(self):
        return self.params

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        """PER batches carry "weights" (importance correction applied to the
        per-sample squared TD) and "indices"; td_abs_* come back so the
        caller can feed buffer.update_priorities."""
        jb = {
            k: jnp.asarray(v)
            for k, v in batch.items()
            if k not in ("weights", "indices")
        }
        w = jnp.asarray(
            batch.get("weights", np.ones(len(batch["rewards"]), np.float32))
        )
        self.params, self.opt_state, loss, td_abs = self._update(
            self.params, self.target_params, self.opt_state, jb, w
        )
        self.updates_done += 1
        if self.updates_done % self.target_update_freq == 0:
            self.target_params = jax.tree_util.tree_map(lambda x: x, self.params)
        out = {"loss": float(loss)}
        if "indices" in batch:
            out["td_abs"] = np.asarray(td_abs)
            out["indices"] = batch["indices"]
        return out


class IMPALALearner:
    """V-trace actor-critic (reference rllib/algorithms/impala/): rollouts
    arrive from asynchronously sampling runners whose policies lag the
    learner; importance-weighted V-trace targets correct the off-policy gap.
    The whole update — target logp/value forward pass, reverse-scan V-trace,
    policy-gradient + value + entropy losses — is one jitted XLA program.

    ``surrogate_clip`` turns this into APPO (rllib/algorithms/appo/):
    the PPO clipped surrogate applied to the V-trace advantage instead of
    the plain policy gradient — same async actor-learner machinery."""

    def __init__(
        self,
        module,
        *,
        lr: float = 3e-4,
        gamma: float = 0.99,
        vf_coeff: float = 0.5,
        entropy_coeff: float = 0.01,
        rho_clip: float = 1.0,
        c_clip: float = 1.0,
        surrogate_clip: float = None,
        seed: int = 0,
    ):
        import optax

        self.module = module
        self.opt = optax.adam(lr)
        self.params = module.init(jax.random.key(seed))
        self.opt_state = self.opt.init(self.params)

        def loss_fn(params, batch):
            # batch arrays are [T, N, ...] time-major; bootstrap obs [N, ...]
            obs, actions = batch["obs"], batch["actions"]
            T, N = actions.shape
            flat_obs = obs.reshape(T * N, -1)
            logits = module.logits(params, flat_obs).reshape(T, N, -1)
            values = module.value(params, flat_obs).reshape(T, N)
            boot_value = module.value(params, batch["next_obs"])  # [N]
            logp_all = jax.nn.log_softmax(logits)
            logp = jnp.take_along_axis(logp_all, actions[..., None], -1)[..., 0]
            rho = jnp.exp(logp - batch["logp"])
            rho_bar = jnp.minimum(rho, rho_clip)
            c_bar = jnp.minimum(rho, c_clip)
            discounts = gamma * (1.0 - batch["dones"])
            next_values = jnp.concatenate([values[1:], boot_value[None]], axis=0)
            deltas = rho_bar * (batch["rewards"] + discounts * next_values - values)

            def scan_fn(acc, xs):
                delta_t, disc_t, c_t = xs
                acc = delta_t + disc_t * c_t * acc
                return acc, acc

            _, vs_minus_v = jax.lax.scan(
                scan_fn,
                jnp.zeros((N,), jnp.float32),
                (deltas, discounts, c_bar),
                reverse=True,
            )
            vs = vs_minus_v + values
            next_vs = jnp.concatenate([vs[1:], boot_value[None]], axis=0)
            pg_adv = rho_bar * (batch["rewards"] + discounts * next_vs - values)
            if surrogate_clip is not None:
                # APPO: clipped surrogate on the V-trace advantage
                adv = jax.lax.stop_gradient(
                    batch["rewards"] + discounts * next_vs - values
                )
                clipped = jnp.clip(rho, 1 - surrogate_clip, 1 + surrogate_clip)
                pg_loss = -jnp.mean(jnp.minimum(rho * adv, clipped * adv))
            else:
                pg_loss = -jnp.mean(logp * jax.lax.stop_gradient(pg_adv))
            vf_loss = jnp.mean((values - jax.lax.stop_gradient(vs)) ** 2)
            entropy = -jnp.mean(jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
            total = pg_loss + vf_coeff * vf_loss - entropy_coeff * entropy
            return total, {
                "pi_loss": pg_loss,
                "vf_loss": vf_loss,
                "entropy": entropy,
                "mean_rho": jnp.mean(rho),
            }

        def update_step(params, opt_state, batch):
            (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params, batch)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            import optax as _optax

            params = _optax.apply_updates(params, updates)
            return params, opt_state, loss, aux

        self._update = jax.jit(update_step)

    def get_weights(self):
        return self.params

    def update(self, rollout: Dict[str, np.ndarray]) -> Dict[str, float]:
        """rollout: time-major [T, N] arrays obs/actions/rewards/dones/logp
        plus bootstrap next_obs [N]."""
        jb = {
            "obs": jnp.asarray(rollout["obs"], jnp.float32),
            "actions": jnp.asarray(rollout["actions"], jnp.int32),
            "rewards": jnp.asarray(rollout["rewards"], jnp.float32),
            "dones": jnp.asarray(rollout["dones"], jnp.float32),
            "logp": jnp.asarray(rollout["logp"], jnp.float32),
            "next_obs": jnp.asarray(rollout["next_obs"], jnp.float32),
        }
        self.params, self.opt_state, loss, aux = self._update(
            self.params, self.opt_state, jb
        )
        out = {"loss": float(loss)}
        out.update({k: float(v) for k, v in aux.items()})
        return out


def compute_gae(rollout: Dict[str, np.ndarray], gamma: float, lam: float):
    """rollout arrays [T, N]; returns flat advantages/returns [T*N]."""
    rewards, values, dones = rollout["rewards"], rollout["values"], rollout["dones"]
    last_values = rollout["last_values"]
    T, N = rewards.shape
    adv = np.zeros((T, N), np.float32)
    last_gae = np.zeros(N, np.float32)
    next_values = last_values
    for t in range(T - 1, -1, -1):
        nonterminal = 1.0 - dones[t]
        delta = rewards[t] + gamma * next_values * nonterminal - values[t]
        last_gae = delta + gamma * lam * nonterminal * last_gae
        adv[t] = last_gae
        next_values = values[t]
    returns = adv + values
    adv_flat = adv.reshape(-1)
    adv_flat = (adv_flat - adv_flat.mean()) / (adv_flat.std() + 1e-8)
    return adv_flat, returns.reshape(-1)


class SACLearner:
    """Soft actor-critic with clipped double-Q, polyak target critics, and
    automatic entropy-temperature tuning (reference rllib/algorithms/sac/;
    the whole update — critics, actor, alpha, target polyak — is one compiled
    XLA program per batch)."""

    def __init__(
        self,
        policy_module,
        q_module,
        *,
        lr: float = 3e-4,
        gamma: float = 0.99,
        tau: float = 0.005,
        target_entropy: float = None,
        seed: int = 0,
    ):
        import optax

        self.policy = policy_module
        self.qnet = q_module
        self.gamma = gamma
        self.tau = tau
        if target_entropy is None:
            target_entropy = -float(policy_module.action_dim)
        self.target_entropy = target_entropy
        kp, kq = jax.random.split(jax.random.key(seed))
        self.params = {
            **policy_module.init(kp),
            **q_module.init(kq),
            "log_alpha": jnp.zeros(()),
        }
        self.target = {k: self.params[k] for k in ("q1", "q2")}
        self.opt = optax.adam(lr)
        self.opt_state = self.opt.init(self.params)
        self._key = jax.random.key(seed + 1)

        def losses(params, target, batch, k1, k2):
            alpha = jnp.exp(params["log_alpha"])
            # critic targets from the frozen nets + current policy (actions
            # are env-scaled on both the buffer and the sampler side)
            next_act, next_logp = policy_module.sample(params, batch["next_obs"], k1)
            tq1, tq2 = q_module.q(target, batch["next_obs"], next_act)
            soft_v = jnp.minimum(tq1, tq2) - jax.lax.stop_gradient(alpha) * next_logp
            y = batch["rewards"] + self.gamma * (1.0 - batch["dones"]) * soft_v
            y = jax.lax.stop_gradient(y)
            q1, q2 = q_module.q(params, batch["obs"], batch["actions"])
            critic_loss = jnp.mean((q1 - y) ** 2) + jnp.mean((q2 - y) ** 2)
            # actor: maximize min-Q of a fresh sample minus entropy cost
            act, logp = policy_module.sample(params, batch["obs"], k2)
            pq1, pq2 = q_module.q(
                jax.lax.stop_gradient({k: params[k] for k in ("q1", "q2")}),
                batch["obs"],
                act,
            )
            actor_loss = jnp.mean(
                jax.lax.stop_gradient(alpha) * logp - jnp.minimum(pq1, pq2)
            )
            # temperature: drive policy entropy toward the target
            alpha_loss = -jnp.mean(
                params["log_alpha"] * jax.lax.stop_gradient(logp + self.target_entropy)
            )
            total = critic_loss + actor_loss + alpha_loss
            return total, {
                "critic_loss": critic_loss,
                "actor_loss": actor_loss,
                "alpha": alpha,
                "entropy": -jnp.mean(logp),
            }

        def update_step(params, target, opt_state, batch, key):
            key, k1, k2 = jax.random.split(key, 3)
            (_, aux), grads = jax.value_and_grad(losses, has_aux=True)(
                params, target, batch, k1, k2
            )
            updates, opt_state = self.opt.update(grads, opt_state, params)
            import optax as _optax

            params = _optax.apply_updates(params, updates)
            target = jax.tree.map(
                lambda t, p: (1 - self.tau) * t + self.tau * p,
                target,
                {k: params[k] for k in ("q1", "q2")},
            )
            return params, target, opt_state, aux, key

        self._update = jax.jit(update_step)

    def get_weights(self):
        return self.params

    def set_weights(self, params):
        self.params = params
        return "ok"

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        self.params, self.target, self.opt_state, aux, self._key = self._update(
            self.params, self.target, self.opt_state, jb, self._key
        )
        return {k: float(v) for k, v in aux.items()}


class TD3Learner:
    """Twin Delayed DDPG (reference rllib/algorithms/td3): deterministic
    actor, clipped double-Q critics, target-policy smoothing (clipped
    Gaussian noise on the target action), and DELAYED actor/target updates
    (policy_delay critic steps per actor step).  One compiled XLA program
    per update; the actor branch is gated by lax.cond on the step counter
    so delay needs no retrace."""

    def __init__(
        self,
        policy_module,
        q_module,
        *,
        lr: float = 3e-4,
        gamma: float = 0.99,
        tau: float = 0.005,
        policy_delay: int = 2,
        target_noise: float = 0.2,
        noise_clip: float = 0.5,
        seed: int = 0,
    ):
        import optax

        self.policy = policy_module
        self.qnet = q_module
        self.gamma = gamma
        self.tau = tau
        self.policy_delay = max(1, int(policy_delay))
        scale = policy_module.action_scale
        kp, kq = jax.random.split(jax.random.key(seed))
        self.params = {**policy_module.init(kp), **q_module.init(kq)}
        self.target = jax.tree.map(lambda x: x, self.params)
        # SEPARATE optimizers: a shared Adam would keep moving the actor on
        # critic-only steps via accumulated momentum (zero grad != zero
        # update), silently defeating the delay
        self.opt_c = optax.adam(lr)
        self.opt_a = optax.adam(lr)
        self.opt_c_state = self.opt_c.init({k: self.params[k] for k in ("q1", "q2")})
        self.opt_a_state = self.opt_a.init({"mu": self.params["mu"]})
        self._key = jax.random.key(seed + 1)
        self.steps = 0

        def critic_loss_fn(params, target, batch, key):
            # target-policy smoothing: noise on the TARGET action, clipped,
            # then clipped back into the action box
            next_mu = policy_module.mean_action(target, batch["next_obs"])
            noise = jnp.clip(
                jax.random.normal(key, next_mu.shape) * target_noise * scale,
                -noise_clip * scale,
                noise_clip * scale,
            )
            next_act = jnp.clip(next_mu + noise, -scale, scale)
            tq1, tq2 = q_module.q(target, batch["next_obs"], next_act)
            y = batch["rewards"] + self.gamma * (1.0 - batch["dones"]) * jnp.minimum(
                tq1, tq2
            )
            y = jax.lax.stop_gradient(y)
            q1, q2 = q_module.q(params, batch["obs"], batch["actions"])
            return jnp.mean((q1 - y) ** 2) + jnp.mean((q2 - y) ** 2)

        def actor_loss_fn(params, batch):
            act = policy_module.mean_action(params, batch["obs"])
            q1, _ = q_module.q(
                jax.lax.stop_gradient({k: params[k] for k in ("q1", "q2")}),
                batch["obs"],
                act,
            )
            return -jnp.mean(q1)

        def update_step(params, target, opt_c_state, opt_a_state, batch, key, step):
            import optax as _optax

            key, kn = jax.random.split(key)
            closs, cgrads = jax.value_and_grad(critic_loss_fn)(
                params, target, batch, kn
            )
            c_upd, opt_c_state = self.opt_c.update(
                {k: cgrads[k] for k in ("q1", "q2")}, opt_c_state
            )
            new_q = _optax.apply_updates({k: params[k] for k in ("q1", "q2")}, c_upd)
            do_actor = (step % self.policy_delay) == 0

            def with_actor(operand):
                mu, a_state = operand
                aloss, agrads = jax.value_and_grad(actor_loss_fn)(params, batch)
                a_upd, a_state = self.opt_a.update({"mu": agrads["mu"]}, a_state)
                return (
                    aloss,
                    _optax.apply_updates({"mu": mu}, a_upd)["mu"],
                    a_state,
                )

            def critics_only(operand):
                mu, a_state = operand
                return jnp.zeros(()), mu, a_state

            aloss, new_mu, opt_a_state = jax.lax.cond(
                do_actor, with_actor, critics_only, (params["mu"], opt_a_state)
            )
            params = {"mu": new_mu, **new_q}
            # delayed target polyak, same cadence as the actor
            target = jax.lax.cond(
                do_actor,
                lambda _: jax.tree.map(
                    lambda t, p: (1 - self.tau) * t + self.tau * p, target, params
                ),
                lambda _: target,
                None,
            )
            return params, target, opt_c_state, opt_a_state, closs, aloss, key

        self._update = jax.jit(update_step)

    def get_weights(self):
        return self.params

    def set_weights(self, params):
        self.params = params
        return "ok"

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        (
            self.params, self.target, self.opt_c_state, self.opt_a_state,
            closs, aloss, self._key,
        ) = self._update(
            self.params, self.target, self.opt_c_state, self.opt_a_state, jb,
            self._key, jnp.asarray(self.steps),
        )
        self.steps += 1
        return {"critic_loss": float(closs), "actor_loss": float(aloss)}
