"""EnvRunner actor: samples rollouts with the current weights (analogue of
the reference's rllib/env/single_agent_env_runner.py on the actor runtime).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np


class EnvRunner:
    def __init__(
        self,
        env_spec,
        module_spec: Dict[str, Any],
        num_envs: int = 4,
        seed: int = 0,
        explore: str = "sample",  # sample | epsilon
        env_to_module=None,  # connector factory: obs pipeline (rllib connectors)
        module_to_env=None,  # connector factory: action pipeline
    ):
        import jax

        from .connectors import ConnectorPipeline
        from .env import VectorEnv
        from .module import DiscretePolicyModule, QModule

        def _mk(factory) -> Optional[ConnectorPipeline]:
            """Accepts: None | pipeline | Connector | list of connectors |
            zero-arg FACTORY returning any of those.  A bare function is
            treated as a factory — wrap batch transforms in rl.Lambda."""
            from .connectors import Connector

            if factory is None:
                return None
            if isinstance(factory, (ConnectorPipeline, Connector, list, tuple)):
                made = factory
            elif callable(factory):
                made = factory()
            else:
                made = factory
            if isinstance(made, ConnectorPipeline):
                return made
            return ConnectorPipeline(made if isinstance(made, (list, tuple)) else [made])

        # env->module obs pipeline runs before EVERY policy forward (sample,
        # bootstrap, evaluate) and rollouts store the TRANSFORMED obs, so
        # training sees exactly what the policy saw
        self.obs_pipe = _mk(env_to_module)
        self.act_pipe = _mk(module_to_env)

        self.env_spec = env_spec
        self.vec = VectorEnv(env_spec, num_envs, seed)
        kind = module_spec.get("kind", "policy")
        if kind == "recurrent":
            from .module import RecurrentPolicyModule

            self.module = RecurrentPolicyModule(
                module_spec["obs_dim"], module_spec["num_actions"],
                module_spec.get("lstm_hidden", 64),
            )
            self.state = self.module.initial_state(num_envs)
        elif kind == "policy":
            self.module = DiscretePolicyModule(
                module_spec["obs_dim"], module_spec["num_actions"],
                module_spec.get("hidden", (64, 64)),
            )
        elif kind == "gaussian":
            from .module import SquashedGaussianModule

            self.module = SquashedGaussianModule(
                module_spec["obs_dim"], module_spec["action_dim"],
                module_spec.get("action_scale", 1.0),
                module_spec.get("hidden", (64, 64)),
            )
        elif kind == "deterministic":
            from .module import DeterministicPolicyModule

            self.module = DeterministicPolicyModule(
                module_spec["obs_dim"], module_spec["action_dim"],
                module_spec.get("action_scale", 1.0),
                module_spec.get("hidden", (64, 64)),
            )
            self.explore_noise = float(module_spec.get("explore_noise", 0.1))
        else:
            self.module = QModule(
                module_spec["obs_dim"], module_spec["num_actions"],
                module_spec.get("hidden", (64, 64)),
            )
        self.kind = kind
        self.params = self.module.init(jax.random.key(seed))
        self.rng = np.random.default_rng(seed + 1)
        self.explore = explore
        self.epsilon = 1.0
        if kind == "gaussian":
            self._sample_key = jax.random.key(seed + 2)
            self._jit_sample = jax.jit(self.module.sample)
            self._jit_mean = jax.jit(self.module.mean_action)
            self._jit_logits = None
            self._jit_value = None
        elif kind == "deterministic":
            self._jit_mean = jax.jit(self.module.mean_action)
            self._jit_logits = None
            self._jit_value = None
        elif kind == "recurrent":
            self._jit_step = jax.jit(self.module.step)
        else:
            self._jit_logits = jax.jit(
                self.module.logits if kind == "policy" else self.module.q_values
            )
            self._jit_value = jax.jit(self.module.value) if kind == "policy" else None

    def set_weights(self, params, epsilon: Optional[float] = None, connector_state=None):
        self.params = params
        if epsilon is not None:
            self.epsilon = epsilon
        if connector_state is not None:
            if self.obs_pipe is not None:
                self.obs_pipe.set_state(connector_state.get("obs"))
            if self.act_pipe is not None:
                self.act_pipe.set_state(connector_state.get("act"))
        return "ok"

    def connector_state(self):
        """Both pipelines' state (stateful action connectors checkpoint
        too); None when nothing is stateful."""
        state = {}
        if self.obs_pipe is not None:
            s = self.obs_pipe.get_state()
            if s is not None:
                state["obs"] = s
        if self.act_pipe is not None:
            s = self.act_pipe.get_state()
            if s is not None:
                state["act"] = s
        return state or None

    def _obs_t(self, obs):
        return self.obs_pipe(obs) if self.obs_pipe is not None else obs

    def _obs_t_frozen(self, obs):
        """Transform WITHOUT updating stateful connector stats — for rollout
        boundaries (bootstrap value, next_obs), whose observations are
        transformed again (with updates) as the first obs of the next
        sample(); updating here would double-count them."""
        if self.obs_pipe is None:
            return obs
        saved = [
            (c, c.update) for c in self.obs_pipe.connectors if hasattr(c, "update")
        ]
        for c, _ in saved:
            c.update = False
        try:
            return self.obs_pipe(obs)
        finally:
            for c, flag in saved:
                c.update = flag

    def _act_t(self, actions):
        return self.act_pipe(actions) if self.act_pipe is not None else actions

    def sync_sample(self, params, num_steps: int) -> Dict[str, np.ndarray]:
        """Fused set_weights + sample for the compiled-DAG experience edge:
        weights arrive through the DAG's input channel (one shm write,
        broadcast to every runner) and the rollout leaves over this node's
        tensor-transport output channel — no per-iteration RPCs."""
        self.set_weights(params)
        return self.sample(num_steps)

    def sample(self, num_steps: int) -> Dict[str, np.ndarray]:
        """Collect num_steps per env. Returns flat [T*N, ...] arrays plus
        bootstrap values and episode metrics."""
        import jax.numpy as jnp

        if self.kind == "recurrent":
            return self._sample_recurrent(num_steps)
        obs_l, act_l, rew_l, done_l, logp_l, val_l = [], [], [], [], [], []
        for _ in range(num_steps):
            obs = self._obs_t(self.vec.obs)
            if self.kind == "gaussian":
                import jax

                self._sample_key, k = jax.random.split(self._sample_key)
                act, _ = self._jit_sample(self.params, jnp.asarray(obs), k)
                actions = np.asarray(act, np.float32)
                logp = np.zeros(len(actions), np.float32)
                values = np.zeros(len(actions), np.float32)
            elif self.kind == "deterministic":
                # TD3 exploration: Gaussian noise on the deterministic
                # action, clipped into the action box
                mu = np.asarray(self._jit_mean(self.params, jnp.asarray(obs)))
                scale = self.module.action_scale
                noise = self.rng.normal(0.0, self.explore_noise * scale, mu.shape)
                actions = np.clip(mu + noise, -scale, scale).astype(np.float32)
                logp = np.zeros(len(actions), np.float32)
                values = np.zeros(len(actions), np.float32)
            elif self.kind == "policy":
                from .module import softmax_sample

                out = np.asarray(self._jit_logits(self.params, jnp.asarray(obs)))
                if self.explore == "sample":
                    actions, logp = softmax_sample(self.rng, out)
                else:
                    actions = out.argmax(-1).astype(np.int32)
                    z = out - out.max(-1, keepdims=True)
                    p = np.exp(z)
                    p /= p.sum(-1, keepdims=True)
                    logp = np.log(p[np.arange(len(actions)), actions] + 1e-9)
                values = np.asarray(self._jit_value(self.params, jnp.asarray(obs)))
            else:  # epsilon-greedy over q-values
                out = np.asarray(self._jit_logits(self.params, jnp.asarray(obs)))
                greedy = out.argmax(-1)
                rand = self.rng.integers(0, out.shape[-1], size=len(greedy))
                mask = self.rng.random(len(greedy)) < self.epsilon
                actions = np.where(mask, rand, greedy).astype(np.int32)
                logp = np.zeros(len(actions), np.float32)
                values = np.zeros(len(actions), np.float32)
            next_obs, rewards, dones = self.vec.step(self._act_t(actions))
            obs_l.append(obs)
            act_l.append(actions)
            rew_l.append(rewards)
            done_l.append(dones)
            logp_l.append(logp)
            val_l.append(values)
        # bootstrap value of the final obs (PPO/GAE); transformed ONCE with
        # frozen stats and reused for next_obs so the stored pair agrees
        tail_obs = self._obs_t_frozen(self.vec.obs)
        if self.kind == "policy":
            last_values = np.asarray(
                self._jit_value(self.params, jnp.asarray(tail_obs))
            )
        else:
            last_values = np.zeros(self.vec.num_envs, np.float32)
        return {
            "obs": np.stack(obs_l),            # [T, N, D]
            "actions": np.stack(act_l),        # [T, N]
            "rewards": np.stack(rew_l),        # [T, N]
            "dones": np.stack(done_l),         # [T, N]
            "logp": np.stack(logp_l),          # [T, N]
            "values": np.stack(val_l),         # [T, N]
            "last_values": last_values,        # [N]
            "next_obs": np.asarray(tail_obs).copy(),  # [N, D] (transformed like obs)
            "metrics": self.vec.drain_metrics(),
        }

    def _sample_recurrent(self, num_steps: int) -> Dict[str, np.ndarray]:
        """Recurrent rollout: hidden state carried across sample() calls and
        zeroed per env at episode ends; the state at rollout start ships
        with the batch so the learner unrolls from the same point."""
        import jax.numpy as jnp

        from .module import softmax_sample

        state0 = self.state.copy()
        obs_l, act_l, rew_l, done_l, logp_l, val_l = [], [], [], [], [], []
        for _ in range(num_steps):
            obs = self._obs_t(self.vec.obs)
            logits, values, new_state = self._jit_step(
                self.params, jnp.asarray(obs), jnp.asarray(self.state)
            )
            actions, logp = softmax_sample(self.rng, np.asarray(logits))
            next_obs, rewards, dones = self.vec.step(self._act_t(actions))
            self.state = np.array(new_state)  # copy: jax buffers are read-only
            self.state[dones.astype(bool)] = 0.0
            obs_l.append(obs)
            act_l.append(actions)
            rew_l.append(rewards)
            done_l.append(dones)
            logp_l.append(logp)
            val_l.append(np.asarray(values))
        tail_obs = self._obs_t_frozen(self.vec.obs)
        _, last_values, _ = self._jit_step(
            self.params, jnp.asarray(tail_obs), jnp.asarray(self.state)
        )
        return {
            "obs": np.stack(obs_l),
            "actions": np.stack(act_l),
            "rewards": np.stack(rew_l),
            "dones": np.stack(done_l),
            "logp": np.stack(logp_l),
            "values": np.stack(val_l),
            "last_values": np.asarray(last_values),
            "next_obs": np.asarray(tail_obs).copy(),
            "state0": state0,
            "metrics": self.vec.drain_metrics(),
        }

    def evaluate(self, num_episodes: int = 5) -> float:
        """Greedy episode returns on a fresh env (recurrent policies thread
        their hidden state through the episode)."""
        import jax.numpy as jnp

        from .env import make_env

        env = make_env(self.env_spec)
        # freeze stateful obs connectors during eval, restoring each
        # connector's PRIOR flag after (a user-frozen normalizer must not
        # be silently re-enabled by an evaluate() call)
        saved_flags = []
        if self.obs_pipe is not None:
            for c in self.obs_pipe.connectors:
                if hasattr(c, "update"):
                    saved_flags.append((c, c.update))
                    c.update = False
        try:
            total = 0.0
            for ep in range(num_episodes):
                obs = env.reset(seed=1000 + ep)
                done, ret = False, 0.0
                if self.kind == "recurrent":
                    state = self.module.initial_state(1)
                while not done:
                    tobs = self._obs_t(obs[None])
                    if self.kind in ("gaussian", "deterministic"):
                        a = np.asarray(self._jit_mean(self.params, jnp.asarray(tobs)))[0]
                        act = self._act_t(a[None])[0]
                    elif self.kind == "recurrent":
                        logits, _, state = self._jit_step(
                            self.params, jnp.asarray(tobs), jnp.asarray(state)
                        )
                        # action connector applies in eval exactly as in
                        # sampling — same policy, same executed actions
                        act = int(self._act_t(np.asarray(logits).argmax(-1))[0])
                    else:
                        out = np.asarray(
                            self._jit_logits(self.params, jnp.asarray(tobs))
                        )
                        act = int(self._act_t(out.argmax(-1))[0])
                    obs, r, done, _ = env.step(act)
                    ret += r
                total += ret
            return total / num_episodes
        finally:
            for c, flag in saved_flags:
                c.update = flag
