"""EnvRunner actor: samples rollouts with the current weights (analogue of
the reference's rllib/env/single_agent_env_runner.py on the actor runtime).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np


class EnvRunner:
    def __init__(
        self,
        env_spec,
        module_spec: Dict[str, Any],
        num_envs: int = 4,
        seed: int = 0,
        explore: str = "sample",  # sample | epsilon
    ):
        import jax

        from .env import VectorEnv
        from .module import DiscretePolicyModule, QModule

        self.env_spec = env_spec
        self.vec = VectorEnv(env_spec, num_envs, seed)
        kind = module_spec.get("kind", "policy")
        if kind == "recurrent":
            from .module import RecurrentPolicyModule

            self.module = RecurrentPolicyModule(
                module_spec["obs_dim"], module_spec["num_actions"],
                module_spec.get("lstm_hidden", 64),
            )
            self.state = self.module.initial_state(num_envs)
        elif kind == "policy":
            self.module = DiscretePolicyModule(
                module_spec["obs_dim"], module_spec["num_actions"],
                module_spec.get("hidden", (64, 64)),
            )
        elif kind == "gaussian":
            from .module import SquashedGaussianModule

            self.module = SquashedGaussianModule(
                module_spec["obs_dim"], module_spec["action_dim"],
                module_spec.get("action_scale", 1.0),
                module_spec.get("hidden", (64, 64)),
            )
        else:
            self.module = QModule(
                module_spec["obs_dim"], module_spec["num_actions"],
                module_spec.get("hidden", (64, 64)),
            )
        self.kind = kind
        self.params = self.module.init(jax.random.key(seed))
        self.rng = np.random.default_rng(seed + 1)
        self.explore = explore
        self.epsilon = 1.0
        if kind == "gaussian":
            self._sample_key = jax.random.key(seed + 2)
            self._jit_sample = jax.jit(self.module.sample)
            self._jit_mean = jax.jit(self.module.mean_action)
            self._jit_logits = None
            self._jit_value = None
        elif kind == "recurrent":
            self._jit_step = jax.jit(self.module.step)
        else:
            self._jit_logits = jax.jit(
                self.module.logits if kind == "policy" else self.module.q_values
            )
            self._jit_value = jax.jit(self.module.value) if kind == "policy" else None

    def set_weights(self, params, epsilon: Optional[float] = None):
        self.params = params
        if epsilon is not None:
            self.epsilon = epsilon
        return "ok"

    def sample(self, num_steps: int) -> Dict[str, np.ndarray]:
        """Collect num_steps per env. Returns flat [T*N, ...] arrays plus
        bootstrap values and episode metrics."""
        import jax.numpy as jnp

        if self.kind == "recurrent":
            return self._sample_recurrent(num_steps)
        obs_l, act_l, rew_l, done_l, logp_l, val_l = [], [], [], [], [], []
        for _ in range(num_steps):
            obs = self.vec.obs
            if self.kind == "gaussian":
                import jax

                self._sample_key, k = jax.random.split(self._sample_key)
                act, _ = self._jit_sample(self.params, jnp.asarray(obs), k)
                actions = np.asarray(act, np.float32)
                logp = np.zeros(len(actions), np.float32)
                values = np.zeros(len(actions), np.float32)
            elif self.kind == "policy":
                from .module import softmax_sample

                out = np.asarray(self._jit_logits(self.params, jnp.asarray(obs)))
                if self.explore == "sample":
                    actions, logp = softmax_sample(self.rng, out)
                else:
                    actions = out.argmax(-1).astype(np.int32)
                    z = out - out.max(-1, keepdims=True)
                    p = np.exp(z)
                    p /= p.sum(-1, keepdims=True)
                    logp = np.log(p[np.arange(len(actions)), actions] + 1e-9)
                values = np.asarray(self._jit_value(self.params, jnp.asarray(obs)))
            else:  # epsilon-greedy over q-values
                out = np.asarray(self._jit_logits(self.params, jnp.asarray(obs)))
                greedy = out.argmax(-1)
                rand = self.rng.integers(0, out.shape[-1], size=len(greedy))
                mask = self.rng.random(len(greedy)) < self.epsilon
                actions = np.where(mask, rand, greedy).astype(np.int32)
                logp = np.zeros(len(actions), np.float32)
                values = np.zeros(len(actions), np.float32)
            next_obs, rewards, dones = self.vec.step(actions)
            obs_l.append(obs)
            act_l.append(actions)
            rew_l.append(rewards)
            done_l.append(dones)
            logp_l.append(logp)
            val_l.append(values)
        # bootstrap value of the final obs (PPO/GAE)
        if self.kind == "gaussian":
            last_values = np.zeros(self.vec.num_envs, np.float32)
        elif self.kind == "policy":
            last_values = np.asarray(
                self._jit_value(self.params, jnp.asarray(self.vec.obs))
            )
        else:
            last_values = np.zeros(self.vec.num_envs, np.float32)
        return {
            "obs": np.stack(obs_l),            # [T, N, D]
            "actions": np.stack(act_l),        # [T, N]
            "rewards": np.stack(rew_l),        # [T, N]
            "dones": np.stack(done_l),         # [T, N]
            "logp": np.stack(logp_l),          # [T, N]
            "values": np.stack(val_l),         # [T, N]
            "last_values": last_values,        # [N]
            "next_obs": self.vec.obs.copy(),   # [N, D]
            "metrics": self.vec.drain_metrics(),
        }

    def _sample_recurrent(self, num_steps: int) -> Dict[str, np.ndarray]:
        """Recurrent rollout: hidden state carried across sample() calls and
        zeroed per env at episode ends; the state at rollout start ships
        with the batch so the learner unrolls from the same point."""
        import jax.numpy as jnp

        from .module import softmax_sample

        state0 = self.state.copy()
        obs_l, act_l, rew_l, done_l, logp_l, val_l = [], [], [], [], [], []
        for _ in range(num_steps):
            obs = self.vec.obs
            logits, values, new_state = self._jit_step(
                self.params, jnp.asarray(obs), jnp.asarray(self.state)
            )
            actions, logp = softmax_sample(self.rng, np.asarray(logits))
            next_obs, rewards, dones = self.vec.step(actions)
            self.state = np.array(new_state)  # copy: jax buffers are read-only
            self.state[dones.astype(bool)] = 0.0
            obs_l.append(obs)
            act_l.append(actions)
            rew_l.append(rewards)
            done_l.append(dones)
            logp_l.append(logp)
            val_l.append(np.asarray(values))
        _, last_values, _ = self._jit_step(
            self.params, jnp.asarray(self.vec.obs), jnp.asarray(self.state)
        )
        return {
            "obs": np.stack(obs_l),
            "actions": np.stack(act_l),
            "rewards": np.stack(rew_l),
            "dones": np.stack(done_l),
            "logp": np.stack(logp_l),
            "values": np.stack(val_l),
            "last_values": np.asarray(last_values),
            "next_obs": self.vec.obs.copy(),
            "state0": state0,
            "metrics": self.vec.drain_metrics(),
        }

    def evaluate(self, num_episodes: int = 5) -> float:
        """Greedy episode returns on a fresh env (recurrent policies thread
        their hidden state through the episode)."""
        import jax.numpy as jnp

        from .env import make_env

        env = make_env(self.env_spec)
        total = 0.0
        for ep in range(num_episodes):
            obs = env.reset(seed=1000 + ep)
            done, ret = False, 0.0
            if self.kind == "recurrent":
                state = self.module.initial_state(1)
            while not done:
                if self.kind == "gaussian":
                    a = np.asarray(self._jit_mean(self.params, jnp.asarray(obs[None])))[0]
                    obs, r, done, _ = env.step(a)
                elif self.kind == "recurrent":
                    logits, _, state = self._jit_step(
                        self.params, jnp.asarray(obs[None]), jnp.asarray(state)
                    )
                    obs, r, done, _ = env.step(int(np.asarray(logits)[0].argmax()))
                else:
                    out = np.asarray(
                        self._jit_logits(self.params, jnp.asarray(obs[None]))
                    )
                    obs, r, done, _ = env.step(int(out[0].argmax()))
                ret += r
            total += ret
        return total / num_episodes
