"""MARWIL: Monotonic Advantage Re-Weighted Imitation Learning.

Reference parity: ``rllib/algorithms/marwil/marwil.py`` (+ the MARWIL loss
in ``marwil/torch/marwil_torch_learner.py``) — offline learning that
interpolates between behavior cloning (beta=0) and advantage-weighted
policy improvement (beta>0): logged actions are imitated with weight
exp(beta * A / c), where A = R - V(s) and c is a running scale estimate of
the advantage magnitude (the "monotonic" normalizer from the paper).

TPU-native shape: one jitted update (policy CE + value regression fused into
a single value_and_grad), running advantage scale carried as a jnp scalar in
the update carry rather than a mutable python float.
"""

from __future__ import annotations

from typing import Dict

import numpy as np


def compute_returns(
    rewards: np.ndarray, dones: np.ndarray, gamma: float = 0.99,
    n_envs: int = 1,
) -> np.ndarray:
    """Discounted Monte-Carlo returns over a recorded transition stream,
    reset at episode boundaries (dones).

    record_rollouts flattens [T, N] batches C-order (row = t*N + n), so a
    shard with n_envs > 1 interleaves N independent env streams; returns
    must run down each column, not the interleaved flat order."""
    flat = np.asarray(rewards, dtype=np.float32)
    d = np.asarray(dones, dtype=bool)
    if n_envs > 1:
        if len(flat) % n_envs:
            raise ValueError(
                f"shard rows {len(flat)} not divisible by n_envs {n_envs}"
            )
        r2 = flat.reshape(-1, n_envs)
        d2 = d.reshape(-1, n_envs)
        out = np.zeros_like(r2)
        acc = np.zeros(n_envs, dtype=np.float32)
        for t in range(r2.shape[0] - 1, -1, -1):
            acc[d2[t]] = 0.0
            acc = r2[t] + gamma * acc
            out[t] = acc
        return out.reshape(-1)
    out = np.zeros(len(flat), dtype=np.float32)
    acc0 = 0.0
    for i in range(len(flat) - 1, -1, -1):
        if d[i]:
            acc0 = 0.0
        acc0 = float(flat[i]) + gamma * acc0
        out[i] = acc0
    return out


class MARWILLearner:
    """One jitted MARWIL update over (obs, actions, returns) minibatches."""

    def __init__(
        self,
        module,
        *,
        beta: float = 1.0,
        vf_coeff: float = 1.0,
        moving_average_sqd_adv_norm_update_rate: float = 1e-8,
        lr: float = 1e-3,
        seed: int = 0,
    ):
        import jax
        import jax.numpy as jnp
        import optax

        self.module = module
        self.opt = optax.adam(lr)
        self.params = module.init(jax.random.key(seed))
        self.opt_state = self.opt.init(self.params)
        # c^2: running estimate of E[A^2] (reference:
        # ma_adv_norm / MOVING_AVERAGE_SQD_ADV_NORM_UPDATE_RATE)
        self.adv_norm_sq = jnp.asarray(1.0, jnp.float32)
        tau = moving_average_sqd_adv_norm_update_rate

        def loss_fn(params, batch, adv_norm_sq):
            logits = module.logits(params, batch["obs"])
            v = module.value(params, batch["obs"])
            ret = batch["returns"]
            adv = ret - jax.lax.stop_gradient(v)
            vf_loss = jnp.mean((ret - v) ** 2)
            if beta != 0.0:
                # update c^2 first, then weight by exp(beta * A / c), both
                # per the paper; clip the exponent like the reference so a
                # stray advantage can't produce an inf weight
                new_norm = adv_norm_sq + tau * (jnp.mean(adv**2) - adv_norm_sq)
                c = jnp.sqrt(new_norm + 1e-8)
                w = jnp.exp(jnp.clip(beta * adv / c, -20.0, 20.0))
                w = jax.lax.stop_gradient(w)
            else:
                new_norm = adv_norm_sq
                w = jnp.ones_like(ret)
            logp = jax.nn.log_softmax(logits)
            act_logp = jnp.take_along_axis(
                logp, batch["actions"][:, None], axis=-1
            )[:, 0]
            policy_loss = -jnp.mean(w * act_logp)
            total = policy_loss + vf_coeff * vf_loss
            return total, (policy_loss, vf_loss, new_norm)

        def update_step(params, opt_state, adv_norm_sq, batch):
            (total, (pl, vl, new_norm)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params, batch, adv_norm_sq)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, new_norm, (total, pl, vl)

        self._update = jax.jit(update_step)
        self._jnp = jnp

    def get_weights(self):
        return self.params

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        jnp = self._jnp
        jb = {
            "obs": jnp.asarray(batch["obs"], jnp.float32),
            "actions": jnp.asarray(batch["actions"], jnp.int32),
            "returns": jnp.asarray(batch["returns"], jnp.float32),
        }
        self.params, self.opt_state, self.adv_norm_sq, (total, pl, vl) = (
            self._update(self.params, self.opt_state, self.adv_norm_sq, jb)
        )
        return {
            "marwil_loss": float(total),
            "policy_loss": float(pl),
            "vf_loss": float(vl),
            "adv_norm": float(self.adv_norm_sq) ** 0.5,
        }


def train_marwil(
    path: str,
    obs_dim: int,
    num_actions: int,
    *,
    beta: float = 1.0,
    gamma: float = 0.99,
    n_envs: int = 1,
    hidden=(64, 64),
    lr: float = 1e-3,
    vf_coeff: float = 1.0,
    batch_size: int = 256,
    num_updates: int = 500,
    seed: int = 0,
) -> MARWILLearner:
    """Offline MARWIL over logged rollouts (rllib algorithms/marwil role):
    returns are computed per shard (time-ordered within a shard) and sampled
    as flat (obs, action, return) rows."""
    from .module import DiscretePolicyModule
    from .offline import RolloutReader

    reader = RolloutReader(path, seed=seed)
    reader.add_derived_column(
        "returns",
        lambda shard: compute_returns(
            shard["rewards"], shard["dones"], gamma=gamma, n_envs=n_envs
        ),
    )
    learner = MARWILLearner(
        DiscretePolicyModule(obs_dim, num_actions, hidden),
        beta=beta, vf_coeff=vf_coeff, lr=lr, seed=seed,
    )
    stats: Dict[str, float] = {}
    for _ in range(num_updates):
        stats = learner.update(reader.sample(batch_size))
    learner.last_stats = stats
    return learner
