"""RLModule: the neural networks, pure-pytree + functional (analogue of the
reference's rllib/core/rl_module/rl_module.py, jax-native instead of torch).
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp


def init_mlp(key, sizes: Sequence[int]) -> list:
    params = []
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, k = jax.random.split(key)
        params.append(
            {
                "w": jax.random.normal(k, (fan_in, fan_out)) * (2.0 / fan_in) ** 0.5,
                "b": jnp.zeros((fan_out,)),
            }
        )
    return params


def mlp_forward(params: list, x: jnp.ndarray) -> jnp.ndarray:
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jnp.tanh(x)
    return x


class DiscretePolicyModule:
    """Separate policy and value MLPs over a flat observation."""

    def __init__(self, obs_dim: int, num_actions: int, hidden: Sequence[int] = (64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hidden = tuple(hidden)

    def init(self, key) -> Dict[str, Any]:
        kp, kv = jax.random.split(key)
        return {
            "pi": init_mlp(kp, (self.obs_dim, *self.hidden, self.num_actions)),
            "vf": init_mlp(kv, (self.obs_dim, *self.hidden, 1)),
        }

    @staticmethod
    def logits(params, obs):
        return mlp_forward(params["pi"], obs)

    @staticmethod
    def value(params, obs):
        return mlp_forward(params["vf"], obs)[..., 0]


class RecurrentPolicyModule:
    """GRU-core actor-critic (analogue of rllib/core/rl_module/ recurrent
    modules, use_lstm=True model config): obs -> encoder MLP -> GRU ->
    policy/value heads.  The hidden state is the API surface — runners carry
    it across steps and learners unroll sequences with the rollout's initial
    state, resetting where episodes ended (lax.scan; no python loops under
    jit)."""

    def __init__(
        self,
        obs_dim: int,
        num_actions: int,
        hidden: int = 64,
        encoder: Sequence[int] = (64,),
    ):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hidden = int(hidden)
        self.encoder = tuple(encoder)

    def init(self, key) -> Dict[str, Any]:
        ke, kg, kp, kv = jax.random.split(key, 4)
        enc_out = self.encoder[-1] if self.encoder else self.obs_dim
        h = self.hidden
        s = (2.0 / (enc_out + h)) ** 0.5
        kz, kr, kn = jax.random.split(kg, 3)
        gru = {
            # update / reset / candidate gates, each over [x, h]
            name: {
                "w": jax.random.normal(k, (enc_out + h, h)) * s,
                "b": jnp.zeros((h,)),
            }
            for name, k in (("z", kz), ("r", kr), ("n", kn))
        }
        return {
            "enc": init_mlp(ke, (self.obs_dim, *self.encoder)) if self.encoder else [],
            "gru": gru,
            "pi": init_mlp(kp, (h, self.num_actions)),
            "vf": init_mlp(kv, (h, 1)),
        }

    def initial_state(self, batch_size: int):
        import numpy as np

        return np.zeros((batch_size, self.hidden), np.float32)

    @staticmethod
    def _encode(params, obs):
        x = obs
        if params["enc"]:
            x = mlp_forward(params["enc"], x)
            x = jnp.tanh(x)  # mlp_forward leaves the last layer linear
        return x

    @staticmethod
    def _gru_cell(params, x, state):
        xh = jnp.concatenate([x, state], axis=-1)
        g = params["gru"]
        z = jax.nn.sigmoid(xh @ g["z"]["w"] + g["z"]["b"])
        r = jax.nn.sigmoid(xh @ g["r"]["w"] + g["r"]["b"])
        xr = jnp.concatenate([x, r * state], axis=-1)
        n = jnp.tanh(xr @ g["n"]["w"] + g["n"]["b"])
        return (1.0 - z) * n + z * state

    def step(self, params, obs, state):
        """One timestep: obs [B, D], state [B, H] ->
        (logits [B, A], value [B], new_state [B, H])."""
        x = self._encode(params, obs)
        h = self._gru_cell(params, x, state)
        return (
            mlp_forward(params["pi"], h),
            mlp_forward(params["vf"], h)[..., 0],
            h,
        )

    def unroll(self, params, obs_seq, state0, resets):
        """Sequence forward: obs_seq [T, B, D], state0 [B, H], resets [T, B]
        — resets[t] = 1 zeroes the state BEFORE consuming obs[t] (callers
        pass the dones shifted by one step, so an episode ending at t-1
        starts t fresh, matching the vector env's auto-reset).  Returns
        (logits [T, B, A], values [T, B], final state)."""

        def body(state, xs):
            obs_t, reset_t = xs
            state = jnp.where(reset_t[:, None] > 0, 0.0, state)
            logits, value, h = self.step(params, obs_t, state)
            return h, (logits, value)

        final, (logits, values) = jax.lax.scan(body, state0, (obs_seq, resets))
        return logits, values, final


class QModule:
    """Q-network (+ the same arch reused for the DQN target net)."""

    def __init__(self, obs_dim: int, num_actions: int, hidden: Sequence[int] = (64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hidden = tuple(hidden)

    def init(self, key) -> Dict[str, Any]:
        return {"q": init_mlp(key, (self.obs_dim, *self.hidden, self.num_actions))}

    @staticmethod
    def q_values(params, obs):
        return mlp_forward(params["q"], obs)


class SquashedGaussianModule:
    """tanh-squashed Gaussian policy over a continuous action box
    (reference rllib/algorithms/sac policy head, jax-native)."""

    LOG_STD_MIN, LOG_STD_MAX = -5.0, 2.0

    def __init__(self, obs_dim: int, action_dim: int, action_scale: float = 1.0,
                 hidden: Sequence[int] = (64, 64)):
        self.obs_dim = obs_dim
        self.action_dim = action_dim
        self.action_scale = float(action_scale)
        self.hidden = tuple(hidden)

    def init(self, key) -> Dict[str, Any]:
        return {"pi": init_mlp(key, (self.obs_dim, *self.hidden, 2 * self.action_dim))}

    def dist(self, params, obs):
        out = mlp_forward(params["pi"], obs)
        mean, log_std = jnp.split(out, 2, axis=-1)
        log_std = jnp.clip(log_std, self.LOG_STD_MIN, self.LOG_STD_MAX)
        return mean, log_std

    def sample(self, params, obs, key):
        """Reparameterized squashed sample -> (action, logp)."""
        mean, log_std = self.dist(params, obs)
        std = jnp.exp(log_std)
        eps = jax.random.normal(key, mean.shape)
        pre = mean + std * eps
        act = jnp.tanh(pre)
        # N(pre; mean, std) log-density with the change of variables for
        # tanh AND the affine action_scale (d(scale*tanh)/dpre adds a
        # log(scale) per dim — omitting it biases entropy by log(scale)/dim)
        logp = -0.5 * (((pre - mean) / std) ** 2 + 2 * log_std + jnp.log(2 * jnp.pi))
        logp = logp - jnp.log(1.0 - act**2 + 1e-6) - jnp.log(self.action_scale)
        logp = jnp.sum(logp, axis=-1)
        return act * self.action_scale, logp

    def mean_action(self, params, obs):
        mean, _ = self.dist(params, obs)
        return jnp.tanh(mean) * self.action_scale


class DeterministicPolicyModule:
    """Deterministic continuous policy mu(s) = tanh(mlp(s)) * scale (the
    TD3/DDPG actor; reference rllib/algorithms/td3).  Exploration noise is
    the runner's job (Gaussian on the action), not the module's."""

    def __init__(self, obs_dim: int, action_dim: int, action_scale: float = 1.0,
                 hidden: Sequence[int] = (64, 64)):
        self.obs_dim = obs_dim
        self.action_dim = action_dim
        self.action_scale = float(action_scale)
        self.hidden = tuple(hidden)

    def init(self, key) -> Dict[str, Any]:
        return {"mu": init_mlp(key, (self.obs_dim, *self.hidden, self.action_dim))}

    def mean_action(self, params, obs):
        return jnp.tanh(mlp_forward(params["mu"], obs)) * self.action_scale


class TwinQModule:
    """Two independent Q(s, a) critics over concatenated obs+action
    (clipped double-Q; reference sac_torch_model.py twin heads)."""

    def __init__(self, obs_dim: int, action_dim: int, hidden: Sequence[int] = (64, 64)):
        self.obs_dim = obs_dim
        self.action_dim = action_dim
        self.hidden = tuple(hidden)

    def init(self, key) -> Dict[str, Any]:
        k1, k2 = jax.random.split(key)
        sizes = (self.obs_dim + self.action_dim, *self.hidden, 1)
        return {"q1": init_mlp(k1, sizes), "q2": init_mlp(k2, sizes)}

    @staticmethod
    def q(params, obs, act):
        x = jnp.concatenate([obs, act], axis=-1)
        return mlp_forward(params["q1"], x)[..., 0], mlp_forward(params["q2"], x)[..., 0]


def softmax_sample(rng, logits: "np.ndarray"):
    """Numpy-side categorical sampling from a batch of logits.
    Returns (actions int32 [B], logp float32 [B]).  Shared by every env
    runner so the sampling numerics live in exactly one place."""
    import numpy as np

    z = logits - logits.max(-1, keepdims=True)
    p = np.exp(z)
    p /= p.sum(-1, keepdims=True)
    actions = np.array([rng.choice(p.shape[-1], p=row) for row in p], np.int32)
    logp = np.log(p[np.arange(len(actions)), actions] + 1e-9).astype(np.float32)
    return actions, logp
