"""RLModule: the neural networks, pure-pytree + functional (analogue of the
reference's rllib/core/rl_module/rl_module.py, jax-native instead of torch).
"""

from __future__ import annotations

from typing import Any, Dict, Sequence

import jax
import jax.numpy as jnp


def init_mlp(key, sizes: Sequence[int]) -> list:
    params = []
    for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
        key, k = jax.random.split(key)
        params.append(
            {
                "w": jax.random.normal(k, (fan_in, fan_out)) * (2.0 / fan_in) ** 0.5,
                "b": jnp.zeros((fan_out,)),
            }
        )
    return params


def mlp_forward(params: list, x: jnp.ndarray) -> jnp.ndarray:
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1:
            x = jnp.tanh(x)
    return x


class DiscretePolicyModule:
    """Separate policy and value MLPs over a flat observation."""

    def __init__(self, obs_dim: int, num_actions: int, hidden: Sequence[int] = (64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hidden = tuple(hidden)

    def init(self, key) -> Dict[str, Any]:
        kp, kv = jax.random.split(key)
        return {
            "pi": init_mlp(kp, (self.obs_dim, *self.hidden, self.num_actions)),
            "vf": init_mlp(kv, (self.obs_dim, *self.hidden, 1)),
        }

    @staticmethod
    def logits(params, obs):
        return mlp_forward(params["pi"], obs)

    @staticmethod
    def value(params, obs):
        return mlp_forward(params["vf"], obs)[..., 0]


class QModule:
    """Q-network (+ the same arch reused for the DQN target net)."""

    def __init__(self, obs_dim: int, num_actions: int, hidden: Sequence[int] = (64, 64)):
        self.obs_dim = obs_dim
        self.num_actions = num_actions
        self.hidden = tuple(hidden)

    def init(self, key) -> Dict[str, Any]:
        return {"q": init_mlp(key, (self.obs_dim, *self.hidden, self.num_actions))}

    @staticmethod
    def q_values(params, obs):
        return mlp_forward(params["q"], obs)
