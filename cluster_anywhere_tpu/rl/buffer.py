"""Replay buffer (analogue of rllib/utils/replay_buffers/ — uniform ring
buffer over flat numpy transitions)."""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class ReplayBuffer:
    def __init__(self, capacity: int, obs_dim: int, seed: int = 0,
                 action_dim: int = 0):
        """action_dim=0 stores scalar int actions (DQN); >0 stores float
        action vectors of that width (SAC)."""
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_dim), np.float32)
        self.next_obs = np.zeros((capacity, obs_dim), np.float32)
        if action_dim > 0:
            self.actions = np.zeros((capacity, action_dim), np.float32)
        else:
            self.actions = np.zeros(capacity, np.int32)
        self.rewards = np.zeros(capacity, np.float32)
        self.dones = np.zeros(capacity, np.float32)
        self.idx = 0
        self.size = 0
        self.rng = np.random.default_rng(seed)

    def add_batch(self, obs, actions, rewards, dones, next_obs):
        for i in range(len(obs)):
            j = self.idx
            self.obs[j] = obs[i]
            self.actions[j] = actions[i]
            self.rewards[j] = rewards[i]
            self.dones[j] = dones[i]
            self.next_obs[j] = next_obs[i]
            self.idx = (self.idx + 1) % self.capacity
            self.size = min(self.size + 1, self.capacity)

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self.rng.integers(0, self.size, size=batch_size)
        return {
            "obs": self.obs[idx],
            "actions": self.actions[idx],
            "rewards": self.rewards[idx],
            "dones": self.dones[idx],
            "next_obs": self.next_obs[idx],
        }

    def __len__(self):
        return self.size
