"""Replay buffers (analogue of rllib/utils/replay_buffers/): a uniform ring
buffer over flat numpy transitions, and a proportional prioritized buffer
(sum-tree, alpha/beta importance correction — the PER of
rllib/utils/replay_buffers/prioritized_episode_buffer.py, transition-level)."""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class ReplayBuffer:
    def __init__(self, capacity: int, obs_dim: int, seed: int = 0,
                 action_dim: int = 0):
        """action_dim=0 stores scalar int actions (DQN); >0 stores float
        action vectors of that width (SAC)."""
        self.capacity = capacity
        self.obs = np.zeros((capacity, obs_dim), np.float32)
        self.next_obs = np.zeros((capacity, obs_dim), np.float32)
        if action_dim > 0:
            self.actions = np.zeros((capacity, action_dim), np.float32)
        else:
            self.actions = np.zeros(capacity, np.int32)
        self.rewards = np.zeros(capacity, np.float32)
        self.dones = np.zeros(capacity, np.float32)
        self.idx = 0
        self.size = 0
        self.rng = np.random.default_rng(seed)

    def add_batch(self, obs, actions, rewards, dones, next_obs):
        for i in range(len(obs)):
            j = self.idx
            self.obs[j] = obs[i]
            self.actions[j] = actions[i]
            self.rewards[j] = rewards[i]
            self.dones[j] = dones[i]
            self.next_obs[j] = next_obs[i]
            self.idx = (self.idx + 1) % self.capacity
            self.size = min(self.size + 1, self.capacity)

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self.rng.integers(0, self.size, size=batch_size)
        return {
            "obs": self.obs[idx],
            "actions": self.actions[idx],
            "rewards": self.rewards[idx],
            "dones": self.dones[idx],
            "next_obs": self.next_obs[idx],
        }

    def __len__(self):
        return self.size


class _SumTree:
    """Flat binary sum-tree: O(log n) priority update and proportional
    prefix-sum sampling.  Leaves are padded to a power of two so every leaf
    sits at the same depth (uniform descent loop, vectorized)."""

    def __init__(self, capacity: int):
        n = 1
        while n < capacity:
            n *= 2
        self.n_leaves = n
        self.tree = np.zeros(2 * n, np.float64)

    def set(self, idx: np.ndarray, priority: np.ndarray) -> None:
        parents = np.asarray(idx, np.int64) + self.n_leaves
        if len(parents) == 0:
            return
        self.tree[parents] = priority
        parents = np.unique(parents // 2)
        while parents[0] >= 1:
            self.tree[parents] = self.tree[2 * parents] + self.tree[2 * parents + 1]
            if parents[0] == 1:
                break
            parents = np.unique(parents // 2)

    @property
    def total(self) -> float:
        return float(self.tree[1])

    def prefix_sample(self, values: np.ndarray) -> np.ndarray:
        """For each v in [0, total), find the leaf whose cumulative range
        contains v (vectorized level-synchronous descent)."""
        idx = np.ones(len(values), np.int64)
        v = values.astype(np.float64).copy()
        while idx[0] < self.n_leaves:
            left = 2 * idx
            lsum = self.tree[left]
            go_right = v >= lsum
            v = np.where(go_right, v - lsum, v)
            idx = np.where(go_right, left + 1, left)
        return idx - self.n_leaves

    def max_leaf(self) -> float:
        return float(self.tree[self.n_leaves :].max())

    def get(self, idx: np.ndarray) -> np.ndarray:
        return self.tree[np.asarray(idx, np.int64) + self.n_leaves]


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional PER (Schaul et al.): sample i with p_i^alpha / sum, and
    correct the induced bias with importance weights (N * P(i))^-beta
    normalized by the max weight.  New transitions enter at the current max
    priority so everything is seen at least once; the learner feeds TD
    errors back via update_priorities."""

    def __init__(
        self,
        capacity: int,
        obs_dim: int,
        seed: int = 0,
        action_dim: int = 0,
        alpha: float = 0.6,
        beta: float = 0.4,
        beta_final: float = 1.0,
        beta_anneal_steps: int = 100_000,
        eps: float = 1e-6,
    ):
        super().__init__(capacity, obs_dim, seed, action_dim)
        self.alpha = alpha
        self.beta0 = beta
        self.beta_final = beta_final
        self.beta_anneal_steps = max(1, beta_anneal_steps)
        self.eps = eps
        self.tree = _SumTree(capacity)
        self._samples_drawn = 0

    @property
    def beta(self) -> float:
        frac = min(1.0, self._samples_drawn / self.beta_anneal_steps)
        return self.beta0 + frac * (self.beta_final - self.beta0)

    def add_batch(self, obs, actions, rewards, dones, next_obs):
        n = len(obs)
        start = self.idx
        super().add_batch(obs, actions, rewards, dones, next_obs)
        new_idx = (start + np.arange(n)) % self.capacity
        p0 = max(self.tree.max_leaf(), 1.0)
        self.tree.set(new_idx, np.full(n, p0))

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        total = self.tree.total
        # stratified: one draw per equal segment of the cumulative mass
        seg = total / batch_size
        v = (np.arange(batch_size) + self.rng.random(batch_size)) * seg
        idx = self.tree.prefix_sample(np.minimum(v, np.nextafter(total, 0)))
        idx = np.minimum(idx, self.size - 1)
        p = self.tree.get(idx) / max(total, 1e-12)
        w = (self.size * np.maximum(p, 1e-12)) ** (-self.beta)
        w /= w.max()
        self._samples_drawn += batch_size
        return {
            "obs": self.obs[idx],
            "actions": self.actions[idx],
            "rewards": self.rewards[idx],
            "dones": self.dones[idx],
            "next_obs": self.next_obs[idx],
            "weights": w.astype(np.float32),
            "indices": idx.astype(np.int64),
        }

    def update_priorities(self, indices: np.ndarray, td_errors: np.ndarray) -> None:
        p = (np.abs(np.asarray(td_errors, np.float64)) + self.eps) ** self.alpha
        self.tree.set(np.asarray(indices, np.int64), p)
