"""Multi-agent RL: dict-keyed envs, policy mapping, per-policy learners.

Reference parity: ``rllib/env/multi_agent_env.py`` (MultiAgentEnv API with
``__all__`` done signaling), ``rllib/policy/policy_map.py`` + the
``policy_mapping_fn`` config surface, and the multi-agent sampling/training
split inside rllib's Algorithm.  Compressed to the same shape as this
package's single-agent stack: env runners are plain actors, each policy owns
one jitted PPOLearner, and a training step is sample -> group-by-policy ->
per-policy GAE + update -> broadcast.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..core import api as _ca
from ..core.actor import kill
from .learner import PPOLearner, compute_gae
from .module import DiscretePolicyModule


class MultiAgentEnv:
    """Dict-keyed environment: every method speaks {agent_id: value}.

    ``step`` returns (obs, rewards, dones, infos); ``dones["__all__"]``
    terminates the episode (multi_agent_env.py contract).  Agent sets are
    fixed per episode for this runtime (no mid-episode joins)."""

    agent_ids: Tuple[str, ...]
    observation_dim: int
    num_actions: int

    def reset(self, seed: Optional[int] = None) -> Dict[str, np.ndarray]:
        raise NotImplementedError

    def step(self, actions: Dict[str, int]):
        raise NotImplementedError


class CoordinationGame(MultiAgentEnv):
    """Repeated 2-player coordination: both pick the same arm -> +1 each.

    Observations encode the opponent's previous action, so coordinated
    equilibria are learnable by independent PPO (the standard smoke test for
    a multi-agent training loop)."""

    agent_ids = ("a0", "a1")
    observation_dim = 3  # one-hot of opponent's last action + "first step" bit
    num_actions = 2
    episode_len = 16

    def __init__(self):
        self.t = 0
        self.last = {aid: -1 for aid in self.agent_ids}

    def _obs(self) -> Dict[str, np.ndarray]:
        out = {}
        for aid in self.agent_ids:
            other = self.agent_ids[1] if aid == self.agent_ids[0] else self.agent_ids[0]
            o = np.zeros(3, np.float32)
            if self.last[other] < 0:
                o[2] = 1.0
            else:
                o[self.last[other]] = 1.0
            out[aid] = o
        return out

    def reset(self, seed: Optional[int] = None) -> Dict[str, np.ndarray]:
        self.t = 0
        self.last = {aid: -1 for aid in self.agent_ids}
        return self._obs()

    def step(self, actions: Dict[str, int]):
        self.t += 1
        self.last = dict(actions)
        r = 1.0 if actions["a0"] == actions["a1"] else 0.0
        rewards = {aid: r for aid in self.agent_ids}
        done = self.t >= self.episode_len
        dones = {aid: done for aid in self.agent_ids}
        dones["__all__"] = done
        return self._obs(), rewards, dones, {}


class RockPaperScissors(MultiAgentEnv):
    """Zero-sum repeated RPS; the classic rllib multi-agent example env."""

    agent_ids = ("player1", "player2")
    observation_dim = 4  # one-hot of opponent's last throw + first-step bit
    num_actions = 3
    episode_len = 10

    _BEATS = {0: 2, 1: 0, 2: 1}  # rock>scissors, paper>rock, scissors>paper

    def __init__(self):
        self.t = 0
        self.last = {aid: -1 for aid in self.agent_ids}

    def _obs(self):
        p1, p2 = self.agent_ids
        out = {}
        for aid, other in ((p1, p2), (p2, p1)):
            o = np.zeros(4, np.float32)
            if self.last[other] < 0:
                o[3] = 1.0
            else:
                o[self.last[other]] = 1.0
            out[aid] = o
        return out

    def reset(self, seed: Optional[int] = None):
        self.t = 0
        self.last = {aid: -1 for aid in self.agent_ids}
        return self._obs()

    def step(self, actions):
        self.t += 1
        self.last = dict(actions)
        a1, a2 = actions["player1"], actions["player2"]
        if a1 == a2:
            r1 = 0.0
        elif self._BEATS[a1] == a2:
            r1 = 1.0
        else:
            r1 = -1.0
        rewards = {"player1": r1, "player2": -r1}
        done = self.t >= self.episode_len
        dones = {aid: done for aid in self.agent_ids}
        dones["__all__"] = done
        return self._obs(), rewards, dones, {}


class MultiAgentEnvRunner:
    """Actor: samples one multi-agent env with per-policy networks, returning
    per-policy [T, ...] rollout arrays (single_agent_env_runner.py's
    multi-agent sibling, flattened for the jitted learners)."""

    def __init__(self, env_creator, policy_specs: Dict[str, dict],
                 policy_mapping: Dict[str, str], seed: int = 0):
        import jax

        self.env = env_creator()
        self.mapping = policy_mapping
        self.modules = {
            pid: DiscretePolicyModule(
                spec["obs_dim"], spec["num_actions"], spec.get("hidden", (64, 64))
            )
            for pid, spec in policy_specs.items()
        }
        self.params = {
            pid: m.init(jax.random.key(seed + i))
            for i, (pid, m) in enumerate(self.modules.items())
        }
        self._jit = {
            pid: (jax.jit(m.logits), jax.jit(m.value))
            for pid, m in self.modules.items()
        }
        self.rng = np.random.default_rng(seed + 17)
        self.obs = self.env.reset(seed=seed)

    def set_weights(self, params: Dict[str, Any], _eps=None):
        self.params.update(params)
        return "ok"

    def _policy_batch(self, pid: str, aids: List[str], obs: Dict[str, np.ndarray]):
        """One batched logits+value dispatch for every agent of a policy
        (same batching the single-agent EnvRunner gets over its N envs)."""
        import jax.numpy as jnp

        from .module import softmax_sample

        logits_fn, value_fn = self._jit[pid]
        stacked = jnp.asarray(np.stack([obs[a] for a in aids]))
        logits = np.asarray(logits_fn(self.params[pid], stacked))
        actions, logp = softmax_sample(self.rng, logits)
        values = np.asarray(value_fn(self.params[pid], stacked), np.float32)
        return actions, logp, values

    def sample(self, num_steps: int) -> Dict[str, Any]:
        """Per-policy rollout arrays over num_steps env steps.  Column order
        is the env's agent_ids declaration order throughout — per-step rows,
        and the bootstrap values — so GAE columns always line up."""
        policy_agents: Dict[str, List[str]] = {pid: [] for pid in self.modules}
        for aid in self.env.agent_ids:
            policy_agents[self.mapping[aid]].append(aid)
        cols: Dict[str, Dict[str, list]] = {
            pid: {k: [] for k in ("obs", "actions", "rewards", "dones", "logp", "values")}
            for pid in self.modules
        }
        ep_returns: List[float] = []
        ep_acc = 0.0
        for _ in range(num_steps):
            prev_obs = self.obs
            acts: Dict[str, int] = {}
            per_policy = {}
            for pid, aids in policy_agents.items():
                if not aids:
                    continue
                actions, logp, values = self._policy_batch(pid, aids, prev_obs)
                per_policy[pid] = (actions, logp, values)
                for i, aid in enumerate(aids):
                    acts[aid] = int(actions[i])
            nobs, rewards, dones, _ = self.env.step(acts)
            ep_acc += float(np.mean(list(rewards.values())))
            for pid, aids in policy_agents.items():
                if not aids:
                    continue
                actions, logp, values = per_policy[pid]
                c = cols[pid]
                c["obs"].append([prev_obs[a] for a in aids])
                c["actions"].append(list(actions))
                c["rewards"].append([rewards[a] for a in aids])
                c["dones"].append([dones.get(a, dones["__all__"]) for a in aids])
                c["logp"].append(list(logp))
                c["values"].append(list(values))
            if dones["__all__"]:
                ep_returns.append(ep_acc)
                ep_acc = 0.0
                nobs = self.env.reset()
            self.obs = nobs
        out: Dict[str, Any] = {"metrics": {
            "episodes": len(ep_returns),
            **({"episode_return_mean": float(np.mean(ep_returns))} if ep_returns else {}),
        }}
        import jax.numpy as jnp

        for pid, aids in policy_agents.items():
            c = cols[pid]
            if not aids or not c["obs"]:
                continue
            ro = {
                "obs": np.asarray(c["obs"], np.float32),          # [T, N, D]
                "actions": np.asarray(c["actions"], np.int32),    # [T, N]
                "rewards": np.asarray(c["rewards"], np.float32),
                "dones": np.asarray(c["dones"]),
                "logp": np.asarray(c["logp"], np.float32),
                "values": np.asarray(c["values"], np.float32),
            }
            # bootstrap values for the final obs, same agent order as the
            # columns above; value-only (no sampling, rng untouched)
            _, value_fn = self._jit[pid]
            stacked = jnp.asarray(np.stack([self.obs[a] for a in aids]))
            ro["last_values"] = np.asarray(
                value_fn(self.params[pid], stacked), np.float32
            )
            out[pid] = ro
        return out


class MultiAgentPPO:
    """Independent PPO over a policy map (the rllib multi-agent default).

    ``policies``: policy_id -> {} (spec overrides); ``policy_mapping_fn``:
    agent_id -> policy_id, resolved once per agent id (fixed agent sets)."""

    def __init__(
        self,
        env_creator: Callable[[], MultiAgentEnv],
        policies: Dict[str, dict],
        policy_mapping_fn: Callable[[str], str],
        *,
        num_env_runners: int = 2,
        rollout_length: int = 128,
        gamma: float = 0.99,
        lam: float = 0.95,
        lr: float = 3e-3,
        hidden: Tuple[int, ...] = (64, 64),
        seed: int = 0,
    ):
        probe = env_creator()
        self.gamma, self.lam = gamma, lam
        self.mapping = {aid: policy_mapping_fn(aid) for aid in probe.agent_ids}
        unknown = set(self.mapping.values()) - set(policies)
        if unknown:
            raise ValueError(f"policy_mapping_fn returned unknown policies {sorted(unknown)}")
        self.specs = {
            pid: {
                "obs_dim": spec.get("obs_dim", probe.observation_dim),
                "num_actions": spec.get("num_actions", probe.num_actions),
                "hidden": spec.get("hidden", hidden),
            }
            for pid, spec in policies.items()
        }
        self.learners = {
            pid: PPOLearner(
                DiscretePolicyModule(s["obs_dim"], s["num_actions"], s["hidden"]),
                lr=lr, seed=seed + j,
            )
            for j, (pid, s) in enumerate(self.specs.items())
        }
        Runner = _ca.remote(MultiAgentEnvRunner)
        self.runners = [
            Runner.remote(env_creator, self.specs, self.mapping, seed=seed + 100 * i)
            for i in range(num_env_runners)
        ]
        self.rollout_length = rollout_length
        self.iteration = 0
        self._broadcast()

    def _broadcast(self):
        weights = {pid: ln.get_weights() for pid, ln in self.learners.items()}
        _ca.get([r.set_weights.remote(weights) for r in self.runners])

    def train(self) -> Dict[str, Any]:
        rollouts = _ca.get([r.sample.remote(self.rollout_length) for r in self.runners])
        metrics: Dict[str, Any] = {}
        rets = [
            ro["metrics"]["episode_return_mean"]
            for ro in rollouts
            if "episode_return_mean" in ro["metrics"]
        ]
        for pid, learner in self.learners.items():
            batches = []
            for ro in rollouts:
                if pid not in ro:
                    continue
                r = ro[pid]
                adv, ret = compute_gae(r, self.gamma, self.lam)
                batches.append({
                    "obs": r["obs"].reshape(-1, r["obs"].shape[-1]),
                    "actions": r["actions"].reshape(-1),
                    "logp_old": r["logp"].reshape(-1),
                    "advantages": adv,
                    "returns": ret,
                })
            if not batches:
                continue
            batch = {k: np.concatenate([b[k] for b in batches]) for k in batches[0]}
            stats = learner.update(batch)
            metrics[pid] = stats
        self._broadcast()
        self.iteration += 1
        metrics["training_iteration"] = self.iteration
        if rets:
            metrics["episode_return_mean"] = float(np.mean(rets))
        return metrics

    def get_policy_weights(self, policy_id: str):
        return self.learners[policy_id].get_weights()

    def stop(self):
        for r in self.runners:
            try:
                kill(r)
            except Exception:
                pass
