"""Versioned shared-memory channels.

Wire format of a slot (all u64 little-endian, 8-byte aligned):

    [magic][version][payload_len][flags][num_readers][ack_0]...[ack_{R-1}] payload...

Protocol (single writer, R registered readers):
  - writer waits until every ack == version, serializes the value into the
    payload area, then publishes by storing version+1;
  - reader r waits until version > ack_r, deserializes, stores ack_r = version.
An 8-byte aligned store through mmap is effectively atomic on the platforms we
target (x86-64/ARM64), and the version store is the release point — payload is
written before version advances, matching the reference's seal-then-notify
semantics (plasma mutable objects, experimental_mutable_object_manager.h:49).

Backing storage is a plain /dev/shm file mmap'd by writer and readers (same
mechanism as core/object_store.py), placed inside the session's shm directory
so stale-session sweeping reclaims it.

Oversized payloads spill to the distributed object store and the channel
carries only the ObjectRef (the reference resizes its backing store;
spill-through keeps the segment bounded instead).
"""

from __future__ import annotations

import mmap
import os
import queue as _queue
import struct
import threading as _threading
import time
import uuid
from typing import Any, List, Optional

_MAGIC = 0x00CA_C4A9
_U64 = struct.Struct("<Q")
_FLAG_CLOSED = 1
_SPILL_BIT = 1 << 63  # payload_len high bit: payload is a spilled ObjectRef

_DEFAULT_BUFFER = 8 * 1024 * 1024
_POLL_S = 20e-6

# hot-path counters, plain ints bumped without a lock (same contract as
# protocol.WIRE_STATS: a lost increment under a race is acceptable, a lock
# in a microsecond-scale channel write is not); util/metrics delta-ships
# them as ca_channel_* cluster counters on every flush
CHANNEL_STATS = {
    "writes": 0,           # payloads published (per underlying slot write)
    "reads": 0,            # payloads consumed
    "spills": 0,           # oversized payloads routed through the object store
    "backpressure_waits": 0,  # writes that found a reader ack outstanding
    "closes": 0,           # close() flags raised
}


class ChannelClosedError(Exception):
    """Raised by read/write when the channel has been shut down."""


class ChannelInterface:
    def write(self, value: Any, timeout: Optional[float] = None):
        raise NotImplementedError

    def read(self, timeout: Optional[float] = None) -> Any:
        raise NotImplementedError

    def close(self):
        raise NotImplementedError


def _now():
    return time.monotonic()


def _chan_dir() -> str:
    """Channel files live under the session's /dev/shm dir so a crashed
    session's sweep (core/api.py:_sweep_stale_sessions) reclaims them."""
    from ..core.worker import try_global_worker

    w = try_global_worker()
    if w is not None and getattr(w, "session_dir", None):
        d = os.path.join("/dev/shm", os.path.basename(w.session_dir))
        os.makedirs(d, exist_ok=True)
        return d
    return "/dev/shm"


class ShmChannel(ChannelInterface):
    """Single-slot channel. Create once (driver side), open by spec elsewhere."""

    def __init__(
        self,
        num_readers: int = 1,
        buffer_size: int = _DEFAULT_BUFFER,
        *,
        path: Optional[str] = None,
    ):
        self.num_readers = num_readers
        self.header_size = 8 * (5 + num_readers)
        self.reader_index = 0
        self._created = path is None
        if path is None:
            path = os.path.join(_chan_dir(), f"chan_{uuid.uuid4().hex[:16]}")
            size = buffer_size + self.header_size
            fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
            try:
                os.ftruncate(fd, size)
                self._mm = mmap.mmap(fd, size)
            finally:
                os.close(fd)
            self._init_header()
        else:
            fd = os.open(path, os.O_RDWR)
            try:
                size = os.fstat(fd).st_size
                self._mm = mmap.mmap(fd, size)
            finally:
                os.close(fd)
        self.path = path
        self.capacity = len(self._mm) - self.header_size
        self._last_spill = None
        # waiter accounting so release() can't unmap the segment while another
        # thread is blocked in the native futex wait on a raw address inside
        # it (ADVICE r1: use-after-unmap). _released is process-local (unlike
        # the shared close flag, which would close the channel for everyone).
        self._released = False
        self._waiters = 0
        self._waiters_lock = _threading.Lock()
        # native futex wait/wake (microsecond wakeups, no spin): fall back to
        # 20us polling when the native library is unavailable
        self._fx = None
        self._addr = 0
        try:
            from ..native import build as _nb

            lib = _nb.load()
            if lib is not None:
                self._fx = lib
                self._addr = _nb.buffer_address(self._mm)
        except Exception:
            self._fx = None

    # -- u64 accessors ------------------------------------------------------

    def _get(self, idx: int) -> int:
        return _U64.unpack_from(self._mm, 8 * idx)[0]

    def _set(self, idx: int, v: int):
        _U64.pack_into(self._mm, 8 * idx, v)

    def _set_wake(self, idx: int, v: int):
        """Release-store + wake futex sleepers on this word."""
        if self._fx is not None:
            self._fx.ca_store_u64_wake(self._addr + 8 * idx, v)
        else:
            self._set(idx, v)

    def _wait_ge(self, idx: int, min_val: int, deadline) -> None:
        """Block until word[idx] >= min_val, honoring close flag + deadline."""
        while True:
            if self._released:
                raise ChannelClosedError
            if self._get(idx) >= min_val:
                return
            if self._get(3) & _FLAG_CLOSED:
                raise ChannelClosedError
            if deadline is not None and _now() > deadline:
                raise TimeoutError("channel wait timed out")
            if self._fx is not None:
                # the C wait watches the close-flag word too, so a close()
                # wake that lands while the waiter is queued returns
                # immediately with rc=2; the 50ms slice bounds the rare lost
                # wake (flag set between the waiter's check and FUTEX_WAIT)
                slice_ns = 50_000_000
                if deadline is not None:
                    slice_ns = min(slice_ns, max(1, int((deadline - _now()) * 1e9)))
                self._fx.ca_wait_u64_ge_flag(
                    self._addr + 8 * idx,
                    min_val,
                    self._addr + 8 * 3,  # flags word
                    _FLAG_CLOSED,
                    slice_ns,
                )
            else:
                time.sleep(_POLL_S)

    def _init_header(self):
        self._set(0, _MAGIC)
        for i in range(1, 5 + self.num_readers):
            self._set(i, 0)
        self._set(4, self.num_readers)

    @property
    def version(self) -> int:
        return self._get(1)

    def spec(self) -> dict:
        return {"kind": "shm", "path": self.path, "num_readers": self.num_readers}

    @classmethod
    def open(cls, spec: dict, reader_index: int = 0) -> "ShmChannel":
        ch = cls(num_readers=spec["num_readers"], path=spec["path"])
        ch.reader_index = reader_index
        return ch

    # -- core protocol ------------------------------------------------------

    def _write_payload(self, chunks, total: int, spilled: bool, deadline):
        """chunks: list of bytes-like pieces written back-to-back (scatter
        write — large array buffers go straight from their source into shm
        with no intermediate contiguous blob)."""
        want = self.version
        for r in range(self.num_readers):
            if self._get(5 + r) < want:
                # a reader hasn't consumed the previous version yet: this
                # write is about to block on backpressure
                CHANNEL_STATS["backpressure_waits"] += 1
            self._wait_ge(5 + r, want, deadline)  # acks only ever increase
        pos = self.header_size
        for c in chunks:
            n = len(c)
            self._mm[pos : pos + n] = c
            pos += n
        self._set(2, total | (_SPILL_BIT if spilled else 0))
        self._set_wake(1, want + 1)  # publish + wake readers

    def _enter(self):
        """Mark this thread as touching the segment (native or mmap) so a
        concurrent release() cannot unmap under it; the whole read()/write()
        critical section is covered, not just the futex wait."""
        with self._waiters_lock:
            if self._released:
                raise ChannelClosedError
            self._waiters += 1

    def _exit(self):
        with self._waiters_lock:
            self._waiters -= 1

    def write(self, value: Any, timeout: Optional[float] = None):
        from ..core.serialization import pack, pack_chunks

        deadline = None if timeout is None else _now() + timeout
        total, chunks = pack_chunks(value)
        spilled = False
        ref = None
        if total > self.capacity:
            from ..core import api as ca

            ref = ca.put(value)
            payload = pack(ref)
            chunks, total, spilled = [payload], len(payload), True
            CHANNEL_STATS["spills"] += 1
        self._enter()
        try:
            self._write_payload(chunks, total, spilled, deadline)
        finally:
            self._exit()
        CHANNEL_STATS["writes"] += 1
        # _write_payload waited for all acks of the previous version, and
        # readers only ack after fetching a spilled payload — so the prior
        # spilled object (if any) has been consumed.  Drop its ref, and keep
        # the new one (None for inline writes) alive until the next write.
        self._last_spill = ref

    def read(self, timeout: Optional[float] = None) -> Any:
        from ..core.serialization import unpack

        deadline = None if timeout is None else _now() + timeout
        self._enter()
        try:
            my_ack = self._get(5 + self.reader_index)
            self._wait_ge(1, my_ack + 1, deadline)
            ver = self.version
            ln = self._get(2)
            spilled = bool(ln & _SPILL_BIT)
            ln &= ~_SPILL_BIT
            value = unpack(bytes(self._mm[self.header_size : self.header_size + ln]))
        finally:
            self._exit()
        if spilled:
            from ..core import api as ca

            # fetch BEFORE acking: the ack is what lets the writer's next
            # write drop its reference to this spilled object
            value = ca.get(value)
        try:
            self._enter()
            try:
                self._set_wake(5 + self.reader_index, ver)
            finally:
                self._exit()
        except ChannelClosedError:
            pass  # released mid-read: the ack is writer bookkeeping only —
            # the value was already read in full, so deliver it
        CHANNEL_STATS["reads"] += 1
        return value

    def wait_consumed(self, timeout: Optional[float] = None) -> bool:
        """Writer-side drain barrier: block until every reader has acked the
        last published version (i.e. the final write has been consumed), so
        release() can't unlink the segment under a reader that hasn't mapped
        or read it yet.  Returns False on timeout or close."""
        deadline = None if timeout is None else _now() + timeout
        want = self.version
        try:
            self._enter()
        except ChannelClosedError:
            return False
        try:
            for r in range(self.num_readers):
                self._wait_ge(5 + r, want, deadline)
            return True
        except (ChannelClosedError, TimeoutError):
            return False
        finally:
            self._exit()

    def close(self):
        try:
            self._enter()
        except ChannelClosedError:
            return  # already released locally; nothing to flag
        CHANNEL_STATS["closes"] += 1
        try:
            self._set(3, _FLAG_CLOSED)
            if self._fx is not None:
                # wake WITHOUT storing: a read-modify-store here could roll
                # back a concurrent publish/ack; sleepers re-check the flag
                self._fx.ca_wake_u64(self._addr + 8)
                for r in range(self.num_readers):
                    self._fx.ca_wake_u64(self._addr + 8 * (5 + r))
        finally:
            self._exit()

    def release(self):
        # flip the process-local flag, wake local sleepers, then wait for
        # every native waiter to leave the segment before unmapping (each
        # waiter's slice is <=50ms, so this drains quickly; cap at 2s so a
        # wedged waiter can't hang release forever — leaking the map is
        # better than a segfault)
        self._released = True
        if self._fx is not None and self._addr:
            try:
                self._fx.ca_wake_u64(self._addr + 8)
                for r in range(self.num_readers):
                    self._fx.ca_wake_u64(self._addr + 8 * (5 + r))
            except Exception:
                pass
        deadline = _now() + 2.0
        while self._waiters and _now() < deadline:
            time.sleep(0.001)
        if self._waiters:
            return  # leak the mapping rather than unmap under a waiter
        try:
            self._mm.close()
        except Exception:
            pass
        if self._created:
            try:
                os.unlink(self.path)
            except OSError:
                pass

    def __reduce__(self):
        raise TypeError("ShmChannel is not serializable; pass spec() and open()")


class BufferedShmChannel(ChannelInterface):
    """N-slot channel for pipelined execution (reference:
    BufferedSharedMemoryChannel, shared_memory_channel.py:534).  Writer and
    each reader advance through slots round-robin, so up to N writes can be
    in flight before the writer blocks on reader acks."""

    def __init__(
        self,
        num_readers: int = 1,
        num_buffers: int = 2,
        buffer_size: int = _DEFAULT_BUFFER,
    ):
        self._chans = [ShmChannel(num_readers, buffer_size) for _ in range(num_buffers)]
        self._wseq = 0
        self._rseq = 0

    def spec(self) -> dict:
        return {"kind": "buffered", "specs": [c.spec() for c in self._chans]}

    @classmethod
    def open(cls, spec: dict, reader_index: int = 0) -> "BufferedShmChannel":
        ch = cls.__new__(cls)
        ch._chans = [ShmChannel.open(s, reader_index) for s in spec["specs"]]
        ch._wseq = 0
        ch._rseq = 0
        return ch

    def write(self, value: Any, timeout: Optional[float] = None):
        self._chans[self._wseq % len(self._chans)].write(value, timeout)
        self._wseq += 1

    def read(self, timeout: Optional[float] = None) -> Any:
        v = self._chans[self._rseq % len(self._chans)].read(timeout)
        self._rseq += 1
        return v

    def wait_consumed(self, timeout: Optional[float] = None) -> bool:
        deadline = None if timeout is None else _now() + timeout
        for c in self._chans:
            left = None if deadline is None else max(0.0, deadline - _now())
            if not c.wait_consumed(left):
                return False
        return True

    def close(self):
        for c in self._chans:
            c.close()

    def release(self):
        for c in self._chans:
            c.release()


def open_channel(spec: dict, reader_index: int = 0) -> ChannelInterface:
    if spec["kind"] == "shm":
        return ShmChannel.open(spec, reader_index)
    if spec["kind"] == "buffered":
        return BufferedShmChannel.open(spec, reader_index)
    raise ValueError(f"unknown channel kind {spec['kind']!r}")


class IntraProcessChannel(ChannelInterface):
    """Same-process channel (reference: intra_process_channel.py)."""

    def __init__(self, maxsize: int = 1):
        self._q: _queue.Queue = _queue.Queue(maxsize=maxsize)
        self._closed = False

    def write(self, value: Any, timeout: Optional[float] = None):
        if self._closed:
            raise ChannelClosedError
        self._q.put(value, timeout=timeout)

    def read(self, timeout: Optional[float] = None) -> Any:
        remaining = timeout
        while True:
            try:
                return self._q.get(timeout=0.05 if remaining is None else min(remaining, 0.05))
            except _queue.Empty:
                if self._closed:
                    raise ChannelClosedError from None
                if remaining is not None:
                    remaining -= 0.05
                    if remaining <= 0:
                        raise TimeoutError("channel read timed out") from None

    def close(self):
        self._closed = True


# NOTE: the reference also has a CompositeChannel (shared_memory_channel.py:648)
# that picks intra-process vs shm transport per reader.  Here actors and the
# driver are always separate processes, so shm is always the right transport
# and no composite selection layer exists; same-actor DAG edges pass values
# in-memory inside the actor loop instead (dag/compiled.py "local" arg specs).
