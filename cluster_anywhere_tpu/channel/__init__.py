"""Typed single-writer / multi-reader channels (analogue of the reference's
ray.experimental.channel: shared_memory_channel.py:151 Channel,
BufferedSharedMemoryChannel:534, IntraProcessChannel),
backed by versioned shared-memory segments instead of mutable plasma objects
(reference C++ experimental_mutable_object_manager.h:49).

These are the zero-RPC transport under compiled DAGs: a writer publishes a new
version in place; readers ack.  Device (jax.Array) payloads cross processes via
device_transport: per-shard zero-copy buffer borrows with sharding metadata,
landed shard-by-shard on the consumer's devices (never assembled on host); the
in-graph ICI path (parallel/) is the TPU fast plane.
"""

from .device_transport import (
    DeviceEnvelope,
    pack_device_value,
    set_transfer_mesh,
    unpack_device_value,
)
from .shm_channel import (
    BufferedShmChannel,
    ChannelClosedError,
    ChannelInterface,
    IntraProcessChannel,
    ShmChannel,
)

__all__ = [
    "ChannelInterface",
    "ShmChannel",
    "BufferedShmChannel",
    "IntraProcessChannel",
    "ChannelClosedError",
    "DeviceEnvelope",
    "pack_device_value",
    "unpack_device_value",
    "set_transfer_mesh",
]
