"""Typed single-writer / multi-reader channels (analogue of the reference's
ray.experimental.channel: shared_memory_channel.py:151 Channel,
BufferedSharedMemoryChannel:534, IntraProcessChannel),
backed by versioned shared-memory segments instead of mutable plasma objects
(reference C++ experimental_mutable_object_manager.h:49).

These are the zero-RPC transport under compiled DAGs: a writer publishes a new
version in place; readers ack.  Device (jax.Array) payloads cross processes by
host staging; the in-graph ICI path (parallel/) is the TPU fast plane.
"""

from .shm_channel import (
    BufferedShmChannel,
    ChannelClosedError,
    ChannelInterface,
    IntraProcessChannel,
    ShmChannel,
)

__all__ = [
    "ChannelInterface",
    "ShmChannel",
    "BufferedShmChannel",
    "IntraProcessChannel",
    "ChannelClosedError",
]
