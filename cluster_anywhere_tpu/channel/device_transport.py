"""Device-native tensor transport: move jax.Array pytrees between processes
without materializing full arrays on the host and without losing sharding.

Role analogue of the reference's NCCL tensor channels
(python/ray/experimental/channel/torch_tensor_nccl_channel.py:44 and
src/ray/core_worker/experimental_mutable_object_manager.h:49), redesigned for
the TPU/XLA memory model (SURVEY.md §7.5):

- producer side: each array leaf is decomposed into its *device shards*.
  Shard buffers are borrowed zero-copy via dlpack (on the CPU backend the
  view IS the device buffer; on TPU the per-shard D2H DMA is the physical
  minimum for crossing a process boundary without a shared ICI program) and
  handed to pickle protocol-5 as out-of-band PickleBuffers, so the shm
  channel scatter-writes them with a single memcpy — the array never passes
  through pickle bytes and is never assembled into one host ndarray.
  Replicated shards are deduplicated: one buffer per distinct shard index.
- consumer side: shards land directly on their target devices
  (jax.device_put per shard) and are stitched with
  jax.make_array_from_single_device_arrays under a reconstructed
  NamedSharding — an equivalent mesh over the consumer's local devices (or
  one registered via set_transfer_mesh).  No full host array is ever built.

In-graph transfers (the true multi-chip path) don't come through here at
all: inside jit/shard_map, XLA moves tensors over ICI via collectives
(parallel/collectives.xla).  This transport is the *between-programs* plane:
DAG edges, actor arguments/returns, and DeviceRef fetches.

Strict mode (CA_DEVICE_TRANSPORT_STRICT=1) turns any full-host-assembly
fallback into an error, so tests can assert the device-native path was
actually taken end to end.
"""

from __future__ import annotations

import os
import pickle
import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "DeviceEnvelope",
    "pack_device_value",
    "unpack_device_value",
    "set_transfer_mesh",
    "stats",
    "reset_stats",
]

_lock = threading.Lock()
_stats = {
    "leaves_packed": 0,
    "dlpack_views": 0,  # zero-copy device-buffer borrows
    "asarray_views": 0,  # numpy fallback (bf16 etc. — dlpack dtype gap)
    "asarray_bytes": 0,  # bytes through that fallback (the copy cost probe)
    "leaves_landed": 0,
    "sharded_landings": 0,  # landed under a reconstructed NamedSharding
    "host_assembles": 0,  # full-host fallback (strict mode forbids)
}
# Both registries are bounded: a long-lived worker registering per-step
# meshes (or landing envelopes from many distinct mesh shapes) must not
# grow them without limit — same bug class as the r3 collectives-KV leak.
_MESH_REGISTRY_CAP = 8
_BUILT_MESHES_CAP = 32
_mesh_registry: List[Any] = []
_built_meshes: "OrderedDict[Tuple, Any]" = OrderedDict()


def stats() -> Dict[str, int]:
    with _lock:
        return dict(_stats)


def reset_stats() -> None:
    with _lock:
        for k in _stats:
            _stats[k] = 0


def _bump(key: str, n: int = 1) -> None:
    with _lock:
        _stats[key] += n


def _strict() -> bool:
    return os.environ.get("CA_DEVICE_TRANSPORT_STRICT", "") not in ("", "0")


def set_transfer_mesh(mesh) -> None:
    """Register the mesh incoming sharded arrays should land on.  Without a
    registration, the envelope's device coordinates (process_index, device
    id) rebuild the producer's exact mesh when the consumer can see those
    devices; otherwise an equivalent mesh (same shape + axis names) is built
    over jax.devices().  Newest registration wins; the registry keeps only
    the last _MESH_REGISTRY_CAP meshes (a per-step registrant must not leak)."""
    with _lock:
        _mesh_registry.append(mesh)
        del _mesh_registry[:-_MESH_REGISTRY_CAP]


class _LeafMarker:
    """Placeholder for an array leaf inside the envelope's skeleton pytree."""

    __slots__ = ("i",)

    def __init__(self, i: int):
        self.i = i

    def __getstate__(self):
        return self.i

    def __setstate__(self, i):
        self.i = i


class _LeafPack:
    """One array leaf: shape/dtype/sharding metadata + raw shard buffers.

    On the producer side `bufs` holds pickle.PickleBuffer views of the
    device shards (out-of-band on the wire); after deserialization they
    come back as memoryviews (or ndarray shims) over the channel's payload.
    """

    __slots__ = ("shape", "dtype", "desc", "keys", "bufs")

    def __init__(self, shape, dtype, desc, keys, bufs):
        self.shape = shape
        self.dtype = dtype
        self.desc = desc
        self.keys = keys
        self.bufs = bufs

    def __getstate__(self):
        return (self.shape, self.dtype, self.desc, self.keys, self.bufs)

    def __setstate__(self, st):
        self.shape, self.dtype, self.desc, self.keys, self.bufs = st


class DeviceEnvelope:
    """A pytree in transit: skeleton with _LeafMarkers + packed leaves.

    `_keepalive` pins the source jax.Arrays while their borrowed dlpack
    views are still being written into the channel; it is dropped on
    serialization (the bytes have been copied out by then).
    """

    __slots__ = ("skeleton", "leaves", "_keepalive")

    def __init__(self, skeleton, leaves, keepalive):
        self.skeleton = skeleton
        self.leaves = leaves
        self._keepalive = keepalive

    def __getstate__(self):
        return (self.skeleton, self.leaves)

    def __setstate__(self, st):
        self.skeleton, self.leaves = st
        self._keepalive = None


# --------------------------------------------------------------------- pack


def _index_key(index, shape) -> Tuple[Tuple[int, int], ...]:
    """Canonical hashable key for a shard's index (tuple of slices)."""
    return tuple(
        (sl.start or 0, sl.stop if sl.stop is not None else dim)
        for sl, dim in zip(index, shape)
    )


def _shard_view(arr) -> np.ndarray:
    """Borrow a single-device array's buffer as an ndarray.  dlpack first
    (zero-copy); np.asarray for dtypes numpy's dlpack can't express (bf16 —
    still zero-copy on the CPU backend, a D2H DMA on TPU)."""
    try:
        v = np.from_dlpack(arr)
        _bump("dlpack_views")
    except Exception:
        v = np.asarray(arr)
        _bump("asarray_views")
        _bump("asarray_bytes", v.nbytes)
    return v


def _as_picklebuffer(v: np.ndarray) -> pickle.PickleBuffer:
    try:
        return pickle.PickleBuffer(v)
    except ValueError:
        # dtypes outside the buffer protocol (bf16/fp8 via ml_dtypes):
        # expose the raw bytes; leaf.dtype reinterprets them on landing
        return pickle.PickleBuffer(v.view(np.uint8))


def _sharding_desc(x) -> Dict[str, Any]:
    import jax

    s = x.sharding
    if isinstance(s, jax.sharding.NamedSharding):
        mesh = s.mesh
        return {
            "kind": "named",
            "mesh_shape": tuple(mesh.devices.shape),
            "axis_names": tuple(mesh.axis_names),
            "spec": tuple(s.spec),
            # device coordinates per flattened mesh position: the consumer
            # rebuilds the producer's EXACT device arrangement when it can
            # resolve them (same jax.distributed runtime, or same-host
            # processes whose local enumerations agree), instead of assuming
            # jax.devices()[:n] row-major order
            "mesh_coords": tuple(
                (int(d.process_index), int(d.id)) for d in mesh.devices.flat
            ),
        }
    if len(getattr(s, "device_set", [None])) <= 1:
        return {"kind": "single"}
    # non-named multi-device sharding (GSPMD/positional): shards still
    # transfer individually; landing reassembles by explicit indices
    return {"kind": "indexed"}


def _pack_jax_leaf(x) -> _LeafPack:
    desc = _sharding_desc(x)
    keys: List[Tuple] = []
    bufs: List[pickle.PickleBuffer] = []
    seen = set()
    for sh in x.addressable_shards:
        key = _index_key(sh.index, x.shape)
        if key in seen:
            continue  # replicated shard: send one copy, not one per device
        seen.add(key)
        v = _shard_view(sh.data)
        if not v.flags.c_contiguous:
            v = np.ascontiguousarray(v)
        keys.append(key)
        bufs.append(_as_picklebuffer(v))
    _bump("leaves_packed")
    return _LeafPack(tuple(x.shape), np.dtype(x.dtype), desc, keys, bufs)


def _pack_host_leaf(x: np.ndarray) -> _LeafPack:
    v = x if x.flags.c_contiguous else np.ascontiguousarray(x)
    _bump("leaves_packed")
    return _LeafPack(
        tuple(x.shape),
        v.dtype,
        {"kind": "single"},
        [_index_key(tuple(slice(0, d) for d in x.shape), x.shape)],
        [_as_picklebuffer(v)],
    )


def pack_device_value(value: Any) -> DeviceEnvelope:
    """Pytree -> DeviceEnvelope.  jax.Array leaves become per-shard buffer
    borrows; numpy leaves ride the same path (they re-enter the device on
    the consumer, per with_tensor_transport semantics); everything else
    stays in the skeleton and is pickled normally (small metadata)."""
    import jax

    leaves: List[_LeafPack] = []
    keepalive: List[Any] = []

    def repl(x):
        if isinstance(x, jax.Array):
            # A multi-host global array ships only its addressable shards
            # (its other shards belong to a jit program's domain, not a
            # channel's); landing verifies coverage and refuses to fabricate
            # the missing regions (_host_assemble's coverage check).
            keepalive.append(x)
            leaves.append(_pack_jax_leaf(x))
            return _LeafMarker(len(leaves) - 1)
        if isinstance(x, np.ndarray) and x.dtype != object:
            keepalive.append(x)
            leaves.append(_pack_host_leaf(x))
            return _LeafMarker(len(leaves) - 1)
        return x

    skeleton = jax.tree.map(repl, value)
    return DeviceEnvelope(skeleton, leaves, keepalive)


# ------------------------------------------------------------------- unpack


def _landing_mesh(
    mesh_shape: Tuple[int, ...],
    axis_names: Tuple[str, ...],
    mesh_coords: Optional[Tuple[Tuple[int, int], ...]] = None,
):
    import jax

    key = (mesh_shape, axis_names, mesh_coords)
    with _lock:
        for m in reversed(_mesh_registry):
            if (
                tuple(m.axis_names) == axis_names
                and tuple(m.devices.shape) == mesh_shape
            ):
                return m
        if key in _built_meshes:
            _built_meshes.move_to_end(key)
            return _built_meshes[key]
    n = 1
    for d in mesh_shape:
        n *= d
    devs = jax.devices()
    mesh = None
    if mesh_coords is not None and len(mesh_coords) == n:
        # exact reconstruction: map each mesh position to the consumer's
        # device with the same (process_index, id).  Resolves whenever both
        # processes are in one jax.distributed runtime, or are same-host
        # processes whose local enumerations agree (then process_index and
        # ids coincide position-for-position).
        by_coord = {(int(d.process_index), int(d.id)): d for d in devs}
        try:
            arranged = [by_coord[c] for c in mesh_coords]
        except KeyError:
            arranged = None  # foreign runtime: fall through to equivalent mesh
        if arranged is not None:
            mesh = jax.sharding.Mesh(
                np.array(arranged).reshape(mesh_shape), axis_names
            )
    if mesh is None:
        if n > len(devs):
            return None
        mesh = jax.sharding.Mesh(
            np.array(devs[:n]).reshape(mesh_shape), axis_names
        )
    with _lock:
        _built_meshes[key] = mesh
        while len(_built_meshes) > _BUILT_MESHES_CAP:
            _built_meshes.popitem(last=False)
    return mesh


def _buf_as_ndarray(buf, dtype, shard_shape) -> np.ndarray:
    if isinstance(buf, np.ndarray) and buf.dtype == dtype:
        return buf.reshape(shard_shape)
    return np.frombuffer(buf, dtype=dtype).reshape(shard_shape)


def _host_assemble(leaf: _LeafPack) -> np.ndarray:
    """Fallback: stitch shards into one host array (forbidden in strict)."""
    if _strict():
        raise RuntimeError(
            "device transport fell back to host assembly under "
            "CA_DEVICE_TRANSPORT_STRICT (incompatible mesh or sharding)"
        )
    _bump("host_assembles")
    total = 1
    for d in leaf.shape:
        total *= d
    covered = 0
    for key in leaf.keys:
        n = 1
        for a, b in key:
            n *= b - a
        covered += n
    if covered < total:
        # producer shipped only its addressable shards (multi-host array);
        # fabricating the uncovered regions would be silent corruption
        raise RuntimeError(
            f"device transport cannot assemble leaf {leaf.shape}: shards cover "
            f"{covered} of {total} elements (array was not fully addressable "
            f"on the producer)"
        )
    out = np.empty(leaf.shape, dtype=leaf.dtype)
    for key, buf in zip(leaf.keys, leaf.bufs):
        shard_shape = tuple(b - a for a, b in key)
        idx = tuple(slice(a, b) for a, b in key)
        out[idx] = _buf_as_ndarray(buf, leaf.dtype, shard_shape)
    return out


def _land_leaf(leaf: _LeafPack):
    import jax

    _bump("leaves_landed")
    desc = leaf.desc
    if desc["kind"] == "named":
        mesh = _landing_mesh(
            desc["mesh_shape"], desc["axis_names"], desc.get("mesh_coords")
        )
        if mesh is not None:
            sharding = jax.sharding.NamedSharding(
                mesh, jax.sharding.PartitionSpec(*desc["spec"])
            )
            by_key = dict(zip(leaf.keys, leaf.bufs))
            idx_map = sharding.addressable_devices_indices_map(leaf.shape)
            arrs = []
            ok = True
            for dev, index in idx_map.items():
                key = _index_key(index, leaf.shape)
                buf = by_key.get(key)
                if buf is None:
                    ok = False  # producer didn't cover this shard (multihost)
                    break
                shard_shape = tuple(b - a for a, b in key)
                arrs.append(
                    jax.device_put(_buf_as_ndarray(buf, leaf.dtype, shard_shape), dev)
                )
            if ok:
                _bump("sharded_landings")
                return jax.make_array_from_single_device_arrays(
                    leaf.shape, sharding, arrs
                )
        return jax.device_put(_host_assemble(leaf))
    if desc["kind"] == "single" and len(leaf.bufs) == 1:
        shard_shape = tuple(b - a for a, b in leaf.keys[0])
        return jax.device_put(_buf_as_ndarray(leaf.bufs[0], leaf.dtype, shard_shape))
    return jax.device_put(_host_assemble(leaf))


def unpack_device_value(env: DeviceEnvelope) -> Any:
    """DeviceEnvelope -> pytree with jax.Array leaves on local devices,
    shards device_put directly onto their target devices under the
    reconstructed sharding."""
    import jax

    landed = [_land_leaf(leaf) for leaf in env.leaves]
    return jax.tree.map(
        lambda x: landed[x.i] if isinstance(x, _LeafMarker) else x,
        env.skeleton,
        is_leaf=lambda x: isinstance(x, _LeafMarker),
    )


def maybe_unpack(value: Any) -> Any:
    """Pass-through helper for channel/RPC read sites."""
    if isinstance(value, DeviceEnvelope):
        return unpack_device_value(value)
    return value
