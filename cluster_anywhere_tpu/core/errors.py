"""Exception hierarchy, mirroring the user-visible error surface of the
reference (python/ray/exceptions.py): task errors wrap the remote traceback,
actor errors mark dead actors, object-loss and timeout errors are distinct.

Failure-class errors (FencedError, DeadActorError, DagTimeoutError,
ObjectLostError) carry a flight-recorder slice: the raising process's recent
decision events (`.flight_events`, plain picklable dicts), so the exception
that reaches the driver brings its own black box — `ca incident` and plain
repr-debugging both read it without another round trip to the cluster.
"""

from __future__ import annotations


def _flight_slice(plane=None):
    """Recent flight-recorder events from THIS process ([] when the plane is
    disabled).  Lazy import: errors must stay importable everywhere."""
    try:
        from ..util import flightrec

        return flightrec.recent(32, plane=plane)
    except Exception:
        return []


class CAError(Exception):
    """Base class for all framework errors."""


class TaskError(CAError):
    """A remote task raised an exception. Re-raised at `get()` on the caller,
    carrying the remote traceback as text."""

    def __init__(self, cause_repr: str, traceback_str: str = ""):
        self.cause_repr = cause_repr
        self.traceback_str = traceback_str
        super().__init__(cause_repr)

    def __str__(self):
        if self.traceback_str:
            return f"{self.cause_repr}\n\n--- remote traceback ---\n{self.traceback_str}"
        return self.cause_repr


class WorkerCrashedError(CAError):
    """The worker process executing the task died unexpectedly."""


class ActorError(CAError):
    """Generic actor-related failure."""


class ActorDiedError(ActorError):
    """The actor is dead (crashed, killed, or out of restart budget); pending
    and future calls fail with this."""


class ActorUnavailableError(ActorError):
    """The actor is temporarily unreachable (e.g. restarting)."""


class DeadActorError(ActorDiedError):
    """An actor hosting a compiled-DAG loop died mid-execute (infrastructure
    death, as opposed to an application error — those travel through the
    channels as _DagError payloads).  Carries the failed actor and the DAG
    nodes it hosted; the DAG is torn down and `recompile()` rebuilds it
    against the restarted actor."""

    def __init__(self, actor_id: str, nodes: tuple = (), detail: str = ""):
        self.actor_id = actor_id
        self.nodes = tuple(nodes)
        self.flight_events = _flight_slice(plane="dag")
        names = ", ".join(self.nodes) or "?"
        msg = (
            f"compiled-DAG actor {actor_id} died mid-execute "
            f"(hosted nodes: {names})"
        )
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


class DagTimeoutError(CAError, TimeoutError):
    """A compiled-DAG execute did not produce its outputs within
    config.dag_execute_timeout_s (or the per-call timeout).  Names the node
    whose output channel stalled so the hang is attributable."""

    def __init__(self, node: str, timeout_s: float, phase: str = "read"):
        self.node = node
        self.timeout_s = timeout_s
        self.phase = phase
        self.flight_events = _flight_slice(plane="dag")
        super().__init__(
            f"compiled-DAG {phase} timed out after {timeout_s:g}s waiting on "
            f"node {node}"
        )


class ObjectLostError(CAError):
    """Object data is unavailable and could not be recovered."""

    def __init__(self, *args):
        self.flight_events = _flight_slice()
        super().__init__(*args)


class GetTimeoutError(CAError, TimeoutError):
    """`get()` exceeded its timeout."""


class TaskCancelledError(CAError):
    """The task was cancelled before/while running."""


class RuntimeEnvSetupError(CAError):
    """Preparing the runtime environment for a task/actor failed."""


class ObjectStoreFullError(CAError):
    """The shared-memory object store could not allocate."""


class StaleObjectError(CAError):
    """A shared-memory slice was recycled since this reference was taken
    (its seal sequence no longer matches); the reader must re-resolve the
    object's current location through the directory."""


class PlacementGroupError(CAError):
    """Placement group could not be created or was removed."""


class FencedError(CAError):
    """An RPC carried a stale node incarnation: the head declared that node
    dead (partition, crash) and adopted its state, so nothing minted under
    the old incarnation may act anymore.  A fenced agent/worker must cancel
    its outstanding leases and zombie tasks, tear down, and rejoin as a
    fresh incarnation — completing in-flight side effects would duplicate
    work the head already resubmitted elsewhere."""

    def __init__(self, *args):
        self.flight_events = _flight_slice(plane="fence")
        super().__init__(*args)
