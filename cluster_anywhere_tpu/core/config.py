"""Central tunables table, the analogue of the reference's RAY_CONFIG macro
table (src/ray/common/ray_config_def.h): every knob has a typed default and an
environment-variable override `CA_<NAME>`.  The resolved config dict is handed
to every spawned process so the whole cluster agrees on values.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field, fields
from typing import Any

_ENV_PREFIX = "CA_"


def _env_override(name: str, default: Any) -> Any:
    raw = os.environ.get(_ENV_PREFIX + name.upper())
    if raw is None:
        return default
    t = type(default)
    if t is bool:
        return raw.lower() in ("1", "true", "yes")
    if t is int:
        return int(raw)
    if t is float:
        return float(raw)
    return raw


@dataclass
class CAConfig:
    # --- object store ---
    inline_object_max_bytes: int = 100 * 1024  # larger objects go to shm
    object_store_memory: int = 2 * 1024**3  # shm budget per node
    shm_parallel_copy_threshold: int = 8 * 1024**2  # use parallel memcpy above
    shm_copy_threads: int = 8

    # --- scheduler / leases ---
    max_leases_per_shape: int = 64  # cap on concurrently held leases per resource shape
    lease_idle_timeout_s: float = 1.0  # return leases idle longer than this
    max_inflight_per_lease: int = 16  # pipelined task pushes per leased worker
    worker_prestart: bool = True
    scheduler_spread_threshold: float = 0.5  # hybrid policy: pack below, spread above
    # --- lease plane (node-local granting; raylet LocalTaskManager analogue) ---
    # the head delegates bounded per-pool lease capacity ("lease blocks") to
    # node agents; submitters dial agents directly for the hot unit-shape
    # lease class, keeping per-task traffic off the head
    lease_delegation: bool = True
    # max delegated workers per (node, pool); 0 = auto (the node's CPU count)
    lease_block_max: int = 0
    # submitter-side lease-directory cache TTL (one lease_dir RPC per pool
    # per TTL while growing, zero in steady state)
    lease_dir_ttl_s: float = 3.0

    # --- ownership plane (core/ownership.py; NSDI'21 ownership protocol) ---
    # owner-resident object lifetime: borrowers settle inc/dec with the
    # OWNER process's ledger over direct connections; the head keeps only
    # the registry (obj_created/obj_release) and adopts orphaned ledgers on
    # owner death.  Off = classic centralized holders at the head.
    owner_plane: bool = True
    # owner_sync digest cadence (ledger deltas ride the housekeeping loop)
    owner_sync_period_s: float = 1.0
    # how long the head (and owner ledgers) hold a refcount inc that arrived
    # before its obj_created/registration (cross-socket ordering), before the
    # entry is swept as orphaned.  Must comfortably exceed the longest task
    # whose return ref is forwarded before completion.
    early_ref_grace_s: float = 600.0

    # --- multi-node ---
    head_host: str = "127.0.0.1"  # TCP bind host for the head (cross-host: 0.0.0.0)
    transfer_chunk_bytes: int = 4 * 1024**2  # node-to-node object pull chunk
    # --- transfer plane (windowed multi-source bulk pulls) ---
    # pull_chunk RPCs kept in flight per source during a node-to-node object
    # pull / client upload / head evacuation (1 = the old serial
    # request-response ping-pong); the same window applies per holder when a
    # pull fans out across multiple live copies
    transfer_window: int = 4
    # when the directory reports several live copies, split the byte range
    # across them and pull concurrently (failed sources re-assign their
    # remaining chunks to survivors instead of failing the transfer)
    transfer_multi_source: bool = True
    # host collective ring default payload encoding ("" = f32 wire bytes,
    # untouched default; "int8"/"bf16" = EQuARX-style block-quantized ring).
    # Per-call allreduce(..., quantize=...) overrides the group default.
    collective_quantize: str = ""
    # elements per quantization block (one f32 scale per block on the wire)
    collective_quant_block: int = 4096
    # test/bench hook: per-pull_chunk serving delay (seconds) — simulates a
    # high-latency link so the windowed-pull A/B measures pipelining, not
    # this host's memcpy speed.  0 = off (production).
    testing_transfer_delay_s: float = 0.0
    # delta-synced node state (ray_syncer analogue): agents send versioned
    # component deltas (node_sync) instead of full per-tick heartbeats; an
    # idle node's tick is a bare keepalive.  Off = legacy full node_heartbeat.
    delta_sync: bool = True

    # --- health / failure detection ---
    health_check_period_s: float = 2.0
    health_check_failure_threshold: int = 5
    worker_register_timeout_s: float = 30.0
    # node memory monitor (memory_monitor.h analogue): kill a worker when
    # node used/total exceeds the threshold; 0 disables the monitor
    memory_usage_threshold: float = 0.95
    memory_monitor_refresh_ms: int = 250
    # drain plane: default evacuation window for `drain_node` / agent SIGTERM
    # self-drain — running tasks get this long to finish before the deadline
    # kill; actors and sole-copy objects migrate to survivors inside it
    drain_deadline_s: float = 30.0
    # bounded-IO defaults (util/aio.py): every control-plane dial goes
    # through aio.dial() with this connect bound — on preemptible VMs a peer
    # can vanish mid-handshake and an unbounded connect parks the caller
    # forever; io_timeout_s bounds single request/response reads and
    # writer drains (NOT persistent-connection read loops, which idle
    # legitimately)
    dial_timeout_s: float = 15.0
    io_timeout_s: float = 60.0

    # --- HA plane (warm-standby head replication / epoch-fenced failover) ---
    # master switch for the head-replication machinery.  With no standby
    # subscribed the active head's only HA cost is a per-snapshot-tick flag
    # check, so this stays on by default.
    ha_plane: bool = True
    # table-delta replication tick on the active head (rides the persist
    # loop); also the standby-liveness heartbeat period on the stream
    ha_repl_interval_s: float = 0.25
    # bounded re-stage window: replication records kept in memory for
    # standbys that reconnect with a watermark; older watermarks get a full
    # state transfer instead
    ha_repl_log_max: int = 4096
    # how long an acked KV commit waits for standby acks before the slow
    # standby is dropped from the sync set (availability over sync once a
    # replica is gone)
    ha_sync_commit_timeout_s: float = 2.0
    # standby-side: how long the active head must stay unreachable (stream
    # closed AND redials failing) before self-promotion; each standby rank
    # waits one extra grace period per rank so replicas don't race
    ha_failover_grace_s: float = 2.0
    # standby self-promotes after the grace window (off = promotion only via
    # `ca head promote` / head_promote RPC)
    ha_auto_promote: bool = True
    # restarting head probes the current head.addr occupant before claiming
    # authority: a live head with a >= epoch means THIS process is the stale
    # one — demote at boot instead of split-braining the registry
    ha_boot_probe: bool = True

    # --- tasks / actors ---
    default_max_retries: int = 3
    lineage_cap: int = 8192  # task specs kept for object reconstruction
    streaming_backpressure: int = 8  # unconsumed items before a generator blocks
    default_actor_max_restarts: int = 0
    actor_restart_backoff_s: float = 0.2
    push_timeout_s: float = 60.0

    # --- compiled DAG plane (dag/compiled.py; channel/shm_channel.py) ---
    # per-execute result deadline: a tick that hasn't produced its outputs
    # within this raises DagTimeoutError naming the stalled node (never a
    # bare hang); also bounds the input-channel backpressure wait
    dag_execute_timeout_s: float = 300.0
    # serving plane: stream ContinuousLLMServer tokens to the proxy over a
    # pre-opened shm channel (per-token cost = one channel write) instead of
    # streaming-RPC frames.  Off = every token rides an RPC frame.
    serve_compiled_dag: bool = True
    # slots in the per-request token channel (tokens in flight before the
    # replica-side writer blocks on the proxy reader)
    serve_dag_stream_buffers: int = 8

    # --- misc ---
    session_dir_root: str = "/tmp/ca_tpu"
    log_to_driver: bool = True
    # --- log plane (util/logplane.py; raylet log-monitor analogue) ---
    log_capture: bool = True  # structured stdout/stderr capture in spawned procs
    log_rotate_bytes: int = 1024 * 1024  # per-process JSONL cap before .1 rollover
    log_ship_interval_s: float = 0.25  # agent/head tail-and-ship period
    log_ship_batch: int = 500  # max records per shipped log_batch
    event_buffer_flush_period_s: float = 1.0
    metrics_report_period_s: float = 5.0
    # --- metrics plane (util/timeseries.py, node-agent /metrics scrape) ---
    # head-free scrape topology: workers ship metric deltas to their node's
    # agent, which serves `GET /metrics` over HTTP (Prometheus exposition)
    # and piggybacks the deltas onto node_sync ticks head-ward.  Off =
    # legacy per-worker metrics_report RPCs straight to the head.
    metrics_plane: bool = True
    # head-side time-series retention: tier-0 sampling cadence (seconds) and
    # ring length; tier 1 is timeseries_tier1_mult x coarser, same length.
    # 0 disables retention entirely.
    timeseries_interval_s: float = 10.0
    timeseries_len: int = 360
    timeseries_tier1_mult: int = 12
    timeseries_max_series: int = 1024
    # event-loop lag self-measurement period for the head (seconds)
    loop_lag_period_s: float = 0.25
    # --- flight recorder (util/flightrec.py) ---
    # per-process bounded ring journal of plane decision events (fence
    # mints/refusals, drain FSM transitions, netchaos firings, DAG
    # recompiles/timeouts, serve shed/drain, train barrier phases, transfer
    # failover, owner adoption), shipped head-ward on the metrics-delta
    # path.  Off = util.flightrec.REC stays None and every record site is a
    # single `is None` branch.
    flightrec_plane: bool = True
    # per-process ring capacity (drop-oldest beyond this)
    flightrec_ring_len: int = 4096
    # head-side merged journal capacity
    flightrec_head_len: int = 50_000
    # deterministic RPC fault injection, modeled on the reference's
    # RAY_testing_rpc_failure (src/ray/rpc/rpc_chaos.h): "method=N" pairs,
    # failing the first N matching RPCs.
    testing_rpc_failure: str = ""
    # deterministic per-method RPC latency injection: "method=MS" pairs add
    # MS milliseconds before each matching send (straggler RPCs; names
    # validated against the protocol contract exactly like the failure knob)
    testing_rpc_delay: str = ""
    # network-chaos plane (core/netchaos.py): per-link blackhole / delay /
    # flap schedules, e.g. "seed=7;n0<>node1:blackhole@1+8".  Empty = every
    # injection hook disabled (no per-frame overhead).
    testing_net_chaos: str = ""

    def __post_init__(self):
        for f in fields(self):
            setattr(self, f.name, _env_override(f.name, getattr(self, f.name)))

    def to_json(self) -> str:
        return json.dumps({f.name: getattr(self, f.name) for f in fields(self)})

    @classmethod
    def from_json(cls, s: str) -> "CAConfig":
        cfg = cls.__new__(cls)
        data = json.loads(s)
        for f in fields(cls):
            setattr(cfg, f.name, data.get(f.name, f.default))
        return cfg


_global_config: CAConfig | None = None


def get_config() -> CAConfig:
    global _global_config
    if _global_config is None:
        _global_config = CAConfig()
    return _global_config


def set_config(cfg: CAConfig) -> None:
    global _global_config
    _global_config = cfg
